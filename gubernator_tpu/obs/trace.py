"""Lightweight span tracing with W3C trace-context propagation.

One request entering the public surface (gRPC, HTTP, or the peerlink lean
link) gets a trace; every hot-path stage it crosses — ingress, the
combiner's batch window wait, the device kernel dispatch, the peer hop to
the owner — records a span under that trace's id. The context rides
outbound hops as a W3C `traceparent` (gRPC metadata on peer forwards; a
reserved carrier item in peerlink frames, service/peerlink.py), so the
owner daemon's spans share the ingress daemon's trace id and the chain
reconstructs end to end from the daemons' /v1/debug/traces ring buffers.

Design constraints, in order:

1. Sample-rate 0 is a hard no-op: `maybe_trace` returns None before any
   allocation, surfaces skip metadata scans entirely, and every
   instrumentation site guards on `span is None`. The only per-request
   cost with tracing off is one ContextVar read on the routing path.
2. No background machinery: finished spans land in a bounded ring buffer
   (newest wins); the debug endpoint groups them by trace id on demand.
3. Spans cross thread pools explicitly (the combiner and forward pool run
   on their own threads): callers capture the current span and attach
   completed child spans via `record_span` — no context copying on the
   hot path.

Slow-request logging: when a ROOT span ends over `slow_ms`, one structured
JSON line (logger `gubernator_tpu.slow`) carries the trace id and its
phase spans — grep-able without a trace UI.
"""

from __future__ import annotations

import contextvars
import json
import logging
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from gubernator_tpu.obs import witness

slow_log = logging.getLogger("gubernator_tpu.slow")


def install_slow_log_file(path: str, max_mb: float = 64.0,
                          backups: int = 2) -> Optional[object]:
    """Attach a size-rotated file sink to the slow-request logger
    (GUBER_SLOW_LOG_PATH / GUBER_SLOW_LOG_MAX_MB). Without a bound the
    one-line-per-slow-request log grows without limit on a node that is
    slow BECAUSE it is sick — exactly when disk is the wrong thing to
    exhaust. Returns the handler (tests close it), None when disabled or
    the path is unwritable (stderr logging still works)."""
    if not path or max_mb <= 0:
        return None
    from logging.handlers import RotatingFileHandler

    try:
        handler = RotatingFileHandler(
            path, maxBytes=int(max_mb * 1024 * 1024), backupCount=backups)
    except OSError:
        logging.getLogger(__name__).exception(
            "slow-log file sink unavailable: %s", path)
        return None
    handler.setFormatter(logging.Formatter("%(message)s"))
    slow_log.addHandler(handler)
    return handler

# W3C traceparent: version "00" - 16-byte trace id - 8-byte span id - flags
_SAMPLED_FLAG = 0x01


def format_traceparent(span: "Span") -> str:
    return f"00-{span.trace_id}-{span.span_id}-01"


def parse_traceparent(header: str):
    """-> (trace_id, span_id, sampled) or None for anything malformed.
    Unknown versions parse leniently (the spec's forward-compat rule)."""
    try:
        parts = header.strip().split("-")
        if len(parts) < 4:
            return None
        version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
        if len(version) != 2 or version == "ff":
            return None
        if len(trace_id) != 32 or len(span_id) != 16:
            return None
        int(version, 16), int(trace_id, 16), int(span_id, 16)  # hex or bust
        if trace_id == "0" * 32 or span_id == "0" * 16:
            return None
        return trace_id, span_id, bool(int(flags, 16) & _SAMPLED_FLAG)
    except (ValueError, AttributeError):
        return None


def traceparent_from_metadata(metadata) -> Optional[str]:
    """Pull `traceparent` out of gRPC invocation metadata (a sequence of
    (key, value) pairs). Callers gate on tracer.active first."""
    if metadata is None:
        return None
    for key, value in metadata:
        if key == "traceparent":
            return value
    return None


class Span:
    """One phase of one traced request. Mutable until finish()."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start_ns",
                 "end_ns", "attrs")

    def __init__(self, trace_id: str, span_id: str, parent_id: str,
                 name: str, start_ns: int):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id  # "" = root of its process's view
        self.name = name
        self.start_ns = start_ns
        self.end_ns = 0
        self.attrs: Optional[Dict[str, object]] = None

    def set(self, key: str, value) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def as_dict(self) -> dict:
        d = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ns": self.start_ns,
            "duration_ms": round((self.end_ns - self.start_ns) / 1e6, 4),
        }
        if self.attrs:
            d["attrs"] = self.attrs
        return d


# The active span for the current thread of execution. Surfaces set it for
# the duration of a handler call; the combiner reads it at submit().
_current: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "guber_trace_span", default=None)


def current() -> Optional[Span]:
    return _current.get()


def use(span: Optional[Span]):
    """Install `span` as the calling context's active span; returns the
    reset token. None is allowed (explicitly clears)."""
    return _current.set(span)


def reset(token) -> None:
    _current.reset(token)


class Tracer:
    """Per-daemon span recorder + sampler (one per Instance, like the
    per-daemon Metrics registry)."""

    def __init__(self, sample: float = 0.0, slow_ms: float = 0.0,
                 ring: int = 2048, service: str = ""):
        self.sample = float(sample)
        self.slow_ms = float(slow_ms)
        self.service = service
        self._ring: "deque[Span]" = deque(maxlen=ring)
        self._lock = witness.make_lock("trace.ring")
        self._rand = random.Random()
        self.stats = {"started": 0, "continued": 0, "spans": 0,
                      "slow_logged": 0}
        # optional hook (wired by the Instance): zero-arg callable giving
        # the profiler's recent serving-cycle decomposition, attached to
        # slow-request log entries so "this request was slow" arrives with
        # "and here is where the last minute's cycle time went"
        self.profile_snapshot = None

    # ------------------------------------------------------------- sampling

    @property
    def active(self) -> bool:
        """False = tracing is fully off; surfaces skip even the header
        scan, so rate 0 adds nothing to the hot path."""
        return self.sample > 0.0

    def maybe_trace(self, name: str,
                    traceparent: Optional[str] = None) -> Optional[Span]:
        """Ingress: continue a remote sampled trace, else sample a new
        one. Returns None (no allocation) when the request is untraced."""
        if not self.active:
            return None
        if traceparent:
            parsed = parse_traceparent(traceparent)
            if parsed is not None and parsed[2]:
                self.stats["continued"] += 1
                return self._new_span(parsed[0], parsed[1], name)
        if self.sample >= 1.0 or self._rand.random() < self.sample:
            self.stats["started"] += 1
            return self._new_span(self._hex(16), "", name)
        return None

    def continue_trace(self, name: str,
                       traceparent: Optional[str]) -> Optional[Span]:
        """Peer surfaces: record ONLY when the remote hop is part of a
        sampled trace — never originate a trace at an internal surface
        (forwarded traffic would double-sample)."""
        if not self.active or not traceparent:
            return None
        parsed = parse_traceparent(traceparent)
        if parsed is None or not parsed[2]:
            return None
        self.stats["continued"] += 1
        return self._new_span(parsed[0], parsed[1], name)

    # ------------------------------------------------------------ recording

    def start_span(self, name: str, parent: Span) -> Span:
        return Span(parent.trace_id, self._hex(8), parent.span_id, name,
                    time.time_ns())

    def record_span(self, name: str, parent: Span, start_ns: int,
                    end_ns: int, attrs: Optional[dict] = None) -> Span:
        """Attach an already-measured interval as a completed child span —
        the cross-thread idiom (combiner windows, forward-pool hops)."""
        span = Span(parent.trace_id, self._hex(8), parent.span_id, name,
                    start_ns)
        span.end_ns = end_ns
        if attrs:
            span.attrs = dict(attrs)
        self._push(span)
        return span

    def finish(self, span: Optional[Span]) -> None:
        if span is None:
            return
        span.end_ns = time.time_ns()
        self._push(span)
        if not span.parent_id and self.slow_ms > 0:
            dur_ms = (span.end_ns - span.start_ns) / 1e6
            if dur_ms >= self.slow_ms:
                self._log_slow(span, dur_ms)

    # ---------------------------------------------------------- inspection

    def traces(self, trace_id: str = "") -> Dict[str, List[dict]]:
        """Ring-buffer dump grouped by trace id (optionally one trace),
        spans in start order — the /v1/debug/traces payload."""
        with self._lock:
            spans = list(self._ring)
        out: Dict[str, List[dict]] = {}
        for s in sorted(spans, key=lambda s: s.start_ns):
            if trace_id and s.trace_id != trace_id:
                continue
            out.setdefault(s.trace_id, []).append(s.as_dict())
        return out

    # ------------------------------------------------------------ internals

    def _new_span(self, trace_id: str, parent_id: str, name: str) -> Span:
        return Span(trace_id, self._hex(8), parent_id, name, time.time_ns())

    def _hex(self, nbytes: int) -> str:
        return f"{self._rand.getrandbits(nbytes * 8):0{nbytes * 2}x}"

    def _push(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)
            self.stats["spans"] += 1

    def _log_slow(self, root: Span, dur_ms: float) -> None:
        self.stats["slow_logged"] += 1
        phases = self.traces(root.trace_id).get(root.trace_id, [])
        entry = {
            "event": "slow_request",
            "service": self.service,
            "trace_id": root.trace_id,
            "name": root.name,
            "duration_ms": round(dur_ms, 3),
            "threshold_ms": self.slow_ms,
            "spans": phases,
        }
        snap = self.profile_snapshot
        if snap is not None:
            try:
                entry["profile"] = snap()
            except Exception:  # noqa: BLE001 — a slow log must still land
                pass
        slow_log.warning(json.dumps(entry, separators=(",", ":")))
