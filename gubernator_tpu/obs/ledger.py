"""Decision ledger: live audit of the "budget is never minted" invariant.

Every authority-delegating subsystem promises the same conservation
property in prose — hot-key leases carve slices out of the owner's
remaining budget, degraded-local serving admits against a local copy,
reshard double-writes during the transfer window, GLOBAL answers from a
local cache — and each bounds its worst-case over-admission by
construction. Nothing measured whether the promise holds under real
traffic. This module is the instrument: every admitted hit is
attributed at decision time to its **source of authority**, and an
off-serving-path auditor checks, per key-window,

    Σ admits across authorities ≤ limit
                                 + minted lease budget
                                 + declared degraded/reshard/global slack

rendering measured over-admission as a distribution (and a violation
counter the `over_admission` anomaly detector gates on), not a hope.

Hot-path contract (the PhaseHist rule from obs/profile.py): the engine's
window paths pay O(1) per *window*, not per lane — each dispatch parks a
handful of small numpy column copies (slot, hits, status, limit, reset)
on a pending ring under a leaf lock. Key resolution (slot → hash-key via
the directory arena walk), bucket folding, window rolling, and the
conservation evaluation all run in `audit()`, off the serving path —
riding the cartographer harvest / anomaly ticker cadence. Lone native
decisions and the non-engine authorities (lease consume, GLOBAL cache,
minted budget) record per key directly: they are already per-item paths.

Authorities:

- ``owner``        — decided against this node's authoritative window
                     (the device table row), including drained lease /
                     GLOBAL hits applied at the owner;
- ``lease``        — served from a locally-held lease slice
                     (service/leases.py try_consume), bounded by the
                     minted budget the owner attached to the grant;
- ``degraded``     — degraded-local fallback while the owner is
                     unreachable (availability over strictness; slack is
                     one window of `limit` per node by construction);
- ``reshard``      — admitted inside a reshard transfer window
                     (double-write / fresh-serve amnesty paths);
- ``global_cache`` — answered from the GLOBAL behavior's local cache
                     ahead of async reconciliation.

The test-only ``mint`` authority has **zero** declared slack: recording
through it manufactures budget from nowhere, which is exactly what the
deliberate-violation drill uses to prove the detector fires.

`GUBER_LEDGER=0` turns every observation site into a single attribute
test; the off path is bit-identical (differential-tested) because the
ledger only ever *reads* the staging/response columns.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from gubernator_tpu.obs import witness

LEDGER_SCHEMA_VERSION = 1

# Attribution taxonomy (docs/observability.md "## Decision ledger" pins
# it; renaming an authority is a schema_version bump, not a drift).
AUTHORITIES = ("owner", "lease", "degraded", "reshard", "global_cache")

# Deliberate-violation drill only: admits with no declared slack.
MINT_AUTHORITY = "mint"

# Authorities whose admissions are covered by a declared slack of one
# window of `limit` each (the documented worst case per subsystem:
# leases.py:29 / reshard.py amnesty / GLOBAL staleness bound).
_SLACK_AUTHORITIES = ("degraded", "reshard", "global_cache")

# log2 over-admission histogram: bucket i holds overshoots <= 2^i hits.
_NBUCKETS = 28

_AUTHORITY: contextvars.ContextVar = contextvars.ContextVar(
    "guber_ledger_authority", default="owner")


def ledger_enabled_default() -> bool:
    """GUBER_LEDGER escape hatch (Go ParseBool values; default on — the
    conservation meter is the always-on invariant check, opting OUT is
    the deliberate act)."""
    raw = os.environ.get("GUBER_LEDGER", "").strip().lower()
    if raw in ("0", "f", "false", "no", "off"):
        return False
    return True


@contextlib.contextmanager
def authority(name: str):
    """Scope every decision recorded inside to `name` — the serving path
    declares its source of authority (degraded-local wraps its engine
    apply, the reshard amnesty path wraps its local apply) and the
    engine hooks pick it up without any new plumbing through the call
    stack."""
    token = _AUTHORITY.set(name)
    try:
        yield
    finally:
        _AUTHORITY.reset(token)


def current_authority() -> str:
    return _AUTHORITY.get()


class _Bucket:
    """Per-key conservation state: the open window plus key-lifetime
    attribution totals (lifetime survives window rolls so the auditor
    can hold it against the device row's col-7 attempted counter)."""

    __slots__ = ("window", "limit", "admits", "attempted", "rejected",
                 "minted", "lifetime_attempted")

    def __init__(self):
        self.window = 0  # reset_time ms identifying the open window
        self.limit = 0
        self.admits: Dict[str, int] = {}
        self.attempted = 0
        self.rejected = 0
        self.minted = 0
        self.lifetime_attempted = 0


class DecisionLedger:
    """Per-node decision ledger + conservation auditor."""

    def __init__(self, enabled: Optional[bool] = None,
                 key_capacity: int = 8192, pending_cap: int = 4096,
                 audit_min_interval_s: float = 2.0,
                 emit: Optional[Callable] = None):
        self.enabled = (ledger_enabled_default()
                        if enabled is None else bool(enabled))
        self.key_capacity = int(key_capacity)
        self.pending_cap = int(pending_cap)
        self.audit_min_interval_s = float(audit_min_interval_s)
        # flight-recorder hook (Instance wires recorder.emit); None keeps
        # the ledger standalone in engine-only tests
        self._emit = emit
        # hot path: window column copies park here — leaf lock, O(1) hold
        self._pending_lock = witness.make_lock("ledger.pending")
        self._pending: List[tuple] = []
        # off-path state: key buckets, distribution, counters
        self._lock = witness.make_lock("ledger.buckets")
        self._buckets: Dict[str, _Bucket] = {}
        self._admits_total: Dict[str, int] = {}
        self._attempted_total = 0
        self._rejected_total = 0
        self._minted_total = 0
        self._windows_rolled = 0
        self._violations = 0
        self._overshoot_hits = 0
        self._max_overshoot = 0
        self._over_counts = [0] * _NBUCKETS
        self._over_n = 0
        self._overflow = 0  # key-capacity evictions declined
        self._pending_dropped = 0  # windows dropped at the ring cap
        self._unattributed = 0  # hits on slots the directory lost
        self._audits = 0
        self._last_audit = 0.0
        self._ground_truth = {"keys_checked": 0, "ledger_hits": 0,
                              "device_hits": 0, "breaches": 0}
        self._recent: List[dict] = []  # last few violation evaluations

    # ------------------------------------------------------------ hot path

    def note_slots(self, packed: np.ndarray, out: np.ndarray,
                   n0: int) -> None:
        """Park one dispatched window's attribution columns: slots+hits
        from the staged wide buffer, status/limit/reset from the response
        rows. O(1) per window — two small block copies and a list
        append; resolution and folding happen in audit()."""
        if not n0:
            return
        # two block copies: slot|hits are adjacent staging rows, the
        # response is one 4-row block — a handful of ns each, vs ~µs for
        # five per-row copies (the parking IS the hot-path cost)
        rec = (packed[:2, :n0].copy(), out[:4, :n0].copy(),
               _AUTHORITY.get())
        with self._pending_lock:
            if len(self._pending) >= self.pending_cap:
                self._pending_dropped += 1
                return
            self._pending.append(rec)

    def note_arrays(self, slots, hits, status, limit, reset) -> None:
        """Generic per-array entry (tests, non-engine batch recorders):
        builds the same (slots+hits, response-rows) record the engine
        block paths park."""
        n = len(slots)
        sh = np.empty((2, n), np.int64)
        sh[0] = slots
        sh[1] = hits
        resp = np.zeros((4, n), np.int64)
        resp[0] = status
        resp[1] = limit
        resp[3] = reset
        rec = (sh, resp, _AUTHORITY.get())
        with self._pending_lock:
            if len(self._pending) >= self.pending_cap:
                self._pending_dropped += 1
                return
            self._pending.append(rec)

    def stash_columns(self, packed: np.ndarray, n0: int):
        """Copy the slot/hits columns of a window whose readback is
        deferred (pipelined launch/collect, columnar submit/complete) —
        the staging buffer may be refilled before the collect runs, so
        the launch side parks copies and the collect side pairs them
        with the response rows via note_slots_deferred."""
        if not n0:
            return None
        return (packed[:2, :n0].copy(), _AUTHORITY.get())

    def note_slots_deferred(self, stash, rows: np.ndarray,
                            n0: int) -> None:
        if stash is None or not n0:
            return
        slots_hits, auth = stash
        rec = (slots_hits, rows[:4, :n0].copy(), auth)
        with self._pending_lock:
            if len(self._pending) >= self.pending_cap:
                self._pending_dropped += 1
                return
            self._pending.append(rec)

    # -------------------------------------------------- per-key recording

    def record_key(self, key: str, hits: int, status: int, limit: int,
                   reset: int, auth: Optional[str] = None) -> None:
        """Attribute one decision by key — the native lone-request path
        and every non-engine authority (lease consume, GLOBAL cache,
        degraded singles) record here directly."""
        if auth is None:
            auth = _AUTHORITY.get()
        with self._lock:
            self._record_locked(key, int(hits), int(status), int(limit),
                                int(reset), auth)

    def record_minted(self, key: str, budget: int) -> None:
        """A lease slice was installed for local consumption: `budget`
        hits of the owner's window are now legitimately spendable here.
        Grows the key's conservation bound for the open window."""
        if budget <= 0:
            return
        with self._lock:
            b = self._bucket_locked(key)
            if b is not None:
                b.minted += int(budget)
            self._minted_total += int(budget)

    # ------------------------------------------------------------ folding

    def _bucket_locked(self, key: str) -> Optional[_Bucket]:
        b = self._buckets.get(key)
        if b is None:
            if len(self._buckets) >= self.key_capacity:
                self._overflow += 1
                return None
            b = _Bucket()
            self._buckets[key] = b
        return b

    def _record_locked(self, key, hits, status, limit, reset, auth):
        b = self._bucket_locked(key)
        if b is None:
            return
        if reset and b.window and reset > b.window:
            self._roll_locked(key, b)
        if reset and not b.window:
            b.window = reset
        if limit:
            b.limit = limit
        b.attempted += hits
        b.lifetime_attempted += hits
        self._attempted_total += hits
        if status == 1:
            b.rejected += hits
            self._rejected_total += hits
        else:
            b.admits[auth] = b.admits.get(auth, 0) + hits
            self._admits_total[auth] = self._admits_total.get(auth, 0) + hits

    def _roll_locked(self, key: str, b: _Bucket) -> None:
        """Finalize one key-window: evaluate conservation, fold the
        overshoot into the distribution, and open a fresh window (the
        lifetime attempted counter survives)."""
        total_admits = sum(b.admits.values())
        if total_admits or b.attempted:
            bound = b.limit + b.minted
            # each exercised slack authority declares one window of
            # `limit` as its documented worst case; an authority that
            # admitted nothing this window contributes no slack
            slack = b.limit * sum(1 for a in _SLACK_AUTHORITIES
                                  if b.admits.get(a, 0))
            overshoot = max(0, total_admits - bound)
            self._windows_rolled += 1
            if overshoot:
                self._overshoot_hits += overshoot
                if overshoot > self._max_overshoot:
                    self._max_overshoot = overshoot
                idx = min(overshoot.bit_length(), _NBUCKETS - 1)
                self._over_counts[idx] += 1
                self._over_n += 1
            if overshoot > slack:
                self._violations += 1
                ev = {"key": key, "window": b.window, "limit": b.limit,
                      "admits": dict(b.admits), "minted": b.minted,
                      "overshoot": overshoot, "slack": slack}
                self._recent.append(ev)
                del self._recent[:-16]
                if self._emit is not None:
                    try:
                        self._emit("ledger.violation", key=key,
                                   overshoot=overshoot, slack=slack,
                                   limit=b.limit, minted=b.minted,
                                   authorities=",".join(sorted(b.admits)))
                    except Exception:  # noqa: BLE001 — audit never raises
                        pass
        b.window = 0
        b.admits = {}
        b.attempted = 0
        b.rejected = 0
        b.minted = 0

    # ------------------------------------------------------------ auditing

    def maybe_audit(self, engine=None, now_ms: Optional[int] = None) -> bool:
        """Rate-limited audit for tickers (the anomaly engine calls this
        every check): no-op inside the min interval."""
        now = time.monotonic()
        if now - self._last_audit < self.audit_min_interval_s:
            return False
        self.audit(engine, now_ms=now_ms)
        return True

    def audit(self, engine=None, now_ms: Optional[int] = None,
              force: bool = False) -> dict:
        """The off-serving-path conservation pass: drain the pending
        window ring, resolve slots to keys through the engine directory,
        fold into key buckets, roll every window the clock has closed
        (all of them under `force` — the scenario sweep wants the final
        open windows judged too), and hold a sample of keys against the
        device table's lifetime col-7 attempted counters as ground
        truth. Returns the audit report also served by endpoint_body."""
        self._last_audit = time.monotonic()
        if now_ms is None:
            now_ms = int(time.time() * 1000)
        with self._pending_lock:
            pending, self._pending = self._pending, []
        resolved: Dict[int, str] = {}
        if pending and engine is not None:
            want = set()
            for sh, _resp, _auth in pending:
                want.update(int(s) for s in sh[0].tolist())
            want.discard(-1)
            try:
                resolved = engine.resolve_slots(want)
            except Exception:  # noqa: BLE001 — audit never raises
                resolved = {}
        with self._lock:
            for sh, resp, auth in pending:
                sl = sh[0].tolist()
                hl = sh[1].tolist()
                stl = resp[0].tolist()
                ll = resp[1].tolist()
                rl = resp[3].tolist()
                for j, s in enumerate(sl):
                    if s < 0:
                        continue  # padding lane, not a lost key
                    key = resolved.get(int(s))
                    if key is None:
                        self._unattributed += hl[j]
                        continue
                    self._record_locked(key, hl[j], stl[j], ll[j],
                                        rl[j], auth)
            for key, b in list(self._buckets.items()):
                if b.window and (force or b.window <= now_ms):
                    self._roll_locked(key, b)
            self._audits += 1
            report = self._report_locked()
        if engine is not None:
            self._ground_truth_check(engine)
            with self._lock:
                report["ground_truth"] = dict(self._ground_truth)
        return report

    def _ground_truth_check(self, engine, sample: int = 64) -> None:
        """Hold the ledger's per-key lifetime attempted totals against
        the device rows' col-7 counters. The device counter is the
        durable on-accelerator truth for owner-resident keys; a key the
        ledger saw MORE attempts for than the device row did (and the
        row was never recycled: device >= ledger holds across expiry
        only one way) is attribution the serving path manufactured."""
        with self._lock:
            keys = [k for k, b in self._buckets.items()
                    if b.lifetime_attempted > 0][:sample]
            ledger_hits = {k: self._buckets[k].lifetime_attempted
                           for k in keys}
        if not keys:
            return
        try:
            device = engine.device_hit_counts(keys)
        except Exception:  # noqa: BLE001 — audit never raises
            return
        checked = lh = dh = breaches = 0
        for k in keys:
            if k not in device:
                continue  # not owner-resident here (leased/remote key)
            checked += 1
            lh += ledger_hits[k]
            dh += device[k]
            if ledger_hits[k] > device[k]:
                breaches += 1
        with self._lock:
            g = self._ground_truth
            g["keys_checked"] += checked
            g["ledger_hits"] += lh
            g["device_hits"] += dh
            g["breaches"] += breaches

    # ------------------------------------------------------------ surfaces

    def totals(self) -> dict:
        with self._lock:
            return {
                "admits": {a: self._admits_total.get(a, 0)
                           for a in AUTHORITIES},
                "admits_other": sum(
                    v for a, v in self._admits_total.items()
                    if a not in AUTHORITIES),
                "attempted": self._attempted_total,
                "rejected": self._rejected_total,
                "minted_budget": self._minted_total,
                "windows_rolled": self._windows_rolled,
                "violations": self._violations,
                "overshoot_hits": self._overshoot_hits,
                "max_overshoot": self._max_overshoot,
                "keys_tracked": len(self._buckets),
                "key_overflow": self._overflow,
                "pending_windows": len(self._pending),
                "pending_dropped": self._pending_dropped,
                "unattributed_hits": self._unattributed,
                "audits": self._audits,
            }

    def _overshoot_locked(self) -> dict:
        out = {"n": self._over_n, "total_hits": self._overshoot_hits,
               "max_hits": self._max_overshoot, "p50_hits": 0,
               "p99_hits": 0}
        if self._over_n:
            for q, field in ((0.50, "p50_hits"), (0.99, "p99_hits")):
                want = q * self._over_n
                seen = 0
                for i, c in enumerate(self._over_counts):
                    seen += c
                    if seen >= want:
                        out[field] = 1 << i
                        break
        return out

    def _report_locked(self) -> dict:
        return {
            "windows_rolled": self._windows_rolled,
            "violations": self._violations,
            "overshoot": self._overshoot_locked(),
            "recent_violations": list(self._recent),
        }

    def debug(self) -> dict:
        """The compact /v1/debug/vars section."""
        t = self.totals()
        with self._lock:
            over = self._overshoot_locked()
        return {
            "enabled": self.enabled,
            "authorities": list(AUTHORITIES),
            "admits": t["admits"],
            "attempted": t["attempted"],
            "rejected": t["rejected"],
            "minted_budget": t["minted_budget"],
            "windows_rolled": t["windows_rolled"],
            "violations": t["violations"],
            "overshoot": over,
            "keys_tracked": t["keys_tracked"],
            "pending_windows": t["pending_windows"],
            "audits": t["audits"],
        }

    def endpoint_body(self) -> dict:
        """The /v1/debug/ledger body (schema pinned by
        tests/test_debug_schema.py)."""
        t = self.totals()
        with self._lock:
            over = self._overshoot_locked()
            recent = list(self._recent)
            ground = dict(self._ground_truth)
        return {
            "schema_version": LEDGER_SCHEMA_VERSION,
            "enabled": self.enabled,
            "authorities": list(AUTHORITIES),
            "totals": t,
            "overshoot": over,
            "recent_violations": recent,
            "ground_truth": ground,
        }
