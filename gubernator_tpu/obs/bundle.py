"""Diagnostic bundles: one JSON artifact capturing a node's incident
state, and the cluster-federated debug view.

A bundle is everything an operator would otherwise collect by hand from
a sick node — /v1/debug/vars, recent traces, the flight-recorder tail,
a metrics snapshot, the config/env fingerprint, and the ring +
peer-circuit view — serialized while the state is still hot. Bundles are
written on demand (/v1/debug/bundle) or by the anomaly engine on a
rising edge (rate-limited, ``GUBER_BUNDLE_DIR``).

The federated view (/v1/debug/cluster) fans a Debug RPC out over the
existing peer ring, merges per-node health/vars/anomaly state, and
stitches cross-node spans by trace id into one causal timeline (span
timestamps are wall-clock ``time.time_ns()``, so ordering holds to
cluster clock sync).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import threading
import time
from typing import Dict, List, Optional

from gubernator_tpu.obs import witness
from gubernator_tpu.obs.introspect import debug_vars

log = logging.getLogger("gubernator_tpu.bundle")

BUNDLE_SCHEMA_VERSION = 1
# newest history samples appended to a bundle (~30 min at the 5 s tick)
HISTORY_TAIL_SAMPLES = 360
# env var names carrying credentials never leave the process in a bundle
_SECRET_PAT = re.compile(r"PASSWORD|SECRET|TOKEN|CREDENTIAL|PRIVATE",
                         re.IGNORECASE)
REDACTED = "**redacted**"


def env_fingerprint() -> Dict[str, str]:
    """Every GUBER_*/JAX_* var shaping this process, secrets redacted
    (GUBER_ETCD_PASSWORD, GUBER_MEMBERLIST_SECRET_KEYS,
    GUBER_CROSS_HOST_SECRET, and anything else matching the pattern)."""
    out: Dict[str, str] = {}
    for k in sorted(os.environ):
        if not (k.startswith("GUBER_") or k.startswith("JAX_")):
            continue
        out[k] = REDACTED if _SECRET_PAT.search(k) else os.environ[k]
    return out


def _health_dict(instance) -> dict:
    try:
        h = instance.health_check()
        return {"status": h.status, "message": h.message,
                "peer_count": h.peer_count}
    except Exception as e:  # noqa: BLE001 — a bundle beats a perfect bundle
        return {"error": str(e)}


def _circuit_view(instance) -> List[dict]:
    out = []
    all_peers = getattr(instance, "all_peer_clients", None)
    if callable(all_peers):
        for p in all_peers():
            c = getattr(p, "circuit", None)
            if c is None:
                continue
            out.append({"peer": p.info.address,
                        "state": c.state_name,
                        "opened_total": c.opened_total})
    return out


def node_report(instance, max_events: int = 512) -> dict:
    """The federation unit: what one node contributes to the cluster
    view (also the Debug RPC response body). A strict subset of the full
    bundle — no metrics text or env fingerprint crosses the wire."""
    report = {
        "schema_version": BUNDLE_SCHEMA_VERSION,
        "node": getattr(instance, "advertise_address", ""),
        "datacenter": getattr(instance, "data_center", ""),
        "captured_at": time.time(),
        "health": _health_dict(instance),
        "vars": debug_vars(instance),
        "circuits": _circuit_view(instance),
    }
    rec = getattr(instance, "recorder", None)
    if rec is not None:
        report["flight_recorder"] = rec.tail(max_events)
    an = getattr(instance, "anomaly", None)
    if an is not None:
        report["anomaly"] = an.debug()
    carto = getattr(instance, "keyspace", None)
    if carto is not None:
        try:
            report["keyspace"] = carto.report()
            report["capacity"] = carto.forecast()
        except Exception:  # noqa: BLE001 — cartography must not break
            pass           # the report
    prof = getattr(instance, "profiler", None)
    if prof is not None:
        try:
            # full endpoint body: phase/lock-site histograms, the live
            # decomposition, and the last deep-capture path — the bundle
            # link an operator follows to the trace artifact
            report["profile"] = prof.endpoint_body()
        except Exception:  # noqa: BLE001 — profiling must not break
            pass           # the report
    led = getattr(instance, "ledger", None)
    if led is not None and getattr(led, "enabled", False):
        try:
            # full endpoint body: per-authority totals, the over-admission
            # distribution, and the recent-violation ring — with the
            # flight-recorder tail above, the causal spine of an
            # over_admission anomaly rides in one artifact
            report["ledger"] = led.endpoint_body()
        except Exception:  # noqa: BLE001 — the audit must not break
            pass           # the report
    tracer = getattr(instance, "tracer", None)
    if tracer is not None:
        report["traces"] = tracer.traces()
    return report


def build_bundle(instance, reason: str = "on-demand",
                 metrics=None) -> dict:
    """The full single-node artifact: node_report plus the process
    fingerprint and a metrics-exposition snapshot."""
    bundle = node_report(instance, max_events=0)  # full recorder tail
    bundle["kind"] = "gubernator-debug-bundle"
    bundle["reason"] = reason
    bundle["env"] = env_fingerprint()
    # the metrics-history tail: the run-up to the incident, not just the
    # instant (obs/history.py; ~30 min at the default 5 s tick)
    hist = getattr(instance, "history", None)
    if hist is not None and hist.enabled:
        bundle["history"] = hist.tail(HISTORY_TAIL_SAMPLES)
    conf = getattr(instance, "conf", None)
    if conf is not None and getattr(conf, "behaviors", None) is not None:
        try:
            bundle["behaviors"] = dataclasses.asdict(conf.behaviors)
        except Exception:  # noqa: BLE001
            bundle["behaviors"] = repr(conf.behaviors)
    m = metrics or (getattr(conf, "metrics", None) if conf else None)
    if m is not None:
        try:
            bundle["metrics_text"] = m.render(instance).decode()
        except Exception as e:  # noqa: BLE001
            bundle["metrics_text"] = f"render failed: {e}"
    return bundle


class BundleWriter:
    """Rate-limited, keep-N bundle sink under GUBER_BUNDLE_DIR.

    Anomaly-triggered captures go through `write_for`, which drops
    writes inside `min_interval_s` of the last (an incident storm must
    not turn the recorder into a disk-filling anomaly of its own) and
    prunes the directory to the newest `keep` bundles."""

    def __init__(self, directory: str, min_interval_s: float = 60.0,
                 keep: int = 20):
        self.directory = directory
        self.min_interval_s = float(min_interval_s)
        self.keep = int(keep)
        self._lock = witness.make_lock("bundle.limiter")
        self._last_write = 0.0
        self.stats = {"written": 0, "suppressed": 0, "errors": 0}

    def write_for(self, instance, reason: str,
                  metrics=None) -> Optional[str]:
        """Capture + write, rate-limited; returns the path or None."""
        now = time.monotonic()
        with self._lock:
            if self._last_write and now - self._last_write \
                    < self.min_interval_s:
                self.stats["suppressed"] += 1
                return None
            self._last_write = now
        try:
            return self.write(build_bundle(instance, reason=reason,
                                           metrics=metrics))
        except Exception:  # noqa: BLE001 — capture must not break serving
            self.stats["errors"] += 1
            log.exception("bundle write failed")
            return None

    def write(self, bundle: dict) -> str:
        os.makedirs(self.directory, exist_ok=True)
        reason = re.sub(r"[^A-Za-z0-9_.-]+", "-",
                        str(bundle.get("reason", "bundle")))[:48]
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        path = os.path.join(
            self.directory,
            f"bundle-{stamp}-{os.getpid()}-{reason}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(bundle, f, separators=(",", ":"), default=str)
        os.replace(tmp, path)
        self.stats["written"] += 1
        self._prune()
        log.warning("diagnostic bundle written: %s (reason=%s)", path,
                    bundle.get("reason"))
        return path

    def _prune(self) -> None:
        try:
            names = sorted(n for n in os.listdir(self.directory)
                           if n.startswith("bundle-")
                           and n.endswith(".json"))
            for n in names[:-self.keep] if self.keep > 0 else []:
                os.unlink(os.path.join(self.directory, n))
        except OSError:
            pass

    def debug(self) -> dict:
        return {"dir": self.directory, "keep": self.keep,
                "min_interval_s": self.min_interval_s, **self.stats}


# ------------------------------------------------------------ federation

def cluster_view(instance, timeout_s: float = 5.0,
                 max_traces: int = 20) -> dict:
    """Fan a Debug RPC out over the peer ring and merge.

    Every local-region + cross-region member answers with its
    node_report; this node contributes its own without a hop. Per-node
    failures degrade to an `errors` entry — a federated view that dies
    with its sickest member would be useless exactly when needed."""
    from gubernator_tpu.service.grpc_api import dial_v1

    self_addr = getattr(instance, "advertise_address", "")
    addresses = [self_addr] if self_addr else []
    all_peers = getattr(instance, "all_peer_clients", None)
    if callable(all_peers):
        for p in all_peers():
            if p.info.address not in addresses:
                addresses.append(p.info.address)

    nodes: Dict[str, dict] = {}
    errors: Dict[str, str] = {}
    for addr in addresses:
        if addr == self_addr:
            nodes[addr] = node_report(instance)
            continue
        try:
            raw = dial_v1(addr).Debug(b"", timeout=timeout_s)
            nodes[addr] = json.loads(raw.decode("utf-8"))
        except Exception as e:  # noqa: BLE001 — degrade per node
            errors[addr] = str(e)

    # merge: which detectors are firing where, and one stitched timeline
    # per trace id across every node that recorded spans for it
    anomalies: Dict[str, List[str]] = {}
    unhealthy: Dict[str, str] = {}
    spans_by_tid: Dict[str, List[dict]] = {}
    for addr, rep in nodes.items():
        for d in (rep.get("anomaly") or {}).get("active", []):
            anomalies.setdefault(d, []).append(addr)
        health = rep.get("health") or {}
        if health.get("status") not in ("healthy", None):
            unhealthy[addr] = health.get("message", "")
        for tid, spans in (rep.get("traces") or {}).items():
            bucket = spans_by_tid.setdefault(tid, [])
            for s in spans:
                bucket.append({**s, "node": addr})

    # capacity & keyspace roll-up: per-peer ownership share vs the ideal
    # 1/N, a cross-node heavy-hitter merge, and the fleet's tightest
    # headroom projection — the skew/headroom view the ROADMAP's
    # resharding and tiering decisions read
    key_counts: Dict[str, int] = {}
    merged_top: List[dict] = []
    capacities: Dict[str, dict] = {}
    for addr, rep in nodes.items():
        ks = rep.get("keyspace") or {}
        occ = ks.get("occupancy") or {}
        if occ.get("key_count") is not None:
            key_counts[addr] = int(occ["key_count"])
        for e in ks.get("top_keys") or []:
            merged_top.append({**e, "node": addr})
        fc = rep.get("capacity") or {}
        if fc:
            capacities[addr] = {k: fc.get(k) for k in (
                "projectable", "key_count", "capacity", "fill_fraction",
                "growth_keys_per_s", "time_to_full_s",
                "time_to_pressure_s")}
    total_keys = sum(key_counts.values())
    ring_balance: dict = {}
    if total_keys > 0 and key_counts:
        ideal = 1.0 / len(key_counts)
        shares = {a: c / total_keys for a, c in key_counts.items()}
        ring_balance = {
            "ideal_share": round(ideal, 6),
            "shares": {a: round(s, 6) for a, s in shares.items()},
            "skew": {a: round(s / ideal, 3) for a, s in shares.items()},
            "max_skew": round(max(shares.values()) / ideal, 3),
        }
    merged_top.sort(key=lambda e: e.get("hits", 0), reverse=True)
    ttfs = [c["time_to_full_s"] for c in capacities.values()
            if c.get("time_to_full_s") is not None]
    keyspace_roll = {
        "total_keys": total_keys,
        "node_key_counts": key_counts,
        "ring_balance": ring_balance,
        "top_keys": merged_top[:20],
    }
    capacity_roll = {
        "min_time_to_full_s": min(ttfs) if ttfs else None,
        "nodes": capacities,
    }

    # handoff roll-up: every in-flight transfer across the ring, both
    # sides merged per transfer id — the mid-deploy "where are my keys"
    # view (docs/OPERATIONS.md "Deploys & resharding")
    handoffs: Dict[str, dict] = {}
    reshard_enabled: List[str] = []
    for addr, rep in nodes.items():
        rs = (rep.get("vars") or {}).get("reshard") or {}
        if rs.get("enabled"):
            reshard_enabled.append(addr)
        for sess in rs.get("sessions") or []:
            xfer = sess.get("xfer", "?")
            entry = handoffs.setdefault(xfer, {"xfer": xfer})
            entry[sess.get("role", "?")] = {**sess, "node": addr}
    reshard_roll = {
        "enabled_nodes": sorted(reshard_enabled),
        "in_flight": sorted(handoffs.values(),
                            key=lambda e: e.get("xfer", "")),
    }

    # conservation roll-up: the cluster-wide budget ledger — per-node
    # violation/overshoot totals plus a fleet admit-by-authority sum.
    # A violation anywhere is a cluster-level "minted budget" sighting,
    # so the roll leads with the total and the guilty nodes.
    ledger_nodes: Dict[str, dict] = {}
    fleet_admits: Dict[str, int] = {}
    fleet_violations = 0
    fleet_overshoot = 0
    for addr, rep in nodes.items():
        lg = rep.get("ledger") or {}
        t = lg.get("totals") or {}
        if not lg.get("enabled"):
            continue
        ledger_nodes[addr] = {
            "violations": int(t.get("violations", 0)),
            "overshoot_hits": int(t.get("overshoot_hits", 0)),
            "max_overshoot": int(t.get("max_overshoot", 0)),
            "minted_budget": int(t.get("minted_budget", 0)),
            "windows_rolled": int(t.get("windows_rolled", 0)),
        }
        fleet_violations += ledger_nodes[addr]["violations"]
        fleet_overshoot += ledger_nodes[addr]["overshoot_hits"]
        for a, n in (t.get("admits") or {}).items():
            fleet_admits[a] = fleet_admits.get(a, 0) + int(n)
    ledger_roll = {
        "enabled_nodes": sorted(ledger_nodes),
        "violations": fleet_violations,
        "overshoot_hits": fleet_overshoot,
        "admits_by_authority": fleet_admits,
        "nodes": ledger_nodes,
        "violating_nodes": sorted(
            a for a, e in ledger_nodes.items() if e["violations"]),
    }

    # profiling roll-up: every node's serial-phase shares side by side —
    # a node whose decomposition diverges from the fleet's is the one to
    # pull a /v1/debug/profile?capture=1 trace from
    node_shares: Dict[str, dict] = {}
    for addr, rep in nodes.items():
        dec = (rep.get("profile") or {}).get("decomposition") or {}
        shares = {p: d["share"] for p, d in dec.items()
                  if isinstance(d, dict) and d.get("share") is not None}
        if shares:
            node_shares[addr] = shares
    hottest = ""
    if node_shares:
        phase_means: Dict[str, float] = {}
        for shares in node_shares.values():
            for p, s in shares.items():
                if p != "queue_wait":  # residency ratio, not a share
                    phase_means[p] = phase_means.get(p, 0.0) + s
        if phase_means:
            hottest = max(phase_means, key=phase_means.get)
    profile_roll = {"node_shares": node_shares, "hottest_phase": hottest}

    recent = sorted(
        spans_by_tid,
        key=lambda tid: max(s["start_ns"] for s in spans_by_tid[tid]),
        reverse=True)[:max_traces]
    stitched = {
        tid: sorted(spans_by_tid[tid], key=lambda s: s["start_ns"])
        for tid in recent
    }
    cross_node = {tid for tid, spans in stitched.items()
                  if len({s["node"] for s in spans}) > 1}

    return {
        "schema_version": BUNDLE_SCHEMA_VERSION,
        "captured_at": time.time(),
        "coordinator": self_addr,
        "member_count": len(addresses),
        "nodes": nodes,
        "errors": errors,
        "anomalies": anomalies,
        "unhealthy": unhealthy,
        "keyspace": keyspace_roll,
        "capacity": capacity_roll,
        "reshard": reshard_roll,
        "ledger": ledger_roll,
        "profile": profile_roll,
        "stitched_traces": stitched,
        "cross_node_traces": sorted(cross_node),
    }
