"""gubernator_tpu — a TPU-native distributed rate-limiting framework.

A from-scratch rebuild of the capabilities of mailgun/gubernator (reference:
/root/reference, see SURVEY.md) designed TPU-first:

- Rate-limit state for millions of keys lives in dense HBM column arrays
  (struct-of-arrays) instead of a per-key heap LRU (reference: cache.go).
- The token/leaky bucket state machines (reference: algorithms.go:24-336)
  collapse into one batched, branchless, masked decision kernel applied per
  batch window (ops/decide.py), optionally as a fused Pallas kernel.
- Key-ownership sharding (reference: hash.go, replicated_hash.go) becomes a
  sharded device mesh axis; GLOBAL/multi-region hit aggregation (reference:
  global.go, multiregion.go) becomes a windowed psum over the mesh
  (parallel/).
- The host tier (gRPC/HTTP serving, batching window, membership) mirrors the
  reference's split between serving and state mutation (service/).

Timestamps and counters are int64 milliseconds, so 64-bit mode is enabled at
import (TPU emulates int64 with int32 pairs; the decision kernel is
bandwidth-bound, not ALU-bound, so this is acceptable and keeps exact parity
with the reference's int64 wire types).
"""

import os as _os

import jax as _jax

# guberlint: disable=knob-drift -- import-time switch: runs before envconf exists, dev/test only (x64 off breaks the i64 lane contract)
if not _os.environ.get("GUBER_TPU_NO_X64"):
    _jax.config.update("jax_enable_x64", True)

from gubernator_tpu.types import (  # noqa: E402
    Algorithm,
    Behavior,
    RateLimitReq,
    RateLimitResp,
    Status,
    has_behavior,
    hash_key,
)

__version__ = "0.1.0"

__all__ = [
    "Algorithm",
    "Behavior",
    "RateLimitReq",
    "RateLimitResp",
    "Status",
    "has_behavior",
    "hash_key",
    "__version__",
]
