"""FNV-1 / FNV-1a 64-bit hashes (pure Python, C++ fast path in native/).

The reference picks peers with fnv1/fnv1a 64-bit (reference:
replicated_hash.go:24,31, cmd/gubernator/config.go:144-162). We use the same
family for deterministic key -> shard ownership so a key's owner is stable
across hosts and restarts.
"""

from __future__ import annotations

_OFFSET = 14695981039346656037
_PRIME = 1099511628211
_MASK = (1 << 64) - 1


def fnv1_64(data: bytes) -> int:
    h = _OFFSET
    for b in data:
        h = ((h * _PRIME) & _MASK) ^ b
    return h


def fnv1a_64(data: bytes) -> int:
    h = _OFFSET
    for b in data:
        h = ((h ^ b) * _PRIME) & _MASK
    return h


def fnv1a_64_str(s: str) -> int:
    return fnv1a_64(s.encode("utf-8"))
