"""Backend capability probes.

Buffer donation lets the decision kernel update the key table in place
(~56 B/key saved per window at 10M keys), but not every PJRT backend
supports it — notably CPU and tunneled single-chip TPU backends
(jax 'axon') reject donated buffers at dispatch. Probe once with a
throwaway array instead of hardcoding a platform list.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.lru_cache(maxsize=None)
def donation_supported() -> bool:
    try:
        f = jax.jit(lambda x: x + 1, donate_argnums=0)
        y = f(jnp.zeros((8,), jnp.int64))
        y.block_until_ready()
        return True
    except Exception:
        return False
