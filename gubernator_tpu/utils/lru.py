"""Host-side LRU cache with TTL + invalidation semantics.

The device key table (models/keyspace.py) is the authoritative state store in
this framework; this host LRU fills the remaining roles the reference's cache
plays (reference: cache.go:32-220):

- the non-owner local cache of GLOBAL rate-limit statuses
  (reference: gubernator.go:226-264);
- the `Cache` SPI surface for embedders;
- hit/miss/size stats for metrics.

Semantics mirrored from the reference: expiry-on-read (an expired item is a
miss and is dropped), `invalid_at` soft invalidation, `update_expiration`,
capacity eviction of the least-recently-used entry, and iteration for
Loader.save snapshots. Default capacity 50k (reference: cache.go:82-84).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Any, Iterator, Optional

from gubernator_tpu.obs import witness
from gubernator_tpu.utils.interval import millisecond_now


@dataclasses.dataclass
class CacheItem:
    key: str = ""
    value: Any = None
    # unix ms when the item is dead and reads treat it as missing
    expire_at: int = 0
    # unix ms after which the item is *suspect* (used by async updates);
    # 0 disables (reference: cache.go:69-76)
    invalid_at: int = 0
    algorithm: int = 0


class LRUCache:
    """Thread-safe LRU with TTL. Callers may also use .lock for multi-op
    critical sections (the reference exposes Lock/Unlock on the interface,
    cache.go:41-42)."""

    def __init__(self, max_size: int = 50_000):
        self._max = max_size if max_size > 0 else 50_000
        self._od: "OrderedDict[str, CacheItem]" = OrderedDict()
        self.lock = witness.make_rlock("lru.cache")
        # stats for metrics exposition (reference: cache.go:45-51)
        self.stat_hit = 0
        self.stat_miss = 0
        self.stat_unexpired_evictions = 0

    def __len__(self) -> int:
        with self.lock:
            return len(self._od)

    def add(self, item: CacheItem) -> bool:
        """Insert/replace; returns True if the key already existed
        (reference: cache.go:117-133)."""
        with self.lock:
            existed = item.key in self._od
            self._od[item.key] = item
            self._od.move_to_end(item.key)
            if len(self._od) > self._max:
                _, old = self._od.popitem(last=False)
                if old.expire_at == 0 or old.expire_at > millisecond_now():
                    self.stat_unexpired_evictions += 1
            return existed

    def get_item(self, key: str) -> Optional[CacheItem]:
        """Expiry-on-read lookup (reference: cache.go:140-165)."""
        with self.lock:
            item = self._od.get(key)
            if item is None:
                self.stat_miss += 1
                return None
            now = millisecond_now()
            if item.invalid_at != 0 and item.invalid_at < now:
                self._od.pop(key, None)
                self.stat_miss += 1
                return None
            if item.expire_at != 0 and item.expire_at < now:
                self._od.pop(key, None)
                self.stat_miss += 1
                return None
            self.stat_hit += 1
            self._od.move_to_end(key)
            return item

    def peek(self, key: str) -> Optional[CacheItem]:
        """Lookup without recency/stat effects."""
        with self.lock:
            return self._od.get(key)

    def remove(self, key: str) -> None:
        with self.lock:
            self._od.pop(key, None)

    def update_expiration(self, key: str, expire_at: int) -> bool:
        """(reference: cache.go:96-102 UpdateExpiration)"""
        with self.lock:
            item = self._od.get(key)
            if item is None:
                return False
            item.expire_at = expire_at
            return True

    def each(self) -> Iterator[CacheItem]:
        """Snapshot iteration (reference: cache.go Each) — used by
        Loader.save at shutdown."""
        with self.lock:
            items = list(self._od.values())
        return iter(items)

    def size(self) -> int:
        return len(self)
