"""JSON-(un)marshalable log level wrapper.

Parity with the reference's `logging` package (logging/logging.go:25-55),
which wraps a logrus level so embedding services can carry it in JSON
config. Here the same contract over Python's stdlib logging: marshals to
the level *name*, unmarshals from either a name or a numeric level, and
accepts the reference's logrus names (panic/fatal/error/warning/info/
debug/trace) as well as Python's.
"""

from __future__ import annotations

import json
import logging

# logrus names → stdlib levels (logrus: panic=0..trace=6; stdlib has no
# panic/trace, so they clamp to the nearest severity)
_LOGRUS_TO_STD = {
    "panic": logging.CRITICAL,
    "fatal": logging.CRITICAL,
    "error": logging.ERROR,
    "warning": logging.WARNING,
    "warn": logging.WARNING,
    "info": logging.INFO,
    "debug": logging.DEBUG,
    "trace": logging.DEBUG,
}
_STD_TO_NAME = {
    logging.CRITICAL: "fatal",
    logging.ERROR: "error",
    logging.WARNING: "warning",
    logging.INFO: "info",
    logging.DEBUG: "debug",
}


class LogLevelJSON:
    """A log level that round-trips through JSON as its name
    (reference: logging/logging.go:25-55)."""

    def __init__(self, level: int = logging.INFO):
        self.level = int(level)

    def __str__(self) -> str:
        return _STD_TO_NAME.get(self.level, str(self.level))

    def __repr__(self) -> str:
        return f"LogLevelJSON({self})"

    def __eq__(self, other) -> bool:
        if isinstance(other, LogLevelJSON):
            return self.level == other.level
        if isinstance(other, int):
            return self.level == other
        return NotImplemented

    def __hash__(self) -> int:
        return hash(self.level)

    def marshal_json(self) -> str:
        # unnamed levels (NOTSET, addLevelName customs) marshal as the bare
        # number so unmarshal_json can always read marshal_json's output
        name = _STD_TO_NAME.get(self.level)
        return json.dumps(name if name is not None else self.level)

    @classmethod
    def unmarshal_json(cls, data: str) -> "LogLevelJSON":
        """Accept a quoted level name or a bare number
        (reference: logging/logging.go:34-50)."""
        v = json.loads(data)
        if isinstance(v, (int, float)):
            return cls(int(v))
        if isinstance(v, str):
            return cls(parse_level(v))
        raise ValueError("invalid log level")


def parse_level(name: str) -> int:
    """Level name → stdlib level; knows both logrus and Python names."""
    low = name.strip().lower()
    if low in _LOGRUS_TO_STD:
        return _LOGRUS_TO_STD[low]
    std = logging.getLevelName(name.strip().upper())
    if isinstance(std, int):
        return std
    raise ValueError(f"not a valid log level: {name!r}")
