from gubernator_tpu.utils.gregorian import (
    GREGORIAN_DAYS,
    GREGORIAN_HOURS,
    GREGORIAN_MINUTES,
    GREGORIAN_MONTHS,
    GREGORIAN_WEEKS,
    GREGORIAN_YEARS,
    GregorianError,
    gregorian_duration,
    gregorian_expiration,
)
from gubernator_tpu.utils.interval import Interval, millisecond_now

__all__ = [
    "GREGORIAN_MINUTES",
    "GREGORIAN_HOURS",
    "GREGORIAN_DAYS",
    "GREGORIAN_WEEKS",
    "GREGORIAN_MONTHS",
    "GREGORIAN_YEARS",
    "GregorianError",
    "gregorian_duration",
    "gregorian_expiration",
    "Interval",
    "millisecond_now",
]
