"""Re-armable one-shot interval timer.

All batching loops in the framework (request micro-batching, GLOBAL sync
windows, broadcast windows) share this primitive: arm it when the first item
enters an empty queue, flush when it fires or when the batch cap is reached
(reference: interval.go:26-69 and its use at peer_client.go:243-283,
global.go:73-112).

Unlike a periodic ticker, `next()` schedules exactly one tick `interval`
seconds later; nothing fires unless armed.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

from gubernator_tpu.obs import witness


def millisecond_now() -> int:
    """Unix time in milliseconds (reference: client.go:62-65)."""
    return time.time_ns() // 1_000_000


class Interval:
    def __init__(self, interval_s: float):
        self._interval = interval_s
        self._timer: Optional[threading.Timer] = None
        self._lock = witness.make_lock("interval.timer")
        #: fires () when an armed tick elapses; consume with `.get()`
        self.c: "queue.Queue[bool]" = queue.Queue()
        self._closed = False

    def next(self) -> None:
        """Arm one tick `interval` seconds from now, replacing any armed tick."""
        with self._lock:
            if self._closed:
                return
            if self._timer is not None:
                self._timer.cancel()
            self._timer = threading.Timer(self._interval, self._fire)
            self._timer.daemon = True
            self._timer.start()

    def _fire(self) -> None:
        self.c.put(True)

    def stop(self) -> None:
        with self._lock:
            self._closed = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
