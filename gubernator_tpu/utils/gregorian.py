"""Gregorian calendar bucket math.

When a request sets Behavior.DURATION_IS_GREGORIAN, the `duration` field is a
calendar-interval code and buckets reset at the end of the current calendar
interval (reference: interval.go:71-145, proto/gubernator.proto:99-119).

The kernel needs two host-precomputed numbers per gregorian request:
- the *expiration*: unix-ms of the last millisecond of the current interval;
- the *full interval duration* in ms (used as the leaky-bucket drain window).

Deviation from the reference (documented in PARITY.md): the reference's
month/year `GregorianDuration` has an operator-precedence bug
(`end.UnixNano() - begin.UnixNano()/1000000`, interval.go:94-102) returning
nanosecond-scale garbage; we return the correct millisecond span.
Weeks are unimplemented in the reference (interval.go:89-90); we implement
them (ISO weeks ending Sunday 23:59:59.999) rather than erroring.
"""

from __future__ import annotations

import datetime as _dt

GREGORIAN_MINUTES = 0
GREGORIAN_HOURS = 1
GREGORIAN_DAYS = 2
GREGORIAN_WEEKS = 3
GREGORIAN_MONTHS = 4
GREGORIAN_YEARS = 5

_MS_MINUTE = 60_000
_MS_HOUR = 3_600_000
_MS_DAY = 86_400_000
_MS_WEEK = 7 * _MS_DAY


class GregorianError(ValueError):
    """Raised when `duration` is not a valid gregorian interval code."""


def _to_ms(dt: _dt.datetime) -> int:
    return int(dt.timestamp() * 1000)


def _next_boundary(now: _dt.datetime, code: int) -> _dt.datetime:
    """Start of the next calendar interval after `now` (local time)."""
    if code == GREGORIAN_MINUTES:
        base = now.replace(second=0, microsecond=0)
        return base + _dt.timedelta(minutes=1)
    if code == GREGORIAN_HOURS:
        base = now.replace(minute=0, second=0, microsecond=0)
        return base + _dt.timedelta(hours=1)
    if code == GREGORIAN_DAYS:
        base = now.replace(hour=0, minute=0, second=0, microsecond=0)
        return base + _dt.timedelta(days=1)
    if code == GREGORIAN_WEEKS:
        base = now.replace(hour=0, minute=0, second=0, microsecond=0)
        return base + _dt.timedelta(days=7 - now.weekday())
    if code == GREGORIAN_MONTHS:
        if now.month == 12:
            return now.replace(
                year=now.year + 1, month=1, day=1, hour=0, minute=0, second=0, microsecond=0
            )
        return now.replace(month=now.month + 1, day=1, hour=0, minute=0, second=0, microsecond=0)
    if code == GREGORIAN_YEARS:
        return now.replace(
            year=now.year + 1, month=1, day=1, hour=0, minute=0, second=0, microsecond=0
        )
    raise GregorianError(
        "behavior DURATION_IS_GREGORIAN is set; but `duration` is not a valid gregorian interval"
    )


def _start_boundary(now: _dt.datetime, code: int) -> _dt.datetime:
    """Start of the current calendar interval containing `now`."""
    if code == GREGORIAN_MINUTES:
        return now.replace(second=0, microsecond=0)
    if code == GREGORIAN_HOURS:
        return now.replace(minute=0, second=0, microsecond=0)
    if code == GREGORIAN_DAYS:
        return now.replace(hour=0, minute=0, second=0, microsecond=0)
    if code == GREGORIAN_WEEKS:
        base = now.replace(hour=0, minute=0, second=0, microsecond=0)
        return base - _dt.timedelta(days=now.weekday())
    if code == GREGORIAN_MONTHS:
        return now.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    if code == GREGORIAN_YEARS:
        return now.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
    raise GregorianError(
        "behavior DURATION_IS_GREGORIAN is set; but `duration` is not a valid gregorian interval"
    )


def gregorian_expiration(now: _dt.datetime, code: int) -> int:
    """Unix-ms of the final millisecond of the current interval.

    Matches the reference convention of "end of interval minus epsilon"
    (reference: interval.go:114-145): e.g. for minutes at 11:20:10 the
    expiry is 11:20:59.999.
    """
    return _to_ms(_next_boundary(now, code)) - 1


def gregorian_duration(now: _dt.datetime, code: int) -> int:
    """Full span of the current calendar interval, in ms.

    Fixed-width for minute/hour/day/week; month/year depend on the calendar
    (reference: interval.go:81-106, with the precedence bug corrected).
    """
    if code == GREGORIAN_MINUTES:
        return _MS_MINUTE
    if code == GREGORIAN_HOURS:
        return _MS_HOUR
    if code == GREGORIAN_DAYS:
        return _MS_DAY
    if code == GREGORIAN_WEEKS:
        return _MS_WEEK
    if code in (GREGORIAN_MONTHS, GREGORIAN_YEARS):
        return _to_ms(_next_boundary(now, code)) - _to_ms(_start_boundary(now, code))
    raise GregorianError(
        "behavior DURATION_IS_GREGORIAN is set; but `duration` is not a valid gregorian interval"
    )
