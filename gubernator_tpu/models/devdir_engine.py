"""Engine variant with the DEVICE-resident key directory (GUBER_DEVICE_DIRECTORY).

The standard Engine resolves key strings to table slots in the host C++
directory before every window — the host-side cost at multi-M
decisions/s. This engine ships only an 8-byte fingerprint per request and
lets the chip resolve (or claim, or LRU-evict) the slot inside the SAME
compiled program that decides the window (ops/devdir.py
probe_assign_evict -> ops/decide.py decide_packed): zero host round trips
per key, which matters when host CPU — not the device — is the serving
bottleneck (DESIGN.md "Device-resident key lookup").

Semantics: responses are bit-identical to the host-directory Engine
(differential-fuzzed, tests/test_devdir_engine.py) with two documented
deviations: eviction is aged (least-recently-used among a key's
PROBE_DEPTH candidates) rather than a global LRU, and two distinct keys
with equal 63-bit fingerprints (~2^-63/pair) alias to one bucket.
In-batch claim conflicts between distinct keys retry in a follow-up
window (bounded; then an error response, never a wrong slot).

Not supported (the device keeps no key strings): Store/Loader hooks and
snapshots — a daemon configured with both fails at boot, honestly.
"""

from __future__ import annotations

import functools as _functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.models.engine import Engine, _bucket_width
from gubernator_tpu.ops.decide import I64, decide_packed
from gubernator_tpu.ops.devdir import (
    key_fingerprint,
    make_fingerprints,
    make_touch,
    probe_assign_evict,
    refresh_vacancies,
)
from gubernator_tpu.types import RateLimitResp

_SWEEP_EVERY = 256  # rounds between fingerprint vacancy sweeps (hygiene)


def _devdir_decide(fps, touch, state, packed, hashes, now_ms, seq):
    """Fused probe + decide: one dispatch, the slot never leaves HBM.
    `seq` is the per-dispatch eviction epoch (ops/devdir.py)."""
    fps, touch, slot, fresh, retry = probe_assign_evict(
        fps, touch, hashes, seq)
    packed = packed.at[0, :].set(slot.astype(I64))
    packed = packed.at[8, :].set(fresh.astype(I64))
    state, out = decide_packed(state, packed, now_ms)
    return fps, touch, state, out, retry


@_functools.lru_cache(maxsize=None)
def _jit_devdir_decide(donate: bool):
    return jax.jit(
        _devdir_decide, donate_argnums=(0, 1, 2) if donate else ())


@_functools.lru_cache(maxsize=None)
def _jit_refresh(donate: bool):
    return jax.jit(
        refresh_vacancies, donate_argnums=(0,) if donate else ())


class DevDirEngine(Engine):
    """Engine with the on-device key directory (see module docstring)."""

    PROBE_RETRIES = 3

    def __init__(self, capacity: int = 1 << 20, min_width: int = 64,
                 max_width: int = 8192, donate: Optional[bool] = None,
                 **kw):
        if kw.get("store") is not None or kw.get("loader") is not None:
            raise ValueError(
                "GUBER_DEVICE_DIRECTORY keeps no key strings on the host: "
                "Store/Loader persistence needs the host directory")
        kw.pop("store", None)
        kw.pop("loader", None)
        super().__init__(capacity=capacity, min_width=min_width,
                         max_width=max_width, donate=donate, **kw)
        # the host directory is unused; the python pipeline feeds windows
        self._prep_fast = None
        self.fps = make_fingerprints(capacity)
        self.touch = make_touch(capacity)
        if donate is None:
            from gubernator_tpu.utils.platform import donation_supported

            donate = donation_supported()
        self._devdir_step = _jit_devdir_decide(donate)
        self._refresh = _jit_refresh(donate)
        self._rounds_since_sweep = 0
        self._probe_seq = 0  # per-dispatch eviction epoch (starts > 0)
        try:  # C fingerprint batch; python twin otherwise
            from gubernator_tpu import native

            native.load_library()
            self._fingerprints = native.fingerprint_batch
        except Exception:  # noqa: BLE001
            self._fingerprints = lambda keys: np.fromiter(
                (key_fingerprint(k) for k in keys), np.int64,
                count=len(keys))

    def key_count(self) -> int:
        """Occupied device-directory slots (nonzero fingerprints). One
        device reduction — scrape-path only, never the serving path."""
        with self._lock:
            return int(jnp.count_nonzero(self.fps))

    # directory-dependent surfaces are honestly unsupported
    def snapshot(self, include_expired: bool = False):
        raise RuntimeError(
            "DevDirEngine keeps no key strings; snapshots need the host "
            "directory engine")

    def supports_columnar(self) -> bool:
        return False

    def warmup(self) -> None:
        """Compile the fused probe+decide program per width bucket."""
        widths = []
        w = self.min_width
        while w < self.max_width:
            widths.append(w)
            w *= 2
        widths.append(self.max_width)
        resp = None
        with self._lock:
            for width in widths:
                packed = np.zeros((9, width), np.int64)
                hashes = np.zeros(width, np.int64)
                self._probe_seq += 1
                self.fps, self.touch, self.state, resp, _ = \
                    self._devdir_step(self.fps, self.touch, self.state,
                                      packed, hashes, 0,
                                      self._probe_seq)
            if resp is not None:
                jax.block_until_ready(resp)

    # ------------------------------------------------------------- internals

    def _split_scannable(self, windows):
        # scan coalescing presumes host-resolved slots; every window rides
        # the fused per-round program here
        return windows, []

    def load_snapshot(self, items) -> int:
        items = list(items)
        if items:
            raise RuntimeError(
                "DevDirEngine cannot seed from snapshots (host directory "
                "unused); start it empty or use the host-directory engine")
        return 0

    def _apply_round(self, round_work, now_ms, responses,
                     skip_store: bool = False, resolved=None) -> None:
        """Probe/retry dispatch of one window. Caller holds the engine
        lock (fps/touch/state are donated and rebound each step)."""
        import time as _time

        stage = self.stats.stage_ns
        if self._rounds_since_sweep >= _SWEEP_EVERY:
            self._rounds_since_sweep = 0
            self.fps = self._refresh(self.fps, self.state, now_ms)
        work = list(round_work)
        for _attempt in range(self.PROBE_RETRIES + 1):
            n = len(work)
            w = _bucket_width(n, self.min_width, self.max_width)
            t0 = _time.perf_counter_ns()
            packed = np.zeros((9, w), np.int64)
            if n:
                packed[1:8, :n] = np.array(
                    [(r.hits, r.limit, r.duration, int(r.algorithm),
                      int(r.behavior), ge, gi)
                     for _i, r, ge, gi in work], np.int64).T
            hashes = np.zeros(w, np.int64)
            if n:
                hashes[:n] = self._fingerprints(
                    [it[1].hash_key() for it in work])
            t1 = _time.perf_counter_ns()
            stage["pack"] += t1 - t0
            self._probe_seq += 1  # fresh epoch per dispatch: a retry can
            # evict what the previous attempt touched, so it terminates
            self.fps, self.touch, self.state, out, retry = \
                self._devdir_step(self.fps, self.touch, self.state,
                                  packed, hashes, now_ms, self._probe_seq)
            out = np.asarray(out)
            retry = np.asarray(retry)
            t2 = _time.perf_counter_ns()
            stage["device"] += t2 - t1
            self.stats.rounds += 1
            self._rounds_since_sweep += 1

            nxt = []
            status, limit, remaining, reset = out[:, :n].tolist()
            rt = retry[:n].tolist()
            for j, item in enumerate(work):
                if rt[j]:
                    nxt.append(item)
                    continue
                st = status[j]
                if st == 1:
                    self.stats.over_limit += 1
                responses[item[0]] = RateLimitResp(
                    status=st, limit=limit[j], remaining=remaining[j],
                    reset_time=reset[j])
            stage["demux"] += _time.perf_counter_ns() - t2
            work = nxt
            if not work:
                return
        for item in work:  # bounded: never a wrong slot, an honest error
            self.stats.errors += 1
            responses[item[0]] = RateLimitResp(
                error="device directory contention: probe window "
                      "exhausted after retries")

    def global_registry_size(self) -> int:  # metrics hook parity
        return 0
