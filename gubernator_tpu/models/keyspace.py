"""Host key directory: string key -> device table slot.

The reference stores buckets in a per-key LRU of Go structs
(reference: cache.go:53-165). Here the bucket state is dense device memory,
and the only per-key host structure is this directory mapping keys to row
indices, with LRU recycling when the table is full. Losing a slot loses that
key's state — the same accepted tradeoff as the reference's LRU eviction and
restart behavior (reference: architecture.md:5-11).

The pure-Python implementation below is the fallback; the C++ directory
(native/keydir.cpp, loaded via gubernator_tpu.native) is the production path
at millions of lookups/sec.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, Sequence, Tuple


class KeyDirectory:
    """LRU map key -> slot over a fixed slot capacity."""

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._map: "OrderedDict[str, int]" = OrderedDict()
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, key: str) -> bool:
        return key in self._map

    def lookup_inject(self, keys: Sequence[str]):
        """Native-API twin: (slots, fresh, inject). The python directory
        has no row mirrors (the native lone-request fast path lives in
        keydir.cpp), so the inject list is always empty."""
        slots, fresh = self.lookup(keys)
        import numpy as np

        return slots, fresh, np.empty((0, 8), np.int64)

    def lookup(self, keys: Sequence[str]) -> Tuple[List[int], List[bool]]:
        """Map keys to slots, assigning (and recycling LRU) as needed.

        Returns (slots, fresh) where fresh[i] means the slot was newly
        assigned to keys[i] and its device row must be treated as vacant.
        Duplicate keys in one call share a slot; only the first sees fresh.

        Keys of the current call are pinned: eviction never recycles a slot
        handed out earlier in the same call, so one kernel round never
        scatters two lanes to one row. Callers must keep
        len(set(keys)) <= capacity (the engine chunks accordingly).
        """
        slots: List[int] = []
        fresh: List[bool] = []
        pinned = set()
        for key in keys:
            slot = self._map.get(key)
            if slot is not None:
                self._map.move_to_end(key)
                pinned.add(key)
                slots.append(slot)
                fresh.append(False)
                continue
            if self._free:
                slot = self._free.pop()
            else:
                slot = None
                for victim in self._map:  # LRU order; skip this call's keys
                    if victim not in pinned:
                        slot = self._map.pop(victim)
                        self.evictions += 1
                        break
                if slot is None:
                    raise RuntimeError(
                        f"key directory over-committed: >{self.capacity} "
                        "distinct keys in one lookup")
            self._map[key] = slot
            pinned.add(key)
            slots.append(slot)
            fresh.append(True)
        return slots, fresh

    def drop(self, key: str) -> None:
        """Forget a key, returning its slot to the free list."""
        slot = self._map.pop(key, None)
        if slot is not None:
            self._free.append(slot)

    def keys(self) -> List[str]:
        return list(self._map.keys())

    def items(self) -> List[Tuple[str, int]]:
        return list(self._map.items())

    def peek_slot(self, key: str) -> int:
        """Slot for key without recency effects; -1 if absent."""
        return self._map.get(key, -1)
