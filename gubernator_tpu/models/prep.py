"""Shared host-side request preprocessing for the engines.

Validation, gregorian precomputation, and duplicate-key *round* splitting are
identical for the single-table engine (models/engine.py) and the mesh-sharded
engine (parallel/sharded.py); both call `preprocess`.

Rounds preserve the reference's same-key sequential semantics: the reference
serializes every request under one cache mutex (reference: gubernator.go:328),
so two hits to one key in a window observe each other. A scatter kernel with
duplicate indices cannot express that, so occurrence k of every key goes to
round k and rounds run back-to-back; almost all real windows are round-1-only.
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict, List, Optional, Sequence, Tuple

from gubernator_tpu.types import (
    ERR_EMPTY_NAME,
    ERR_EMPTY_UNIQUE_KEY,
    Behavior,
    RateLimitReq,
    RateLimitResp,
)
from gubernator_tpu.utils.gregorian import (
    GregorianError,
    gregorian_duration,
    gregorian_expiration,
)

# (original batch index, request, greg_expire_ms, greg_interval_ms)
WorkItem = Tuple[int, RateLimitReq, int, int]

_GREG = int(Behavior.DURATION_IS_GREGORIAN)


def bucket_width(n: int, lo: int, hi: int) -> int:
    """Round a batch width up to a power-of-two bucket in [lo, hi] so XLA
    compiles a handful of program shapes and reuses them."""
    w = lo
    while w < n:
        w *= 2
    return min(w, hi)


def bucket_pow2(n: int) -> int:
    """Next power of two ≥ n — bounds the number of compiled scan depths."""
    k = 1
    while k < n:
        k *= 2
    return k


def bucket_splits(n: int, lo: int, hi: int) -> List[int]:
    """Sub-window item counts for an n-item columnar chunk.

    Chunks wider than one engine window (n > hi) must split. Stepping at
    raw `hi` mints the capped terminal shape on capacity-capped engines
    (hi not a power of two) and strands one-item straggler windows when a
    chunk lands just over a window boundary; splitting on the pow2 bucket
    ladder instead keeps every sub-window — and every scan stack built
    over them — on exactly the shapes warmup()/warmup_pipeline() compiled.
    Every piece but the last is the largest pow2 bucket width ≤ hi; the
    remainder rides as one final piece (bucket_width pads it)."""
    cap = lo
    while cap * 2 <= hi:
        cap *= 2
    out = []
    while n > cap:
        out.append(cap)
        n -= cap
    if n:
        out.append(n)
    return out


def preprocess(
    requests: Sequence[RateLimitReq], now_ms: int
) -> Tuple[List[Optional[RateLimitResp]], List[List[WorkItem]], int]:
    """Validate + precompute calendar fields + split into collision-free rounds.

    Returns (responses, rounds, n_errors): `responses` is the output list with
    error entries already filled (None elsewhere); each round is a list of
    WorkItems whose keys are distinct within the round.
    """
    responses: List[Optional[RateLimitResp]] = [None] * len(requests)
    rounds: List[List[WorkItem]] = []
    occurrence: Dict[str, int] = {}
    occ_get = occurrence.get
    n_errors = 0
    local_now = None  # lazily computed once per batch
    for i, r in enumerate(requests):
        # validate_request semantics, inlined for the per-window hot loop
        if not r.unique_key:
            responses[i] = RateLimitResp(error=ERR_EMPTY_UNIQUE_KEY)
            n_errors += 1
            continue
        if not r.name:
            responses[i] = RateLimitResp(error=ERR_EMPTY_NAME)
            n_errors += 1
            continue
        ge = gi = 0
        if int(r.behavior) & _GREG:
            try:
                if local_now is None:
                    local_now = _dt.datetime.fromtimestamp(now_ms / 1000.0)
                ge = gregorian_expiration(local_now, r.duration)
                gi = gregorian_duration(local_now, r.duration)
            except GregorianError as e:
                responses[i] = RateLimitResp(error=str(e))
                n_errors += 1
                continue
        k = r.name + "_" + r.unique_key  # hash_key(), inlined
        j = occ_get(k, 0)
        occurrence[k] = j + 1
        if len(rounds) <= j:
            rounds.append([])
        rounds[j].append((i, r, ge, gi))
    return responses, rounds, n_errors
