"""Single-host rate-limit engine: host batching over the device kernel.

This is the TPU-native analogue of the reference's core request path
(reference: gubernator.go:110-224 fan-out + algorithms.go under one mutex):
instead of 1000 goroutines contending on a lock, a request batch becomes one
device program. The engine owns:

- the device key table (ops/decide.py row-major i64[C, 8] rows in HBM);
- the host key directory (models/keyspace.py);
- duplicate-key *rounds*: the reference's mutex serializes same-key requests
  inside a batch; we split a window so each kernel call touches each slot at
  most once, preserving exact sequential semantics (occurrence k of a key
  goes to round k);
- width bucketing: batches are padded to power-of-two widths so XLA compiles
  a handful of programs, then reuses them;
- the Store/Loader persistence hooks (store.py; reference: store.go).

The engine is synchronous and thread-safe via one lock — the service layer
(service/) puts the async micro-batching window in front of it.
"""

from __future__ import annotations

import functools as _functools
import threading
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from gubernator_tpu.obs import witness
from gubernator_tpu.models.keyspace import KeyDirectory
from gubernator_tpu.models.prep import (
    bucket_pow2 as _bucket_pow2,
    bucket_width as _bucket_width,
    preprocess,
)
from gubernator_tpu.ops.decide import (
    I32,
    I64,
    TableState,
    compact_window,
    decide_packed_lean,
    decide_scan_packed_lean,
    lean_capacity_ok,
    lean_window,
    staging_policy,
    decide_packed,
    decide_packed_compact,
    decide_scan_packed,
    decide_scan_packed_compact,
    kernel_telemetry,
    make_table,
    pack_window,
    pad_to_drop,
    widen_compact_out,
)
from gubernator_tpu.native import PREP_OVERCOMMIT
from gubernator_tpu.obs.profile import Profiler
from gubernator_tpu.store import BucketSnapshot, Loader, Store
from gubernator_tpu.types import (
    SLOW_PATH_BEHAVIOR_MASK as _NATIVE_SINGLE_SLOW_MASK,
    Behavior,
    RateLimitReq,
    RateLimitResp,
)
from gubernator_tpu.utils.interval import millisecond_now

_GREG_MASK = int(Behavior.DURATION_IS_GREGORIAN)


def _inject_rows(state: TableState, slot, algo, limit, remaining, duration,
                 stamp, expire_at, status) -> TableState:
    """Scatter host-provided rows into the table (store read-through/loader)."""
    slot = pad_to_drop(slot, state.shape[-2])
    rows = jnp.stack(
        [algo.astype(I64), limit, remaining, duration, stamp, expire_at,
         status.astype(I64), jnp.zeros_like(limit)],
        axis=1,
    )
    return state.at[slot].set(rows, mode="drop")


def _gather_rows(state: TableState, slot):
    """Fetch rows for store write-through / snapshotting (7-column tuple,
    TableState row field order)."""
    g = jnp.maximum(slot, 0)
    rows = state[g]
    return tuple(rows[:, i] for i in range(7))


# Jitted callables are shared process-wide (keyed by donate flag) so N
# engines in one process — the in-process cluster harness boots several —
# compile each batch width once, not once per engine.
@_functools.lru_cache(maxsize=None)
def _jit_decide_packed(donate: bool):
    return jax.jit(decide_packed, donate_argnums=(0,) if donate else ())


@_functools.lru_cache(maxsize=None)
def _jit_decide_scan(donate: bool):
    return jax.jit(decide_scan_packed, donate_argnums=(0,) if donate else ())


@_functools.lru_cache(maxsize=None)
def _jit_decide_packed_compact(donate: bool):
    return jax.jit(decide_packed_compact,
                   donate_argnums=(0,) if donate else ())


@_functools.lru_cache(maxsize=None)
def _jit_decide_scan_compact(donate: bool):
    return jax.jit(decide_scan_packed_compact,
                   donate_argnums=(0,) if donate else ())


@_functools.lru_cache(maxsize=None)
def _jit_decide_packed_lean(donate: bool):
    return jax.jit(decide_packed_lean,
                   donate_argnums=(0,) if donate else ())


@_functools.lru_cache(maxsize=None)
def _jit_decide_scan_lean(donate: bool):
    return jax.jit(decide_scan_packed_lean,
                   donate_argnums=(0,) if donate else ())


@_functools.lru_cache(maxsize=None)
def _jit_inject(donate: bool):
    return jax.jit(_inject_rows, donate_argnums=(0,) if donate else ())


@_functools.lru_cache(maxsize=None)
def _jit_gather():
    return jax.jit(_gather_rows)


@_functools.lru_cache(maxsize=None)
def _jit_slab(rows: int):
    """Fixed-shape row-slab fetch for the streamed snapshot: one compiled
    program regardless of table size or start offset."""
    return jax.jit(lambda st, i: jax.lax.dynamic_slice_in_dim(st, i, rows,
                                                              axis=0))


class EngineStats:
    """Counters plus a cumulative per-stage wall-clock breakdown.

    The stage clocks (nanoseconds) split a window's host path — validate/
    round-split (`prep`), key-directory resolution (`lookup`), Store
    read-through/write-through I/O (`store`), staging-buffer fill (`pack`),
    kernel dispatch + readback (`device`), response demux (`demux`) — so an
    operator can see WHERE a slow window went without a profiler attached.
    Lock-acquisition waits are deliberately excluded (deltas are computed
    before entering the engine lock). Exposed as
    engine_stage_seconds_total{stage=...} in /metrics (the reference has no
    tracing tier at all, SURVEY §5.1)."""

    STAGES = ("prep", "lookup", "store", "pack", "device", "demux")

    def __init__(self):
        self.requests = 0
        self.batches = 0
        self.rounds = 0
        self.over_limit = 0
        self.errors = 0
        self.native_singles = 0  # lone requests decided in C (no dispatch)
        self.stage_ns = {s: 0 for s in self.STAGES}

    def as_dict(self) -> Dict[str, int]:
        d = dict(requests=self.requests, batches=self.batches,
                 rounds=self.rounds, over_limit=self.over_limit,
                 errors=self.errors, native_singles=self.native_singles)
        for s, ns in self.stage_ns.items():
            d[f"{s}_ns"] = ns
        return d


class Engine:
    """One device's (or host's) authoritative rate-limit state + kernel."""

    def __init__(
        self,
        capacity: int = 1 << 20,
        store: Optional[Store] = None,
        loader: Optional[Loader] = None,
        min_width: int = 64,
        max_width: int = 8192,
        donate: Optional[bool] = None,
    ):
        self.capacity = capacity
        self.state = make_table(capacity)
        from gubernator_tpu import native
        from gubernator_tpu.native import make_key_directory

        self.directory = make_key_directory(capacity)
        # native one-pass window prep: only over the C++ directory (it calls
        # the KeyDir handle directly); python-directory engines keep the
        # python pipeline
        self._prep_fast = (
            native.prep_pack_fast
            if isinstance(self.directory, native.NativeKeyDirectory)
            else None
        )
        self.store = store
        self.loader = loader
        self.min_width = min_width
        # one kernel round must never need more distinct slots than exist
        self.max_width = min(max_width, capacity)
        self.stats = EngineStats()
        # daemon-registry histograms (service/metrics.py); attached by the
        # daemon/harness after construction, None keeps every observation
        # site a no-op
        self.metrics = None
        # hot-key detector (service/leases.py HotKeyTracker); attached by
        # LeaseManager.arm() when GUBER_HOT_LEASES is set — same None-is-off
        # contract as metrics, so the staging dispatchers stay untouched
        # when the lease tier is disabled
        self.hot_tracker = None
        # continuous cycle profiler (obs/profile.py): lock-wait, prep,
        # dispatch, readback and demux streaming histograms feeding
        # /v1/debug/profile. Always constructed; GUBER_PROFILE=0 turns
        # every observation site into a single attribute test
        self.profiler = Profiler()
        # decision ledger (obs/ledger.py): per-window attribution columns
        # for the conservation auditor; attached by the Instance, None
        # (or a disabled ledger) keeps every window hook a no-op
        self.ledger = None
        self._lock = witness.make_lock("engine")
        if donate is None:
            from gubernator_tpu.utils.platform import donation_supported

            donate = donation_supported()
        self._decide_packed = _jit_decide_packed(donate)
        self._decide_scan = _jit_decide_scan(donate)
        self._decide_packed_compact = _jit_decide_packed_compact(donate)
        self._decide_scan_compact = _jit_decide_scan_compact(donate)
        self._decide_packed_lean = _jit_decide_packed_lean(donate)
        self._decide_scan_lean = _jit_decide_scan_lean(donate)
        # lean staging needs every slot to fit the 24-bit lane field
        self._lean_ok = lean_capacity_ok(capacity)
        self._inject = _jit_inject(donate)
        self._gather = _jit_gather()
        # Staging wire-format policy: "auto" (default) ships each window
        # on the leanest eligible wire — lean i32[W] (4 B/lane), compact
        # i32[5, W] (20 B/lane), wide i64[9, W] as the last resort — all
        # held bit-identical by TestLeanStaging/TestCompactStaging.
        self._staging = staging_policy()
        if loader is not None:
            if hasattr(loader, "load_slabs"):
                self.load_snapshot_slabs(loader.load_slabs())
            else:
                self.load_snapshot(loader.load())

    # ------------------------------------------------------------------ API

    def warmup(self) -> None:
        """Compile the decision kernel for every width bucket up front.

        XLA compiles one program per batch width; without this the first
        request at each width pays seconds of compile latency — fatal inside
        the 500 µs-windowed peer-forwarding path. Daemons call this before
        serving (no reference analogue; compilation is a TPU concern)."""
        # enumerate exactly the widths bucket_width can produce, including
        # the capped terminal width when max_width isn't min_width * 2^k
        widths = []
        w = self.min_width
        while w < self.max_width:
            widths.append(w)
            w *= 2
        widths.append(self.max_width)
        resp = None
        both = self._staging != "wide"
        with self._lock:
            for width in widths:
                packed = np.zeros((9, width), np.int64)
                packed[0, :] = -1  # all padding lanes
                self.state, resp = self._decide_packed(self.state, packed, 0)
                if both:  # auto mode serves from any eligible wire format
                    self.state, resp = self._decide_packed_compact(
                        self.state, compact_window(packed), 0)
                    if self._lean_ok:
                        ln = lean_window(packed, self.capacity)
                        self.state, resp = self._decide_packed_lean(
                            self.state, ln[0], jnp.asarray(ln[1]), 0)
            # every scan-path shape: depths 2..=_MAX_SCAN at min_width (the
            # fast path dispatches nothing else — see _split_scannable)
            k = 2
            while k <= self._MAX_SCAN:
                stacked = np.zeros((k, 9, self.min_width), np.int64)
                stacked[:, 0, :] = -1
                self.state, resp = self._decide_scan(self.state, stacked, 0)
                if both:
                    self.state, resp = self._decide_scan_compact(
                        self.state, compact_window(stacked), 0)
                    if self._lean_ok:
                        ln = lean_window(stacked, self.capacity)
                        self.state, resp = self._decide_scan_lean(
                            self.state, ln[0], jnp.asarray(ln[1]), 0)
                k *= 2
            # serving-path auxiliary jits: the lone-miss mirror seed's
            # 1-slot gather and the mirror-flush inject at its common
            # (min-width) bucket. A cold compile of either inside a
            # peerlink/gRPC-front worker stalls a LIVE response for the
            # whole compile (~30 s on a tunneled TPU — observed as a
            # first-RPC deadline, r4).
            jax.block_until_ready(
                self._gather(self.state, jnp.zeros(1, I32)))
            warm_inject = np.zeros((1, 8), np.int64)
            warm_inject[0, 0] = -1  # dropped lane: compile, mutate nothing
            self._apply_inject_rows(warm_inject)
            if resp is not None:
                jax.block_until_ready(resp)

    # -------------------------------------------------- staging dispatch
    # Every window dispatch funnels through these two helpers so the
    # wide/compact wire-format switch lives in exactly one place
    # (VERDICT r3 item 1: auto-selected by eligibility).

    def _dispatch_staged(self, packed: np.ndarray, now_ms):
        """Dispatch one wide-format i64[9, W] window, shipping it lean
        (4 B/lane — the hits==1, few-configs serving shape) when eligible,
        compact (20 B/lane) otherwise, wide as the last resort. Returns an
        opaque handle for _fetch_staged. Caller holds the engine lock
        (self.state is donated and rebound here)."""
        ht = self.hot_tracker
        if ht is not None:
            # the staged rows are already host numpy: two bulk adds per
            # window, no per-key cost (service/leases.py)
            ht.feed_slots(packed[0], packed[1])
        w = packed.shape[1]
        if self._staging != "wide":
            if self._lean_ok:
                ln = lean_window(packed, self.capacity)
                if ln is not None:
                    lanes = jnp.asarray(ln[1])
                    if kernel_telemetry.needs_probe("packed_lean", w):
                        kernel_telemetry.offer_probe(
                            "packed_lean", w, self._decide_packed_lean,
                            (self.state, ln[0], lanes, now_ms))
                    t = time.perf_counter_ns()
                    self.state, out = self._decide_packed_lean(
                        self.state, ln[0], lanes, now_ms)
                    kernel_telemetry.note(
                        "packed_lean", w,
                        dur_ns=time.perf_counter_ns() - t)
                    return out, now_ms
            c = compact_window(packed)
            if c is not None:
                if kernel_telemetry.needs_probe("packed_compact", w):
                    kernel_telemetry.offer_probe(
                        "packed_compact", w, self._decide_packed_compact,
                        (self.state, c, now_ms))
                t = time.perf_counter_ns()
                self.state, out = self._decide_packed_compact(
                    self.state, c, now_ms)
                kernel_telemetry.note(
                    "packed_compact", w,
                    dur_ns=time.perf_counter_ns() - t)
                return out, now_ms
        if kernel_telemetry.needs_probe("packed_wide", w):
            kernel_telemetry.offer_probe(
                "packed_wide", w, self._decide_packed,
                (self.state, packed, now_ms))
        t = time.perf_counter_ns()
        self.state, out = self._decide_packed(self.state, packed, now_ms)
        kernel_telemetry.note("packed_wide", w,
                              dur_ns=time.perf_counter_ns() - t)
        return out, None

    def _dispatch_scan_staged(self, stacked: np.ndarray, now_ms):
        """decide_scan dispatch of a wide i64[K, 9, W] stack, shipped
        lean/compact when eligible. Handle contract matches
        _dispatch_staged. Caller holds the engine lock."""
        ht = self.hot_tracker
        if ht is not None:
            ht.feed_slots(stacked[:, 0, :], stacked[:, 1, :])
        k, w = stacked.shape[0], stacked.shape[2]
        if self._staging != "wide":
            if self._lean_ok:
                ln = lean_window(stacked, self.capacity)
                if ln is not None:
                    lanes = jnp.asarray(ln[1])
                    if kernel_telemetry.needs_probe("scan_lean", w):
                        kernel_telemetry.offer_probe(
                            "scan_lean", w, self._decide_scan_lean,
                            (self.state, ln[0], lanes, now_ms))
                    t = time.perf_counter_ns()
                    self.state, out = self._decide_scan_lean(
                        self.state, ln[0], lanes, now_ms)
                    kernel_telemetry.note(
                        "scan_lean", w, depth=k,
                        dur_ns=time.perf_counter_ns() - t)
                    return out, now_ms
            c = compact_window(stacked)
            if c is not None:
                if kernel_telemetry.needs_probe("scan_compact", w):
                    kernel_telemetry.offer_probe(
                        "scan_compact", w, self._decide_scan_compact,
                        (self.state, c, now_ms))
                t = time.perf_counter_ns()
                self.state, out = self._decide_scan_compact(
                    self.state, c, now_ms)
                kernel_telemetry.note(
                    "scan_compact", w, depth=k,
                    dur_ns=time.perf_counter_ns() - t)
                return out, now_ms
        if kernel_telemetry.needs_probe("scan_wide", w):
            kernel_telemetry.offer_probe(
                "scan_wide", w, self._decide_scan,
                (self.state, stacked, now_ms))
        t = time.perf_counter_ns()
        self.state, out = self._decide_scan(self.state, stacked, now_ms)
        kernel_telemetry.note("scan_wide", w, depth=k,
                              dur_ns=time.perf_counter_ns() - t)
        return out, None

    def _obs_device(self, ns: int, lanes: int) -> None:
        """Feed one window's device dispatch+readback wall time and live
        lane count into the daemon-registry histograms (no-op until a
        Metrics is attached)."""
        m = self.metrics
        if m is not None:
            m.engine_device_dispatch_ms.observe(ns / 1e6)
            m.engine_window_lanes.observe(lanes)

    def key_count(self) -> int:
        """Live key-table occupancy (the cache_size /
        engine_key_table_size gauge source)."""
        return len(self.directory)

    def kernel_fingerprints(self) -> Dict[str, str]:
        """HLO fingerprints of the canonical decision programs: the wide
        per-window kernel and the depth-2 scan at min_width. Every
        staging variant lowers from the same decide body, so any kernel
        change — a jax/libtpu bump, a decide.py edit, an XLA flag drift
        — shows here. Boot-time introspection only (cmd/daemon.py
        compares across boots and emits profile.recompile on drift);
        lowering traces but never compiles."""
        from gubernator_tpu.obs.profile import hlo_fingerprint

        with self._lock:
            state_aval = jax.ShapeDtypeStruct(self.state.shape,
                                              self.state.dtype)
        w = self.min_width
        out: Dict[str, str] = {}
        try:
            packed = jax.ShapeDtypeStruct((9, w), I64)
            out[f"packed_wide@{w}"] = hlo_fingerprint(
                self._decide_packed.lower(
                    state_aval, packed, 0).as_text())
            stacked = jax.ShapeDtypeStruct((2, 9, w), I64)
            out[f"scan_wide@{w}"] = hlo_fingerprint(
                self._decide_scan.lower(
                    state_aval, stacked, 0).as_text())
        except Exception:  # noqa: BLE001 — introspection must not break boot
            pass
        return out

    @staticmethod
    def _fetch_staged(handle) -> np.ndarray:
        """Block on a dispatched window and return the wide i64 response
        rows regardless of which wire format carried it."""
        out, compact_now = handle
        if compact_now is not None:
            return widen_compact_out(out, compact_now)
        return np.asarray(out)

    def get_rate_limits(
        self, requests: Sequence[RateLimitReq], now_ms: Optional[int] = None
    ) -> List[RateLimitResp]:
        """Decide a batch. Exact per-key sequential semantics, any batch size."""
        if now_ms is None:
            now_ms = millisecond_now()
        if (self._prep_fast is not None and self.store is None
                and 0 < len(requests) <= self.max_width):
            fast = self._fast_window(requests, now_ms)
            if fast is not None:
                return fast
        return self._slow_window(requests, now_ms)

    def _slow_window(self, requests, now_ms,
                     count_batch: bool = True) -> List[RateLimitResp]:
        """The python pipeline: full validation, gregorian precompute, and
        duplicate-key round splitting (models/prep.py). `count_batch` is
        False when called as a fast window's leftover tail — the client
        batch was already counted there."""
        t0 = time.perf_counter_ns()
        responses, rounds, n_errors = preprocess(requests, now_ms)
        prep_ns = time.perf_counter_ns() - t0  # excludes the lock wait below
        prof = self.profiler
        prof.observe("prep", prep_ns)

        tq = time.perf_counter_ns() if prof.enabled else 0
        with self._lock:
            if tq:
                prof.lock_wait("slow_window", time.perf_counter_ns() - tq)
            self.stats.stage_ns["prep"] += prep_ns
            self.stats.requests += len(requests)
            self.stats.batches += 1 if count_batch else 0
            self.stats.errors += n_errors
            windows = []
            for round_work in rounds:
                self.stats.rounds += 1
                for start in range(0, len(round_work), self.max_width):
                    windows.append(round_work[start:start + self.max_width])
            head, tail = self._split_scannable(windows)
            for wk in head:
                self._apply_round(wk, now_ms, responses)
            if tail:
                self._apply_windows_scanned(tail, now_ms, responses)
        return responses  # type: ignore[return-value]

    def _fast_window(self, requests, now_ms) -> Optional[List[RateLimitResp]]:
        """Native one-pass window: validate + first-occurrence round split +
        directory lookup + pack in one C call (native/keydir.cpp
        keydir_prep_pack_fast). Lanes the C pass can't take — invalid,
        gregorian, duplicate occurrences — come back as leftover item
        indices and run through the python pipeline AFTER this round, which
        preserves exact per-key sequential semantics. (The lock is released
        between the round and the tail: another caller's window may
        interleave there, exactly as the reference's per-request mutex
        allows between two same-batch goroutines, gubernator.go:126-213,328
        — the python pipeline's whole-batch lock is stricter than both.)
        Returns None only for windows the native path can't start at all
        (nothing mutated)."""
        w = _bucket_width(len(requests), self.min_width, self.max_width)
        packed = np.zeros((9, w), np.int64)
        prof = self.profiler
        tq = time.perf_counter_ns() if prof.enabled else 0
        with self._lock:
            t0 = time.perf_counter_ns()  # excludes the lock wait
            if tq:
                prof.lock_wait("fast_window", t0 - tq)
            n0, lane_item, leftover, inject = self._prep_fast(
                self.directory, requests, packed, _GREG_MASK)
            if n0 == PREP_OVERCOMMIT:
                # mirror rows collected before the abort must still land
                # (unreachable on this engine — max_width <= capacity —
                # but the invariant is cheap to keep)
                self._apply_inject_rows(inject)
                raise RuntimeError(
                    f"key directory over-committed: >{self.capacity} "
                    "distinct keys in one lookup")
            if n0 < 0:
                return None
            stage = self.stats.stage_ns
            t1 = time.perf_counter_ns()
            stage["prep"] += t1 - t0
            prof.observe("prep", t1 - t0)
            self.stats.requests += n0
            self.stats.batches += 1
            self._apply_inject_rows(inject)
            responses: List[Optional[RateLimitResp]] = [None] * len(requests)
            if n0:
                self.stats.rounds += 1
                staged = self._dispatch_staged(packed, now_ms)
                td = time.perf_counter_ns()
                out = self._fetch_staged(staged)
                t2 = time.perf_counter_ns()
                stage["device"] += t2 - t1
                self._obs_device(t2 - t1, n0)
                prof.observe("dispatch", td - t1)
                prof.observe("readback", t2 - td)
                status, limit, remaining, reset = out[:, :n0].tolist()
                over = 0
                for j, i in enumerate(lane_item.tolist()):
                    st = status[j]
                    if st == 1:
                        over += 1
                    responses[i] = RateLimitResp(
                        status=st, limit=limit[j], remaining=remaining[j],
                        reset_time=reset[j])
                self.stats.over_limit += over
                demux_ns = time.perf_counter_ns() - t2
                stage["demux"] += demux_ns
                prof.observe("demux", demux_ns)
                led = self.ledger
                if led is not None and led.enabled:
                    led.note_slots(packed, out, n0)
        if len(leftover):
            idxs = leftover.tolist()
            tail = self._slow_window(
                [requests[i] for i in idxs], now_ms, count_batch=False)
            for i, resp in zip(idxs, tail):
                responses[i] = resp
        return responses  # type: ignore[return-value]

    # ----------------------------------------------------- pipelined serving
    # The launch/collect split of the request-object path: the combiner
    # (service/combiner.py) keeps up to GUBER_PIPELINE_DEPTH window groups
    # in flight — launch N+1 is admitted while window N's readback is still
    # crossing the link. Per-key sequential semantics survive because (a)
    # launches are serialized under the engine lock, so host prep order ==
    # dispatch order, and (b) the device state chain (each launch consumes
    # the previous launch's table) orders the windows' effects on device —
    # the same argument submit_columnar already rides. Leftover lanes
    # (duplicate occurrences, gregorian, invalid) are retired AT LAUNCH,
    # between this group's dispatch and any later launch, so a key's later
    # arrivals can never overtake its packed first occurrence
    # (tests/test_pipeline.py proves this with a duplicate-key hammer
    # differential against the serial path).

    def supports_pipeline(self) -> bool:
        """True when the non-blocking launch/collect split is available:
        native one-pass prep and no Store hooks (a Store needs synchronous
        host calls around every window)."""
        return self._prep_fast is not None and self.store is None

    def launch_windows(self, windows, now_ms: Optional[int] = None,
                       staging=None):
        """Dispatch 1..K request-object windows as ONE device launch
        (K > 1 rides the scan kernel) without blocking on the readback.

        `windows` is a list of request lists, each 0 < len <= max_width;
        `staging`, when given, is a dict the engine parks reusable staging
        buffers in (keyed by shape) — the combiner hands each pipeline
        slot its own dict so a buffer is never rewritten while its launch
        may still be reading it. Returns an opaque handle for
        collect_windows, or None when the pipelined path cannot take the
        group at all (nothing mutated, nothing dispatched)."""
        if not self.supports_pipeline():
            return None
        k_req = len(windows)
        if not 0 < k_req <= self._MAX_SCAN:
            return None
        if any(not 0 < len(wk) <= self.max_width for wk in windows):
            return None
        if now_ms is None:
            now_ms = millisecond_now()
        w = max(_bucket_width(len(wk), self.min_width, self.max_width)
                for wk in windows)
        kb = _bucket_pow2(k_req) if k_req > 1 else 1
        shape = (kb, 9, w)
        buf = None if staging is None else staging.get(shape)
        if buf is None:
            buf = np.zeros(shape, np.int64)
            if staging is not None:
                staging[shape] = buf
        else:
            buf.fill(0)  # the prep contract: zeroed staging rows
        # Segmented group launch. A window whose prep yields LEFTOVERS
        # (duplicate occurrences, gregorian, invalid) CUTS the group: the
        # segment so far dispatches and its tails retire before any later
        # window preps — the ISSUE's pipeline-barrier rule. Otherwise a
        # key pending in window k's tail could be overtaken by its next
        # arrival packed into window k+1 of the same launch, breaking the
        # per-key submission order the serial combiner guarantees. The
        # common serving shape (distinct keys, hits=1) never cuts: one
        # scan dispatch for the whole group.
        meta: List[Optional[tuple]] = [None] * k_req
        tails: List[Optional[list]] = [None] * k_req
        segments = []  # (staged, k_start, m, scanned) in launch order
        prof = self.profiler
        led = self.ledger
        if led is not None and not led.enabled:
            led = None
        stashes: List[Optional[tuple]] = [None] * k_req
        k = 0
        while k < k_req:
            seg_start = k
            tq = time.perf_counter_ns() if prof.enabled else 0
            with self._lock:
                t0 = time.perf_counter_ns()  # excludes the lock wait
                if tq:
                    prof.lock_wait("launch_windows", t0 - tq)
                total = 0
                rounds = 0
                cut = False
                while k < k_req and not cut:
                    wk = windows[k]
                    n0, lane_item, leftover, inject = self._prep_fast(
                        self.directory, wk, buf[k], _GREG_MASK)
                    if n0 == PREP_OVERCOMMIT:
                        self._apply_inject_rows(inject)
                        raise RuntimeError(
                            f"key directory over-committed: "
                            f">{self.capacity} distinct keys in one lookup")
                    if n0 < 0:
                        # defensive — the size preconditions above rule
                        # this out; nothing was committed for THIS window,
                        # so it retires whole through the python tail
                        buf[k][0, :] = -1
                        meta[k] = (0, None,
                                   np.arange(len(wk), dtype=np.int32))
                        k += 1
                        cut = True
                        break
                    self._apply_inject_rows(inject)
                    if n0 == 0:
                        buf[k][0, :] = -1  # prep leaves slot row zeroed
                    meta[k] = (n0, lane_item, leftover)
                    total += n0
                    rounds += 1 if n0 else 0
                    k += 1
                    cut = len(leftover) > 0
                m = k - seg_start
                t1 = time.perf_counter_ns()
                self.stats.stage_ns["prep"] += t1 - t0
                prof.observe("prep", t1 - t0)
                self.stats.requests += total
                self.stats.batches += m
                self.stats.rounds += rounds
                if m == 1:
                    staged = self._dispatch_staged(buf[seg_start], now_ms)
                    scanned = False
                else:
                    kb2 = _bucket_pow2(m)
                    if seg_start == 0 and k == k_req and kb2 == kb:
                        # the whole group in one segment: dispatch the
                        # staging stack itself, marking the pow2 pads
                        stack = buf
                        for kk in range(k_req, kb):
                            stack[kk][0, :] = -1
                    elif kb2 == m:
                        stack = buf[seg_start:k]  # contiguous prefix run
                    else:  # rare (a cut left a non-pow2 run): copy-pad
                        stack = np.zeros((kb2, 9, w), np.int64)
                        stack[:m] = buf[seg_start:k]
                        stack[m:, 0, :] = -1
                    staged = self._dispatch_scan_staged(stack, now_ms)
                    scanned = True
                td = time.perf_counter_ns()
                self.stats.stage_ns["device"] += td - t1
                prof.observe("dispatch", td - t1)
                if led is not None:
                    # the staging buffer is reused across launches; the
                    # collect side pairs these copies with the readback
                    for kk in range(seg_start, k):
                        stashes[kk] = led.stash_columns(
                            buf[kk], meta[kk][0])
            segments.append((staged, seg_start, m, scanned))
            # Leftover tails retire NOW — after this segment's dispatch,
            # before any later window preps — preserving per-key
            # submission order exactly as the serial path does.
            # _slow_window blocks on its own readback; rare path.
            for kk in range(seg_start, k):
                leftover = meta[kk][2]
                if leftover is not None and len(leftover):
                    idxs = leftover.tolist()
                    tails[kk] = self._slow_window(
                        [windows[kk][i] for i in idxs], now_ms,
                        count_batch=False)
        return (segments, windows, meta, tails, stashes)

    def collect_windows(self, handle):
        """Block on a launched group's readbacks (in dispatch order) and
        demux: returns one response list per window, in launch order. Runs
        outside the engine lock — dispatch order is already fixed — so
        later launches proceed while this readback drains."""
        segments, windows, meta, tails, stashes = handle
        led = self.ledger
        if led is not None and not led.enabled:
            led = None
        results: List[Optional[list]] = [None] * len(windows)
        over = 0
        lanes = 0
        t_fetch = 0
        t0 = time.perf_counter_ns()
        for staged, seg_start, m, scanned in segments:
            tf = time.perf_counter_ns()
            out = self._fetch_staged(staged)  # device sync, this segment
            t_fetch += time.perf_counter_ns() - tf
            for k in range(seg_start, seg_start + m):
                wk = windows[k]
                n0, lane_item, leftover = meta[k]
                responses: List[Optional[RateLimitResp]] = [None] * len(wk)
                if n0:
                    rows = out[k - seg_start] if scanned else out
                    status, limit, remaining, reset = rows[:, :n0].tolist()
                    over += status.count(1)
                    if n0 == len(wk):
                        # nothing was skipped, so lanes are in request
                        # order — build the list directly (the common
                        # serving shape; ~2x less python per decision
                        # than the scatter loop)
                        responses = [
                            RateLimitResp(st, li, re_, rs)
                            for st, li, re_, rs in zip(
                                status, limit, remaining, reset)
                        ]
                    else:
                        for j, i in enumerate(lane_item.tolist()):
                            responses[i] = RateLimitResp(
                                status[j], limit[j], remaining[j], reset[j])
                    lanes += n0
                    if led is not None:
                        led.note_slots_deferred(stashes[k], rows, n0)
                tail = tails[k]
                if tail is not None:
                    for i, resp in zip(leftover.tolist(), tail):
                        responses[i] = resp
                results[k] = responses
        t2 = time.perf_counter_ns()
        self._obs_device(t_fetch, lanes)
        prof = self.profiler
        prof.observe("readback", t_fetch)
        prof.observe("demux", t2 - t0 - t_fetch)
        with self._lock:  # concurrent completers: counters stay exact
            self.stats.over_limit += over
            self.stats.stage_ns["device"] += t_fetch
            self.stats.stage_ns["demux"] += t2 - t0 - t_fetch
        return results

    def launch_noop(self, width: Optional[int] = None):
        """Dispatch one all-padding window (every lane drops — the table
        is untouched) and return its handle: the combiner's depth
        auto-probe times these to pick cycles-in-flight without mutating
        state."""
        w = width or self.min_width
        packed = np.zeros((9, w), np.int64)
        packed[0, :] = -1
        with self._lock:
            return self._dispatch_staged(packed, 0)

    def collect_noop(self, handle) -> None:
        """Block on a launch_noop readback."""
        self._fetch_staged(handle)

    def warmup_pipeline(self, max_group: int = 8) -> None:
        """Compile the group-launch scan shapes (pow2 depths <= max_group
        at max_width) the pipelined combiner dispatches under bursts.
        Separate from warmup() so the extra boot cost is opt-in (daemons
        with pipelining on); a cold compile of a scan shape inside a live
        window would stall that window for the whole compile."""
        if not self.supports_pipeline():
            return
        both = self._staging != "wide"
        resp = None
        with self._lock:
            k = 2
            while k <= min(max_group, self._MAX_SCAN):
                stacked = np.zeros((k, 9, self.max_width), np.int64)
                stacked[:, 0, :] = -1
                self.state, resp = self._decide_scan(self.state, stacked, 0)
                if both:
                    self.state, resp = self._decide_scan_compact(
                        self.state, compact_window(stacked), 0)
                    if self._lean_ok:
                        ln = lean_window(stacked, self.capacity)
                        self.state, resp = self._decide_scan_lean(
                            self.state, ln[0], jnp.asarray(ln[1]), 0)
                k *= 2
            if resp is not None:
                jax.block_until_ready(resp)

    # ------------------------------------------------------- columnar path

    def supports_columnar(self) -> bool:
        """True when the zero-object serving path is available: native
        directory + no Store hooks (stores need per-round host calls)."""
        return self._prep_fast is not None and self.store is None

    def submit_columnar(self, n: int, keys, key_off, name_len, hits, limit,
                        duration, algorithm, behavior, slow_mask: int,
                        now_ms: Optional[int] = None):
        """Dispatch one columnar window: the wire columns (peerlink's
        pls_next_batch layout) go through the GIL-free C prep straight into
        the staging buffer and onto the device — no RateLimitReq objects.

        Returns a handle for complete_columnar, or None when the columnar
        path cannot take the window at all (nothing mutated). The dispatch
        is ASYNC: callers may submit further windows before completing
        earlier ones (≥2 in flight hides device latency; the state chain
        orders them). Items the C pass can't take come back as `leftover`
        indices from complete_columnar — run them through the request-object
        path AFTER this round (per-key sequential order holds because a
        leftover key's first occurrence, if packed, dispatched first)."""
        if not 0 < n <= self.max_width:
            return None
        if now_ms is None:
            now_ms = millisecond_now()
        from gubernator_tpu import native

        w = _bucket_width(n, self.min_width, self.max_width)
        packed = np.zeros((9, w), np.int64)
        prof = self.profiler
        tq = time.perf_counter_ns() if prof.enabled else 0
        with self._lock:
            t0 = time.perf_counter_ns()  # excludes the lock wait
            if tq:
                prof.lock_wait("submit_columnar", t0 - tq)
            n0, lane_item, leftover, inject = native.prep_pack_columnar(
                self.directory, n, keys, key_off, name_len, hits, limit,
                duration, algorithm, behavior, slow_mask, packed)
            if n0 == PREP_OVERCOMMIT:
                self._apply_inject_rows(inject)
                raise RuntimeError(
                    f"key directory over-committed: >{self.capacity} "
                    "distinct keys in one lookup")
            if n0 < 0:
                return None
            t1 = time.perf_counter_ns()
            self.stats.stage_ns["prep"] += t1 - t0
            prof.observe("prep", t1 - t0)
            self.stats.requests += n0
            self.stats.batches += 1
            self._apply_inject_rows(inject)
            handle = None
            stash = None
            if n0:
                self.stats.rounds += 1
                handle = self._dispatch_staged(packed, now_ms)
                td = time.perf_counter_ns()
                self.stats.stage_ns["device"] += td - t1
                prof.observe("dispatch", td - t1)
                led = self.ledger
                if led is not None and led.enabled:
                    stash = led.stash_columns(packed, n0)
        return (handle, lane_item, leftover, n0, stash)

    def complete_columnar(self, handle, out_status, out_limit,
                          out_remaining, out_reset) -> np.ndarray:
        """Read back a submitted window and scatter the four response rows
        into the caller's columns at the packed items' positions (runs
        outside the engine lock — dispatch order is already fixed).
        Returns the leftover item indices."""
        staged, lane_item, leftover, n0, stash = handle
        if n0:
            t0 = time.perf_counter_ns()
            rows = self._fetch_staged(staged)  # device sync for THIS window
            t1 = time.perf_counter_ns()
            led = self.ledger
            if led is not None and led.enabled:
                led.note_slots_deferred(stash, rows, n0)
            out_status[lane_item] = rows[0, :n0]
            out_limit[lane_item] = rows[1, :n0]
            out_remaining[lane_item] = rows[2, :n0]
            out_reset[lane_item] = rows[3, :n0]
            over = int(np.count_nonzero(rows[0, :n0] == 1))
            t2 = time.perf_counter_ns()
            self._obs_device(t1 - t0, n0)
            prof = self.profiler
            prof.observe("readback", t1 - t0)
            prof.observe("demux", t2 - t1)
            with self._lock:  # concurrent completers: counters stay exact
                self.stats.over_limit += over
                self.stats.stage_ns["device"] += t1 - t0
                self.stats.stage_ns["demux"] += t2 - t1
        return leftover

    # ------------------------------------------- pipelined columnar serving
    # The launch/collect split of the COLUMNAR path: the zero-object twin
    # of launch_windows/collect_windows, driven by the peerlink service
    # (service/peerlink.py _columnar_chunk). Per-key wire order survives
    # by the identical argument: launches serialize under the engine lock
    # (prep order == dispatch order), the device state chain orders the
    # windows' effects, and a window whose prep yields LEFTOVERS cuts the
    # group — the caller must collect and retire them through the
    # request-object path before launching any later sub-window.

    def launch_columnar_windows(self, windows, slow_mask: int,
                                now_ms: Optional[int] = None, staging=None):
        """Dispatch a PREFIX of 1..K columnar sub-windows as ONE device
        launch (K > 1 rides the scan kernel) without blocking on the
        readback.

        `windows` is a list of column tuples (n, keys, key_off, name_len,
        hits, limit, duration, algorithm, behavior) in the peerlink wire
        layout (see submit_columnar), each 0 < n <= max_width; `staging`
        follows the launch_windows contract (one dict per pipeline slot).
        Windows prep in order under ONE lock hold; the first window whose
        prep yields leftovers (duplicates, gregorian, slow-mask demotions,
        invalid) is the LAST window dispatched — the group-cut barrier.

        Returns None when the path cannot take the FIRST window at all
        (nothing mutated — fall back to the object path); otherwise an
        opaque handle for collect_columnar_windows with the cross-backend
        contract: handle[0] is the per-window meta list (len = windows
        CONSUMED, each meta's last element the leftover item indices) and
        handle[1] an over-commit error message or None. On over-commit
        the windows prepped before the failure still dispatch (their
        directory commits must reach the device); the failing window and
        everything after is NOT consumed — the caller error-fills those
        items."""
        if not self.supports_columnar():
            return None
        k_req = len(windows)
        if not 0 < k_req <= self._MAX_SCAN:
            return None
        if any(not 0 < wc[0] <= self.max_width for wc in windows):
            return None
        if now_ms is None:
            now_ms = millisecond_now()
        from gubernator_tpu import native

        w = max(_bucket_width(wc[0], self.min_width, self.max_width)
                for wc in windows)
        kb = _bucket_pow2(k_req) if k_req > 1 else 1
        shape = (kb, 9, w)
        buf = None if staging is None else staging.get(shape)
        if buf is None:
            buf = np.zeros(shape, np.int64)
            if staging is not None:
                staging[shape] = buf
        else:
            buf.fill(0)  # the prep contract: zeroed staging rows
        metas: List[tuple] = []
        failed = None
        prof = self.profiler
        led = self.ledger
        if led is not None and not led.enabled:
            led = None
        stashes: List[Optional[tuple]] = []
        tq = time.perf_counter_ns() if prof.enabled else 0
        with self._lock:
            t0 = time.perf_counter_ns()  # excludes the lock wait
            if tq:
                prof.lock_wait("launch_columnar_windows", t0 - tq)
            total = 0
            rounds = 0
            for k, wc in enumerate(windows):
                (n, keys, key_off, name_len, hits, limit, duration,
                 algorithm, behavior) = wc
                n0, lane_item, leftover, inject = native.prep_pack_columnar(
                    self.directory, n, keys, key_off, name_len, hits,
                    limit, duration, algorithm, behavior, slow_mask,
                    buf[k])
                if n0 == PREP_OVERCOMMIT:
                    # earlier windows committed directory state and MUST
                    # still dispatch; this window and the rest are not
                    # consumed (the caller error-fills their items)
                    self._apply_inject_rows(inject)
                    buf[k][0, :] = -1  # partially-written row: all padding
                    failed = (f"key directory over-committed: "
                              f">{self.capacity} distinct keys in one "
                              "lookup")
                    break
                if n0 < 0:
                    if k == 0:
                        return None  # nothing mutated: object-path fallback
                    # defensive — the size preconditions rule this out;
                    # nothing committed for THIS window, so it retires
                    # whole through the caller's leftover path, cutting
                    # the group here
                    buf[k][0, :] = -1
                    metas.append((0, None, np.arange(n, dtype=np.int32)))
                    break
                self._apply_inject_rows(inject)
                if n0 == 0:
                    buf[k][0, :] = -1  # prep leaves the slot row zeroed
                metas.append((n0, lane_item, leftover))
                total += n0
                rounds += 1 if n0 else 0
                if len(leftover):
                    break  # group-cut barrier: leftovers retire first
            m = len(metas)
            t1 = time.perf_counter_ns()
            self.stats.stage_ns["prep"] += t1 - t0
            prof.observe("prep", t1 - t0)
            self.stats.requests += total
            self.stats.batches += m
            self.stats.rounds += rounds
            staged = None
            scanned = False
            if total:
                if m == 1:
                    staged = self._dispatch_staged(buf[0], now_ms)
                else:
                    kb2 = _bucket_pow2(m)
                    stack = buf if kb2 == kb else buf[:kb2]
                    for kk in range(m, kb2):
                        stack[kk][0, :] = -1  # unprepped rows: all padding
                    staged = self._dispatch_scan_staged(stack, now_ms)
                    scanned = True
                td = time.perf_counter_ns()
                self.stats.stage_ns["device"] += td - t1
                prof.observe("dispatch", td - t1)
                if led is not None:
                    stashes = [led.stash_columns(buf[kk], metas[kk][0])
                               for kk in range(m)]
        return (metas, failed, staged, scanned, stashes)

    def collect_columnar_windows(self, handle, outs):
        """Block on a launched columnar group's readback (runs outside the
        engine lock — dispatch order is already fixed) and scatter each
        window's response rows into the caller's column buffers. `outs`
        is one (status, limit, remaining, reset) array 4-tuple per
        CONSUMED window, each sized to that window's item count. Returns
        the per-window leftover index arrays — at most the LAST consumed
        window's is non-empty (the group-cut barrier)."""
        metas, _failed, staged, scanned, stashes = handle
        led = self.ledger
        if led is not None and not led.enabled:
            led = None
        t0 = time.perf_counter_ns()
        rows_all = self._fetch_staged(staged) if staged is not None else None
        t1 = time.perf_counter_ns()
        over = 0
        lanes = 0
        leftovers = []
        for k, ((n0, lane_item, leftover), out) in enumerate(
                zip(metas, outs)):
            if n0:
                rows = rows_all[k] if scanned else rows_all
                st, li, re, rs = out
                st[lane_item] = rows[0, :n0]
                li[lane_item] = rows[1, :n0]
                re[lane_item] = rows[2, :n0]
                rs[lane_item] = rows[3, :n0]
                over += int(np.count_nonzero(rows[0, :n0] == 1))
                lanes += n0
                if led is not None and k < len(stashes):
                    led.note_slots_deferred(stashes[k], rows, n0)
            leftovers.append(leftover)
        t2 = time.perf_counter_ns()
        if lanes:
            self._obs_device(t1 - t0, lanes)
        prof = self.profiler
        prof.observe("readback", t1 - t0)
        prof.observe("demux", t2 - t1)
        with self._lock:  # concurrent completers: counters stay exact
            self.stats.over_limit += over
            self.stats.stage_ns["device"] += t1 - t0
            self.stats.stage_ns["demux"] += t2 - t1
        return leftovers

    # --------------------------------------------- native lone-request path

    def _apply_inject_rows(self, inject) -> None:
        """Scatter reconciled mirror rows (native lone-path decisions,
        keydir.cpp Mirror) into the device table BEFORE the window whose
        lookup surfaced them. Caller holds the engine lock."""
        if inject is None or len(inject) == 0:
            return
        m = len(inject)
        w = _bucket_width(m, self.min_width, self.max_width)
        pad = w - m
        z = np.zeros(pad, np.int64)

        def col(f):
            return jnp.asarray(np.concatenate([inject[:, f], z]), I64)

        self.state = self._inject(
            self.state,
            jnp.asarray(np.concatenate(
                [inject[:, 0], np.full(pad, -1)]).astype(np.int32), I32),
            col(1).astype(I32), col(2), col(3), col(4), col(5), col(6),
            col(7).astype(I32),
        )

    def decide_native_single(self, req: RateLimitReq,
                             now_ms: int = 0) -> Optional[RateLimitResp]:
        """The native lone-request fast path (VERDICT r2 item 6): decide a
        NO_BATCHING single against the key's directory-resident row mirror
        entirely in C (keydir.cpp decide_one) — no kernel dispatch, no
        engine lock (the KeyDir mutex serializes against batch lookups).
        None = miss (cold/invalidated mirror, masked behavior, store
        attached): take the kernel path, then seed_mirror()."""
        d = self.directory
        if self.store is not None or not hasattr(d, "decide_one"):
            return None
        if int(req.behavior) & _NATIVE_SINGLE_SLOW_MASK:
            return None
        if not req.name or not req.unique_key:
            return None  # the kernel path produces the validation error
        out = d.decide_one(req.hash_key(), req.hits, req.limit,
                           req.duration, int(req.algorithm),
                           int(req.behavior), now_ms)
        if out is None:
            return None
        self.stats.requests += 1
        self.stats.native_singles += 1
        if out[0] == 1:
            self.stats.over_limit += 1
        if self.hot_tracker is not None:
            # native decides bypass the staging dispatchers, so they feed
            # the detector by key instead of by slot row
            self.hot_tracker.feed_key(req.hash_key(), req.hits)
        led = self.ledger
        if led is not None and led.enabled:
            # native decides bypass the staging buffers too: attribute by
            # key directly (a lone request already pays a python wrapper)
            led.record_key(req.hash_key(), req.hits, int(out[0]),
                           int(out[1]), int(out[3]))
        return RateLimitResp(status=int(out[0]), limit=out[1],
                             remaining=out[2], reset_time=out[3])

    def seed_mirror(self, key: str) -> bool:
        """Copy a key's post-window device row into its directory mirror so
        subsequent lone requests decide natively. Called after a lone miss
        took the kernel path (one gather dispatch, amortized across every
        native decision the mirror then serves)."""
        d = self.directory
        if self.store is not None or not hasattr(d, "mirror_seed"):
            return False
        with self._lock:
            slot = d.peek_slot(key)
            if slot < 0:
                return False
            cols = self._gather(self.state, jnp.asarray([slot], I32))
            row = [int(np.asarray(c)[0]) for c in cols]
            if row[0] < 0:
                return False  # vacant row: nothing to mirror
            d.mirror_seed(key, row)
        return True

    # ------------------------------------------------------ hot-key support

    def resolve_slots(self, slots) -> dict:
        """Map a SMALL set of slots back to their hash-key strings.

        The directory only maps key→slot; the reverse walk costs one
        items_raw arena scan, so the hot-key tracker calls this once per
        detection window and only for the few slots that crossed the rate
        threshold — never on the serving path. Slots without a live
        directory entry (recycled mid-window) are simply absent from the
        result."""
        want = set(int(s) for s in slots)
        if not want:
            return {}
        out: dict = {}
        if hasattr(self.directory, "items_raw"):
            blob, off, slots32 = self.directory.items_raw()
            sl = np.asarray(slots32, np.int64)
            off = np.asarray(off, np.int64)
            hit = np.nonzero(np.isin(
                sl, np.fromiter(want, np.int64, len(want))))[0]
            for i in hit:
                lo, hi = int(off[i]), int(off[i + 1])
                try:
                    out[int(sl[i])] = bytes(blob[lo:hi]).decode("utf-8")
                except UnicodeDecodeError:
                    continue
        else:  # python-twin directory
            for key, s in self.directory.items():
                if int(s) in want:
                    out[int(s)] = key
        return out

    def device_hit_counts(self, keys) -> dict:
        """Per-key lifetime attempt counters from device row field 7
        (ops/decide.py accumulates every round's requested hits there —
        the durable, on-device view the windowed host tracker samples).
        Debug/test surface: one gather dispatch for the whole key list."""
        d = self.directory
        peek = getattr(d, "peek_slot", None)
        with self._lock:
            pairs = []
            for key in keys:
                if peek is not None:
                    slot = peek(key)
                else:
                    slot = dict(d.items()).get(key, -1)
                if slot >= 0:
                    pairs.append((key, int(slot)))
            if not pairs:
                return {}
            # direct fancy-index fetch: _gather serves the 7 snapshot
            # fields only, and this debug surface needn't be jitted
            rows = np.asarray(
                self.state[jnp.asarray([s for _, s in pairs], I32)])
        return {key: int(rows[i, 7]) for i, (key, _) in enumerate(pairs)}

    def rows_for_keys(self, keys):
        """Point-read the named keys' live rows -> (found_keys,
        rows i64[len(found), 7]) in BucketSnapshot field order — the
        reshard exporter's settle read (service/reshard.py): called under
        its authority fence, so the rows ARE the keys' final state on
        this node. Reconciles the native lone-path mirror first (like
        snapshot_slabs) so fast-path decisions newer than the device
        rows are included; keys that are absent, vacant, or expired are
        simply not in found_keys (the exporter sends them as vacant)."""
        now = millisecond_now()
        d = self.directory
        peek = getattr(d, "peek_slot", None)
        with self._lock:
            if hasattr(d, "mirror_flush"):
                while True:
                    inj = d.mirror_flush()
                    if not len(inj):
                        break
                    self._apply_inject_rows(inj)
            table = None if peek is not None else dict(d.items())
            pairs = []
            for key in keys:
                slot = peek(key) if peek is not None \
                    else table.get(key, -1)
                if slot >= 0:
                    pairs.append((key, int(slot)))
            if not pairs:
                return [], np.zeros((0, 7), np.int64)
            rows = np.asarray(
                self.state[jnp.asarray([s for _, s in pairs], I32)],
                np.int64)[:, :7]
        live = (rows[:, 0] >= 0) & (rows[:, 5] >= now)
        found = [key for (key, _), ok in zip(pairs, live) if ok]
        return found, np.ascontiguousarray(rows[live])

    # ------------------------------------------------------- persistence SPI

    def load_snapshot(self, items) -> int:
        """Seed table rows from a Loader (reference: gubernator.go:75-83).

        Consumes any iterable INCREMENTALLY (a streamed Loader at 10M keys
        must not be materialized: the dataclasses alone would cost
        gigabytes) — one max_width chunk of rows exists at a time. The
        engine lock is taken PER CHUNK and never while pulling the source
        iterator: the source may be this engine's own snapshot_stream
        (whose slab fetches take the same non-reentrant lock), and a
        Loader's file/JSON work must not stall serving for the whole
        restore."""
        import itertools

        it_stream = iter(items)
        n = 0
        while True:
            chunk = list(itertools.islice(it_stream, self.max_width))
            if not chunk:
                break
            with self._lock:
                slots, _ = self.directory.lookup([it.key for it in chunk])
                w = _bucket_width(len(chunk), self.min_width, self.max_width)
                pad = w - len(chunk)
                self.state = self._inject(
                    self.state,
                    jnp.asarray(slots + [-1] * pad, I32),
                    jnp.asarray([it.algo for it in chunk] + [0] * pad, I32),
                    jnp.asarray([it.limit for it in chunk] + [0] * pad, I64),
                    jnp.asarray([it.remaining for it in chunk] + [0] * pad, I64),
                    jnp.asarray([it.duration for it in chunk] + [0] * pad, I64),
                    jnp.asarray([it.stamp for it in chunk] + [0] * pad, I64),
                    jnp.asarray([it.expire_at for it in chunk] + [0] * pad, I64),
                    jnp.asarray([it.status for it in chunk] + [0] * pad, I32),
                )
                n += len(chunk)
        return n

    def load_snapshot_slabs(self, slabs) -> int:
        """Binary restore: consume (key_blob, key_offsets i64[m+1],
        rows i64[m, 7]) chunks — snapshot_slabs' shape — with no per-row
        host objects. Same locking contract as load_snapshot (the lock is
        taken per inject chunk, never while pulling the source)."""
        lookup_raw = getattr(self.directory, "lookup_raw", None)
        n = 0
        for blob, off, rows in slabs:
            off = np.asarray(off, np.int64)
            rows = np.asarray(rows, np.int64)
            m = len(off) - 1
            for s in range(0, m, self.max_width):
                e = min(s + self.max_width, m)
                cnt = e - s
                r = rows[s:e]
                with self._lock:
                    if lookup_raw is not None:
                        sub = bytes(blob[off[s]:off[e]])
                        slots, _fresh, _inj = lookup_raw(
                            sub, off[s:e + 1] - off[s])
                        slots = slots.astype(np.int64)
                    else:
                        keys = [blob[off[i]:off[i + 1]].decode("utf-8")
                                for i in range(s, e)]
                        got, _ = self.directory.lookup(keys)
                        slots = np.asarray(got, np.int64)
                    w = _bucket_width(cnt, self.min_width, self.max_width)
                    pad = w - cnt

                    def col(c, dtype):
                        return jnp.asarray(
                            np.pad(c, (0, pad)).astype(dtype))

                    self.state = self._inject(
                        self.state,
                        jnp.asarray(np.pad(slots, (0, pad),
                                           constant_values=-1), I32),
                        col(r[:, 0], np.int32), col(r[:, 1], np.int64),
                        col(r[:, 2], np.int64), col(r[:, 3], np.int64),
                        col(r[:, 4], np.int64), col(r[:, 5], np.int64),
                        col(r[:, 6], np.int32),
                    )
                    n += cnt
        return n

    # ~16 MB of rows per device->host slab: the streamed snapshot's peak
    # host footprint per step, and one compiled slice program total
    _SNAPSHOT_SLAB_ROWS = 1 << 18

    def snapshot_slabs(self, include_expired: bool = False):
        """Stream live rows as binary SLABS (reference: gubernator.go:86-105
        Close/save): yields (key_blob: bytes, key_offsets: i64[m+1],
        rows: i64[m, 7]) chunks with NO per-row host objects — the 10×
        lever over JSONL at production scale (VERDICT r4 item 5). Row
        field order matches BucketSnapshot: algo, limit, remaining,
        duration, stamp, expire_at, status.

        The naive dump at production scale is ruinous twice over: one
        gather dispatch per 8192-key chunk (1,200+ launches at 10M keys)
        and a fully-materialized list of 10M dataclasses (gigabytes of
        host objects). This generator fetches the table in fixed-shape
        row SLABS (one compiled dynamic-slice program, ~16 MB per fetch),
        filters each slab vectorized in numpy, and emits only the live
        rows — peak extra host memory is one slab plus its live subset,
        regardless of table size. Rows stream in slot order.

        Locking: the engine lock is taken PER SLAB, never across a yield
        (a suspended or leaked generator must not wedge the engine — the
        lock is non-reentrant and serving would block forever). Under a
        quiesced engine (shutdown, the normal snapshot moment) the cut is
        exact; under live traffic each slab is internally consistent and
        an entry whose slot was recycled between the directory walk and
        its slab is re-validated (one batch peek per slab) and skipped
        rather than attributed to the wrong key."""
        now = millisecond_now()
        with self._lock:
            if hasattr(self.directory, "mirror_flush"):
                # native lone-path decisions newer than the device rows
                # must reconcile before the gather
                while True:
                    inj = self.directory.mirror_flush()
                    if not len(inj):
                        break
                    self._apply_inject_rows(inj)
            if hasattr(self.directory, "items_raw"):
                blob, off, slots32 = self.directory.items_raw()
            else:  # python-twin directory: build the arena once
                entries = self.directory.items()
                keys_b = [k.encode("utf-8") for k, _ in entries]
                blob = b"".join(keys_b)
                off = np.zeros(len(keys_b) + 1, np.int64)
                if keys_b:
                    np.cumsum([len(b) for b in keys_b], out=off[1:])
                slots32 = np.fromiter((s for _, s in entries), np.int32,
                                      count=len(entries))
        n = len(slots32)
        if n == 0:
            return
        off = np.asarray(off, np.int64)
        lens = off[1:] - off[:-1]
        slots = slots32.astype(np.int64)
        order = np.argsort(slots, kind="stable")
        slots_sorted = slots[order]
        S = min(self._SNAPSHOT_SLAB_ROWS, self.capacity)
        slab_fn = _jit_slab(S)
        batch_peek = getattr(self.directory, "peek_slots_raw", None)
        peek_one = getattr(self.directory, "peek_slot", None)
        blob_arr = np.frombuffer(blob, np.uint8)

        def gather_keys(sel):
            """Vectorized sub-arena build: the selected keys' bytes and
            offsets without a python loop over 256K slices."""
            ln = lens[sel]
            sub_off = np.zeros(sel.size + 1, np.int64)
            np.cumsum(ln, out=sub_off[1:])
            total = int(sub_off[-1])
            # absolute byte positions: each key's start repeated over its
            # length, plus the within-key offset
            pos = np.repeat(off[sel] - sub_off[:-1], ln) + \
                np.arange(total, dtype=np.int64)
            return blob_arr[pos].tobytes(), sub_off

        for a in range(0, self.capacity, S):
            lo, hi = np.searchsorted(slots_sorted, (a, a + S))
            if lo == hi:
                continue  # no directory entries in this row range
            # dynamic_slice CLAMPS an out-of-range start: fetch the
            # final partial slab from capacity-S and index relative to
            # the clamped start (it still covers [a, capacity))
            cs = min(a, self.capacity - S)
            with self._lock:
                slab = np.asarray(slab_fn(self.state, cs))
            idx = order[lo:hi]  # original entry index, slot order
            ent_slots = slots_sorted[lo:hi]
            rows = slab[ent_slots - cs]  # [n, 8] in slot order
            live = rows[:, 0] >= 0  # algo < 0 marks a vacant row
            if not include_expired:
                live &= rows[:, 5] >= now
            sel = idx[live]
            if sel.size == 0:
                continue
            ent_sel = ent_slots[live].astype(np.int32)
            sub_blob, sub_off = gather_keys(sel)
            # slot recycled mid-dump: not this key's row anymore
            if batch_peek is not None:
                okm = batch_peek(sub_blob, sub_off) == ent_sel
            elif peek_one is not None:
                okm = np.fromiter(
                    (peek_one(sub_blob[sub_off[k]:sub_off[k + 1]]
                              .decode("utf-8")) == int(s)
                     for k, s in enumerate(ent_sel)), bool, count=sel.size)
            else:
                okm = np.ones(sel.size, bool)
            rows_live = rows[live]
            if not okm.all():
                keep = np.flatnonzero(okm)
                sub_blob, sub_off = gather_keys(sel[keep])
                rows_live = rows_live[keep]
            yield sub_blob, sub_off, np.ascontiguousarray(rows_live[:, :7])

    def snapshot_stream(self, include_expired: bool = False):
        """Stream live rows as BucketSnapshots — the object-level view of
        snapshot_slabs (same walk, same ordering, same consistency
        contract); slab-level consumers (the binary Loader) should use
        snapshot_slabs directly and skip 10M dataclass constructions."""
        for blob, off, rows in self.snapshot_slabs(include_expired):
            for j in range(len(off) - 1):
                r = rows[j]
                yield BucketSnapshot(
                    key=blob[off[j]:off[j + 1]].decode("utf-8"),
                    algo=int(r[0]), limit=int(r[1]), remaining=int(r[2]),
                    duration=int(r[3]), stamp=int(r[4]),
                    expire_at=int(r[5]), status=int(r[6]))

    def snapshot(self, include_expired: bool = False) -> List[BucketSnapshot]:
        """Materialized snapshot_stream (small tables / tests). At
        production scale prefer streaming straight into the Loader."""
        return list(self.snapshot_stream(include_expired))

    def close(self) -> None:
        """Persist via the Loader, mirroring daemon shutdown
        (reference: gubernator.go:86-105). A slab-capable Loader gets the
        binary stream (no per-row objects); plain Loaders keep the
        BucketSnapshot SPI."""
        if self.loader is not None:
            if hasattr(self.loader, "save_slabs"):
                self.loader.save_slabs(self.snapshot_slabs())
            else:
                self.loader.save(self.snapshot_stream())

    # ------------------------------------------------------------- internals

    # Multi-window groups ride one lax.scan dispatch; cap the group so the
    # staging buffer and the set of compiled scan depths stay small. Scan
    # groups are always min_width wide, so warmup() can pre-compile every
    # (depth, width) shape this path can ever dispatch.
    _MAX_SCAN = 32

    def _split_scannable(self, windows):
        """Split the window list into a per-round head and a scannable tail.

        The tail is the maximal run of trailing windows no wider than
        min_width — round sizes only shrink (round k+1's keys are a subset of
        round k's), so the small windows the scan path exists for (duplicate-
        key rounds; a hot-key herd is d one-item rounds) always sit at the
        end. Wide windows keep the per-round path: they are one amortized
        dispatch already, and admitting them would make the scan width
        unbounded (unwarmable shapes, oversized padding).

        A Store keeps the scan path (VERDICT r2 item 5): its hooks batch to
        one read-through before the tail (on the tail's first window — a
        superset of every later round's keys, so it covers the whole tail)
        and one write-through after it with each key's FINAL post-tail row.
        The reference pays one OnChange per hit (algorithms.go:64-68); the
        batched design persists the same end state in one host call per
        window (PARITY #8). The capacity guard keeps a group's up-front
        directory lookups from recycling a slot an earlier window in the
        group already claimed.
        """
        if len(windows) <= 1:
            return windows, []
        split = len(windows)
        while split > 0 and len(windows[split - 1]) <= self.min_width:
            split -= 1
        tail = windows[split:]
        if len(tail) < 2 or sum(len(w) for w in tail) * 4 > self.capacity:
            return windows, []
        return windows[:split], tail

    def _apply_windows_scanned(self, windows, now_ms, responses) -> None:
        """Retire every scannable window in ⌈N/32⌉ dispatches.

        The worst case this exists for is a hot-key thundering herd: d
        duplicates of one key = d rounds, which the per-round path pays d
        full dispatches for — launch overhead (plus a network round trip on
        a tunneled device) per dispatch, while the kernel body is cheap."""
        stage = self.stats.stage_ns
        width = self.min_width  # _split_scannable guarantees every window fits
        union = None  # per-key first occurrence across the WHOLE tail
        if self.store is not None and windows:
            # one batched read-through / write-through for the WHOLE tail,
            # over the union of its keys. (The first window alone is NOT a
            # superset: when round 0 chunks at max_width, a later round's
            # keys may live in a HEAD chunk — e.g. rounds [64+2, 4, 4]
            # split the 4 duplicated keys away from tail window 0.)
            seen_keys = {}
            for wk in windows:
                for item in wk:
                    k = item[1].hash_key()
                    if k not in seen_keys:
                        seen_keys[k] = item
            union_items = list(seen_keys.items())  # [(key, item)], in order
            t = time.perf_counter_ns()
            ukeys = [k for k, _ in union_items]
            uslots, ufresh, inj0 = self.directory.lookup_inject(ukeys)
            self._apply_inject_rows(inj0)
            t2 = time.perf_counter_ns()
            stage["lookup"] += t2 - t
            uwork = [it for _, it in union_items]
            ufresh = self._store_read_through(
                uwork, ukeys, uslots, ufresh, now_ms)
            stage["store"] += time.perf_counter_ns() - t2
            union = (uwork, ukeys, uslots)
            # Per-window slot/fresh come from THIS lookup, not re-lookups:
            # a second directory lookup would clear the fresh flag of any
            # first-occurrence key in a LATER tail window (round 0 chunked
            # at max_width), making the kernel treat a recycled slot's
            # stale row as live. `fresh` is consumed by the key's first
            # window; later rounds of the same key see False.
            slot_map = dict(zip(ukeys, uslots))
            fresh_map = {k: f for k, f in zip(ukeys, ufresh) if f}
        for g0 in range(0, len(windows), self._MAX_SCAN):
            group = windows[g0:g0 + self._MAX_SCAN]
            if len(group) == 1:
                # a trailing singleton (e.g. 33 windows -> groups [32, 1])
                # rides the already-warmed single-window program; warmup
                # compiles scan depths {2..32} only
                resolved = None
                if union is not None:
                    wk = group[0]
                    ks = [item[1].hash_key() for item in wk]
                    resolved = ([slot_map[k] for k in ks],
                                [fresh_map.pop(k, False) for k in ks])
                self._apply_round(group[0], now_ms, responses,
                                  skip_store=self.store is not None,
                                  resolved=resolved)
                continue
            k = _bucket_pow2(len(group))
            stacked = np.zeros((k, 9, width), np.int64)
            stacked[:, 0, :] = -1  # pad windows are all padding lanes
            host_ns = 0
            for gi, wk in enumerate(group):
                t = time.perf_counter_ns()
                if union is not None:
                    keys = [item[1].hash_key() for item in wk]
                    slots = [slot_map[k] for k in keys]
                    fresh = [fresh_map.pop(k, False) for k in keys]
                else:
                    keys = [item[1].hash_key() for item in wk]
                    slots, fresh, inj = self.directory.lookup_inject(keys)
                    self._apply_inject_rows(inj)
                t2 = time.perf_counter_ns()
                stage["lookup"] += t2 - t
                pack_window(wk, slots, fresh, width, out=stacked[gi])
                t3 = time.perf_counter_ns()
                stage["pack"] += t3 - t2
                host_ns += t3 - t
            prof = self.profiler
            prof.observe("prep", host_ns)
            t = time.perf_counter_ns()
            staged = self._dispatch_scan_staged(stacked, now_ms)
            td = time.perf_counter_ns()
            out = self._fetch_staged(staged)
            t2 = time.perf_counter_ns()
            stage["device"] += t2 - t
            self._obs_device(t2 - t, sum(len(w) for w in group))
            prof.observe("dispatch", td - t)
            prof.observe("readback", t2 - td)
            led = self.ledger
            for gi, wk in enumerate(group):
                n = len(wk)
                status, limit, remaining, reset = out[gi, :, :n].tolist()
                for j, (i, _r, _ge, _gi) in enumerate(wk):
                    st = status[j]
                    if st == 1:
                        self.stats.over_limit += 1
                    responses[i] = RateLimitResp(
                        status=st, limit=limit[j],
                        remaining=remaining[j], reset_time=reset[j])
                if led is not None and led.enabled:
                    led.note_slots(stacked[gi], out[gi], n)
            demux_ns = time.perf_counter_ns() - t2
            stage["demux"] += demux_ns
            prof.observe("demux", demux_ns)
        if union is not None:
            # one batched write-through with each key's FINAL post-tail row
            uwork, ukeys, uslots = union
            t = time.perf_counter_ns()
            self._store_write_through(uwork, ukeys, uslots, now_ms)
            stage["store"] += time.perf_counter_ns() - t

    def _apply_round(self, round_work, now_ms, responses,
                     skip_store: bool = False, resolved=None) -> None:
        """One window, one dispatch. `skip_store` marks a tail singleton
        inside _apply_windows_scanned, whose batched read/write-through
        already covers these keys; `resolved` carries that pass's
        (slots, fresh) so no re-lookup clears a fresh flag. Caller holds
        the engine lock."""
        stage = self.stats.stage_ns
        prof = self.profiler
        n = len(round_work)
        t = time.perf_counter_ns()
        keys = [item[1].hash_key() for item in round_work]
        if resolved is not None:
            slots, fresh = resolved
        else:
            slots, fresh, inj = self.directory.lookup_inject(keys)
            self._apply_inject_rows(inj)
        lookup_ns = time.perf_counter_ns() - t
        stage["lookup"] += lookup_ns

        use_store = self.store is not None and not skip_store
        if use_store:
            t = time.perf_counter_ns()
            fresh = self._store_read_through(round_work, keys, slots, fresh, now_ms)
            stage["store"] += time.perf_counter_ns() - t

        w = _bucket_width(n, self.min_width, self.max_width)
        # one staging buffer up, one back: off-chip round trips are the
        # serving path's dominant cost, so the window crosses exactly twice
        t = time.perf_counter_ns()
        packed = pack_window(round_work, slots, fresh, w)
        t2 = time.perf_counter_ns()
        stage["pack"] += t2 - t
        # lookup + pack are host prep in the profiler's cycle taxonomy
        prof.observe("prep", lookup_ns + (t2 - t))
        staged = self._dispatch_staged(packed, now_ms)
        td = time.perf_counter_ns()
        out = self._fetch_staged(staged)
        t3 = time.perf_counter_ns()
        stage["device"] += t3 - t2
        self._obs_device(t3 - t2, n)
        prof.observe("dispatch", td - t2)
        prof.observe("readback", t3 - td)

        # one C-level tolist beats four per-element int() casts per lane
        status, limit, remaining, reset = out[:, :n].tolist()
        for j, (i, _r, _ge, _gi) in enumerate(round_work):
            st = status[j]
            if st == 1:
                self.stats.over_limit += 1
            responses[i] = RateLimitResp(
                status=st, limit=limit[j], remaining=remaining[j],
                reset_time=reset[j])
        demux_ns = time.perf_counter_ns() - t3
        stage["demux"] += demux_ns
        prof.observe("demux", demux_ns)
        led = self.ledger
        if led is not None and led.enabled:
            led.note_slots(packed, out, n)

        if use_store:
            t = time.perf_counter_ns()
            self._store_write_through(round_work, keys, slots, now_ms)
            stage["store"] += time.perf_counter_ns() - t

    def _store_read_through(self, round_work, keys, slots, fresh, now_ms):
        """Consult the store for rows the table can't serve
        (reference: algorithms.go:26-33). Caller holds the engine lock."""
        slot_arr = jnp.asarray(slots, I32)
        algo_c, _, _, _, _, exp_c, _ = (np.asarray(c) for c in
                                        self._gather(self.state, slot_arr))
        inj = {"slot": [], "algo": [], "limit": [], "remaining": [],
               "duration": [], "stamp": [], "expire_at": [], "status": []}
        fresh = list(fresh)
        for j, (i, r, _ge, _gi) in enumerate(round_work):
            live = not fresh[j] and int(algo_c[j]) >= 0 and now_ms <= int(exp_c[j])
            if live and int(algo_c[j]) != int(r.algorithm):
                # algorithm switch discards the old bucket everywhere
                # (reference: algorithms.go:54-62)
                self.store.remove(keys[j])
                live = False
            if live:
                continue
            item = self.store.get(r)
            if item is None:
                continue
            inj["slot"].append(slots[j])
            inj["algo"].append(item.algo)
            inj["limit"].append(item.limit)
            inj["remaining"].append(item.remaining)
            inj["duration"].append(item.duration)
            inj["stamp"].append(item.stamp)
            inj["expire_at"].append(item.expire_at)
            inj["status"].append(item.status)
            fresh[j] = False  # the injected row is now live
        if inj["slot"]:
            m = len(inj["slot"])
            w = _bucket_width(m, self.min_width, self.max_width)
            pad = w - m
            self.state = self._inject(
                self.state,
                jnp.asarray(inj["slot"] + [-1] * pad, I32),
                jnp.asarray(inj["algo"] + [0] * pad, I32),
                jnp.asarray(inj["limit"] + [0] * pad, I64),
                jnp.asarray(inj["remaining"] + [0] * pad, I64),
                jnp.asarray(inj["duration"] + [0] * pad, I64),
                jnp.asarray(inj["stamp"] + [0] * pad, I64),
                jnp.asarray(inj["expire_at"] + [0] * pad, I64),
                jnp.asarray(inj["status"] + [0] * pad, I32),
            )
        return fresh

    def _store_write_through(self, round_work, keys, slots, now_ms):
        """Report post-decision rows (reference: algorithms.go:64-68,175-177);
        discarded buckets get `remove` (reference: algorithms.go:37-39,57-59).
        Caller holds the engine lock."""
        slot_arr = jnp.asarray(slots, I32)
        cols = [np.asarray(c) for c in self._gather(self.state, slot_arr)]
        for j, (i, r, _ge, _gi) in enumerate(round_work):
            algo = int(cols[0][j])
            if algo < 0:
                # token RESET_REMAINING cleared the row
                self.store.remove(keys[j])
                self.directory.drop(keys[j])
                continue
            self.store.on_change(r, BucketSnapshot(
                key=keys[j], algo=algo, limit=int(cols[1][j]),
                remaining=int(cols[2][j]), duration=int(cols[3][j]),
                stamp=int(cols[4][j]), expire_at=int(cols[5][j]),
                status=int(cols[6][j])))
