from gubernator_tpu.models.keyspace import KeyDirectory
from gubernator_tpu.models.engine import Engine

__all__ = ["KeyDirectory", "Engine"]
