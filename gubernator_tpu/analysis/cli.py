"""guberlint CLI: `make lint` / `python -m gubernator_tpu.analysis`.

Exit 0 on a clean tree, 1 when any unwaived finding exists. The output
format is one `path:line: [rule] message` per finding — editor- and
grep-friendly, same shape as the compiler diagnostics it complements.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from gubernator_tpu.analysis import core

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        "guberlint",
        description="AST-driven invariant analyzer for gubernator_tpu")
    parser.add_argument("--root", default=REPO_ROOT,
                        help="repo checkout to analyze (default: this one)")
    parser.add_argument("--only", default="",
                        help="comma-separated rule ids to run")
    parser.add_argument("--list", action="store_true", dest="list_rules",
                        help="print the rule catalogue and exit")
    parser.add_argument("--show-waived", action="store_true",
                        help="also print suppressed findings + waivers")
    opts = parser.parse_args(argv)

    rules = core.all_rules()
    if opts.list_rules:
        for rid in sorted(rules):
            print(f"{rid:24s} {rules[rid].doc}")
        return 0

    only = [r for r in opts.only.split(",") if r]
    try:
        findings, suppressed = core.run(opts.root, only=only)
    except ValueError as e:
        print(f"guberlint: {e}", file=sys.stderr)
        return 2

    for f in findings:
        print(f.render())
    if opts.show_waived:
        for f, w in suppressed:
            print(f"WAIVED {f.render()}  [-- {w.justification}]")
    ran = ", ".join(sorted(only or rules))
    if findings:
        print(f"\nguberlint: {len(findings)} finding(s) "
              f"({len(suppressed)} waived) across rules: {ran}")
        return 1
    print(f"guberlint: clean ({len(suppressed)} waived) "
          f"across rules: {ran}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
