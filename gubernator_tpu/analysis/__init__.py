"""guberlint: AST-driven invariant analysis for gubernator_tpu.

The repo's load-bearing disciplines as tier-1 gates — see
docs/static-analysis.md for the rule catalogue and the historical bug
each rule guards against. Run via `make lint` or
`python -m gubernator_tpu.analysis`.
"""

from gubernator_tpu.analysis.core import (  # noqa: F401
    Finding,
    RepoIndex,
    Rule,
    all_rules,
    register,
    run,
)
