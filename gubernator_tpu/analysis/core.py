"""guberlint core: rule registry, repo index, waivers, findings.

The repo's load-bearing disciplines — donated-buffer reads under the
engine lock, no blocking calls inside a lock scope, GUBER_* knobs flowing
through envconf -> example.conf -> docs, escape hatches with differential
tests, metric/event/fault registries in sync with their docs — existed
only as convention and review memory. This package turns each one into a
machine-checked invariant: every rule is grounded in a real historical
bug (docs/static-analysis.md catalogues them), `make lint` runs the set,
and tests/test_lint.py makes zero-findings-on-HEAD a tier-1 gate the same
way `make bench-check` gates perf.

Waiver syntax (inline, justification REQUIRED after ``--``)::

    x = backend.state  # guberlint: disable=lock-discipline -- stub backend has no lock

A waiver on its own line covers the next code line; a file-scoped
variant (``guberlint: file-disable`` with the same ``=rule -- why``
tail) anywhere in the file covers the whole file. A waiver without a
justification is itself a finding (rule ``waiver-syntax``) — the
justification is the reviewable artifact.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# `#` for python/conf, `//` for the C++ sources
WAIVER_RE = re.compile(
    r"(?:#|//)\s*guberlint:\s*(file-)?disable=([a-z0-9_,-]+)"
    r"\s*(?:--\s*(.*?))?\s*$")

# anything that looks like a waiver attempt but fails WAIVER_RE is a
# malformed waiver, reported rather than silently ignored
_WAIVERISH_RE = re.compile(r"(?:#|//)\s*guberlint:\s*(?:file-)?disable")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a concrete location."""

    rule: str
    path: str  # repo-relative
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclasses.dataclass
class Waiver:
    rule: str
    line: int  # line the waiver comment sits on
    file_scope: bool
    justification: str

    def covers(self, rule: str, line: int) -> bool:
        if self.rule not in (rule, "all"):
            return False
        # same line, or a standalone waiver comment covering the next line
        return self.file_scope or line in (self.line, self.line + 1)


class SourceFile:
    """One scanned file: text, lines, lazy AST, parsed waivers."""

    def __init__(self, root: str, relpath: str):
        self.root = root
        self.relpath = relpath
        with open(os.path.join(root, relpath), encoding="utf-8",
                  errors="replace") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self._tree: Optional[ast.Module] = None
        self._tree_error: Optional[str] = None
        self.waivers: List[Waiver] = []
        self.waiver_findings: List[Finding] = []
        self._parse_waivers()

    @property
    def tree(self) -> Optional[ast.Module]:
        if self._tree is None and self._tree_error is None:
            try:
                self._tree = ast.parse(self.text)
            except SyntaxError as e:  # non-Python or broken file
                self._tree_error = str(e)
        return self._tree

    def _parse_waivers(self) -> None:
        for i, line in enumerate(self.lines, 1):
            m = WAIVER_RE.search(line)
            if not m:
                if _WAIVERISH_RE.search(line):
                    self.waiver_findings.append(Finding(
                        "waiver-syntax", self.relpath, i,
                        "unparseable guberlint waiver (want a comment of "
                        "the form 'guberlint: "
                        "disable=<rule-id> -- <justification>')"))
                continue
            file_scope = bool(m.group(1))
            rules = [r for r in m.group(2).split(",") if r]
            justification = (m.group(3) or "").strip()
            if not justification:
                self.waiver_findings.append(Finding(
                    "waiver-syntax", self.relpath, i,
                    "guberlint waiver without a justification — append "
                    "'-- <why this is safe>'"))
                continue
            for rule in rules:
                self.waivers.append(
                    Waiver(rule, i, file_scope, justification))

    def waived(self, rule: str, line: int) -> Optional[Waiver]:
        for w in self.waivers:
            if w.covers(rule, line):
                return w
        return None


class RepoIndex:
    """Lazy file index rules query. `root` is the repo checkout; rules
    address files by repo-relative path so a corpus test can point the
    same rule at a miniature fake repo (tests/test_lint_corpus.py)."""

    # python trees the AST rules walk (repo-relative)
    CODE_DIRS = ("gubernator_tpu", "scripts")
    CODE_FILES = ("bench.py",)

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._files: Dict[str, Optional[SourceFile]] = {}

    # ------------------------------------------------------------ access

    def exists(self, relpath: str) -> bool:
        return os.path.exists(os.path.join(self.root, relpath))

    def get(self, relpath: str) -> Optional[SourceFile]:
        """SourceFile for `relpath`, or None when absent (corpus repos
        carry only the files their rule under test needs)."""
        if relpath not in self._files:
            if self.exists(relpath):
                self._files[relpath] = SourceFile(self.root, relpath)
            else:
                self._files[relpath] = None
        return self._files[relpath]

    def walk(self, subdir: str, suffix: str = ".py") -> List[str]:
        """Sorted repo-relative paths under `subdir` with `suffix`."""
        base = os.path.join(self.root, subdir)
        out: List[str] = []
        for dirpath, dirnames, filenames in os.walk(base):
            # lint_corpus holds the golden-violation corpus — miniature
            # fake repos full of DELIBERATE findings and malformed
            # waivers (tests/test_lint_corpus.py points rules at them
            # one root at a time); the real repo scan must never recurse
            # into it
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git", ".jax_cache",
                                        "lint_corpus")]
            for name in sorted(filenames):
                if name.endswith(suffix):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, name), self.root))
        return sorted(out)

    def python_files(self) -> List[str]:
        """Every non-test python file the repo-wide rules scan."""
        out: List[str] = []
        for d in self.CODE_DIRS:
            if self.exists(d):
                out.extend(self.walk(d, ".py"))
        for f in self.CODE_FILES:
            if self.exists(f):
                out.append(f)
        return out


class Rule:
    """Base class; subclasses set `id`/`doc` and implement check()."""

    id: str = ""
    doc: str = ""  # one-line invariant statement (rule catalogue)

    def check(self, repo: RepoIndex) -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: instantiate and register a Rule."""
    rule = rule_cls()
    if not rule.id:
        raise ValueError(f"{rule_cls.__name__} has no id")
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _REGISTRY[rule.id] = rule
    return rule_cls


def all_rules() -> Dict[str, Rule]:
    # import for side effect: rule modules self-register
    from gubernator_tpu.analysis import rules  # noqa: F401

    return dict(_REGISTRY)


def run(root: str, only: Sequence[str] = (),
        ) -> Tuple[List[Finding], List[Tuple[Finding, Waiver]]]:
    """Run rules against the checkout at `root`.

    Returns (findings, suppressed): `findings` is what gates CI;
    `suppressed` pairs each waived finding with its waiver so the corpus
    test can prove waivers actually suppress and operators can audit the
    waiver inventory (`--show-waived`).
    """
    repo = RepoIndex(root)
    rules = all_rules()
    if only:
        unknown = sorted(set(only) - set(rules))
        if unknown:
            raise ValueError(f"unknown rule id(s): {unknown}")
        rules = {k: v for k, v in rules.items() if k in only}

    findings: List[Finding] = []
    suppressed: List[Tuple[Finding, Waiver]] = []
    seen: set = set()  # several AST nodes can yield one logical finding
    for rule in rules.values():
        for f in rule.check(repo):
            if f in seen:
                continue
            seen.add(f)
            sf = repo.get(f.path)
            waiver = sf.waived(f.rule, f.line) if sf is not None else None
            if waiver is not None:
                suppressed.append((f, waiver))
            else:
                findings.append(f)
    # malformed waivers are findings regardless of which rules ran
    for relpath, sf in list(repo._files.items()):  # noqa: SLF001
        if sf is not None:
            findings.extend(sf.waiver_findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, suppressed


# --------------------------------------------------------------- helpers

def iter_lock_withs(tree: ast.AST):
    """Yield (With node, lock item expr) for every `with <lock>` scope.

    A with-item counts as a lock when its source rendering mentions
    'lock' — matches every discipline the repo uses: `with self._lock`,
    `with eng._lock`, `with lock:`, `with self._peer_lock`."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                src = ast.unparse(item.context_expr)
                if "lock" in src.lower():
                    yield node, item.context_expr
                    break


def node_lines(node: ast.AST) -> Tuple[int, int]:
    return node.lineno, getattr(node, "end_lineno", node.lineno)


def enclosing_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    """child -> parent map (ast has no parent pointers)."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents
