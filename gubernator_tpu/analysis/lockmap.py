"""lockmap: whole-repo static lock-order analysis (layer 1).

guberlint's lexical rules (`lock-discipline`, `blocking-under-lock`)
check what happens *inside* one lock scope; they cannot see the order in
which two scopes nest across functions — the bug class behind the PR 14
reshard NOT_MINE/PLANNING deflakes. This module builds the repo's
acquisition-order digraph and proves it acyclic:

1. **Harvest the lock identity model.** Every load-bearing lock is
   constructed through `obs/witness.py`'s factories with a canonical
   class-name literal (`witness.make_lock("engine")`); the harvest reads
   those literals straight from the construction sites, so the static
   graph and the runtime witness share node names by construction. Raw
   `threading.Lock()` assignments that bypass the factories still get
   auto-derived names (`<modstem>.<attr>`) so nothing hides from the
   graph.

2. **Resolve every acquisition site.** `with <expr>:` scopes and bare
   `.acquire()` calls are canonicalized back to a lock class: `self.X`
   through the enclosing class's construction sites, condition aliases
   (`self._cond = threading.Condition(self._lock)`) through their
   backing lock, other receivers through a repo-unique attribute match.
   Lock-ish expressions that stay unresolvable are counted and surfaced
   in the report — an unresolved lock is a hole in the proof, not a
   silent pass.

3. **Follow calls made while a lock is held.** A bounded interprocedural
   walk (repo-own modules only, call depth ``MAX_CALL_DEPTH``) computes
   for each function the set of lock classes it may transitively
   acquire; every acquisition reachable under a held lock contributes an
   edge `held -> acquired` with a `path:line` witness chain recording
   the call hops.

4. **Cycles are findings.** Any strongly-connected component (including
   a non-reentrant class that can re-acquire itself through a call
   chain) yields a `lock-order` finding anchored at the first witness
   site, waivable with justification like every guberlint rule.

The committed `lockmap.json` pins the graph in both directions (`make
lockmap` / tests/test_lockmap.py): an edge the analysis no longer
produces AND an edge the baseline doesn't carry both fail, the same
two-direction discipline `registry-drift` applies to event kinds. The
runtime witness (obs/witness.py) then checks real executions against the
same committed edge set.

This module also hosts the **donated-buffer lifetime dataflow** behind
the `donation-flow` rule: within each function it tracks local names
captured from a donated device-array attribute (`v = backend.state`),
finds the donate-and-rebind dispatch (`X.state, r = f(X.state, ...)`),
and flags any later read of the stale capture that is not preceded by a
fresh re-read — the exact PR 10 cartographer bug class, found by
dataflow instead of lexical matching.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from gubernator_tpu.analysis.core import RepoIndex

# factory name -> lock kind (reentrant kinds may self-nest)
_FACTORIES = {
    "make_lock": "lock",
    "make_rlock": "rlock",
    "make_condition": "rcondition",
}
_REENTRANT_KINDS = frozenset({"rlock", "rcondition"})

# bounded call-graph walk: a chain of more hops than this is treated as
# not acquiring (under-approximation; the runtime witness is the
# backstop for anything deeper)
MAX_CALL_DEPTH = 4

# expressions that *look* like synchronization but resolve to no class
# are reported as holes; anything else (`with open(...)`) is ignored
_LOCKISH_RE = re.compile(r"lock(?!map)|cond|mutex|_gate\b", re.IGNORECASE)

# the witness IS the runtime half of this analysis: its internal mutex
# guards pure dict bookkeeping and never calls out while held, and its
# wrapper classes would read as lock constructions. Excluded wholesale.
_SKIP_FILES = frozenset({"gubernator_tpu/obs/witness.py"})

# the duck-typed call fallback (resolve a method by repo-unique name)
# must never fire for names shared with builtin containers/stdlib
# objects — `self._ring.clear()` is a deque, not EventRing.clear
_COMMON_METHODS = frozenset({
    "accept", "acquire", "add", "append", "appendleft", "bind", "cancel",
    "clear", "close", "connect", "copy", "count", "debug", "decode",
    "discard", "encode", "error", "exception", "extend", "flush", "format",
    "get", "info", "items", "join", "keys", "listen", "notify",
    "notify_all", "pop", "popleft", "put", "read", "recv", "release",
    "remove", "result", "send", "set", "setdefault", "sort",
    "split", "start", "strip", "submit", "update", "values", "wait",
    "warning", "write",
})

# inheritance chains walked when resolving `self.X` / `self.m()` that
# the class itself doesn't define
_MAX_MRO_DEPTH = 5

# attributes holding donated device arrays (same set as rules/locks.py)
DONATED_ATTRS = frozenset({"state", "fps", "touch"})
_ENGINEISH_RE = re.compile(r"(^|\.)_?(backend|engine|eng)$")


@dataclasses.dataclass(frozen=True)
class LockSite:
    path: str
    line: int

    def render(self) -> str:
        return f"{self.path}:{self.line}"


@dataclasses.dataclass
class LockClass:
    name: str
    kind: str  # lock | rlock | rcondition
    sites: List[LockSite]
    registered: bool  # True: witness factory; False: auto-named raw lock


@dataclasses.dataclass(frozen=True)
class Edge:
    src: str
    dst: str
    # each witness is a chain of "path:line" hops: the outer acquisition
    # site, then call sites, ending at the inner acquisition site
    witness: Tuple[str, ...]


class LockGraph:
    """The built graph plus everything the report and rules need."""

    def __init__(self):
        self.classes: Dict[str, LockClass] = {}
        self.edges: Dict[Tuple[str, str], List[Tuple[str, ...]]] = {}
        self.unresolved: List[Tuple[str, int, str]] = []  # path, line, expr

    def add_edge(self, src: str, dst: str, witness: Sequence[str]) -> None:
        chains = self.edges.setdefault((src, dst), [])
        w = tuple(witness)
        if w not in chains and len(chains) < 5:  # cap per-edge provenance
            chains.append(w)

    def edge_pairs(self) -> List[Tuple[str, str]]:
        return sorted(self.edges)

    def cycles(self) -> List[List[str]]:
        """Strongly-connected components with >1 node, plus self-loops
        on non-reentrant classes, as sorted node lists."""
        out: List[List[str]] = []
        for comp in _tarjan_sccs(
                sorted(self.classes),
                {n: sorted({d for (s, d) in self.edges if s == n})
                 for n in self.classes}):
            if len(comp) > 1:
                out.append(sorted(comp))
        for (s, d) in self.edges:
            if s == d and self.classes.get(s) is not None \
                    and self.classes[s].kind not in _REENTRANT_KINDS:
                out.append([s])
        return sorted(out)

    def cycle_edges(self, cycle: List[str]) -> List[Edge]:
        """The edges internal to one cycle, each with its first witness."""
        nodes = set(cycle)
        out = []
        for (s, d), chains in sorted(self.edges.items()):
            if s in nodes and d in nodes and (len(cycle) > 1 or s == d):
                out.append(Edge(s, d, chains[0]))
        return out


def _tarjan_sccs(nodes: List[str],
                 succ: Dict[str, List[str]]) -> List[List[str]]:
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan (the call graph walk already recurses; keep
        # the SCC pass safe from deep graphs)
        work = [(v, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recursed = False
            children = succ.get(node, [])
            while pi < len(children):
                w = children[pi]
                pi += 1
                work[-1] = (node, pi)
                if w not in index:
                    work.append((w, 0))
                    recursed = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if recursed:
                continue
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for v in nodes:
        if v not in index:
            strongconnect(v)
    return sccs


# ---------------------------------------------------------------- build


def build(repo: RepoIndex) -> LockGraph:
    """Build the acquisition-order graph for the checkout behind `repo`.

    Memoized on the RepoIndex instance: the `lock-order` rule, the drift
    check, and the report all share one build per run."""
    cached = getattr(repo, "_lockmap_graph", None)
    if cached is not None:
        return cached
    b = _Builder(repo)
    graph = b.run()
    repo._lockmap_graph = graph  # noqa: SLF001 - intentional memo slot
    return graph


class _FuncInfo:
    __slots__ = ("key", "path", "node", "cls")

    def __init__(self, key, path, node, cls):
        self.key = key  # (path, classname_or_None, funcname)
        self.path = path
        self.node = node
        self.cls = cls


class _Builder:
    def __init__(self, repo: RepoIndex):
        self.repo = repo
        self.graph = LockGraph()
        # (path, classname_or_None, attr) -> lock class name
        self.reg: Dict[Tuple[str, Optional[str], str], str] = {}
        # condition aliases resolved after harvest:
        # (path, cls, attr) -> (path, cls, backing_attr)
        self.aliases: Dict[Tuple[str, Optional[str], str],
                           Tuple[str, Optional[str], str]] = {}
        # attr -> set of lock class names (repo-unique fallback)
        self.by_attr: Dict[str, Set[str]] = {}
        self.funcs: Dict[Tuple, _FuncInfo] = {}
        self.methods_by_name: Dict[str, List[Tuple]] = {}
        self.mod_funcs: Dict[str, Dict[str, Tuple]] = {}
        # per-module import alias -> module relpath (module imports AND
        # from-imports of classes, mapped to the defining module)
        self.imports: Dict[str, Dict[str, str]] = {}
        # (path, classname) -> list of base-expression strings
        self.class_bases: Dict[Tuple[str, str], List[str]] = {}
        # (path, class, attr) -> (path, class) of the repo type the
        # attr is constructed as (`self._global_cache = LRUCache(...)`)
        self.attr_types: Dict[Tuple[str, Optional[str], str],
                              Tuple[str, str]] = {}
        self._summaries: Dict[Tuple, Dict[str, Tuple[str, ...]]] = {}
        self._in_progress: Set[Tuple] = set()
        self._aliases_memo: Dict[Tuple, Dict[str, str]] = {}
        self._cur_aliases: Dict[str, str] = {}

    # ------------------------------------------------------------- run

    def run(self) -> LockGraph:
        files = self.repo.python_files()
        trees = {}
        for relpath in files:
            if relpath in _SKIP_FILES:
                continue
            sf = self.repo.get(relpath)
            if sf is not None and sf.tree is not None:
                trees[relpath] = sf.tree
        for relpath, tree in trees.items():
            self._index_functions(relpath, tree)
            self._index_imports(relpath, tree)
        for relpath, tree in trees.items():
            self._harvest(relpath, tree)
        self._resolve_aliases()
        for info in self.funcs.values():
            self._walk_function(info)
        return self.graph

    # --------------------------------------------------------- harvest

    def _register(self, key: Tuple[str, Optional[str], str], name: str,
                  kind: str, site: LockSite, registered: bool,
                  is_attr: bool = True) -> None:
        self.reg[key] = name
        cls = self.graph.classes.get(name)
        if cls is None:
            self.graph.classes[name] = LockClass(name, kind, [site],
                                                 registered)
        else:
            if site not in cls.sites:
                cls.sites.append(site)
            if registered and not cls.registered:
                cls.registered = True
        # the attr-unique fallback map: auto-named bare-Name locks
        # (function locals, script helpers) stay out of it — a local
        # `lock = threading.Lock()` in a CLI must not shadow `self.lock`
        # resolution elsewhere
        if registered or is_attr:
            self.by_attr.setdefault(key[2], set()).add(name)

    def _harvest(self, relpath: str, tree: ast.Module) -> None:
        """Find lock construction sites: witness factory calls (the
        canonical registrations), raw threading primitives (auto-named),
        and condition aliases over an existing lock attribute."""
        for cls_name, node in _assignments(tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            attr = _simple_target(target)
            if attr is None:
                continue
            key = (relpath, cls_name, attr)
            is_attr = isinstance(target, ast.Attribute)
            site = LockSite(relpath, node.lineno)
            fac = _find_factory_call(node.value)
            if fac is not None:
                fname, lock_name = fac
                self._register(key, lock_name, _FACTORIES[fname], site,
                               registered=True, is_attr=is_attr)
                continue
            raw = _raw_threading_kind(node.value)
            if raw is not None:
                kind, backing = raw
                if backing is not None:
                    # threading.Condition(self._lock): alias to backing
                    self.aliases[key] = (relpath, cls_name, backing)
                    continue
                auto = f"{_modstem(relpath)}.{attr.lstrip('_')}"
                self._register(key, auto, kind, site, registered=False,
                               is_attr=is_attr)
                continue
            # `self.X = RepoClass(...)`: type the attribute so
            # `self.X.lock` resolves through RepoClass's registration
            if is_attr and isinstance(node.value, ast.Call):
                ctor = self._resolve_ctor(relpath, node.value.func)
                if ctor is not None:
                    self.attr_types[key] = ctor

    def _resolve_ctor(self, path: str, fn: ast.AST
                      ) -> Optional[Tuple[str, str]]:
        if isinstance(fn, ast.Name):
            if (path, fn.id) in self.class_bases:
                return (path, fn.id)
            mod_path = self.imports.get(path, {}).get(fn.id)
            if mod_path is not None \
                    and (mod_path, fn.id) in self.class_bases:
                return (mod_path, fn.id)
        elif isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Name):
            mod_path = self.imports.get(path, {}).get(fn.value.id)
            if mod_path is not None \
                    and (mod_path, fn.attr) in self.class_bases:
                return (mod_path, fn.attr)
        return None

    def _resolve_aliases(self) -> None:
        for key, backing_key in self.aliases.items():
            name = self.reg.get(backing_key)
            if name is None and backing_key[1] is not None:
                # backing lock assigned in another class/module: fall
                # back to the attr-unique map
                cands = self.by_attr.get(backing_key[2], set())
                if len(cands) == 1:
                    name = next(iter(cands))
            if name is not None:
                self.reg[key] = name
                self.by_attr.setdefault(key[2], set()).add(name)

    # ----------------------------------------------------------- index

    def _index_functions(self, relpath: str, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = (relpath, None, node.name)
                self.funcs[key] = _FuncInfo(key, relpath, node, None)
                self.mod_funcs.setdefault(relpath, {})[node.name] = key
            elif isinstance(node, ast.ClassDef):
                self.class_bases[(relpath, node.name)] = [
                    ast.unparse(b) for b in node.bases]
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        key = (relpath, node.name, sub.name)
                        self.funcs[key] = _FuncInfo(key, relpath, sub,
                                                    node.name)
                        self.methods_by_name.setdefault(
                            sub.name, []).append(key)

    def _index_imports(self, relpath: str, tree: ast.Module) -> None:
        amap = self.imports.setdefault(relpath, {})
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.startswith("gubernator_tpu"):
                        amap[alias.asname or alias.name.split(".")[-1]] = \
                            _mod_to_path(alias.name)
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.startswith("gubernator_tpu"):
                mod_path = _mod_to_path(node.module)
                for alias in node.names:
                    sub_path = _mod_to_path(f"{node.module}.{alias.name}")
                    if self.repo.exists(sub_path):
                        # `from pkg import module`
                        amap[alias.asname or alias.name] = sub_path
                    elif self.repo.exists(mod_path):
                        # `from pkg.module import ClassOrFn`: the alias
                        # names a symbol defined in mod_path
                        amap[alias.asname or alias.name] = mod_path

    # ----------------------------------------------------- class chains

    def _resolve_base(self, path: str, base: str
                      ) -> Optional[Tuple[str, str]]:
        """Resolve a base-class expression string to (path, classname)."""
        if "." in base:
            alias, _, cls = base.rpartition(".")
            mod_path = self.imports.get(path, {}).get(alias)
            if mod_path is not None and (mod_path, cls) in self.class_bases:
                return (mod_path, cls)
            return None
        if (path, base) in self.class_bases:
            return (path, base)
        mod_path = self.imports.get(path, {}).get(base)
        if mod_path is not None and (mod_path, base) in self.class_bases:
            return (mod_path, base)
        return None

    def _mro(self, path: str, cls: str) -> List[Tuple[str, str]]:
        """(path, class) chain: the class itself then its bases, BFS,
        depth-bounded and cycle-guarded."""
        out = [(path, cls)]
        seen = {(path, cls)}
        frontier = [(path, cls)]
        for _ in range(_MAX_MRO_DEPTH):
            nxt = []
            for p, c in frontier:
                for base in self.class_bases.get((p, c), []):
                    r = self._resolve_base(p, base)
                    if r is not None and r not in seen:
                        seen.add(r)
                        out.append(r)
                        nxt.append(r)
            if not nxt:
                break
            frontier = nxt
        return out

    # --------------------------------------------- lock canonicalization

    def canonicalize(self, expr: ast.AST, path: str, cls: Optional[str],
                     aliases: Optional[Dict[str, str]] = None,
                     ) -> Optional[str]:
        """Map a lock expression at a use site to its canonical class
        name, or None when unresolvable."""
        if isinstance(expr, ast.Attribute):
            recv = expr.value
            # typed receiver: `self.X.lock` where self.X was constructed
            # as a repo class that registers `lock`
            if isinstance(recv, ast.Attribute) \
                    and isinstance(recv.value, ast.Name) \
                    and recv.value.id == "self" and cls is not None:
                for p, c in self._mro(path, cls):
                    t = self.attr_types.get((p, c, recv.attr))
                    if t is None:
                        continue
                    for p2, c2 in self._mro(*t):
                        name = self.reg.get((p2, c2, expr.attr))
                        if name is not None:
                            return name
            recv_src = ast.unparse(recv)
            return self._attr_class(path, cls, recv_src, expr.attr)
        if isinstance(expr, ast.Name):
            if aliases and expr.id in aliases:
                return aliases[expr.id]
            name = self.reg.get((path, None, expr.id))
            if name is not None:
                return name
            cands = self.by_attr.get(expr.id, set())
            if len(cands) == 1:
                return next(iter(cands))
        return None

    def _attr_class(self, path: str, cls: Optional[str], recv_src: str,
                    attr: str) -> Optional[str]:
        if recv_src == "self" and cls is not None:
            for p, c in self._mro(path, cls):
                name = self.reg.get((p, c, attr))
                if name is not None:
                    return name
        cands = self.by_attr.get(attr, set())
        if len(cands) == 1:
            return next(iter(cands))
        # `backend._lock` / `eng._lock`: the duck-typed engine receiver
        # the lexical rules already recognize — resolve to the engine
        # class when it exists (the corpus repos may not have one)
        if attr == "_lock" and _ENGINEISH_RE.search(recv_src) \
                and "engine" in self.graph.classes:
            return "engine"
        return None

    # ----------------------------------------------------- call resolve

    def resolve_call(self, call: ast.Call, path: str,
                     cls: Optional[str]) -> Optional[Tuple]:
        fn = call.func
        if isinstance(fn, ast.Name):
            # bare name: same-module function, else a from-imported one
            key = self.mod_funcs.get(path, {}).get(fn.id)
            if key is not None:
                return key
            mod_path = self.imports.get(path, {}).get(fn.id)
            if mod_path is not None:
                return self.mod_funcs.get(mod_path, {}).get(fn.id)
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        meth = fn.attr
        recv = fn.value
        if isinstance(recv, ast.Name):
            if recv.id == "self" and cls is not None:
                for p, c in self._mro(path, cls):
                    key = (p, c, meth)
                    if key in self.funcs:
                        return key
            mod_path = self.imports.get(path, {}).get(recv.id)
            if mod_path is not None:
                key = self.mod_funcs.get(mod_path, {}).get(meth)
                if key is not None:
                    return key
        if isinstance(recv, ast.Call) and isinstance(recv.func, ast.Name) \
                and recv.func.id == "super" and cls is not None:
            for p, c in self._mro(path, cls)[1:]:
                key = (p, c, meth)
                if key in self.funcs:
                    return key
        # duck-typed receiver: resolve only when the method name is
        # repo-unique AND not shared with a builtin container/stdlib
        # protocol, else under-approximate
        if meth in _COMMON_METHODS:
            return None
        cands = self.methods_by_name.get(meth, [])
        if len(cands) == 1:
            return cands[0]
        return None

    # ----------------------------------------------------- local aliases

    def local_aliases(self, key: Tuple) -> Dict[str, str]:
        """Function-local lock aliases: `lock = self._lock`,
        `lock = getattr(backend, "_lock", None)` (the keyspace harvest
        pattern). One pass per function, memoized. A name rebound to two
        different classes in one function is dropped (ambiguous)."""
        memo = self._aliases_memo.get(key)
        if memo is not None:
            return memo
        info = self.funcs[key]
        out: Dict[str, str] = {}
        poisoned: Set[str] = set()
        for node in ast.walk(info.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            tgt = node.targets[0].id
            lock = None
            v = node.value
            if isinstance(v, ast.Attribute):
                lock = self.canonicalize(v, info.path, info.cls)
            else:
                g = _getattr_parts(v)
                if g is not None:
                    lock = self._attr_class(info.path, info.cls, *g)
            if lock is None:
                if tgt in out:
                    poisoned.add(tgt)
                continue
            if tgt in out and out[tgt] != lock:
                poisoned.add(tgt)
            out[tgt] = lock
        for tgt in poisoned:
            out.pop(tgt, None)
        self._aliases_memo[key] = out
        return out

    # ------------------------------------------------ function summaries

    def summary(self, key: Tuple, depth: int = MAX_CALL_DEPTH,
                ) -> Dict[str, Tuple[str, ...]]:
        """Lock classes function `key` may transitively acquire, each
        with the shortest `path:line` witness chain found. Bounded by
        `depth` call hops and cycle-guarded."""
        memo = self._summaries.get(key)
        if memo is not None:
            return memo
        if key in self._in_progress or depth <= 0:
            return {}
        self._in_progress.add(key)
        info = self.funcs[key]
        aliases = self.local_aliases(key)
        out: Dict[str, Tuple[str, ...]] = {}

        def note(name: str, chain: Tuple[str, ...]) -> None:
            cur = out.get(name)
            if cur is None or len(chain) < len(cur):
                out[name] = chain

        for node, kind in _sync_events(info.node):
            if kind == "with" or kind == "acquire":
                expr = node.context_expr if kind == "with" else \
                    node.func.value
                here = f"{info.path}:{expr.lineno}"
                lock = self.canonicalize(expr, info.path, info.cls,
                                         aliases)
                if lock is not None:
                    note(lock, (here,))
            elif kind == "call":
                callee = self.resolve_call(node, info.path, info.cls)
                if callee is None or callee == key:
                    continue
                here = f"{info.path}:{node.lineno}"
                for lock, chain in self.summary(callee, depth - 1).items():
                    note(lock, (here,) + chain)
        self._in_progress.discard(key)
        self._summaries[key] = out
        return out

    # -------------------------------------------------- edge extraction

    def _walk_function(self, info: _FuncInfo) -> None:
        self._cur_aliases = self.local_aliases(info.key)
        self._walk_nodes(info, info.node.body, ())

    def _walk_nodes(self, info: _FuncInfo, nodes, held) -> None:
        for node in nodes:
            self._walk_node(info, node, held)

    def _walk_node(self, info: _FuncInfo, node: ast.AST, held) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            # deferred execution: a closure defined under a lock runs at
            # its call site, which is checked wherever that happens
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                lock = self.canonicalize(item.context_expr, info.path,
                                         info.cls, self._cur_aliases)
                here = f"{info.path}:{item.context_expr.lineno}"
                if lock is None:
                    src = ast.unparse(item.context_expr)
                    if _LOCKISH_RE.search(src):
                        self.graph.unresolved.append(
                            (info.path, item.context_expr.lineno, src))
                    continue
                for h_name, h_site in new_held:
                    self.graph.add_edge(h_name, lock, (h_site, here))
                new_held = new_held + ((lock, here),)
            self._walk_nodes(info, node.body, new_held)
            return
        if isinstance(node, ast.Call):
            self._handle_call(info, node, held)
            for child in ast.iter_child_nodes(node):
                self._walk_node(info, child, held)
            return
        for child in ast.iter_child_nodes(node):
            self._walk_node(info, child, held)

    def _handle_call(self, info: _FuncInfo, call: ast.Call, held) -> None:
        fn = call.func
        here = f"{info.path}:{call.lineno}"
        # direct .acquire() on a resolvable lock expression
        if isinstance(fn, ast.Attribute) and fn.attr == "acquire":
            lock = self.canonicalize(fn.value, info.path, info.cls,
                                     self._cur_aliases)
            if lock is not None:
                for h_name, h_site in held:
                    self.graph.add_edge(h_name, lock, (h_site, here))
                return
        if not held:
            return
        callee = self.resolve_call(call, info.path, info.cls)
        if callee is None:
            return
        for lock, chain in self.summary(callee).items():
            for h_name, h_site in held:
                self.graph.add_edge(h_name, lock, (h_site, here) + chain)


# ------------------------------------------------------------ ast utils


def _assignments(tree: ast.Module):
    """Yield (enclosing class name or None, Assign node) pairs for every
    assignment in the module, including inside methods."""
    def visit(node, cls_name):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from visit(child, child.name)
            elif isinstance(child, ast.Assign):
                yield cls_name, child
                yield from visit(child, cls_name)
            else:
                yield from visit(child, cls_name)
    yield from visit(tree, None)


def _getattr_parts(value: ast.AST) -> Optional[Tuple[str, str]]:
    """`getattr(X, "attr"[, default])` -> (receiver_src, attr)."""
    if isinstance(value, ast.Call) and isinstance(value.func, ast.Name) \
            and value.func.id == "getattr" and len(value.args) >= 2 \
            and isinstance(value.args[1], ast.Constant) \
            and isinstance(value.args[1].value, str):
        return ast.unparse(value.args[0]), value.args[1].value
    return None


def _simple_target(target: ast.AST) -> Optional[str]:
    """`self.X = ...` or module/function-level `X = ...` -> attr name."""
    if isinstance(target, ast.Attribute) \
            and isinstance(target.value, ast.Name) \
            and target.value.id == "self":
        return target.attr
    if isinstance(target, ast.Name):
        return target.id
    return None


def _find_factory_call(value: ast.AST) -> Optional[Tuple[str, str]]:
    """First witness factory call anywhere in `value` (handles
    `threading.Condition(witness.make_lock("x"))`)."""
    for node in ast.walk(value):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        fname = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if fname in _FACTORIES and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            return fname, node.args[0].value
    return None


def _raw_threading_kind(value: ast.AST
                        ) -> Optional[Tuple[str, Optional[str]]]:
    """Classify a raw threading primitive construction.

    Returns (kind, backing_attr): backing_attr is set for
    `threading.Condition(self.X)` aliases, else None."""
    if not isinstance(value, ast.Call):
        return None
    src = ast.unparse(value.func)
    if src == "threading.Lock":
        return ("lock", None)
    if src == "threading.RLock":
        return ("rlock", None)
    if src == "threading.Condition":
        if value.args:
            arg = value.args[0]
            if isinstance(arg, ast.Attribute) \
                    and isinstance(arg.value, ast.Name) \
                    and arg.value.id == "self":
                return ("rcondition", arg.attr)
            if isinstance(arg, ast.Call) \
                    and ast.unparse(arg.func) == "threading.Lock":
                return ("lock", None)
        return ("rcondition", None)
    return None


def _sync_events(fn: ast.AST):
    """Yield (node, kind) for every with-item, .acquire() call, and
    plain call in `fn`'s body, skipping nested function/class bodies.
    kind: "with" yields the withitem, "acquire"/"call" yield Call."""
    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    yield item, "with"
            if isinstance(child, ast.Call):
                fn_ = child.func
                if isinstance(fn_, ast.Attribute) and fn_.attr == "acquire":
                    yield child, "acquire"
                else:
                    yield child, "call"
            yield from visit(child)
    yield from visit(fn)


def _modstem(relpath: str) -> str:
    stem = os.path.splitext(os.path.basename(relpath))[0]
    return stem if stem != "__init__" else \
        os.path.basename(os.path.dirname(relpath))


def _mod_to_path(module: str) -> str:
    path = module.replace(".", "/") + ".py"
    return path


# ------------------------------------------------- donated-buffer flow


@dataclasses.dataclass(frozen=True)
class DonationFinding:
    path: str
    line: int
    var: str
    receiver: str
    attr: str
    donated_at: int


def donation_findings(repo: RepoIndex) -> List[DonationFinding]:
    """Per-function dataflow over donated device-array attributes.

    A *capture* is `v = X.state` (X engine-ish, or `self` in a class the
    lexical rule already recognizes as an array holder). A *donation* is
    the donate-and-rebind assignment `X.state, ... = f(X.state, ...)` —
    any Assign whose value is a Call and whose targets rebind the same
    attribute. Any read of `v` after a donation that happened after the
    capture, with no fresh re-read in between, is a stale donated
    reference: by readback time XLA has deleted the buffer."""
    from gubernator_tpu.analysis.rules.locks import _donated_classes

    out: List[DonationFinding] = []
    for relpath in repo.python_files():
        if not relpath.startswith("gubernator_tpu/"):
            continue
        sf = repo.get(relpath)
        tree = sf.tree if sf is not None else None
        if tree is None:
            continue
        donated_classes = {c.name for c in _donated_classes(tree)}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(_scan_function(relpath, node, donated_classes,
                                          tree))
    return sorted(out, key=lambda f: (f.path, f.line, f.var))


def _donated_attr(expr: ast.AST, donated_classes: Set[str],
                  in_class: Optional[str]) -> Optional[Tuple[str, str]]:
    """(receiver_src, attr) when `expr` reads a donated array attr."""
    if not (isinstance(expr, ast.Attribute) and expr.attr in DONATED_ATTRS):
        return None
    recv = ast.unparse(expr.value)
    if recv == "self":
        if in_class in donated_classes:
            return recv, expr.attr
        return None
    if _ENGINEISH_RE.search(recv):
        return recv, expr.attr
    return None


def _scan_function(relpath: str, fn: ast.AST, donated_classes: Set[str],
                   tree: ast.Module) -> List[DonationFinding]:
    in_class = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and fn in ast.walk(node):
            in_class = node.name
            break

    captures: Dict[str, List[Tuple[int, bool, str, str]]] = {}
    donations: List[Tuple[int, str, str]] = []  # line, receiver, attr

    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            continue
        if isinstance(node, ast.Assign):
            # donation: value is a Call, some target rebinds X.<attr>
            if isinstance(node.value, ast.Call):
                for tgt in node.targets:
                    elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                    for el in elts:
                        d = _donated_attr(el, donated_classes, in_class)
                        if d is not None:
                            donations.append((node.lineno, d[0], d[1]))
            # assignment events per simple-name target
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    d = _donated_attr(node.value, donated_classes, in_class)
                    if d is not None:
                        captures.setdefault(tgt.id, []).append(
                            (node.lineno, True, d[0], d[1]))
                    else:
                        captures.setdefault(tgt.id, []).append(
                            (node.lineno, False, "", ""))

    if not donations:
        return []

    findings: List[DonationFinding] = []
    reported: Set[Tuple[str, int]] = set()
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)):
            continue
        events = captures.get(node.id)
        if not events:
            continue
        last = None
        for ev in sorted(events):
            if ev[0] < node.lineno:
                last = ev
        if last is None or not last[1]:
            continue
        cap_line, _, recv, attr = last
        for d_line, d_recv, d_attr in donations:
            if cap_line < d_line < node.lineno \
                    and d_recv == recv and d_attr == attr:
                key = (node.id, node.lineno)
                if key not in reported:
                    reported.add(key)
                    findings.append(DonationFinding(
                        relpath, node.lineno, node.id, recv, attr, d_line))
                break
    return findings


# ------------------------------------------------------- baseline file


def baseline_path(root: str) -> str:
    return os.path.join(root, "lockmap.json")


def load_baseline(root: str) -> Optional[dict]:
    import json
    try:
        with open(baseline_path(root), encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def render_baseline(graph: LockGraph, prior: Optional[dict]) -> dict:
    """The committed lockmap.json payload: static edge pairs pinned both
    directions, runtime-observed extras carried over from the prior
    baseline (they are maintained by hand, each with a `why`)."""
    return {
        "version": 1,
        "classes": {
            name: {
                "kind": c.kind,
                "registered": c.registered,
                "sites": sorted(s.render() for s in c.sites),
            }
            for name, c in sorted(graph.classes.items())
        },
        "static_edges": [list(p) for p in graph.edge_pairs()],
        "runtime_edges": (prior or {}).get("runtime_edges", []),
    }


def diff_baseline(graph: LockGraph, baseline: Optional[dict]
                  ) -> Tuple[List[Tuple[str, str]], List[Tuple[str, str]]]:
    """(missing_from_baseline, gone_from_analysis) — the two-direction
    drift pin. Empty/empty means the committed lockmap is current."""
    have = set(graph.edge_pairs())
    pinned = {tuple(e) for e in (baseline or {}).get("static_edges", [])}
    return sorted(have - pinned), sorted(pinned - have)
