"""`native-warnings` — promote the C++ warning surface to an error gate.

keydir.cpp and peerlink.cpp run the repo's sharpest concurrency (the
TSAN harness in tests/test_tsan.py hammers the real thread disciplines);
g++ has no clang `-Wthread-safety`, so the strongest always-on gate this
toolchain offers is `-Wall -Wextra` promoted to errors. scripts/
build_native.py compiles with the same set + `-Werror`, and this rule
runs the cheap `-fsyntax-only` variant inside `make lint` so a new
warning fails the lint gate even before anyone rebuilds the .so cache.

Skips silently when g++ is absent (the lint gate must not invent an
environment requirement tier-1 doesn't already have).
"""

from __future__ import annotations

import os
import re
import shutil
import subprocess
import sysconfig
from typing import Iterable

from gubernator_tpu.analysis.core import Finding, RepoIndex, Rule, register

NATIVE_DIR = "gubernator_tpu/native"

# `-Wall -Wextra` everywhere; build_native.py must carry the same set
# (plus -Werror) so lint and the shipped .so agree on the surface
WARN_FLAGS = ("-Wall", "-Wextra")

# a second pass under the sanitizer flag set `make sanitize` builds
# with: -fsanitize changes the frontend's constant folding and
# builtin expansion enough that some diagnostics fire only there, and
# a source that stops compiling under instrumentation would silently
# rot the TSan/ASan suites between rebuilds. (thread+undefined is the
# combinable pair; address conflicts with thread and adds no extra
# frontend diagnostics beyond this set.)
SYNTAX_PASSES = ((), ("-fsanitize=thread,undefined", "-pthread"))

_DIAG_RE = re.compile(r"^(?P<path>[^:]+):(?P<line>\d+):\d+:\s*"
                      r"(?:warning|error):\s*(?P<msg>.*)$")


@register
class NativeWarningsRule(Rule):
    id = "native-warnings"
    doc = ("gubernator_tpu/native/*.cpp must compile clean under "
           "-Wall -Wextra (promoted to -Werror in scripts/build_native.py)")

    def check(self, repo: RepoIndex) -> Iterable[Finding]:
        if shutil.which("g++") is None:
            return
        native = os.path.join(repo.root, NATIVE_DIR)
        if not os.path.isdir(native):
            return
        pyinc = f"-I{sysconfig.get_paths()['include']}"
        for name in sorted(os.listdir(native)):
            if not name.endswith(".cpp"):
                continue
            src = os.path.join(native, name)
            relpath = f"{NATIVE_DIR}/{name}"
            seen = set()
            for extra in SYNTAX_PASSES:
                proc = subprocess.run(
                    ["g++", "-fsyntax-only", *WARN_FLAGS, *extra,
                     "-std=c++17", pyinc, src],
                    capture_output=True, text=True, timeout=120)
                tag = f" [{extra[0]}]" if extra else ""
                for raw in (proc.stderr or "").splitlines():
                    m = _DIAG_RE.match(raw.strip())
                    if not m or os.path.basename(m.group("path")) != name:
                        continue
                    key = (int(m.group("line")), m.group("msg"))
                    if key in seen:
                        continue
                    seen.add(key)
                    yield Finding(self.id, relpath, key[0],
                                  f"g++ diagnostic: {key[1]}{tag}")
