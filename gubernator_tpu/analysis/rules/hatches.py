"""`escape-hatch` — every perf-path feature flag has a differential test.

The standing constraint (ROADMAP) that let the pipeline, lease, wire-v2,
and reshard refactors land safely: a perf path ships with a lock-step /
serial / off escape hatch, and a test proves the hatch bit-identical to
the old behavior. This rule pins the second half mechanically: for each
registered hatch, at least one file under `tests/` must reference the
flag (env name or its BehaviorConfig/DaemonConfig attribute) AND carry a
differential marker ("differential", "bit-identical", "lock-step",
"byte-identical") — the vocabulary every such test in this repo already
uses. A hatch whose differential test is deleted or renamed away fails
tier-1 at that PR, not at the next 3 a.m. bisect.
"""

from __future__ import annotations

import re
from typing import Iterable, List, Sequence, Tuple

from gubernator_tpu.analysis.core import Finding, RepoIndex, Rule, register

# (env knob, source-level aliases a test may use instead of the env name)
HATCHES: Sequence[Tuple[str, Tuple[str, ...]]] = (
    ("GUBER_WIRE_V2", ("wire_v2",)),
    ("GUBER_COLUMNAR_PIPELINE", ("columnar_pipeline",)),
    ("GUBER_HOT_LEASES", ("hot_leases",)),
    ("GUBER_RESHARD", ("reshard",)),
    ("GUBER_PIPELINE_DEPTH", ("pipeline_depth",)),
    ("GUBER_DEVICE_DIRECTORY", ("device_directory", "DevDirEngine")),
    ("GUBER_PROFILE", ("profile_enabled",)),
    ("GUBER_LOCK_WITNESS", ("lock_witness", "witness_enabled")),
    ("GUBER_LEDGER", ("ledger_enabled",)),
    ("GUBER_AUTOPILOT", ("autopilot",)),
)

DIFF_RE = re.compile(
    r"differential|bit.?identical|lock.?step|byte.?identical",
    re.IGNORECASE)

TESTS_DIR = "tests"
ENVCONF = "gubernator_tpu/cmd/envconf.py"


@register
class EscapeHatchRule(Rule):
    id = "escape-hatch"
    doc = ("every perf-path feature flag must be exercised by a tests/ "
           "file containing a differential assertion marker")

    # overridable for the corpus harness
    hatches: Sequence[Tuple[str, Tuple[str, ...]]] = HATCHES

    def check(self, repo: RepoIndex) -> Iterable[Finding]:
        test_files = repo.walk(TESTS_DIR, ".py")
        for env, aliases in self.hatches:
            tokens = (env,) + aliases
            referencing: List[str] = []
            differential = False
            for relpath in test_files:
                text = repo.get(relpath).text
                if any(t in text for t in tokens):
                    referencing.append(relpath)
                    if DIFF_RE.search(text):
                        differential = True
            if differential:
                continue
            path, line = self._anchor(repo, env)
            if not referencing:
                yield Finding(
                    self.id, path, line,
                    f"escape hatch {env} has no test under tests/ "
                    "referencing it — a hatch nobody exercises is a "
                    "hatch that silently rotted shut")
            else:
                yield Finding(
                    self.id, path, line,
                    f"escape hatch {env} is referenced by "
                    f"{', '.join(referencing[:3])} but none of those "
                    "files carries a differential marker "
                    "(differential / bit-identical / lock-step) — the "
                    "hatch must be proven equivalent, not just toggled")

    @staticmethod
    def _anchor(repo: RepoIndex, env: str) -> Tuple[str, int]:
        """Anchor the finding at the knob's envconf parse site (the
        flag's definition), falling back to example.conf."""
        for relpath in (ENVCONF, "example.conf"):
            sf = repo.get(relpath)
            if sf is None:
                continue
            for i, line in enumerate(sf.lines, 1):
                if env in line:
                    return relpath, i
        return ENVCONF, 1
