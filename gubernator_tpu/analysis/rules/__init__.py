"""Rule modules self-register on import (analysis/core.py register)."""

from gubernator_tpu.analysis.rules import (  # noqa: F401
    controllers,
    hatches,
    knobs,
    lockorder,
    locks,
    native,
    registries,
)
