"""Lock-discipline rules.

`lock-discipline` — the PR 10 cartographer race, generalized. The
serving path donates the engine's device arrays (`state`, and the devdir
engine's `fps`/`touch`) to XLA each dispatch and rebinds the attribute;
any reader holding a stale reference sees a deleted array by readback
time. So every read of those attributes in `models/`, `obs/`, `service/`
must happen lexically inside a `with <lock>` scope — or inside a
function that declares the caller-holds-the-lock contract (name ends in
`_locked`, or docstring says so), which is this repo's equivalent of a
clang thread-safety REQUIRES annotation.

`blocking-under-lock` — the converse discipline: the engine/store lock
serializes every decision window, so an RPC, socket op, `time.sleep`, or
subprocess call made while holding it stalls the entire serving spine
(one slow peer would become a global outage). No blocking call may sit
lexically inside a lock scope; deferred work (closures defined under the
lock) is exempt because definition is not execution.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set

from gubernator_tpu.analysis.core import (
    Finding,
    RepoIndex,
    Rule,
    iter_lock_withs,
    register,
)

# directories the donated-buffer discipline governs (repo-relative)
LOCK_SCOPE_DIRS = (
    "gubernator_tpu/models",
    "gubernator_tpu/obs",
    "gubernator_tpu/service",
)

# attributes holding donated device arrays
DONATED_ATTRS = frozenset({"state", "fps", "touch"})

# a function whose docstring states the caller already holds the lock is
# a declared contract, not a violation (the call sites are checked where
# they take the lock)
_HOLDS_RE = re.compile(
    r"caller(s)?\s+(must\s+)?(already\s+)?hold|lock\s+(is\s+)?held"
    r"|under\s+the\s+\w*\s*lock|with\s+the\s+\w*\s*lock\s+held",
    re.IGNORECASE)


def _declares_lock_held(fn: ast.AST) -> bool:
    name = getattr(fn, "name", "")
    if name.endswith("_locked"):
        return True
    doc = ast.get_docstring(fn) or ""
    return bool(_HOLDS_RE.search(doc))


def _function_nodes(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _in_scope(repo: RepoIndex, relpath: str) -> bool:
    return any(relpath.startswith(d + "/") or relpath.startswith(d + "\\")
               for d in LOCK_SCOPE_DIRS)


# receivers other than `self` that plausibly hold an engine: `backend`,
# `eng`, `self._engine`, `inst.backend` — but not `sess`, `circuit`, `s`
# (reshard session status strings and circuit-breaker enums also use the
# attribute name `state` and are plain python ints/strings, not arrays)
_ENGINEISH_RE = re.compile(r"(^|\.)_?(backend|engine|eng)$")


def _donated_classes(tree: ast.Module) -> Set[ast.ClassDef]:
    """Classes that actually bind donated device arrays: some method
    assigns `self.state`/`self.fps`/`self.touch` from a *call* (array
    constructors / jit dispatch results). Classes that assign these
    names from constants or plain names (circuit-breaker enums, reshard
    session status strings) are not array holders."""
    out: Set[ast.ClassDef] = set()
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and tgt.attr in DONATED_ATTRS
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    out.add(cls)
    return out


@register
class LockDisciplineRule(Rule):
    id = "lock-discipline"
    doc = ("reads of donated device arrays (.state/.fps/.touch) in "
           "models/, obs/, service/ must sit inside a `with <lock>` "
           "scope or a declared caller-holds-lock function")

    def check(self, repo: RepoIndex) -> Iterable[Finding]:
        for relpath in repo.python_files():
            if not _in_scope(repo, relpath):
                continue
            sf = repo.get(relpath)
            tree = sf.tree
            if tree is None:
                continue
            lock_withs = {w for w, _ in iter_lock_withs(tree)}
            parents = _parent_map(tree)
            donated = _donated_classes(tree)
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Attribute)
                        and node.attr in DONATED_ATTRS):
                    continue
                recv = node.value
                if isinstance(recv, ast.Name) and recv.id == "self":
                    cls = _enclosing_class(node, parents)
                    if cls is None or cls not in donated:
                        continue
                elif not _ENGINEISH_RE.search(ast.unparse(recv)):
                    continue
                verdict = _lock_verdict(node, parents, lock_withs)
                if verdict == "ok":
                    continue
                obj = ast.unparse(node.value)
                yield Finding(
                    self.id, relpath, node.lineno,
                    f"`{obj}.{node.attr}` read outside a lock scope — the "
                    "serving path donates this array and rebinds the "
                    "attribute; hold the engine lock (or declare the "
                    "caller-holds-lock contract) to avoid the "
                    "deleted-array race")


def _enclosing_class(node: ast.AST, parents) -> ast.AST:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur
        cur = parents.get(cur)
    return None


def _parent_map(tree: ast.Module):
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _lock_verdict(node: ast.AST, parents, lock_withs: Set[ast.AST]) -> str:
    """Climb lexically outward: a lock `with` before the enclosing
    function means locked; construction scopes (`__init__`, module
    setup at class body level) and declared-contract functions pass."""
    cur = parents.get(node)
    while cur is not None:
        if cur in lock_withs:
            return "ok"
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if cur.name == "__init__" or _declares_lock_held(cur):
                # __init__ builds the arrays before the object is shared
                return "ok"
            return "violation"
        if isinstance(cur, ast.Lambda):
            return "ok"  # deferred execution: checked at the call site
        cur = parents.get(cur)
    return "ok"  # module level: import-time, single-threaded


# ------------------------------------------------------------- blocking

# calls that block on external progress: never inside a lock scope
_BLOCKING_MODULES = frozenset({"subprocess", "requests"})
_BLOCKING_SOCKET_METHODS = frozenset({
    "connect", "connect_ex", "accept", "recv", "recvfrom", "sendall",
    "makefile",
})
_RPC_METHODS = frozenset({
    # gRPC stub surface (service/pb/*_pb2_grpc): a peer RPC under the
    # engine lock serializes the cluster behind one peer's latency
    "GetRateLimits", "GetPeerRateLimits", "UpdatePeerGlobals",
    "HealthCheck", "Debug",
})


def _blocking_reason(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id == "sleep":
            return "time.sleep"
        return ""
    if not isinstance(fn, ast.Attribute):
        return ""
    if fn.attr == "sleep" and isinstance(fn.value, ast.Name) \
            and fn.value.id == "time":
        return "time.sleep"
    if isinstance(fn.value, ast.Name) and fn.value.id in _BLOCKING_MODULES:
        return f"{fn.value.id}.{fn.attr}"
    if fn.attr in _BLOCKING_SOCKET_METHODS:
        return f"socket .{fn.attr}()"
    if fn.attr in _RPC_METHODS:
        return f"peer RPC .{fn.attr}()"
    if fn.attr == "create_connection" and isinstance(fn.value, ast.Name) \
            and fn.value.id == "socket":
        return "socket.create_connection"
    return ""


# locks whose PURPOSE is serializing socket IO (peerlink's `_wlock`
# write-serialization lock): a blocking send is their job, and they are
# never held across engine state
_IO_LOCK_RE = re.compile(r"wlock|write|sock|io_?lock", re.IGNORECASE)


@register
class BlockingUnderLockRule(Rule):
    id = "blocking-under-lock"
    doc = ("no RPC, socket op, time.sleep, or subprocess call lexically "
           "inside an engine/store lock scope")

    def check(self, repo: RepoIndex) -> Iterable[Finding]:
        for relpath in repo.python_files():
            if not _in_scope(repo, relpath):
                continue
            sf = repo.get(relpath)
            tree = sf.tree
            if tree is None:
                continue
            for with_node, lock_expr in iter_lock_withs(tree):
                lock_src = ast.unparse(lock_expr)
                if _IO_LOCK_RE.search(lock_src):
                    continue
                for call in _calls_in_scope(with_node):
                    reason = _blocking_reason(call)
                    if reason:
                        yield Finding(
                            self.id, relpath, call.lineno,
                            f"{reason} while holding `{lock_src}` — a "
                            "blocking call under the lock stalls every "
                            "serving window behind it; move it outside "
                            "the critical section")


def _calls_in_scope(with_node: ast.With) -> List[ast.Call]:
    """Calls lexically inside the with body, NOT descending into nested
    function definitions (deferred execution is the call site's
    problem, not the definition site's)."""
    out: List[ast.Call] = []
    stack: List[ast.AST] = []
    for stmt in with_node.body:
        stack.append(stmt)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out
