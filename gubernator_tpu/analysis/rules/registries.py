"""`registry-drift` — generalize the PR 9 metric/doc lint to every
name registry the operator surface depends on.

tests/test_metrics_docs.py proved the pattern for Prometheus families:
an undocumented name is a dashboard nobody builds, a documented-but-gone
name is a dashboard that silently flatlines. The same failure mode
exists for three more registries, and PR 11 demonstrated the drift is
real (reshard.* flight-recorder events shipped without rows in the
Flight recorder table):

- flight-recorder event kinds: every `emit("x.y")` in code must appear
  in docs/observability.md's "## Flight recorder" table, and every kind
  the table promises must still be emitted somewhere;
- fault-injection transports: service/faults.py TRANSPORTS must each be
  documented as `transport=<name>` under docs/, and every literal passed
  to `faults.on_call(peer, "<t>")` must be a registered transport;
- /v1/debug/vars sections: every section `obs/introspect.py` can emit
  must be declared in tests/test_debug_schema.py's ALWAYS/OPTIONAL sets
  (the schema contract), and no declared section may be stale;
- debug endpoints: every `/v1/debug/<name>` route the HTTP gateway
  serves must have a row in docs/observability.md's "## Debug
  endpoints" table, and every row must name a route the gateway still
  dispatches (PR 13 motivation: /v1/debug/profile and /v1/debug/kernels
  must not become the next undocumented surface);
- named scenarios: every entry in scenarios/spec.py SCENARIO_NAMES must
  have a row in docs/observability.md's "## Scenario atlas" table, and
  every row must name a scenario the registry still builds — the atlas
  is the operator's drill menu, and a drill the docs don't name (or
  promise but the registry dropped) is a verdict nobody runs.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Tuple

from gubernator_tpu.analysis.core import Finding, RepoIndex, Rule, register

OBS_DOC = "docs/observability.md"
FAULTS = "gubernator_tpu/service/faults.py"
INTROSPECT = "gubernator_tpu/obs/introspect.py"
SCHEMA_TEST = "tests/test_debug_schema.py"
GATEWAY = "gubernator_tpu/service/http_gateway.py"
SCENARIOS = "gubernator_tpu/scenarios/spec.py"

_EMIT_FNS = frozenset({"emit", "_emit", "_record"})


def _emitted_kinds(repo: RepoIndex
                   ) -> Tuple[Dict[str, Tuple[str, int]],
                              Dict[str, Tuple[str, int]]]:
    """(exact kinds, glob prefixes) -> first emit site. A kind is a
    dotted string literal first argument to emit/_emit/_record; an
    f-string with a dotted constant head (`f"anomaly.{name}"`) is a
    glob prefix covering everything under it."""
    exact: Dict[str, Tuple[str, int]] = {}
    globs: Dict[str, Tuple[str, int]] = {}
    for relpath in repo.python_files():
        sf = repo.get(relpath)
        tree = sf.tree
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else "")
            if name not in _EMIT_FNS:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if "." in arg.value:
                    exact.setdefault(arg.value, (relpath, node.lineno))
            elif isinstance(arg, ast.JoinedStr) and arg.values:
                head = arg.values[0]
                if isinstance(head, ast.Constant) \
                        and isinstance(head.value, str) \
                        and head.value.endswith("."):
                    globs.setdefault(head.value, (relpath, node.lineno))
    return exact, globs


def _documented_kinds(repo: RepoIndex
                      ) -> Tuple[Dict[str, int], Dict[str, int]]:
    """Kinds from the '## Flight recorder' table's first column:
    backticked dotted names; `foo.*` documents the whole prefix."""
    sf = repo.get(OBS_DOC)
    exact: Dict[str, int] = {}
    globs: Dict[str, int] = {}
    if sf is None:
        return exact, globs
    in_section = False
    for i, line in enumerate(sf.lines, 1):
        if line.startswith("## "):
            in_section = line.strip() == "## Flight recorder"
            continue
        if not in_section or not line.startswith("|"):
            continue
        first_cell = line.split("|")[1]
        for name in re.findall(r"`([a-z0-9_.*]+)`", first_cell):
            if "." not in name:
                continue
            if name.endswith("*"):
                globs.setdefault(name[:-1], i)
            else:
                exact.setdefault(name, i)
    return exact, globs


@register
class RegistryDriftRule(Rule):
    id = "registry-drift"
    doc = ("flight-recorder kinds, fault transports, /v1/debug/vars "
           "sections, debug endpoints, and named scenarios must stay in "
           "sync with their documented registries")

    def check(self, repo: RepoIndex) -> Iterable[Finding]:
        yield from self._check_events(repo)
        yield from self._check_faults(repo)
        yield from self._check_debug_sections(repo)
        yield from self._check_debug_endpoints(repo)
        yield from self._check_scenarios(repo)

    # ---------------------------------------------------------- events

    def _check_events(self, repo: RepoIndex) -> Iterable[Finding]:
        if repo.get(OBS_DOC) is None:
            return
        em_exact, em_globs = _emitted_kinds(repo)
        doc_exact, doc_globs = _documented_kinds(repo)
        if not doc_exact and not doc_globs:
            return  # corpus repo without the doc section

        for kind, (path, line) in sorted(em_exact.items()):
            if kind in doc_exact:
                continue
            if any(kind.startswith(g) for g in doc_globs):
                continue
            yield Finding(
                self.id, path, line,
                f"flight-recorder kind '{kind}' is emitted but missing "
                f"from the {OBS_DOC} '## Flight recorder' table — an "
                "undocumented event is invisible to the incident runbook")
        for prefix, (path, line) in sorted(em_globs.items()):
            if prefix in doc_globs:
                continue
            if any(k.startswith(prefix) for k in doc_exact):
                continue
            yield Finding(
                self.id, path, line,
                f"flight-recorder kind family '{prefix}*' is emitted but "
                f"undocumented in the {OBS_DOC} '## Flight recorder' table")
        for kind, line in sorted(doc_exact.items()):
            if kind in em_exact:
                continue
            if any(kind.startswith(p) for p in em_globs):
                continue
            yield Finding(
                self.id, OBS_DOC, line,
                f"flight-recorder kind '{kind}' is documented but nothing "
                "emits it — the runbook promises an event that will never "
                "appear")
        for prefix, line in sorted(doc_globs.items()):
            if prefix in em_globs:
                continue
            if any(k.startswith(prefix) for k in em_exact):
                continue
            yield Finding(
                self.id, OBS_DOC, line,
                f"flight-recorder family '{prefix}*' is documented but "
                "nothing emits under it")

    # ---------------------------------------------------------- faults

    def _check_faults(self, repo: RepoIndex) -> Iterable[Finding]:
        sf = repo.get(FAULTS)
        if sf is None or sf.tree is None:
            return
        transports: List[Tuple[str, int]] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name) and t.id == "TRANSPORTS"
                            for t in node.targets) \
                    and isinstance(node.value, ast.Tuple):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant):
                        transports.append((elt.value, node.lineno))
        if not transports:
            return
        docs_text = "\n".join(
            repo.get(doc).text for doc in repo.walk("docs", ".md"))
        for name, line in transports:
            if f"transport={name}" not in docs_text:
                yield Finding(
                    self.id, FAULTS, line,
                    f"fault transport '{name}' is registered in TRANSPORTS "
                    "but docs/ never shows `transport="
                    f"{name}` — operators can't discover a choke point "
                    "the docs don't name")
        registered = {n for n, _ in transports}
        for relpath in repo.python_files():
            tsf = repo.get(relpath)
            if tsf.tree is None:
                continue
            for node in ast.walk(tsf.tree):
                if isinstance(node, ast.Call) and len(node.args) >= 2:
                    fn = node.func
                    fname = fn.id if isinstance(fn, ast.Name) else (
                        fn.attr if isinstance(fn, ast.Attribute) else "")
                    if fname != "on_call":
                        continue
                    arg = node.args[1]
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str) \
                            and arg.value not in registered:
                        yield Finding(
                            self.id, relpath, node.lineno,
                            f"faults.on_call transport '{arg.value}' is "
                            "not in service/faults.py TRANSPORTS — an "
                            "unregistered choke point is unreachable "
                            "from any GUBER_FAULT_SPEC plan")

    # --------------------------------------------------- debug sections

    def _check_debug_sections(self, repo: RepoIndex) -> Iterable[Finding]:
        isf = repo.get(INTROSPECT)
        tsf = repo.get(SCHEMA_TEST)
        if isf is None or tsf is None \
                or isf.tree is None or tsf.tree is None:
            return
        fn = next((n for n in ast.walk(isf.tree)
                   if isinstance(n, ast.FunctionDef)
                   and n.name == "debug_vars"), None)
        if fn is None:
            return
        emitted = _toplevel_sections(fn)

        declared: Dict[str, int] = {}
        for node in ast.walk(tsf.tree):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id in ("ALWAYS", "OPTIONAL")
                            for t in node.targets) \
                    and isinstance(node.value, ast.Set):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant):
                        declared.setdefault(elt.value, node.lineno)
        if not declared:
            return
        for name, line in sorted(emitted.items()):
            if name not in declared:
                yield Finding(
                    self.id, INTROSPECT, line,
                    f"/v1/debug/vars section '{name}' is emitted by "
                    f"debug_vars() but not declared in {SCHEMA_TEST} "
                    "ALWAYS/OPTIONAL — the schema contract no longer "
                    "covers it")
        for name, line in sorted(declared.items()):
            if name not in emitted:
                yield Finding(
                    self.id, SCHEMA_TEST, line,
                    f"/v1/debug/vars section '{name}' is declared in "
                    f"ALWAYS/OPTIONAL but debug_vars() never emits it — "
                    "a stale schema promise")


    # ------------------------------------------------------- scenarios

    def _check_scenarios(self, repo: RepoIndex) -> Iterable[Finding]:
        ssf = repo.get(SCENARIOS)
        if ssf is None or ssf.tree is None:
            return
        registered: List[Tuple[str, int]] = []
        for node in ast.walk(ssf.tree):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id == "SCENARIO_NAMES"
                            for t in node.targets) \
                    and isinstance(node.value, ast.Tuple):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        registered.append((elt.value, node.lineno))
        documented = _documented_scenarios(repo)
        if not registered or not documented:
            return  # corpus repo without the atlas or the doc table
        doc_names = set(documented)
        for name, line in registered:
            if name not in doc_names:
                yield Finding(
                    self.id, SCENARIOS, line,
                    f"scenario '{name}' is registered in SCENARIO_NAMES "
                    f"but missing from the {OBS_DOC} '## Scenario atlas' "
                    "table — a drill the runbook doesn't name is a "
                    "verdict nobody runs")
        reg_names = {n for n, _ in registered}
        for name, line in sorted(documented.items()):
            if name not in reg_names:
                yield Finding(
                    self.id, OBS_DOC, line,
                    f"scenario '{name}' is documented but the registry "
                    "no longer builds it — the runbook promises a drill "
                    "that raises KeyError")

    # -------------------------------------------------- debug endpoints

    def _check_debug_endpoints(self, repo: RepoIndex) -> Iterable[Finding]:
        gsf = repo.get(GATEWAY)
        if gsf is None or gsf.tree is None:
            return
        served: Dict[str, int] = {}
        for node in ast.walk(gsf.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and node.value.startswith("/v1/debug/") \
                    and len(node.value) > len("/v1/debug/"):
                served.setdefault(node.value, node.lineno)
        documented = _documented_endpoints(repo)
        if not served or not documented:
            return  # corpus repo without the gateway or the doc table
        for route, line in sorted(served.items()):
            if route not in documented:
                yield Finding(
                    self.id, GATEWAY, line,
                    f"debug endpoint '{route}' is served by the gateway "
                    f"but missing from the {OBS_DOC} '## Debug endpoints' "
                    "table — an undocumented endpoint is a surface "
                    "operators never find")
        for route, line in sorted(documented.items()):
            if route not in served:
                yield Finding(
                    self.id, OBS_DOC, line,
                    f"debug endpoint '{route}' is documented but the "
                    "gateway never dispatches it — the runbook promises "
                    "a surface that 404s")


def _documented_scenarios(repo: RepoIndex) -> Dict[str, int]:
    """Scenario names from the '## Scenario atlas' table's first
    column: backticked hyphenated names."""
    sf = repo.get(OBS_DOC)
    out: Dict[str, int] = {}
    if sf is None:
        return out
    in_section = False
    for i, line in enumerate(sf.lines, 1):
        if line.startswith("## "):
            in_section = line.strip() == "## Scenario atlas"
            continue
        if not in_section or not line.startswith("|"):
            continue
        first_cell = line.split("|")[1]
        for name in re.findall(r"`([a-z0-9-]+)`", first_cell):
            out.setdefault(name, i)
    return out


def _documented_endpoints(repo: RepoIndex) -> Dict[str, int]:
    """Routes from the '## Debug endpoints' table's first column:
    backticked `/v1/debug/<name>` paths (query-string examples after
    `?` are ignored)."""
    sf = repo.get(OBS_DOC)
    out: Dict[str, int] = {}
    if sf is None:
        return out
    in_section = False
    for i, line in enumerate(sf.lines, 1):
        if line.startswith("## "):
            in_section = line.strip() == "## Debug endpoints"
            continue
        if not in_section or not line.startswith("|"):
            continue
        first_cell = line.split("|")[1]
        for name in re.findall(r"`(/v1/debug/[a-z0-9_]+)", first_cell):
            out.setdefault(name, i)
    return out


def _toplevel_sections(fn: ast.FunctionDef) -> Dict[str, int]:
    """Top-level /v1/debug/vars section names debug_vars() can emit:
    keys of the `out`/`out: dict` initializer literal plus every
    `out["name"] = ...` assignment. Nested dict literals (per-peer
    entries etc.) are not sections and are not collected."""
    sections: Dict[str, int] = {}
    for node in ast.walk(fn):
        init = None
        if isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.target.id == "out":
            init = node.value
        elif isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "out"
                        for t in node.targets):
            init = node.value
        if isinstance(init, ast.Dict):
            for key in init.keys:
                if isinstance(key, ast.Constant) \
                        and isinstance(key.value, str):
                    sections.setdefault(key.value, key.lineno)
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "out" \
                        and isinstance(tgt.slice, ast.Constant) \
                        and isinstance(tgt.slice.value, str):
                    sections.setdefault(tgt.slice.value, tgt.lineno)
    return sections
