"""lock-order and donation-flow: the lockmap-backed guberlint rules.

Both rules are thin adapters over `analysis/lockmap.py` (the build is
memoized on the RepoIndex, so `lock-order`, the drift gate, and
`scripts/lockmap_report.py` share one interprocedural pass per run).

`lock-order` — the acquisition-order digraph must be acyclic. A cycle
means two threads can take the same pair of locks in opposite orders,
which is a deadlock waiting for the right interleaving; the PR 14
reshard NOT_MINE/PLANNING deflakes were this class (engine lock vs
transfer-session lock taken in both orders across the import path and
the drill killer thread). The finding is anchored at the first witness
site of the lexicographically smallest edge in the cycle, and renders
every edge with its `path:line` witness chain so the fix (or the waiver
justification) can name the exact frames.

`donation-flow` — a local captured from a donated device-array attribute
(`rows = backend.state`) must not be read after a later donate-and-
rebind dispatch (`backend.state, hits = decide(backend.state, ...)`)
without a fresh re-read: XLA deletes the donated buffer at dispatch, so
the stale capture is a use-after-free that surfaces as
"Array has been deleted" only under the right thread timing — the PR 10
cartographer harvest bug. `lock-discipline` (lexical) checks reads sit
under the lock; this rule checks the *lifetime* ordering even inside a
single function.
"""

from __future__ import annotations

import ast
from typing import Iterable

from gubernator_tpu.analysis import lockmap
from gubernator_tpu.analysis.core import Finding, RepoIndex, Rule, register


def _first_site(edge: lockmap.Edge) -> tuple:
    path, _, line = edge.witness[0].rpartition(":")
    return path, int(line)


@register
class LockOrderRule(Rule):
    id = "lock-order"
    doc = ("the whole-repo lock acquisition-order graph must be acyclic "
           "(every cycle is a deadlock schedule; see `make lockmap`)")

    def check(self, repo: RepoIndex) -> Iterable[Finding]:
        graph = lockmap.build(repo)
        for cycle in graph.cycles():
            edges = graph.cycle_edges(cycle)
            if not edges:
                continue
            anchor = _first_site(edges[0])
            chains = "; ".join(
                f"{e.src}->{e.dst} via {' -> '.join(e.witness)}"
                for e in edges)
            if len(cycle) == 1:
                msg = (f"non-reentrant lock class `{cycle[0]}` can "
                       f"re-acquire itself ({chains}) — self-deadlock; "
                       "break the chain or make the class reentrant")
            else:
                msg = (f"lock-order cycle {' -> '.join(cycle)} — two "
                       f"threads taking these in opposite orders "
                       f"deadlock; edges: {chains}")
            yield Finding(self.id, anchor[0], anchor[1], msg)


@register
class DonationFlowRule(Rule):
    id = "donation-flow"
    doc = ("a local captured from a donated array attr (.state/.fps/"
           ".touch) must be re-read after any donate-and-rebind "
           "dispatch, not used stale")

    def check(self, repo: RepoIndex) -> Iterable[Finding]:
        for f in lockmap.donation_findings(repo):
            yield Finding(
                self.id, f.path, f.line,
                f"`{f.var}` (captured from `{f.receiver}.{f.attr}`) is "
                f"read after the donate-and-rebind dispatch at line "
                f"{f.donated_at} — the donated buffer is deleted at "
                f"dispatch; re-read `{f.receiver}.{f.attr}` (under the "
                "engine lock) after the rebind")
