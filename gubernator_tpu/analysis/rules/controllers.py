"""`controller-bounds` — every autopilot-actuated knob declares bounds.

The autopilot (service/autopilot.py) is only safe because every move is
clamped inside a declared [floor, ceiling] band and paced by a bounded
step — a controller wired to a knob with no declared band is an
unbounded actuator, exactly what the subsystem promises not to be. And
a knob the autopilot can move must be one an operator can find: its env
name needs a row in the knob docs, or the first incident review reads a
flight-recorder `autopilot.move` against a knob nobody documented.

This rule pins both halves mechanically, from the module-level KNOBS /
CONTROLLERS literals (they are literals BY CONTRACT so this parse stays
a dumb AST walk):

- every knob named in a CONTROLLERS entry has a KNOBS entry;
- every KnobSpec declares numeric floor/ceiling/step, with
  floor <= ceiling and step > 0;
- every KNOBS entry's `env` knob appears in the operator docs.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from gubernator_tpu.analysis.core import Finding, RepoIndex, Rule, register

AUTOPILOT = "gubernator_tpu/service/autopilot.py"
KNOB_DOCS = ("docs/OPERATIONS.md", "docs/observability.md")


def _module_literal(tree: ast.AST, name: str) -> Optional[ast.expr]:
    """The value expression of a module-level `NAME = ...` (plain or
    annotated) assignment."""
    for node in ast.iter_child_nodes(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return node.value
        elif isinstance(node, ast.AnnAssign):
            if isinstance(node.target, ast.Name) \
                    and node.target.id == name and node.value is not None:
                return node.value
    return None


@register
class ControllerBoundsRule(Rule):
    id = "controller-bounds"
    doc = ("every autopilot-actuated knob must declare floor/ceiling/"
           "step in the KNOBS registry and its env knob must appear in "
           "the operator docs")

    # overridable for the corpus harness
    autopilot_path = AUTOPILOT
    knob_docs = KNOB_DOCS

    def check(self, repo: RepoIndex) -> Iterable[Finding]:
        sf = repo.get(self.autopilot_path)
        if sf is None or sf.tree is None:
            return  # tree has no autopilot module: nothing to bound
        knobs = self._knob_specs(sf.tree)
        for cname, knob, line in self._actuated(sf.tree):
            if knob not in knobs:
                yield Finding(
                    self.id, self.autopilot_path, line,
                    f"controller '{cname}' actuates knob '{knob}' with "
                    "no KNOBS entry — every controller-movable knob "
                    "must declare its floor/ceiling/step band in the "
                    "central registry")
        for kname, (kwargs, line) in knobs.items():
            missing = [f for f in ("floor", "ceiling", "step")
                       if f not in kwargs]
            if missing:
                yield Finding(
                    self.id, self.autopilot_path, line,
                    f"knob '{kname}' KnobSpec declares no "
                    f"{'/'.join(missing)} — an actuator without a "
                    "declared band/step is unbounded")
                continue
            floor, ceiling, step = (kwargs["floor"], kwargs["ceiling"],
                                    kwargs["step"])
            if not all(isinstance(v, (int, float))
                       for v in (floor, ceiling, step)):
                yield Finding(
                    self.id, self.autopilot_path, line,
                    f"knob '{kname}' floor/ceiling/step must be numeric "
                    "literals (the band is a reviewed constant, not a "
                    "computed value)")
                continue
            if floor > ceiling:
                yield Finding(
                    self.id, self.autopilot_path, line,
                    f"knob '{kname}' declares floor {floor} > ceiling "
                    f"{ceiling} — an empty band")
            if step <= 0:
                yield Finding(
                    self.id, self.autopilot_path, line,
                    f"knob '{kname}' declares step {step} — moves must "
                    "be bounded by a positive step")
            env = kwargs.get("env")
            if isinstance(env, str) and not self._documented(repo, env):
                yield Finding(
                    self.id, self.autopilot_path, line,
                    f"knob '{kname}' env {env} has no row in the knob "
                    f"docs ({', '.join(self.knob_docs)}) — a knob the "
                    "autopilot can move must be one an operator can "
                    "find")

    @staticmethod
    def _knob_specs(tree: ast.AST
                    ) -> Dict[str, Tuple[Dict[str, object], int]]:
        """KNOBS entries: name -> (KnobSpec keyword literals, line)."""
        out: Dict[str, Tuple[Dict[str, object], int]] = {}
        val = _module_literal(tree, "KNOBS")
        if not isinstance(val, ast.Dict):
            return out
        for key, spec in zip(val.keys, val.values):
            if not (isinstance(key, ast.Constant)
                    and isinstance(key.value, str)):
                continue
            kwargs: Dict[str, object] = {}
            if isinstance(spec, ast.Call):
                for kw in spec.keywords:
                    if kw.arg and isinstance(kw.value, ast.Constant):
                        kwargs[kw.arg] = kw.value.value
            out[key.value] = (kwargs, spec.lineno)
        return out

    @staticmethod
    def _actuated(tree: ast.AST) -> List[Tuple[str, str, int]]:
        """CONTROLLERS entries: (controller name, knob name, line)."""
        out: List[Tuple[str, str, int]] = []
        val = _module_literal(tree, "CONTROLLERS")
        if not isinstance(val, (ast.Tuple, ast.List)):
            return out
        for elt in val.elts:
            if not isinstance(elt, ast.Dict):
                continue
            fields: Dict[str, ast.expr] = {
                k.value: v for k, v in zip(elt.keys, elt.values)
                if isinstance(k, ast.Constant) and isinstance(k.value, str)}
            name_node = fields.get("name")
            cname = name_node.value if (
                isinstance(name_node, ast.Constant)
                and isinstance(name_node.value, str)) else "?"
            knobs_node = fields.get("knobs")
            if isinstance(knobs_node, (ast.Tuple, ast.List)):
                for kn in knobs_node.elts:
                    if isinstance(kn, ast.Constant) \
                            and isinstance(kn.value, str):
                        out.append((cname, kn.value, kn.lineno))
        return out

    def _documented(self, repo: RepoIndex, env: str) -> bool:
        for relpath in self.knob_docs:
            sf = repo.get(relpath)
            if sf is not None and env in sf.text:
                return True
        return False
