"""`knob-drift` — every GUBER_* knob flows through the full surface.

The configuration contract this repo has kept since PR 1: a knob that
exists in code must be (a) visible in `cmd/envconf.py` (the one place
the daemon resolves configuration, so `--config` files and the env stay
equivalent), (b) present in `example.conf` (the operator's discovery
surface), and (c) mentioned somewhere under `docs/` (the meaning).
Conversely a knob in `example.conf` that no code reads is a dead
promise. This rule fired for 20+ knobs when it was first written —
observability-plane knobs (PR 9/10) had envconf parsing but never made
the example conf.

Dev-only knobs read before configuration exists (import-time switches
like GUBER_TPU_NO_X64) carry inline waivers at their read site — the
waiver justification documents why they bypass envconf.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Set, Tuple

from gubernator_tpu.analysis.core import Finding, RepoIndex, Rule, register

# a knob literal: GUBER_ followed by caps; the lookahead rejects prose
# prefix mentions (patterns such as GUBER_ETCD_TLS_* name a family, not
# a knob, and must not backtrack into a shorter false match)
KNOB_RE = re.compile(r"GUBER_[A-Z0-9_]*[A-Z0-9](?![A-Z0-9_*])")

ENVCONF = "gubernator_tpu/cmd/envconf.py"
CONF = "example.conf"
DOCS_DIR = "docs"


def _knob_sites(sf) -> Dict[str, List[int]]:
    """knob name -> lines referencing it in one file."""
    out: Dict[str, List[int]] = {}
    for i, line in enumerate(sf.lines, 1):
        for m in KNOB_RE.finditer(line):
            out.setdefault(m.group(0), []).append(i)
    return out


@register
class KnobDriftRule(Rule):
    id = "knob-drift"
    doc = ("every GUBER_* knob in code must be resolved in cmd/envconf.py, "
           "listed in example.conf, and documented under docs/; every "
           "example.conf knob must still be read by code")

    def check(self, repo: RepoIndex) -> Iterable[Finding]:
        # knob -> [(path, line), ...] across all scanned code
        code_sites: Dict[str, List[Tuple[str, int]]] = {}
        for relpath in repo.python_files():
            sf = repo.get(relpath)
            for knob, lines in _knob_sites(sf).items():
                code_sites.setdefault(knob, []).extend(
                    (relpath, ln) for ln in lines)

        conf_sf = repo.get(CONF)
        conf_knobs: Dict[str, int] = {}
        if conf_sf is not None:
            for knob, lines in _knob_sites(conf_sf).items():
                conf_knobs.setdefault(knob, lines[0])

        envconf_knobs: Set[str] = set()
        env_sf = repo.get(ENVCONF)
        if env_sf is not None:
            envconf_knobs = set(_knob_sites(env_sf))

        doc_knobs: Set[str] = set()
        for doc in repo.walk(DOCS_DIR, ".md"):
            doc_knobs |= set(_knob_sites(repo.get(doc)))

        for knob in sorted(code_sites):
            sites = sorted(code_sites[knob])
            missing = []
            if env_sf is not None and knob not in envconf_knobs:
                missing.append("cmd/envconf.py")
            if conf_sf is not None and knob not in conf_knobs:
                missing.append("example.conf")
            if repo.exists(DOCS_DIR) and knob not in doc_knobs:
                missing.append("docs/")
            if not missing:
                continue
            path, line = _waived_or_first(repo, self.id, sites)
            yield Finding(
                self.id, path, line,
                f"{knob} is referenced in code but absent from "
                f"{', '.join(missing)} — add it to the full knob surface "
                "or waive the dev-only read with a justification")

        # dead knobs: promised to operators, read by nothing
        if conf_sf is not None:
            for knob, line in sorted(conf_knobs.items()):
                if knob not in code_sites:
                    yield Finding(
                        self.id, CONF, line,
                        f"{knob} appears in example.conf but no code "
                        "reads it — delete the dead knob or wire it up")


def _waived_or_first(repo: RepoIndex, rule_id: str,
                     sites: List[Tuple[str, int]]) -> Tuple[str, int]:
    """Attach the finding to a waived reference site when one exists
    (so one inline waiver at any read site covers the knob), else to
    the first reference."""
    for path, line in sites:
        sf = repo.get(path)
        if sf is not None and sf.waived(rule_id, line) is not None:
            return path, line
    return sites[0]
