import sys

from gubernator_tpu.analysis.cli import main

sys.exit(main())
