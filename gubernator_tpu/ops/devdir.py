"""Device-resident key directory: open-addressing probe on the chip.

GRADUATED (round-3; prototype was round-1 review item 6, hardened per the
round-2 verdict item 2). The production engines map key strings to table
slots in the host key directory (native/keydir.cpp) — the admitted
host-side cost at multi-M decisions/s (keydir.cpp:5-8, SURVEY §7 hard
part #1: "without host round-trips per key"). This module moves the probe
on-device: the host ships only an 8-byte hash fingerprint per request,
and the chip resolves (or claims) the slot with a vectorized
open-addressing probe — the slot never returns to the host, feeding
decide() directly in the same compiled program (models/devdir_engine.py).

Design:
- the directory is one i64[C] fingerprint column plus an i64[C] last-use
  stamp column; slot IS the probe position, so directory and bucket table
  share indexing (the bucket row's algo=-1 vacancy remains the state
  authority).
- probe: D candidate positions (h + d) % C gathered in ONE [B, D] gather
  (the row-major lesson: batched gathers beat per-element probes), then a
  branchless first-match / first-empty select.
- fingerprints are fnv1a64 masked to 63 bits, +1 to keep 0 = empty.
- IN-BATCH PRIORITY PASS: two DISTINCT keys claiming one position in the
  same batch are resolved by an argsort pass (duplicate claim positions
  sort adjacent; the highest lane wins, losers demote to the retry lane)
  — no last-scatter-wins races, and no O(C) scratch per window.
- AGED EVICTION: a probe whose candidate window has no match and no
  vacancy claims the LEAST-RECENTLY-USED candidate instead (touch stamps
  maintained on every match/claim), after protecting positions matched or
  claimed this batch. The evicted tenant's bucket simply ends (the host
  directory's LRU semantics); un-evictable probes (every candidate
  touched this very batch) return the retry lane.

Retry lanes (slot == -1) are re-dispatched by the engine in a follow-up
window — by then the contested claims have settled. 63-bit fingerprint
equality of two DISTINCT keys (~2^-63 per pair) aliases them to one
bucket; documented, not defended.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from gubernator_tpu.ops.decide import I32, I64, ROW_ALGO, pad_to_drop
from gubernator_tpu.utils.fnv import fnv1a_64_str

PROBE_DEPTH = 16  # candidate positions per key; full = retry lane


def key_fingerprint(key: str) -> int:
    """63-bit nonzero fingerprint of a key (0 is the empty sentinel)."""
    return (fnv1a_64_str(key) & ((1 << 63) - 1)) | 1


def make_fingerprints(capacity: int) -> jax.Array:
    return jnp.zeros((capacity,), I64)


def make_touch(capacity: int) -> jax.Array:
    return jnp.zeros((capacity,), I64)


def _claim_winners(claim_ok: jax.Array, cslot: jax.Array) -> jax.Array:
    """In-batch priority pass: among lanes claiming the same position,
    exactly one (the highest lane id) wins. Argsort groups duplicate
    positions adjacently; a lane wins iff its (position, lane) key is the
    last of its position group. O(B log B), no O(C) scratch."""
    B = cslot.shape[0]
    lane = jnp.arange(B, dtype=I64)
    sent = jnp.asarray(jnp.iinfo(jnp.int64).max // 2, I64)
    key = jnp.where(claim_ok, cslot.astype(I64) * B + lane, sent + lane)
    order = jnp.argsort(key)
    sorted_pos = key[order] // B
    is_last = jnp.concatenate(
        [sorted_pos[1:] != sorted_pos[:-1],
         jnp.ones((1,), dtype=bool)])
    won = jnp.zeros((B,), dtype=bool).at[order].set(is_last)
    return won & claim_ok


def probe_assign(
    fps: jax.Array, hashes: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Resolve-or-claim a slot for every key hash, on device (no eviction
    — the standalone building block; engines use probe_assign_evict).

    fps: i64[C] fingerprint column; hashes: i64[B] (0 for padding lanes).
    Returns (new_fps, slot i32[B], fresh bool[B]); slot is -1 for padding
    lanes, probes that exhausted PROBE_DEPTH, and in-batch claim LOSERS
    (distinct keys contesting one empty position — retry next window).
    """
    C = fps.shape[0]
    B = hashes.shape[0]
    active = hashes != 0
    base = jnp.abs(hashes) % C
    # ONE [B, D] gather instead of D sequential probes
    pos = (base[:, None] + jnp.arange(PROBE_DEPTH, dtype=I64)[None, :]) % C
    cand = fps[pos]  # i64[B, D]

    is_match = cand == hashes[:, None]
    is_empty = cand == 0
    big = jnp.asarray(PROBE_DEPTH + 1, I32)
    d_idx = jnp.arange(PROBE_DEPTH, dtype=I32)[None, :]
    first_match = jnp.min(jnp.where(is_match, d_idx, big), axis=1)
    first_empty = jnp.min(jnp.where(is_empty, d_idx, big), axis=1)

    matched = first_match <= PROBE_DEPTH
    claimable = (~matched) & (first_empty <= PROBE_DEPTH)
    depth = jnp.where(matched, first_match, first_empty)
    slot64 = jnp.take_along_axis(
        pos, jnp.minimum(depth, PROBE_DEPTH - 1)[:, None].astype(I64), axis=1
    )[:, 0]

    # in-batch priority pass: distinct keys contesting one empty position
    # (duplicate hashes of the SAME key converge benignly, but the engine
    # never sends same-key duplicates in one window anyway)
    want = active & claimable
    won = _claim_winners(want, slot64)
    ok = active & (matched | won)
    slot = jnp.where(ok, slot64, -1).astype(I32)
    fresh = won

    claim_slot = pad_to_drop(jnp.where(fresh, slot, -1), C)
    new_fps = fps.at[claim_slot].set(
        jnp.where(fresh, hashes, 0), mode="drop")
    return new_fps, slot, fresh


def probe_assign_evict(
    fps: jax.Array, touch: jax.Array, hashes: jax.Array, seq
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """probe_assign + aged (LRU-approximate) eviction: a full candidate
    window claims its least-recently-used position instead of failing.

    `seq` is a per-DISPATCH monotone epoch (NOT wall time: many windows
    run per millisecond, and eviction protection must cover exactly the
    positions matched/claimed THIS batch — a wall-clock stamp would also
    freeze out retries issued in the same millisecond).

    Returns (fps, touch, slot i32[B], fresh bool[B], retry bool[B]);
    retry lanes (in-batch claim losers, un-evictable windows) re-dispatch
    in a follow-up window with a fresh epoch.
    """
    C = fps.shape[0]
    B = hashes.shape[0]
    now = jnp.asarray(seq, I64)
    active = hashes != 0
    base = jnp.abs(hashes) % C
    pos = (base[:, None] + jnp.arange(PROBE_DEPTH, dtype=I64)[None, :]) % C
    cand = fps[pos]

    is_match = (cand == hashes[:, None]) & active[:, None]
    is_empty = cand == 0
    big = jnp.asarray(PROBE_DEPTH + 1, I32)
    d_idx = jnp.arange(PROBE_DEPTH, dtype=I32)[None, :]
    first_match = jnp.min(jnp.where(is_match, d_idx, big), axis=1)
    first_empty = jnp.min(jnp.where(is_empty, d_idx, big), axis=1)
    matched = active & (first_match <= PROBE_DEPTH)
    mslot = jnp.take_along_axis(
        pos, jnp.minimum(first_match, PROBE_DEPTH - 1)[:, None].astype(I64),
        axis=1)[:, 0]

    # protect matched positions from eviction BEFORE victims are chosen:
    # their touch moves to `now`, so no victim this batch can be younger
    mpos = pad_to_drop(jnp.where(matched, mslot, -1), C)
    touch = touch.at[mpos].set(now, mode="drop")

    has_empty = first_empty <= PROBE_DEPTH
    eslot = jnp.take_along_axis(
        pos, jnp.minimum(first_empty, PROBE_DEPTH - 1)[:, None].astype(I64),
        axis=1)[:, 0]
    ctouch = touch[pos]  # AFTER the match-touch scatter
    oldest_d = jnp.argmin(ctouch, axis=1)
    vslot = jnp.take_along_axis(pos, oldest_d[:, None], axis=1)[:, 0]
    vtouch = jnp.take_along_axis(ctouch, oldest_d[:, None], axis=1)[:, 0]
    can_evict = vtouch < now  # strictly older than this batch

    want_claim = active & ~matched
    cslot = jnp.where(has_empty, eslot, vslot)
    claim_ok = want_claim & (has_empty | can_evict)
    won = _claim_winners(claim_ok, cslot)

    slot = jnp.where(matched, mslot,
                     jnp.where(won, cslot, -1)).astype(I32)
    fresh = won
    retry = active & (slot < 0)

    wpos = pad_to_drop(jnp.where(won, cslot, -1), C)
    fps = fps.at[wpos].set(jnp.where(won, hashes, 0), mode="drop")
    touch = touch.at[wpos].set(now, mode="drop")
    return fps, touch, slot, fresh, retry


def refresh_vacancies(fps: jax.Array, table: jax.Array,
                      now_ms) -> jax.Array:
    """Clear fingerprints whose bucket row is vacant or expired — the lazy
    recycling pass (host directory handles this with its LRU; here one
    full-column sweep, amortized across many windows)."""
    from gubernator_tpu.ops.decide import ROW_EXPIRE

    dead = (table[:, ROW_ALGO] < 0) | (
        jnp.asarray(now_ms, I64) > table[:, ROW_EXPIRE])
    return jnp.where(dead, jnp.zeros_like(fps), fps)
