"""Device-resident key directory: open-addressing probe on the chip.

PROTOTYPE (round-1 review item 6). The production engines map key strings
to table slots in the host key directory (native/keydir.cpp) — the
admitted host-side bottleneck at multi-M decisions/s (keydir.cpp:5-8,
SURVEY §7 hard part #1: "without host round-trips per key"). This module
moves the probe on-device: the host ships only an 8-byte hash fingerprint
per request, and the chip resolves (or claims) the slot with a vectorized
open-addressing probe — the slot never returns to the host, feeding
decide() directly in the same compiled program.

Design:
- the directory is one i64[C] fingerprint column; slot IS the probe
  position, so directory and bucket table share indexing (the bucket
  row's algo=-1 vacancy remains the state authority).
- probe: D candidate positions (h + d) % C gathered in ONE [B, D] gather
  (the row-major lesson: batched gathers beat per-element probes), then a
  branchless first-match / first-empty select.
- fingerprints are fnv1a64 masked to 63 bits, +1 to keep 0 = empty.

Known prototype limits (documented, not hidden):
- two DIFFERENT keys colliding on the same empty position within ONE
  batch both claim it (last scatter wins); the engines' rounds machinery
  dedups same-key repeats but not distinct-key hash collisions. A
  production version needs an in-batch priority pass.
- no LRU eviction: a probe that finds neither match nor vacancy within D
  returns slot -1 (host fallback lane). Capacity is over-provisioned 2x
  instead, and expiry recycles rows lazily via refresh_vacancies().

Honest verdict from the bench comparison (DESIGN.md "Device-resident key
lookup"): see the numbers there — the host C++ directory stays the
default; this path wins only when host CPU, not the device, is the
serving bottleneck.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from gubernator_tpu.ops.decide import I32, I64, ROW_ALGO, pad_to_drop
from gubernator_tpu.utils.fnv import fnv1a_64_str

PROBE_DEPTH = 16  # candidate positions per key; full = host-fallback lane


def key_fingerprint(key: str) -> int:
    """63-bit nonzero fingerprint of a key (0 is the empty sentinel)."""
    return (fnv1a_64_str(key) & ((1 << 63) - 1)) | 1


def make_fingerprints(capacity: int) -> jax.Array:
    return jnp.zeros((capacity,), I64)


def probe_assign(
    fps: jax.Array, hashes: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Resolve-or-claim a slot for every key hash, on device.

    fps: i64[C] fingerprint column; hashes: i64[B] (0 for padding lanes).
    Returns (new_fps, slot i32[B], fresh bool[B]); slot is -1 for padding
    lanes and for probes that exhausted PROBE_DEPTH (host fallback).
    """
    C = fps.shape[0]
    B = hashes.shape[0]
    active = hashes != 0
    base = jnp.abs(hashes) % C
    # ONE [B, D] gather instead of D sequential probes
    pos = (base[:, None] + jnp.arange(PROBE_DEPTH, dtype=I64)[None, :]) % C
    cand = fps[pos]  # i64[B, D]

    is_match = cand == hashes[:, None]
    is_empty = cand == 0
    big = jnp.asarray(PROBE_DEPTH + 1, I32)
    d_idx = jnp.arange(PROBE_DEPTH, dtype=I32)[None, :]
    first_match = jnp.min(jnp.where(is_match, d_idx, big), axis=1)
    first_empty = jnp.min(jnp.where(is_empty, d_idx, big), axis=1)

    matched = first_match <= PROBE_DEPTH
    claimable = (~matched) & (first_empty <= PROBE_DEPTH)
    depth = jnp.where(matched, first_match, first_empty)
    slot64 = jnp.take_along_axis(
        pos, jnp.minimum(depth, PROBE_DEPTH - 1)[:, None].astype(I64), axis=1
    )[:, 0]
    ok = active & (matched | claimable)
    slot = jnp.where(ok, slot64, -1).astype(I32)
    fresh = ok & claimable

    # claim the fresh positions (duplicate hashes in one batch converge on
    # the same position and write the same fingerprint — benign; DISTINCT
    # colliding keys are the documented prototype limit)
    claim_slot = pad_to_drop(jnp.where(fresh, slot, -1), C)
    new_fps = fps.at[claim_slot].set(
        jnp.where(fresh, hashes, 0), mode="drop")
    return new_fps, slot, fresh


def refresh_vacancies(fps: jax.Array, table: jax.Array,
                      now_ms) -> jax.Array:
    """Clear fingerprints whose bucket row is vacant or expired — the lazy
    recycling pass (host directory handles this with its LRU; here one
    full-column sweep, amortized across many windows)."""
    from gubernator_tpu.ops.decide import ROW_EXPIRE

    dead = (table[:, ROW_ALGO] < 0) | (
        jnp.asarray(now_ms, I64) > table[:, ROW_EXPIRE])
    return jnp.where(dead, jnp.zeros_like(fps), fps)
