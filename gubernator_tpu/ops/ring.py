"""Pallas ICI ring collectives.

The GLOBAL sync's two information flows (parallel/global_sync.py) are both
all-reduce-sums of small int64 vectors: per-device hit deltas, and the
owner-masked response columns whose sum IS the broadcast (non-owners
contribute zeros). XLA lowers `psum` to its own collective schedule; this
module provides the same reduction as an explicit Pallas ring — a
rotate-and-accumulate: each device starts its own value around the ring,
and on every hop forwards the value it just RECEIVED to its right
neighbour over ICI RDMA (`pltpu.make_async_remote_copy`) while adding it
to a local accumulator, so after N-1 hops every device has seen (and
summed) every other device's original value.

For the ~8 KB payloads GLOBAL sync moves, XLA's psum is already optimal and
remains the default (DESIGN.md "Why the decide kernel is XLA, not Pallas" —
same reasoning); the ring exists as the hand-scheduled ICI path for
payloads/topologies where XLA's choice is wrong, and as the compiled
building block a future in-kernel hot-key broadcast would extend. It runs
under Pallas TPU interpret mode on the CPU test mesh (tests/test_ring.py
holds it bit-equal to psum) and compiles for real ICI on TPU.

Reference contrast: the equivalent data movement in the reference is the
GLOBAL gRPC fan-in + fan-out (global.go:116-156, 219-236) — O(peers) unary
RPCs per window instead of one ring pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

I32 = jnp.int32


def _ring_kernel(n_devices: int, axis_name: str, mesh_axes,
                 local_ref, out_ref, comm_ref, acc_ref, send_sem, recv_sem):
    """All-reduce-sum around a 1-D ring over mesh axis `axis_name`.

    comm_ref is a 2-slot VMEM double buffer: slot `step % 2` holds the value
    being forwarded this hop, the RDMA lands the neighbour's value in slot
    `(step + 1) % 2`. acc_ref accumulates everything seen. `mesh_axes` is
    the full axis-name tuple of the enclosing shard_map's mesh — MESH
    addressing takes one coordinate per axis, and non-ring axes keep the
    sender's own coordinate."""
    my_id = jax.lax.axis_index(axis_name).astype(I32)
    n = jnp.int32(n_devices)
    acc_ref[...] = local_ref[...]
    comm_ref[0] = local_ref[...]
    for step in range(n_devices - 1):
        dst = jax.lax.rem(my_id + jnp.int32(1), n)
        if len(mesh_axes) == 1:
            # LOGICAL scalar addressing — the only form the CPU interpreter
            # supports (jax dma_start discharge handles 1 named axis only)
            device_id, id_type = dst, pltpu.DeviceIdType.LOGICAL
        else:
            # compiled Mosaic accepts per-axis MESH coordinates
            device_id = tuple(
                dst if a == axis_name else jax.lax.axis_index(a).astype(I32)
                for a in mesh_axes
            )
            id_type = pltpu.DeviceIdType.MESH
        send_slot, recv_slot = step % 2, (step + 1) % 2
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_ref.at[send_slot],
            dst_ref=comm_ref.at[recv_slot],
            send_sem=send_sem.at[send_slot],
            recv_sem=recv_sem.at[recv_slot],
            device_id=device_id,
            device_id_type=id_type,
        )
        rdma.start()
        rdma.wait()
        acc_ref[...] = acc_ref[...] + comm_ref[recv_slot]
    out_ref[...] = acc_ref[...]


def make_ring_all_reduce(n_devices: int, length: int, dtype=jnp.int64,
                         axis_name: str = "shard",
                         mesh_axes=None,
                         interpret: bool = None,
                         collective_id: int = 0):
    """fn(x: dtype[length]) -> dtype[length], for use INSIDE a shard_map
    whose mesh includes axis `axis_name` of n_devices. Sums every device's
    x around the ring; other mesh axes (`mesh_axes` lists the full axis
    order, default just the ring axis) stay at the caller's coordinate.

    `interpret` defaults to True off-TPU (the CPU test mesh) and False on
    TPU, where the kernel compiles to real ICI RDMAs. `collective_id`
    names the barrier-semaphore group: rings that may execute CONCURRENTLY
    in one program (no data dependence between them) must use distinct ids
    or they consume each other's semaphore signals.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    kernel = functools.partial(
        _ring_kernel, n_devices, axis_name,
        tuple(mesh_axes) if mesh_axes is not None else (axis_name,))

    def ring(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((length,), dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((2, length), dtype),
                pltpu.VMEM((length,), dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            interpret=interpret,
            compiler_params=pltpu.CompilerParams(collective_id=collective_id),
        )(x)

    return ring
