from gubernator_tpu.ops.decide import (
    ReqBatch,
    RespBatch,
    TableState,
    decide,
    make_decide_jit,
    make_table,
)

__all__ = [
    "TableState",
    "ReqBatch",
    "RespBatch",
    "decide",
    "make_decide_jit",
    "make_table",
]
