"""Sequential pure-Python oracle for the decision kernel.

This is the executable specification of the rate-limit semantics: a direct,
readable, one-request-at-a-time implementation of the behavior the batched
kernel (ops/decide.py) must reproduce. Tests drive random request streams
through both and require bit-identical responses and state.

The semantics follow the reference algorithms (reference: algorithms.go:24-336)
including its quirks:

- token OVER_LIMIT is sticky on the stored row once remaining hits zero,
  and is reported even on hits=0 peeks (algorithms.go:112-115);
- a request for more than remains is rejected WITHOUT deducting
  (algorithms.go:125-129, :273-278);
- a first-ever request with hits > limit stores an undrained token bucket
  (remaining = limit) but an empty leaky bucket (algorithms.go:160-165,:319-323);
- RESET_REMAINING deletes a token bucket but refills a leaky bucket
  (algorithms.go:36-47, :205-207);
- leaky leak math is integer: rate = duration // limit ms/token,
  leak = elapsed // rate (algorithms.go:214,:233-240), and UpdatedAt snaps
  to `now` on any non-peek request against a non-empty bucket — the
  sub-rate elapsed residue is consumed (algorithms.go:261-264).

Documented deviations from the reference (see PARITY.md): leaky expiry is
refreshed as now+duration (the reference's `now*duration` at algorithms.go:287
is an evident typo), leaky reset_time is now+rate on creation too (the
reference returns a bare duration at algorithms.go:315), and rates are
clamped to >= 1ms/token to avoid the reference's division-by-zero panic when
limit > duration.

Validity domain: the oracle computes with python's unbounded ints, while
the kernel (and the reference's Go int64 arithmetic) wraps at 2^63. The
two agree for any inputs whose intermediate sums stay within int64 —
e.g. now + duration, remaining + leak — which is every realistic request
and everything the differential fuzz generates; feed durations near 2^63
and the oracle diverges from BOTH wrap-identical implementations.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from gubernator_tpu.types import Algorithm, Behavior, Status

VACANT = -1


@dataclasses.dataclass
class Row:
    """One bucket row — mirrors TableState columns."""

    algo: int = VACANT
    limit: int = 0
    remaining: int = 0
    duration: int = 0
    stamp: int = 0  # token CreatedAt / leaky UpdatedAt
    expire_at: int = 0
    status: int = 0


@dataclasses.dataclass
class OracleResp:
    status: int
    limit: int
    remaining: int
    reset_time: int


def oracle_decide(
    table: Dict[str, Row],
    key: str,
    *,
    hits: int,
    limit: int,
    duration: int,
    algorithm: int,
    behavior: int,
    now: int,
    greg_expire: int = 0,
    greg_interval: int = 0,
) -> OracleResp:
    """Apply one request to `table`, mutating it; returns the response."""
    greg = bool(behavior & Behavior.DURATION_IS_GREGORIAN)
    reset_rem = bool(behavior & Behavior.RESET_REMAINING)

    row = table.get(key)
    # expiry-on-read + algorithm switch both mean "no usable row"
    alive = row is not None and row.algo == algorithm and now <= row.expire_at

    if algorithm == Algorithm.TOKEN_BUCKET:
        if alive:
            assert row is not None
            if reset_rem:
                del table[key]
                return OracleResp(Status.UNDER_LIMIT, limit, limit, 0)
            rem = min(row.remaining, limit) if row.limit != limit else row.remaining
            new_exp = greg_expire if greg else row.stamp + duration
            dur_changed = row.duration != duration
            if dur_changed and new_exp < now:
                del table[key]
                alive = False  # fall through to create
            else:
                exp = new_exp if dur_changed else row.expire_at
                status_resp = row.status
                status_store = row.status
                if hits != 0:
                    if rem == 0:
                        status_resp = status_store = Status.OVER_LIMIT
                    elif hits > rem:
                        status_resp = Status.OVER_LIMIT
                    else:
                        rem -= hits
                row.limit = limit
                row.remaining = rem
                row.duration = duration
                row.expire_at = exp
                row.status = status_store
                return OracleResp(status_resp, limit, rem, exp)
        # vacant / expired / switched / recreated
        exp = greg_expire if greg else now + duration
        over = hits > limit
        rem = limit if over else limit - hits
        table[key] = Row(
            algo=Algorithm.TOKEN_BUCKET,
            limit=limit,
            remaining=rem,
            duration=duration,
            stamp=now,
            expire_at=exp,
            status=Status.UNDER_LIMIT,
        )
        return OracleResp(
            Status.OVER_LIMIT if over else Status.UNDER_LIMIT, limit, rem, exp
        )

    # ---- leaky bucket ----
    if alive:
        assert row is not None
        rem = limit if reset_rem else row.remaining
        dur = greg_expire - now if greg else duration
        rate = max((greg_interval if greg else duration) // max(limit, 1), 1)
        elapsed = max(now - row.stamp, 0)
        rem = min(limit, rem + elapsed // rate)
        rem_zero = rem == 0
        over = hits > rem
        deduct = hits != 0 and not rem_zero and not over
        if not rem_zero and hits != 0:
            row.stamp = now
        if deduct:
            row.expire_at = now + dur
        new_rem = rem - hits if deduct else rem
        row.limit = limit
        row.duration = dur
        row.remaining = new_rem
        status = (
            Status.OVER_LIMIT
            if (rem_zero or (hits != 0 and over))
            else Status.UNDER_LIMIT
        )
        return OracleResp(status, limit, new_rem, now + rate)

    dur = greg_expire - now if greg else duration
    rate = max(dur // max(limit, 1), 1)
    over = hits > limit
    rem = 0 if over else limit - hits
    table[key] = Row(
        algo=Algorithm.LEAKY_BUCKET,
        limit=limit,
        remaining=rem,
        duration=dur,
        stamp=now,
        expire_at=now + dur,
        status=Status.UNDER_LIMIT,
    )
    return OracleResp(
        Status.OVER_LIMIT if over else Status.UNDER_LIMIT, limit, rem, now + rate
    )
