"""The batched rate-limit decision kernel.

This is the TPU-native replacement for the reference's per-key bucket state
machines (reference: algorithms.go:24-336). Where the reference walks one
request at a time through branchy Go code under a global cache mutex
(reference: gubernator.go:327-347), here the whole batch window is a single
branchless masked tensor program:

    gather state rows -> compute token & leaky paths as mask lattices
                      -> select -> scatter rows back

State is ONE row-major i64[C, 8] array in HBM — 64 bytes per key slot, ~640 MB
at 10M keys — resident on one chip, shardable across a mesh (parallel/).
Row-major matters enormously on TPU: XLA executes random-index gather/scatter
roughly element-at-a-time, so a struct-of-arrays layout (seven separate
columns) costs 14 serialized random HBM touches per decision and capped the
chip at ~1M decisions/s; one 64-byte row gather + one row scatter per
decision runs the same workload ~5.6x faster (measured on v5e — see
DESIGN.md "Row-major state").

Semantics are bit-exact with the reference's integer math (the reference's
leaky bucket is already integer: ``rate = duration/limit`` and
``leak = elapsed/rate`` are int64 divisions, algorithms.go:214,235), with a
small set of deliberate bug-fix deviations documented in PARITY.md and
mirrored by the oracle (ops/oracle.py) used to test this kernel.

Batch-internal duplicate keys: the reference serializes all requests under a
mutex, so two hits to one key in a window observe each other. A scatter with
duplicate indices cannot express the OVER_LIMIT-doesn't-deduct rule
(algorithms.go:125-129), so the engine (models/engine.py) splits a window
into collision-free *rounds* — occurrence k of every key goes to round k.
Almost all real windows are round-1-only.
"""

from __future__ import annotations

import threading
from typing import Dict, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp

from gubernator_tpu.obs import witness
from gubernator_tpu.types import Algorithm, Behavior, Status


class KernelTelemetry:
    """Process-wide kernel dispatch accounting + cost introspection.

    The engines report every device launch here — which kernel (wide /
    compact / lean, per-window / scan), at which width, at which scan
    depth — so an operator can see the compiled-program mix actually
    serving traffic (each distinct shape is one XLA program; an unexpected
    width churn here means warmup() and live traffic disagree). Totals are
    process-wide: in-process cluster harnesses share one registry, exactly
    like the shared jit caches they mirror. Exported in /v1/debug/vars
    ("kernel") and as engine_kernel_dispatch_total{kernel,width}.

    The profiling plane (obs/profile.py) extends each (kernel, width)
    with a live dispatch-time histogram (`dur_ns` on note) and a lazily
    computed XLA cost record — flops, bytes accessed, HLO fingerprint —
    from the abstract shapes of the first real dispatch (`offer_probe`;
    the costs compile OFF the serving path, on first /v1/debug/kernels
    access)."""

    def __init__(self):
        self._lock = witness.make_lock("kernel.telemetry")
        self._counts: Dict[Tuple[str, int], int] = {}
        self._lanes = 0
        self._hists: Dict[Tuple[str, int], "object"] = {}
        self._probes: Dict[Tuple[str, int], tuple] = {}
        self._costs: Dict[Tuple[str, int], dict] = {}

    def note(self, kernel: str, width: int, depth: int = 1,
             lanes: int = 0, dur_ns: int = 0) -> None:
        """One dispatch of `kernel` at staging width `width` retiring
        `depth` windows (scan kernels) and `lanes` live lanes; `dur_ns`,
        when nonzero, is the dispatch-call wall time."""
        key = (kernel, width)
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + depth
            self._lanes += lanes
            hist = self._hists.get(key) if dur_ns else None
            if dur_ns and hist is None:
                from gubernator_tpu.obs.profile import PhaseHist

                hist = self._hists.setdefault(key, PhaseHist())
        if dur_ns and hist is not None:
            hist.observe(dur_ns)

    def needs_probe(self, kernel: str, width: int) -> bool:
        """True until a cost probe is parked for (kernel, width) — a
        single dict test, cheap enough for the dispatch hot path."""
        return (kernel, width) not in self._probes

    def offer_probe(self, kernel: str, width: int, fn, args) -> None:
        """Park the abstract call shape of (kernel, width)'s first real
        dispatch: `fn` is the jitted callable, `args` its concrete
        arguments (captured BEFORE the call — donation invalidates them
        after). Cost analysis lowers/compiles from these avals later,
        off the serving path."""
        avals = tuple(
            jax.ShapeDtypeStruct(a.shape, a.dtype)
            if hasattr(a, "shape") and hasattr(a, "dtype") else a
            for a in args)
        with self._lock:
            self._probes.setdefault((kernel, width), (fn, avals))

    def _compute_cost(self, fn, avals) -> dict:
        """Lower + compile one probe and extract the cost record. Any
        failure (backend without cost analysis, shape drift) degrades to
        an error record — introspection must not break the endpoint."""
        from gubernator_tpu.obs.profile import hlo_fingerprint

        out: dict = {}
        try:
            lowered = fn.lower(*avals)
            out["fingerprint"] = hlo_fingerprint(lowered.as_text())
        except Exception as e:  # noqa: BLE001 — degrade, don't break
            return {"error": f"lower: {e}"}
        try:
            ca = lowered.compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if ca:
                out["flops"] = float(ca.get("flops", 0.0))
                out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
        except Exception as e:  # noqa: BLE001 — degrade, don't break
            out["cost_error"] = str(e)
        return out

    def kernel_costs(self) -> Dict[Tuple[str, int], dict]:
        """Cost records for every probed (kernel, width), computing and
        caching any not yet analyzed (first call after new shapes pays
        the compiles; callers are debug endpoints, never serving)."""
        with self._lock:
            pending = {k: v for k, v in self._probes.items()
                       if k not in self._costs}
        for key, (fn, avals) in pending.items():
            cost = self._compute_cost(fn, avals)
            with self._lock:
                self._costs[key] = cost
        with self._lock:
            return dict(self._costs)

    def kernels_body(self) -> dict:
        """The schema-pinned /v1/debug/kernels body
        (tests/test_debug_schema.py)."""
        from gubernator_tpu.obs.profile import KERNELS_SCHEMA_VERSION

        costs = self.kernel_costs()
        with self._lock:
            counts = dict(self._counts)
            hists = dict(self._hists)
            lanes = self._lanes
        kernels = {}
        for (k, w), n in sorted(counts.items()):
            hist = hists.get((k, w))
            kernels[f"{k}@{w}"] = {
                "windows": n,
                "dispatch_ns": hist.snapshot() if hist is not None else None,
                "cost": costs.get((k, w)),
            }
        return {
            "schema_version": KERNELS_SCHEMA_VERSION,
            "lanes_total": lanes,
            "kernels": kernels,
        }

    def fingerprints(self) -> Dict[str, str]:
        """{kernel@width: HLO fingerprint} for every analyzed probe."""
        return {f"{k}@{w}": c["fingerprint"]
                for (k, w), c in self.kernel_costs().items()
                if "fingerprint" in c}

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "windows": {f"{k}@{w}": n
                            for (k, w), n in sorted(self._counts.items())},
                "lanes_total": self._lanes,
            }

    def counts(self) -> Dict[Tuple[str, int], int]:
        with self._lock:
            return dict(self._counts)

    def dispatch_totals(self) -> Dict[Tuple[str, int], Tuple[int, int]]:
        """{(kernel, width): (dispatches, total_ns)} — the cheap scrape
        read behind engine_kernel_dispatch_seconds (no quantile math)."""
        with self._lock:
            hists = dict(self._hists)
        return {key: hist.totals() for key, hist in hists.items()}


kernel_telemetry = KernelTelemetry()

I32 = jnp.int32
I64 = jnp.int64

# State-column algorithm codes: table slots hold -1 when vacant.
_VACANT = -1


# Row field indices of the i64[..., C, TABLE_ROW_FIELDS] bucket table.
# `stamp` is the token bucket's CreatedAt and the leaky bucket's UpdatedAt
# (the reference keeps them in two different structs, store.go:11-24);
# `status` persists the token bucket's sticky OVER_LIMIT
# (algorithms.go:113-115); the 8th field pads the row to 64 bytes so one
# slot is one aligned DMA burst.
ROW_ALGO = 0  # -1 vacant, 0 token, 1 leaky
ROW_LIMIT = 1
ROW_REMAINING = 2
ROW_DURATION = 3  # ms
ROW_STAMP = 4  # unix ms
ROW_EXPIRE = 5  # unix ms (doubles as token ResetTime)
ROW_STATUS = 6
TABLE_ROW_FIELDS = 8

# The device table type: plain jax.Array i64[..., C, TABLE_ROW_FIELDS].
TableState = jax.Array


class ReqBatch(NamedTuple):
    """One device-ready batch window of requests.

    `slot` is the table row the host key-directory assigned; -1 marks padding
    lanes (dropped on scatter). `fresh` is True when the directory newly
    assigned (or recycled) the slot, so whatever the row holds is garbage.
    `greg_expire`/`greg_interval` are host-precomputed calendar values, only
    read when the DURATION_IS_GREGORIAN bit is set.
    """

    slot: jax.Array  # i32[B]
    hits: jax.Array  # i64[B]
    limit: jax.Array  # i64[B]
    duration: jax.Array  # i64[B]
    algorithm: jax.Array  # i32[B]
    behavior: jax.Array  # i32[B]
    greg_expire: jax.Array  # i64[B]
    greg_interval: jax.Array  # i64[B]
    fresh: jax.Array  # bool[B]


class RespBatch(NamedTuple):
    status: jax.Array  # i32[B]
    limit: jax.Array  # i64[B]
    remaining: jax.Array  # i64[B]
    reset_time: jax.Array  # i64[B]


def make_table(capacity: int) -> TableState:
    """Fresh vacant table: i64[capacity, 8] rows with algo = -1."""
    return (
        jnp.zeros((capacity, TABLE_ROW_FIELDS), I64)
        .at[:, ROW_ALGO].set(_VACANT)
    )


def _sel(default: jax.Array, *pairs) -> jax.Array:
    """Chained masked select; later pairs win over earlier ones."""
    out = default
    for mask, val in pairs:
        out = jnp.where(mask, val, out)
    return out


def pad_to_drop(slot: jax.Array, capacity: int) -> jax.Array:
    """Remap -1 padding lanes PAST capacity so scatter mode="drop" discards
    them: drop only drops out-of-range-high indices — negatives wrap
    NumPy-style, so a raw -1 lane would scatter into the LAST slot and
    clobber whatever bucket lives there once the table fills. Every scatter
    of host-routed slots must go through this."""
    return jnp.where(slot < 0, capacity, slot)


def decide(state: TableState, reqs: ReqBatch, now_ms: jax.Array) -> Tuple[TableState, RespBatch]:
    """Apply one collision-free batch of requests to the table.

    Pure function: returns the updated table and per-request responses.
    All requests in the batch must target distinct slots (engine guarantees
    via rounds); padding lanes carry slot == -1.
    """
    now = jnp.asarray(now_ms, I64)
    slot = reqs.slot
    active = slot >= 0
    gslot = jnp.maximum(slot, 0)  # clipped gather index for padding lanes

    # ONE 64-byte row gather per lane (the layout that keeps TPU
    # gather/scatter off the serialized random-element path)
    rows = state[gslot]  # i64[B, 8]
    st_algo = rows[:, ROW_ALGO]
    st_limit = rows[:, ROW_LIMIT]
    st_rem = rows[:, ROW_REMAINING]
    st_dur = rows[:, ROW_DURATION]
    st_stamp = rows[:, ROW_STAMP]
    st_exp = rows[:, ROW_EXPIRE]
    st_status = rows[:, ROW_STATUS]

    r_hits = reqs.hits
    r_limit = reqs.limit
    r_dur = reqs.duration
    is_tok = reqs.algorithm == Algorithm.TOKEN_BUCKET
    greg = (reqs.behavior & Behavior.DURATION_IS_GREGORIAN) != 0
    reset_rem = (reqs.behavior & Behavior.RESET_REMAINING) != 0
    peek = r_hits == 0

    OVER = jnp.asarray(Status.OVER_LIMIT, I32)
    UNDER = jnp.asarray(Status.UNDER_LIMIT, I32)

    # A slot is a hit only if occupied, unexpired (expiry-on-read,
    # cache.go:140-165) and running the same algorithm (an algorithm switch
    # recreates the bucket, algorithms.go:54-62,195-203).
    occupied = active & (~reqs.fresh) & (st_algo >= 0)
    alive = occupied & (now <= st_exp) & (st_algo == reqs.algorithm)

    # ---------------- token bucket, existing row (algorithms.go:35-134) ----
    tok_reset = alive & is_tok & reset_rem  # expire the bucket entirely
    lim_changed = st_limit != r_limit
    t_rem0 = jnp.where(lim_changed, jnp.minimum(st_rem, r_limit), st_rem)
    dur_changed = st_dur != r_dur
    t_new_exp = jnp.where(greg, reqs.greg_expire, st_stamp + r_dur)
    # a duration change that lands the bucket in the past recreates it
    # (algorithms.go:95-101)
    tok_recreate = alive & is_tok & ~reset_rem & dur_changed & (t_new_exp < now)
    tok_exists = alive & is_tok & ~reset_rem & ~tok_recreate
    te_exp = jnp.where(dur_changed, t_new_exp, st_exp)
    t_rem_zero = t_rem0 == 0
    t_over_req = r_hits > t_rem0  # reject without deducting (algorithms.go:125-129)
    t_deduct = (~peek) & (~t_rem_zero) & (~t_over_req)
    te_rem = jnp.where(t_deduct, t_rem0 - r_hits, t_rem0)
    te_status_resp = jnp.where((~peek) & (t_rem_zero | t_over_req), OVER, st_status)
    # only draining to zero persists OVER on the row (algorithms.go:112-115)
    te_status_store = jnp.where((~peek) & t_rem_zero, OVER, st_status)

    # ---------------- token bucket, vacant/recreate (algorithms.go:136-178) -
    tok_miss = active & is_tok & (~alive | tok_recreate)
    m_exp = jnp.where(greg, reqs.greg_expire, now + r_dur)
    m_over = r_hits > r_limit
    # first request over the limit: reject but store an *undrained* bucket
    # (algorithms.go:160-165)
    m_rem = jnp.where(m_over, r_limit, r_limit - r_hits)

    # ---------------- leaky bucket, existing row (algorithms.go:194-289) ----
    leak_exists = alive & ~is_tok
    l_rem0 = jnp.where(reset_rem, r_limit, st_rem)
    l_dur = jnp.where(greg, reqs.greg_expire - now, r_dur)
    l_rate = jnp.maximum(
        jnp.where(greg, reqs.greg_interval, r_dur) // jnp.maximum(r_limit, 1), 1
    )
    elapsed = jnp.maximum(now - st_stamp, 0)
    l_rem1 = jnp.minimum(r_limit, l_rem0 + elapsed // l_rate)
    l_rem_zero = l_rem1 == 0
    l_over_req = r_hits > l_rem1
    l_deduct = (~peek) & (~l_rem_zero) & (~l_over_req)
    le_rem = jnp.where(l_deduct, l_rem1 - r_hits, l_rem1)
    # an empty bucket rejects *without* consuming the leak residue
    # (UpdatedAt held back, algorithms.go:255-264)
    le_stamp = jnp.where((~l_rem_zero) & (~peek), now, st_stamp)
    le_status = jnp.where(l_rem_zero | ((~peek) & l_over_req), OVER, UNDER)
    le_exp = jnp.where(l_deduct, now + l_dur, st_exp)

    # ---------------- leaky bucket, vacant (algorithms.go:291-336) ----------
    leak_miss = active & (~is_tok) & ~alive
    lm_dur = jnp.where(greg, reqs.greg_expire - now, r_dur)
    lm_rate = jnp.maximum(lm_dur // jnp.maximum(r_limit, 1), 1)
    lm_over = r_hits > r_limit
    lm_rem = jnp.where(lm_over, jnp.zeros_like(r_limit), r_limit - r_hits)

    # ---------------- select new state ------------------------------------
    n_algo = _sel(
        st_algo,
        (tok_exists | tok_miss, jnp.asarray(Algorithm.TOKEN_BUCKET, I32)),
        (leak_exists | leak_miss, jnp.asarray(Algorithm.LEAKY_BUCKET, I32)),
        (tok_reset, jnp.asarray(_VACANT, I32)),
    )
    touched = tok_exists | tok_miss | leak_exists | leak_miss
    n_limit = jnp.where(touched, r_limit, st_limit)
    n_rem = _sel(
        st_rem,
        (tok_exists, te_rem),
        (tok_miss, m_rem),
        (leak_exists, le_rem),
        (leak_miss, lm_rem),
    )
    n_dur = _sel(
        st_dur,
        (tok_exists | tok_miss, r_dur),
        (leak_exists, l_dur),
        (leak_miss, lm_dur),
    )
    n_stamp = _sel(
        st_stamp,
        (tok_miss | leak_miss, now),
        (leak_exists, le_stamp),
    )
    n_exp = _sel(
        st_exp,
        (tok_exists, te_exp),
        (tok_miss, m_exp),
        (leak_exists, le_exp),
        (leak_miss, now + lm_dur),
    )
    n_status = _sel(
        st_status,
        (tok_exists, te_status_store),
        (tok_miss | leak_miss, UNDER),
    )

    sslot = pad_to_drop(slot, state.shape[-2])
    new_rows = jnp.stack(
        [
            n_algo.astype(I64),
            n_limit,
            n_rem,
            n_dur,
            n_stamp,
            n_exp,
            n_status.astype(I64),
            # field 7: per-key lifetime attempt counter — every round adds
            # its requested hits (admitted or rejected), giving the lease
            # tier a device-resident hit count with zero extra dispatches
            # (service/leases.py). Responses and snapshots never read it,
            # so decision outputs are bit-identical with leases off.
            rows[:, 7] + jnp.where(active, r_hits, 0),
        ],
        axis=1,
    )
    # ONE row scatter back (mode="drop" discards the remapped pad lanes)
    new_state = state.at[sslot].set(new_rows, mode="drop")

    # ---------------- select response --------------------------------------
    z64 = jnp.zeros_like(r_limit)
    resp = RespBatch(
        status=_sel(
            jnp.zeros_like(st_status),
            (tok_exists, te_status_resp),
            (tok_miss, jnp.where(m_over, OVER, UNDER)),
            (leak_exists, le_status),
            (leak_miss, jnp.where(lm_over, OVER, UNDER)),
            (tok_reset, UNDER),
        ).astype(I32),
        limit=jnp.where(active, r_limit, z64),
        remaining=_sel(
            z64,
            (tok_exists, te_rem),
            (tok_miss, m_rem),
            (leak_exists, le_rem),
            (leak_miss, lm_rem),
            (tok_reset, r_limit),
        ),
        reset_time=_sel(
            z64,
            (tok_exists, te_exp),
            (tok_miss, m_exp),
            (leak_exists, now + l_rate),
            (leak_miss, now + lm_rate),
            (tok_reset, z64),
        ),
    )
    return new_state, resp


def decide_packed(
    state: TableState, packed: jax.Array, now_ms: jax.Array
) -> Tuple[TableState, jax.Array]:
    """decide() over a single staging buffer.

    `packed` is i64[9, B] — one host→device transfer per window instead of
    nine column uploads; the response comes back as i64[4, B], one
    device→host readback instead of four. Off-chip round trips are the
    serving path's real cost (HBM-adjacent compute is ~µs; each transfer
    pays dispatch + interconnect latency), so the hot path stages through
    exactly one buffer each way. The host-side packer is pack_window below
    — the row-order contract lives only in this file.
    """
    reqs = ReqBatch(
        slot=packed[0].astype(I32),
        hits=packed[1],
        limit=packed[2],
        duration=packed[3],
        algorithm=packed[4].astype(I32),
        behavior=packed[5].astype(I32),
        greg_expire=packed[6],
        greg_interval=packed[7],
        fresh=packed[8] != 0,
    )
    new_state, resp = decide(state, reqs, now_ms)
    out = jnp.stack(
        [resp.status.astype(I64), resp.limit, resp.remaining, resp.reset_time]
    )
    return new_state, out


def decide_scan_packed(
    state: TableState, packed_k: jax.Array, now_ms: jax.Array
) -> Tuple[TableState, jax.Array]:
    """Apply K packed windows sequentially in ONE device dispatch.

    `packed_k` is i64[K, 9, B]; the result is i64[K, 4, B]. Window k+1
    observes window k's table writes, exactly as K separate decide_packed
    calls would — `lax.scan` compiles the kernel body once and loops on
    device, so the per-window cost collapses from one full dispatch (launch
    overhead plus, on a tunneled device, a network round trip — see
    DESIGN.md "Measurement honesty") to the on-device loop carry. The
    engine uses this to retire all duplicate-key *rounds* of a window — a
    hot-key thundering herd is the worst case, d duplicates = d rounds —
    in one launch instead of d.
    """

    def body(st, pk):
        st2, out = decide_packed(st, pk, now_ms)
        return st2, out

    return jax.lax.scan(body, state, packed_k)


# ---------------------------------------------------------------- compact
# Ingest-bound links (the tunneled bench rig; any slow PCIe/NIC path) pay
# per-byte for every staging row, so the hot path offers a second wire
# format: i32[5, B] up (slot, hits, limit, duration, meta) and i32[4, B]
# back (status, limit, remaining, reset_delta) — 20+16 bytes/decision
# instead of the wide format's 72+32. Eligibility: values in [0, 2^31) and
# no DURATION_IS_GREGORIAN lanes (calendar spans exceed i32; the serving
# fast paths already route gregorian to the wide pipeline). The response's
# reset_time rides as a delta from `now` (always ≥ 0 for live buckets;
# an absolute 0 — RESET_REMAINING, padding — is the sentinel -1).

COMPACT_ROWS = 5
_META_BEHAVIOR_SHIFT = 1
_META_BEHAVIOR_MASK = 0x3F
_META_FRESH = 1 << 7
_I32_MAX = (1 << 31) - 1


def decide_packed_compact(
    state: TableState, packed: jax.Array, now_ms: jax.Array
) -> Tuple[TableState, jax.Array]:
    """decide() over one compact i32[5, B] staging buffer.

    Bit-identical to decide_packed on any window compact_window() accepts —
    held so by TestCompactStaging's differential. Returns i32[4, B]."""
    meta = packed[4]
    zero64 = jnp.zeros(packed.shape[-1], I64)
    reqs = ReqBatch(
        slot=packed[0],
        hits=packed[1].astype(I64),
        limit=packed[2].astype(I64),
        duration=packed[3].astype(I64),
        algorithm=meta & 1,
        behavior=(meta >> _META_BEHAVIOR_SHIFT) & _META_BEHAVIOR_MASK,
        greg_expire=zero64,
        greg_interval=zero64,
        fresh=(meta & _META_FRESH) != 0,
    )
    new_state, resp = decide(state, reqs, now_ms)
    return new_state, _compact_response(resp, now_ms)


def _compact_response(resp, now_ms) -> jax.Array:
    """Pack a RespBatch into the compact i32[4, B] wire rows (status, limit,
    remaining, reset delta; absolute-zero reset encodes as -1). Shared by
    the compact and interned kernels so the response contract has one
    writer."""
    now = jnp.asarray(now_ms, I64)
    delta = jnp.where(resp.reset_time == 0, -1, resp.reset_time - now)
    return jnp.stack([
        resp.status,
        resp.limit.astype(I32),
        resp.remaining.astype(I32),
        delta.astype(I32),
    ])


def decide_scan_packed_compact(
    state: TableState, packed_k: jax.Array, now_ms: jax.Array
) -> Tuple[TableState, jax.Array]:
    """K compact windows in one dispatch: i32[K, 5, B] -> i32[K, 4, B],
    window k+1 observing window k's writes (see decide_scan_packed)."""

    def body(st, pk):
        st2, out = decide_packed_compact(st, pk, now_ms)
        return st2, out

    return jax.lax.scan(body, state, packed_k)


def compact_window(packed):
    """Wide i64[9, W] (or [K, 9, W]) staging -> compact i32, or None when
    any lane is ineligible (gregorian, or a value outside [0, 2^31))."""
    import numpy as np

    vals = packed[..., 1:4, :]
    if (vals < 0).any() or (vals > _I32_MAX).any():
        return None
    if (packed[..., 5, :] & int(Behavior.DURATION_IS_GREGORIAN)).any():
        return None
    out = np.empty(packed.shape[:-2] + (COMPACT_ROWS, packed.shape[-1]),
                   np.int32)
    out[..., 0, :] = packed[..., 0, :]
    out[..., 1:4, :] = vals
    out[..., 4, :] = (
        (packed[..., 4, :] & 1)
        | ((packed[..., 5, :] & _META_BEHAVIOR_MASK) << _META_BEHAVIOR_SHIFT)
        | ((packed[..., 8, :] != 0) << 7)
    )
    return out


def widen_compact_out(out, now_ms: int):
    """Compact i32[..., 4, B] responses -> the wide i64 rows decide_packed
    returns (reset_delta -1 decodes to absolute 0)."""
    import numpy as np

    wide = np.asarray(out).astype(np.int64)
    delta = wide[..., 3, :]
    wide[..., 3, :] = np.where(delta < 0, 0, now_ms + delta)
    return wide


# ---------------------------------------------------------------- interned
# Real fleets run a handful of limit CONFIGS (limit, duration pairs) over
# millions of keys — the reference's requests repeat the same RateLimit
# name/limit/duration per route (gubernator.proto RateLimitReq). The
# interned wire format exploits that: the host interns each window's
# (limit, duration) pairs into a tiny i64[N_CFG, 2] table shipped alongside
# (4 KB — noise), and each lane carries only slot + one packed meta word:
# i32[2, B] up = 8 bytes/decision instead of compact's 20 or wide's 72.
# The kernel gathers limit/duration back out of the config table — a [B]
# gather over a VMEM-resident 256-row table, free next to the HBM row
# gather. Responses reuse the compact i32[4, B] contract.
#
# meta word layout (bit 31 clear, always non-negative):
#   [14:0]  hits        (eligibility: 0 <= hits < 2^15)
#   [15]    algorithm
#   [21:16] behavior    (6 bits, same mask as compact)
#   [22]    fresh
#   [30:23] config id   (eligibility: <= 256 distinct pairs per stack)

INTERN_ROWS = 2
INTERN_MAX_CFG = 256
_INT_HITS_BITS = 15
_INT_HITS_MAX = (1 << _INT_HITS_BITS) - 1
_INT_ALGO_SHIFT = 15
_INT_BEHAVIOR_SHIFT = 16
_INT_FRESH_SHIFT = 22
_INT_CFG_SHIFT = 23


def decide_packed_interned(
    state: TableState, packed: jax.Array, cfg: jax.Array, now_ms: jax.Array
) -> Tuple[TableState, jax.Array]:
    """decide() over one interned i32[2, B] staging buffer + i64[N, 2]
    config table. Bit-identical to decide_packed on any window
    intern_window() accepts (TestInternedStaging differential).
    Returns the compact i32[4, B] response rows."""
    meta = packed[1]
    cfgid = (meta >> _INT_CFG_SHIFT) & (INTERN_MAX_CFG - 1)
    zero64 = jnp.zeros(packed.shape[-1], I64)
    reqs = ReqBatch(
        slot=packed[0],
        hits=(meta & _INT_HITS_MAX).astype(I64),
        limit=cfg[cfgid, 0],
        duration=cfg[cfgid, 1],
        algorithm=(meta >> _INT_ALGO_SHIFT) & 1,
        behavior=(meta >> _INT_BEHAVIOR_SHIFT) & _META_BEHAVIOR_MASK,
        greg_expire=zero64,
        greg_interval=zero64,
        fresh=(meta & (1 << _INT_FRESH_SHIFT)) != 0,
    )
    new_state, resp = decide(state, reqs, now_ms)
    return new_state, _compact_response(resp, now_ms)


def decide_scan_packed_interned(
    state: TableState, packed_k: jax.Array, cfg: jax.Array, now_ms: jax.Array
) -> Tuple[TableState, jax.Array]:
    """K interned windows in one dispatch: i32[K, 2, B] + one shared
    i64[N, 2] config table -> i32[K, 4, B], window k+1 observing window
    k's writes (see decide_scan_packed)."""

    def body(st, pk):
        st2, out = decide_packed_interned(st, pk, cfg, now_ms)
        return st2, out

    return jax.lax.scan(body, state, packed_k)


def _intern_pairs(packed):
    """Shared eligibility gate for the two Python interners: the
    (limit << 31) | duration pair per lane, or None when any lane cannot
    ride the interned format (gregorian, hits outside [0, 2^15),
    limit/duration outside [0, 2^31))."""
    hits = packed[..., 1, :]
    if (hits < 0).any() or (hits > _INT_HITS_MAX).any():
        return None
    vals = packed[..., 2:4, :]
    if (vals < 0).any() or (vals > _I32_MAX).any():
        return None
    if (packed[..., 5, :] & int(Behavior.DURATION_IS_GREGORIAN)).any():
        return None
    # both < 2^31: injective, fits i64
    return (packed[..., 2, :] << 31) | packed[..., 3, :]


def _emit_interned(packed, inv):
    """Shared meta-word emission: wide staging + per-lane config ids ->
    interned i32 rows. The bit layout has THREE writers (here, the two
    callers' id assignment aside: keydir.cpp keydir_prep_pack_interned)
    and one reader (decide_packed_interned) — keep them in sync."""
    import numpy as np

    out = np.empty(packed.shape[:-2] + (INTERN_ROWS, packed.shape[-1]),
                   np.int32)
    out[..., 0, :] = packed[..., 0, :]
    out[..., 1, :] = (
        packed[..., 1, :]
        | ((packed[..., 4, :] & 1) << _INT_ALGO_SHIFT)
        | ((packed[..., 5, :] & _META_BEHAVIOR_MASK) << _INT_BEHAVIOR_SHIFT)
        | ((packed[..., 8, :] != 0).astype(np.int64) << _INT_FRESH_SHIFT)
        | (inv.astype(np.int64) << _INT_CFG_SHIFT)
    )
    return out


def intern_window(packed):
    """Wide i64[9, W] (or [K, 9, W]) staging -> (interned i32 rows,
    i64[INTERN_MAX_CFG, 2] config table), or None when any lane is
    ineligible (see _intern_pairs) or the stack holds more than
    INTERN_MAX_CFG distinct (limit, duration) pairs. Padding lanes
    (slot == -1) intern like any other (their zero config occupies one
    table row)."""
    import numpy as np

    pair = _intern_pairs(packed)
    if pair is None:
        return None
    cfg_vals, inv = np.unique(pair, return_inverse=True)
    if cfg_vals.size > INTERN_MAX_CFG:
        return None
    cfg = np.zeros((INTERN_MAX_CFG, 2), np.int64)
    cfg[: cfg_vals.size, 0] = cfg_vals >> 31
    cfg[: cfg_vals.size, 1] = cfg_vals & _I32_MAX
    return _emit_interned(packed, inv.reshape(pair.shape)), cfg


class InternCache:
    """Stateful interner for a serving loop: the config table persists
    across windows, so the per-window cost is one searchsorted against the
    (tiny, sorted) known-pair array instead of np.unique's full sort of
    every lane. New pairs grow the table (stable ids — already-issued
    meta words stay valid); overflow past INTERN_MAX_CFG or any
    ineligible lane returns None for that window (caller falls back to
    wide/compact staging), leaving the cache intact."""

    def __init__(self):
        import numpy as np

        self._sorted_pairs = np.empty(0, np.int64)  # sorted for searchsorted
        self._sorted_ids = np.empty(0, np.int64)  # pair -> stable config id
        self.cfg = np.zeros((INTERN_MAX_CFG, 2), np.int64)
        self.n_cfg = 0

    def intern(self, packed):
        """Wide i64[..., 9, W] staging -> interned i32 rows (the shared
        self.cfg table ships alongside), or None when ineligible."""
        import numpy as np

        pair = _intern_pairs(packed)
        if pair is None:
            return None
        flat = pair.ravel()
        pos = np.searchsorted(self._sorted_pairs, flat)
        pos_c = np.minimum(pos, max(self._sorted_pairs.size - 1, 0))
        known = (self._sorted_pairs.size > 0) \
            and bool((self._sorted_pairs[pos_c] == flat).all())
        if not known:
            new = np.unique(flat) if self._sorted_pairs.size == 0 else \
                np.setdiff1d(np.unique(flat), self._sorted_pairs,
                             assume_unique=True)
            if self.n_cfg + new.size > INTERN_MAX_CFG:
                return None
            ids = np.arange(self.n_cfg, self.n_cfg + new.size)
            self.cfg[ids, 0] = new >> 31
            self.cfg[ids, 1] = new & _I32_MAX
            self.n_cfg += new.size
            self._sorted_pairs = np.concatenate([self._sorted_pairs, new])
            self._sorted_ids = np.concatenate([self._sorted_ids, ids])
            order = np.argsort(self._sorted_pairs, kind="stable")
            self._sorted_pairs = self._sorted_pairs[order]
            self._sorted_ids = self._sorted_ids[order]
            pos = np.searchsorted(self._sorted_pairs, flat)
        inv = self._sorted_ids[pos].reshape(pair.shape)
        return _emit_interned(packed, inv)


# ------------------------------------------------------------------ lean
# The dominant serving shape — hits == 1 (one decision per request), a
# handful of limit configs, no gregorian — needs even less than interned's
# 8 B/decision: ONE i32 word per lane. The config table absorbs algorithm
# and behavior alongside (limit, duration), hits = 1 is implied, and the
# slot rides in the low 24 bits (table <= 2^24 - 1 slots; the 10M-key
# north-star uses 10,000,001 < 16,777,215). 4 B up + 8 B back (the serving
# loop's two-row response) = 12 B/decision round trip vs interned's 16 —
# the wire lever DESIGN.md "Next wire lever" specs for link-bound rigs.
#
# lane word layout (i32; bit 31 participates in the config id, so the
# word may be negative — every decode masks):
#   [23:0]  slot        (all-ones 0xFFFFFF = padding sentinel)
#   [24]    fresh
#   [31:25] config id   (<= 128 distinct (limit, duration, algo,
#                        behavior) tuples per deployment epoch)

LEAN_MAX_CFG = 128
_LEAN_SLOT_MASK = (1 << 24) - 1
_LEAN_PAD = _LEAN_SLOT_MASK  # slot sentinel: capacity must stay below it
_LEAN_FRESH_SHIFT = 24
_LEAN_CFG_SHIFT = 25


def staging_policy() -> str:
    """GUBER_STAGING resolution, shared by the single-chip and mesh
    engines (one parse, one error message): 'auto' ships each window on
    the leanest eligible wire format, 'wide' pins the i64[9] contract
    (e.g. to rule the switch out while debugging)."""
    import os

    # guberlint: disable=knob-drift -- kernel-debug pin read at engine build, before a DaemonConfig exists; not an operator surface
    s = os.environ.get("GUBER_STAGING", "auto")
    if s not in ("auto", "wide"):
        raise ValueError(
            f"GUBER_STAGING={s!r}: must be 'auto' or 'wide'"
            " (lean/compact cannot be pinned — ineligible windows need"
            " the wide format)")
    return s


def lean_capacity_ok(capacity: int) -> bool:
    """Slots must fit the 24-bit lane field with 0xFFFFFF reserved for
    padding — a deployment-time property, checked once per engine."""
    return capacity <= _LEAN_SLOT_MASK


def decide_packed_lean(
    state: TableState, packed: jax.Array, cfg: jax.Array, now_ms: jax.Array
) -> Tuple[TableState, jax.Array]:
    """decide() over one lean i32[B] lane word per request + i64[128, 4]
    config table of (limit, duration, algorithm, behavior) rows. hits = 1
    implied. Bit-identical to decide_packed on any window lean_window()
    accepts (TestLeanStaging differential). Returns the compact i32[4, B]
    response rows."""
    lane = packed
    slot24 = lane & _LEAN_SLOT_MASK
    slot = jnp.where(slot24 == _LEAN_PAD, jnp.asarray(-1, I32), slot24)
    cfgid = (lane >> _LEAN_CFG_SHIFT) & (LEAN_MAX_CFG - 1)
    zero64 = jnp.zeros(lane.shape[-1], I64)
    reqs = ReqBatch(
        slot=slot,
        hits=jnp.ones(lane.shape[-1], I64),
        limit=cfg[cfgid, 0],
        duration=cfg[cfgid, 1],
        algorithm=cfg[cfgid, 2].astype(I32),
        behavior=cfg[cfgid, 3].astype(I32),
        greg_expire=zero64,
        greg_interval=zero64,
        fresh=((lane >> _LEAN_FRESH_SHIFT) & 1) != 0,
    )
    new_state, resp = decide(state, reqs, now_ms)
    return new_state, _compact_response(resp, now_ms)


def decide_scan_packed_lean(
    state: TableState, packed_k: jax.Array, cfg: jax.Array, now_ms: jax.Array
) -> Tuple[TableState, jax.Array]:
    """K lean windows in one dispatch: i32[K, B] + one shared i64[128, 4]
    config table -> i32[K, 4, B], window k+1 observing window k's writes
    (see decide_scan_packed)."""

    def body(st, pk):
        st2, out = decide_packed_lean(st, pk, cfg, now_ms)
        return st2, out

    return jax.lax.scan(body, state, packed_k)


def lean_window(packed, capacity: int):
    """Wide i64[9, W] (or [K, 9, W]) staging -> (lean i32[W] / [K, W] lane
    words, i64[LEAN_MAX_CFG, 4] config table), or None when any non-padding
    lane is ineligible: hits != 1, gregorian, limit/duration outside
    [0, 2^31), behavior past 6 bits, algorithm past 1 bit, slot too wide
    for 24 bits, or > LEAN_MAX_CFG distinct (limit, duration, algorithm,
    behavior) tuples. Padding lanes emit the 0xFFFFFF sentinel and occupy
    no config row.

    Host cost ~120 ns/item (masks + two 1-D uniques) to drop the wire
    from 72 to 4 B/lane — clearly worth it on link-bound paths (tunnel
    rigs, NIC-attached chips, the mesh engine's [R,S,...] buffer) and
    roughly break-even against the host budget on a locally-attached
    single chip; the C serving emitter (keydir_prep_pack_lean) writes
    lean directly and pays none of this."""
    import numpy as np

    if not lean_capacity_ok(capacity):
        return None
    slot = packed[..., 0, :]
    live = slot >= 0
    if (slot >= _LEAN_PAD).any():
        return None
    hits = packed[..., 1, :]
    limit = packed[..., 2, :]
    dur = packed[..., 3, :]
    algo = packed[..., 4, :]
    beh = packed[..., 5, :]
    bad = (
        (hits != 1)
        | (limit < 0) | (limit > _I32_MAX)
        | (dur < 0) | (dur > _I32_MAX)
        | ((algo & ~1) != 0)
        | ((beh & ~_META_BEHAVIOR_MASK) != 0)
        | ((beh & int(Behavior.DURATION_IS_GREGORIAN)) != 0)
    )
    if bool((bad & live).any()):
        return None
    # intern the (limit, duration, algorithm, behavior) tuples via TWO
    # 1-D uniques over injective packed keys — np.unique(axis=0) on the
    # stacked tuples costs ~1.9 µs/item (structured-view sort), two
    # plain i64 sorts cost ~20 ns/item
    pair = (limit[live] << 31) | dur[live]  # both < 2^31: injective
    meta7 = algo[live] | (beh[live] << 1)  # 7 bits
    u1, inv1 = np.unique(pair, return_inverse=True)
    u2, inv = np.unique(inv1.astype(np.int64) * 128 + meta7,
                        return_inverse=True)
    if u2.size > LEAN_MAX_CFG:
        return None
    cfg = np.zeros((LEAN_MAX_CFG, 4), np.int64)
    pairs = u1[u2 >> 7]
    cfg[: u2.size, 0] = pairs >> 31
    cfg[: u2.size, 1] = pairs & _I32_MAX
    cfg[: u2.size, 2] = u2 & 1
    cfg[: u2.size, 3] = (u2 & 127) >> 1
    lanes = np.full(slot.shape, _LEAN_PAD, np.int64)
    # astype before shifting: numpy 1.x value-based casting would promote
    # the bool to a small int dtype and overflow the 24-bit shift
    lanes[live] = (
        slot[live]
        | ((packed[..., 8, :][live] != 0).astype(np.int64)
           << _LEAN_FRESH_SHIFT)
        | (inv.reshape(-1).astype(np.int64) << _LEAN_CFG_SHIFT)
    )
    # bit 31 of the cfgid field lands in the i32 sign bit — wrap the bit
    # pattern through uint32 (every reader masks, so negatives are fine)
    return lanes.astype(np.uint32).view(np.int32), cfg


def pack_window(items, slots, fresh, width: int, out=None):
    """Host-side packer for decide_packed: i64[9, width] from one window.

    `items` are prep WorkItems (resp_index, req, greg_expire, greg_interval);
    lanes beyond len(items) are padding (slot = -1). decide_packed is the
    only reader of the packed row order; it has TWO writers — this function
    and the native fast path (native/keydir.cpp keydir_prep_pack_fast) —
    which must stay in sync. `out`, when given, must be a zero-filled i64[9, width] view
    (e.g. one window's slice of a scan group's staging buffer) and is
    filled in place instead of allocating.
    """
    import numpy as np

    n = len(items)
    packed = np.zeros((9, width), np.int64) if out is None else out
    packed[0, :n] = slots
    packed[0, n:] = -1
    if n:
        packed[1:8, :n] = np.array(
            [
                (r.hits, r.limit, r.duration, int(r.algorithm),
                 int(r.behavior), ge, gi)
                for _i, r, ge, gi in items
            ],
            np.int64,
        ).T
    packed[8, :n] = fresh
    return packed


def make_decide_jit(donate: bool = None):
    """Compiled decide(). Donating the table keeps the 7 HBM columns in place
    across windows instead of allocating a fresh ~56B/key copy per call —
    but some backends reject donation, so probe unless told."""
    if donate is None:
        from gubernator_tpu.utils.platform import donation_supported

        donate = donation_supported()
    return jax.jit(decide, donate_argnums=(0,) if donate else ())


def pad_batch(reqs: ReqBatch, to_size: int) -> ReqBatch:
    """Pad a host-built batch to a bucketed size to bound recompilation."""
    b = reqs.slot.shape[0]
    if b == to_size:
        return reqs
    pad = to_size - b

    def _pad(x, fill):
        return jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])

    return ReqBatch(
        slot=_pad(reqs.slot, -1),
        hits=_pad(reqs.hits, 0),
        limit=_pad(reqs.limit, 0),
        duration=_pad(reqs.duration, 0),
        algorithm=_pad(reqs.algorithm, 0),
        behavior=_pad(reqs.behavior, 0),
        greg_expire=_pad(reqs.greg_expire, 0),
        greg_interval=_pad(reqs.greg_interval, 0),
        fresh=_pad(reqs.fresh, False),
    )


def batch_from_columns(
    slot: Sequence[int],
    hits: Sequence[int],
    limit: Sequence[int],
    duration: Sequence[int],
    algorithm: Sequence[int],
    behavior: Sequence[int],
    greg_expire: Sequence[int],
    greg_interval: Sequence[int],
    fresh: Sequence[bool],
) -> ReqBatch:
    """Build a device batch from host lists (numpy staging happens in jnp)."""
    return ReqBatch(
        slot=jnp.asarray(slot, I32),
        hits=jnp.asarray(hits, I64),
        limit=jnp.asarray(limit, I64),
        duration=jnp.asarray(duration, I64),
        algorithm=jnp.asarray(algorithm, I32),
        behavior=jnp.asarray(behavior, I32),
        greg_expire=jnp.asarray(greg_expire, I64),
        greg_interval=jnp.asarray(greg_interval, I64),
        fresh=jnp.asarray(fresh, jnp.bool_),
    )
