"""Device mesh construction and key-table sharding.

The reference shards its key space across a cluster of Go processes with a
consistent-hash ring: exactly one peer owns each key and all mutation happens
there (reference: architecture.md:13-17, hash.go:83-99). Here the same
ownership idea maps onto a TPU mesh: the key table's slot dimension is
sharded over a 2-D mesh of axes ("region", "shard"); a key's owner chip is a
deterministic hash of the key, and all mutation of that key's row happens in
that chip's HBM shard.

- axis "shard": intra-pod key-space partition (the ICI tier — replaces the
  reference's peer-to-peer gRPC forwarding, peers.proto:28-34).
- axis "region": the DCN tier (replaces the reference's multi-datacenter
  region pickers, region_picker.go:7-95).

Host processes still route *requests* to the owning host (service tier, like
the reference's PeersV1 forwarding) — the mesh shards *state* within the
process group.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gubernator_tpu.ops.decide import (
    I64,
    ROW_ALGO,
    TABLE_ROW_FIELDS,
    TableState,
    _VACANT,
)
from gubernator_tpu.utils.fnv import fnv1a_64_str

REGION_AXIS = "region"
SHARD_AXIS = "shard"


def shard_map():
    """jax.shard_map across jax versions: top-level since 0.6 (kwarg
    `check_vma`), under jax.experimental.shard_map before that (kwarg
    `check_rep`) — the mesh tier is otherwise version-portable, so
    resolve the symbol and the kwarg rename in one place.

    The legacy fallback pins check_rep=False: 0.4.x's replication-
    inference rewrite intermittently aborts the process DURING TRACING
    (SIGABRT under partial_eval -> _standard_rewrite_rule, reproduced
    ~2/3 runs by tests/test_fuzz.py's mesh differential). The flag only
    controls that static inference — out_specs still define the output
    shardings — so disabling it is behavior-neutral and keeps the
    interpreter alive."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map as sm

    def compat(f, **kwargs):
        kwargs.pop("check_vma", None)
        kwargs["check_rep"] = False
        return sm(f, **kwargs)

    return compat


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A mesh plus the table geometry sharded over it."""

    mesh: Mesh
    capacity_per_shard: int

    @property
    def n_regions(self) -> int:
        return self.mesh.devices.shape[0]

    @property
    def n_shards(self) -> int:
        return self.mesh.devices.shape[1]

    @property
    def n_owners(self) -> int:
        return self.n_regions * self.n_shards

    @property
    def capacity(self) -> int:
        return self.n_owners * self.capacity_per_shard

    def state_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(REGION_AXIS, SHARD_AXIS, None, None))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def owner_coords(self, owner: int) -> Tuple[int, int]:
        return divmod(owner, self.n_shards)


def make_mesh(
    n_shards: Optional[int] = None,
    n_regions: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the ("region", "shard") mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    if n_shards is None:
        if len(devices) % n_regions:
            raise ValueError(
                f"{len(devices)} devices not divisible into {n_regions} regions")
        n_shards = len(devices) // n_regions
    need = n_regions * n_shards
    if need > len(devices):
        raise ValueError(f"need {need} devices, have {len(devices)}")
    arr = np.array(devices[:need], dtype=object).reshape(n_regions, n_shards)
    return Mesh(arr, (REGION_AXIS, SHARD_AXIS))


def shard_of_key(key: str, n_owners: int) -> int:
    """Deterministic owner (linear mesh index) of a rate-limit key.

    The reference's consistent-hash `Get` (reference: hash.go:83-99) serves
    the same role for host peers; for device shards a plain mod is ideal —
    the mesh never resizes without a restart, so ring stability is moot.
    """
    return fnv1a_64_str(key) % n_owners


def make_sharded_table(plan: MeshPlan) -> TableState:
    """Fresh vacant row table i64[R, S, C, 8] sharded over the mesh."""
    R, S, C = plan.n_regions, plan.n_shards, plan.capacity_per_shard

    @partial(jax.jit, out_shardings=plan.state_sharding())
    def _make() -> TableState:
        return (
            jnp.zeros((R, S, C, TABLE_ROW_FIELDS), I64)
            .at[..., ROW_ALGO].set(_VACANT)
        )

    return _make()
