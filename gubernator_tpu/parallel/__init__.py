from gubernator_tpu.parallel.mesh import (
    MeshPlan,
    make_mesh,
    make_sharded_table,
    shard_of_key,
)
from gubernator_tpu.parallel.global_sync import GlobalMirror, make_global_sync
from gubernator_tpu.parallel.sharded import ShardedEngine, make_decide_sharded

__all__ = [
    "MeshPlan",
    "make_mesh",
    "make_sharded_table",
    "shard_of_key",
    "GlobalMirror",
    "make_global_sync",
    "ShardedEngine",
    "make_decide_sharded",
]
