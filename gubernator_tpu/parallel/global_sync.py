"""GLOBAL-behavior synchronization as mesh collectives.

The reference implements Behavior=GLOBAL with two async gRPC pipelines
(reference: global.go:73-156 hit-forwarding to the owner, global.go:159-239
owner broadcast to every peer). On a TPU mesh both pipelines collapse into
ONE compiled step with two psums:

1. hit aggregation: every device contributes its locally-accumulated hit
   deltas for all registered global keys; `psum` over ("region", "shard")
   yields the cluster-total hits per key — this *is* the reference's
   `sendHits` group-by-owner fan-in (global.go:116-156), minus the RPCs.
2. owner apply: each key's owner lane (and only it) scatters the summed hits
   through the ordinary decision kernel into its authoritative table shard —
   the reference's `GetPeerRateLimits`-at-owner path (gubernator.go:267-284).
3. broadcast: the owner's fresh RateLimitResp columns are masked to zero on
   non-owners and `psum`med again, leaving every device holding the same
   authoritative mirror — the reference's `UpdatePeerGlobals` fan-out
   (global.go:219-236) as a single collective.

Hosts answer GLOBAL requests from the (host-copied) mirror between syncs,
exactly like the reference's non-owner local-cache answer
(gubernator.go:226-247).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from gubernator_tpu.ops.decide import I32, ReqBatch, TableState, decide
from gubernator_tpu.parallel.mesh import (
    MeshPlan, REGION_AXIS, SHARD_AXIS, shard_map as _shard_map)


class GlobalMirror(NamedTuple):
    """Replicated authoritative status of every registered global key
    (the payload of the reference's UpdatePeerGlobals, proto/peers.proto:49-53)."""

    status: jax.Array  # i32[G]
    limit: jax.Array  # i64[G]
    remaining: jax.Array  # i64[G]
    reset_time: jax.Array  # i64[G]


class GlobalConfig(NamedTuple):
    """Replicated per-global-key request config, maintained by the host from
    the latest request seen (the reference stores the whole RateLimitReq in
    its broadcast queue, global.go:194-217)."""

    slot: jax.Array  # i32[G] owner-shard table slot; -1 unregistered
    owner: jax.Array  # i32[G] linear mesh index of the owning device
    limit: jax.Array  # i64[G]
    duration: jax.Array  # i64[G]
    algorithm: jax.Array  # i32[G]
    behavior: jax.Array  # i32[G] (GLOBAL bit already stripped by the host)
    greg_expire: jax.Array  # i64[G]
    greg_interval: jax.Array  # i64[G]
    fresh: jax.Array  # bool[G] owner slot newly assigned


def make_global_sync(plan: MeshPlan, donate: bool = False,
                     collectives: str = "psum"):
    """Compile the one-step GLOBAL sync over the plan's mesh.

    Returns fn(state, delta, cfg, now) -> (state, mirror, zeroed delta):
    - state: sharded TableState [R, S, C]
    - delta: i64[R, S, G] — each device's local hit deltas (sharded)
    - cfg: GlobalConfig of replicated [G] arrays

    `collectives` picks the reduction implementation: "psum" (XLA's
    collective schedule, the default — optimal for these ~8 KB payloads) or
    "ring" (the explicit Pallas ICI ring of ops/ring.py; single-region
    meshes only — the ring circles the shard axis, so a second region would
    silently sum region-locally). The ring variant compiles only on real
    TPU meshes: the CPU Pallas interpreter's remote DMA supports a single
    named mesh axis, so the CPU test mesh (2-D region×shard) cannot execute
    it — tests/test_ring.py instead holds the ring kernel bit-equal to psum
    on a 1-D mesh.
    """
    if collectives not in ("psum", "ring"):
        raise ValueError(f"unknown collectives '{collectives}'")
    if collectives == "ring" and plan.n_regions != 1:
        raise ValueError(
            "ring collectives support single-region meshes only (the ring "
            "reduces over the shard axis; psum handles multi-region)")
    S = plan.n_shards
    state_spec = P(REGION_AXIS, SHARD_AXIS, None, None)
    delta_spec = P(REGION_AXIS, SHARD_AXIS, None)
    rep = P()

    def _ring(length: int, collective_id: int):
        from gubernator_tpu.ops.ring import make_ring_all_reduce

        return make_ring_all_reduce(
            S, length, dtype=I64, axis_name=SHARD_AXIS,
            mesh_axes=(REGION_AXIS, SHARD_AXIS), collective_id=collective_id)

    def _step(
        state: TableState, delta: jax.Array, cfg: GlobalConfig, now: jax.Array
    ) -> Tuple[TableState, GlobalMirror, jax.Array]:
        local_state = state.reshape(state.shape[-2:])  # i64[C, 8]
        local_delta = delta.reshape(delta.shape[-1:])  # i64[G]

        if collectives == "psum":
            total = jax.lax.psum(local_delta, (REGION_AXIS, SHARD_AXIS))
        else:
            total = _ring(local_delta.shape[0], 0)(local_delta)
        my_id = (
            jax.lax.axis_index(REGION_AXIS) * S + jax.lax.axis_index(SHARD_AXIS)
        ).astype(I32)
        mine = (cfg.owner == my_id) & (cfg.slot >= 0)

        reqs = ReqBatch(
            slot=jnp.where(mine, cfg.slot, -1),
            hits=total,
            limit=cfg.limit,
            duration=cfg.duration,
            algorithm=cfg.algorithm,
            behavior=cfg.behavior,
            greg_expire=cfg.greg_expire,
            greg_interval=cfg.greg_interval,
            fresh=cfg.fresh,
        )
        new_local, resp = decide(local_state, reqs, now)

        # the broadcast IS an all-reduce of owner-masked columns (non-owners
        # contribute zeros)
        cols = (resp.status.astype(jnp.int64), resp.limit,
                resp.remaining, resp.reset_time)
        if collectives == "psum":
            summed = [
                jax.lax.psum(jnp.where(mine, c, jnp.zeros_like(c)),
                             (REGION_AXIS, SHARD_AXIS))
                for c in cols
            ]
        else:
            # one stacked ring pass (distinct collective_id from the delta
            # ring above: the two have a data dependence through `resp`, but
            # sharing a barrier-semaphore group across pallas_calls is not
            # something to rely on)
            stacked = jnp.concatenate(
                [jnp.where(mine, c, jnp.zeros_like(c)) for c in cols])
            out = _ring(stacked.shape[0], 1)(stacked)
            g = cols[0].shape[0]
            summed = [out[i * g:(i + 1) * g] for i in range(4)]
        mirror = GlobalMirror(
            status=summed[0].astype(I32),
            limit=summed[1],
            remaining=summed[2],
            reset_time=summed[3],
        )
        new_state = new_local.reshape((1, 1) + new_local.shape)
        return new_state, mirror, jnp.zeros_like(delta)

    mapped = _shard_map()(
        _step,
        mesh=plan.mesh,
        in_specs=(state_spec, delta_spec, rep, rep),
        out_specs=(state_spec, rep, delta_spec),
        # the pallas ring's out_shape carries no varying-mesh-axes metadata,
        # so the static VMA checker can't type it; the kernel itself is
        # device-symmetric (every device runs the same N-1 hops)
        check_vma=(collectives == "psum"),
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())
