"""Multi-host tier: jax.distributed process groups + DCN collectives.

The reference scales across machines with one flat gRPC peer mesh
(reference: peers.proto:28-34, peer_client.go) — every aggregate flow
(GLOBAL hit forwarding, owner broadcasts) is O(peers) unary RPCs. Here the
host tier keeps gRPC for *request routing* (service/instance.py forwards to
the owning host exactly like the reference), while the *aggregate* flows can
ride XLA collectives across the whole process group:

- `initialize_from_env()` forms the jax.distributed process group
  (GUBER_COORDINATOR_ADDRESS / GUBER_NUM_HOSTS / GUBER_HOST_ID — the same
  role as the reference's discovery wiring, cmd/gubernator/main.go:87-121,
  but for the device fabric rather than the serving fabric). After it, the
  processes share one global device view and collectives cross host
  boundaries over ICI within a pod and DCN between pods.
- `CrossHostHitSync` is the DCN analogue of parallel/global_sync.py's
  intra-host psum: each host contributes its per-global-key hit-delta
  vector; ONE psum leaves every host holding the cluster-total — the
  reference needs a gRPC fan-in to the owner plus a fan-out broadcast
  (global.go:116-156, 219-236) for the same information flow.

Lockstep contract: every participating host must call `step()` the same
number of times (SPMD). Drive it from a fixed-cadence sync loop, never
on-demand; a host that stops ticking stalls the collective on every other
host (jax.distributed surfaces missing-participant errors after its
timeout). This is the standard TPU-fleet pattern — the serving path is
never blocked by the sync loop.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax
import numpy as np

from gubernator_tpu.parallel.mesh import shard_map as _shard_map

log = logging.getLogger("gubernator_tpu.multihost")

NODE_AXIS = "node"


def initialize_from_env(
    coordinator_address: Optional[str] = None,
    num_hosts: Optional[int] = None,
    host_id: Optional[int] = None,
) -> bool:
    """Form the cross-host process group; no-op for single-host deployments.

    Arguments default to GUBER_COORDINATOR_ADDRESS, GUBER_NUM_HOSTS and
    GUBER_HOST_ID. Returns True when a multi-host group was initialized.
    Must run before the first jax backend use in the process.
    """
    if coordinator_address is None:
        coordinator_address = os.environ.get("GUBER_COORDINATOR_ADDRESS", "")
    if num_hosts is None:
        num_hosts = int(os.environ.get("GUBER_NUM_HOSTS", "1"))
    if host_id is None:
        host_id = int(os.environ.get("GUBER_HOST_ID", "0"))
    if num_hosts <= 1:
        return False
    if not coordinator_address:
        raise ValueError(
            "GUBER_NUM_HOSTS > 1 requires GUBER_COORDINATOR_ADDRESS")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_hosts,
        process_id=host_id,
    )
    log.info(
        "joined process group: host %d/%d, %d global / %d local devices",
        host_id, num_hosts, len(jax.devices()), len(jax.local_devices()),
    )
    return True


def make_node_mesh(devices=None) -> jax.sharding.Mesh:
    """1-D mesh over every device of every host (the collective fabric)."""
    devices = list(devices if devices is not None else jax.devices())
    return jax.sharding.Mesh(np.array(devices, dtype=object), (NODE_AXIS,))


class CollectiveGlobalChannel:
    """One lockstep dispatch carrying the whole cross-host GLOBAL exchange.

    Three logical flows share a single collective step (the reference needs
    two asynchronous gRPC pipelines for the same information movement,
    global.go:73-156 hit fan-in and global.go:159-239 state fan-out):

    - ``delta``  i64[G]: this host's queued hit deltas → psum = cluster total
      per slot, delivered to the slot owner.
    - ``claim``  i64[G]: nonzero key-claim hash per slot this host uses.
      Slots are assigned deterministically (hash of the key), so two hosts
      using the same slot for DIFFERENT keys is possible; the claim triple
      (sum, max, count) lets every host verify agreement — a slot is clean
      for me iff ``sum == count * max and max == my_claim``. Hosts only
      contribute deltas/state on slots verified clean on a PREVIOUS tick,
      so a conflict can never mix two keys' hits.
    - ``state``  i64[5, G]: rows (valid, status, limit, remaining,
      reset_time). The owning host contributes its authoritative post-apply
      state with valid=1; psum hands it to every host. valid != 1 (owner
      missing, or two hosts claiming ownership during a membership change)
      means "do not apply this tick".

    Lockstep contract is the same as CrossHostHitSync: every host calls
    step() in the same sequence, on a fixed cadence.
    """

    def __init__(self, global_capacity: int, mesh=None):
        self.global_capacity = global_capacity
        self.mesh = mesh if mesh is not None else make_node_mesh()
        self._n_local = len(self.mesh.local_devices)
        self._row = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec(NODE_AXIS, None))
        self._row3 = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec(NODE_AXIS, None, None))

        def _exchange(delta, claim, state):
            # each block sees ONE device's contribution rows
            import jax.numpy as jnp

            d = jax.lax.psum(delta[0], NODE_AXIS)
            c_sum = jax.lax.psum(claim[0], NODE_AXIS)
            c_max = jax.lax.pmax(claim[0], NODE_AXIS)
            c_cnt = jax.lax.psum(
                (claim[0] != 0).astype(jnp.int64), NODE_AXIS)
            st = jax.lax.psum(state[0], NODE_AXIS)
            return d, c_sum, c_max, c_cnt, st

        spec_r = jax.sharding.PartitionSpec(NODE_AXIS, None)
        spec_r3 = jax.sharding.PartitionSpec(NODE_AXIS, None, None)
        self._step = jax.jit(_shard_map()(
            _exchange, mesh=self.mesh,
            in_specs=(spec_r, spec_r, spec_r3),
            out_specs=(jax.sharding.PartitionSpec(),) * 5,
        ))
        self.steps = 0

    def warm(self, timeout_s: float = 600.0) -> None:
        """Compile the exchange and form the fabric context in LOCKSTEP.

        The backend's first cross-host exchange has a fixed internal
        context-formation deadline (Gloo on CPU: ~30 s). Hosts whose
        compiles serialize — cold caches, shared CPUs, heterogeneous boot
        times — enter their first exchange minutes apart and the earliest
        one times out, killing the whole process group. So: (1) AOT-compile
        the step locally (arbitrary skew is fine), (2) rendezvous every
        host at the coordination service's barrier (already up — the
        process group formed at boot), (3) run one all-zeros exchange with
        every host inside the deadline window. Call at BOOT, before the
        tick cadence starts: a broken fabric fails loudly here instead of
        mid-serving."""
        G = self.global_capacity
        d = np.zeros((self._n_local, G), np.int64)
        s = np.zeros((self._n_local, 5, G), np.int64)
        args = (
            jax.make_array_from_process_local_data(self._row, d),
            jax.make_array_from_process_local_data(self._row, d),
            jax.make_array_from_process_local_data(self._row3, s),
        )
        self._step.lower(*args).compile()  # local compile, no exchange
        try:
            from jax._src import distributed

            client = distributed.global_state.client
        except Exception:  # noqa: BLE001 — older jax layouts
            client = None
        if client is None:
            log.warning(
                "no distributed-client barrier available: hosts enter the "
                "first exchange unsynchronized — serialized cold-cache "
                "compiles can blow the fabric's context-formation deadline")
        else:
            client.wait_at_barrier(
                "guber_collective_warm", int(timeout_s * 1000))
        self.step(np.zeros(G, np.int64), np.zeros(G, np.int64),
                  np.zeros((5, G), np.int64))
        log.info("collective channel warmed (fabric context formed)")

    def step(self, delta: np.ndarray, claim: np.ndarray,
             state: np.ndarray):
        """One collective tick. Returns host arrays
        (total_delta[G], claim_sum[G], claim_max[G], claim_cnt[G],
        state[5, G])."""
        G = self.global_capacity
        d = np.zeros((self._n_local, G), np.int64)
        c = np.zeros((self._n_local, G), np.int64)
        s = np.zeros((self._n_local, 5, G), np.int64)
        d[0], c[0], s[0] = delta, claim, state
        args = (
            jax.make_array_from_process_local_data(self._row, d),
            jax.make_array_from_process_local_data(self._row, c),
            jax.make_array_from_process_local_data(self._row3, s),
        )
        out = self._step(*args)
        self.steps += 1
        return tuple(np.asarray(o) for o in out)


class CrossHostHitSync:
    """Lockstep psum of per-host hit-delta vectors across the process group.

    Layout: a global i64[D, G] array (D = all devices, G = global-key
    capacity) sharded one row per device. Each host writes its delta into
    its FIRST local device's row, zeros elsewhere; the psum over the node
    axis leaves every host the cluster total. Call `step` at a fixed
    cadence from every host (see the lockstep contract in the module doc).
    """

    def __init__(self, global_capacity: int, mesh=None):
        self.global_capacity = global_capacity
        self.mesh = mesh if mesh is not None else make_node_mesh()
        self._n_local = len(self.mesh.local_devices)
        self._row_sharding = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec(NODE_AXIS, None))

        def _psum(delta):
            # each shard_map block is ONE device's (1, G) row slice
            return jax.lax.psum(delta[0], NODE_AXIS)

        self._step = jax.jit(_shard_map()(
            _psum, mesh=self.mesh,
            in_specs=jax.sharding.PartitionSpec(NODE_AXIS, None),
            out_specs=jax.sharding.PartitionSpec(),
        ))
        self.steps = 0

    def step(self, local_delta: np.ndarray) -> np.ndarray:
        """One collective tick: contribute this host's i64[G] delta, return
        the i64[G] total over every host."""
        rows = np.zeros((self._n_local, self.global_capacity), np.int64)
        rows[0] = local_delta
        garr = jax.make_array_from_process_local_data(self._row_sharding, rows)
        out = self._step(garr)
        self.steps += 1
        return np.asarray(out)
