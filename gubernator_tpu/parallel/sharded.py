"""Mesh-sharded rate-limit engine: the multi-chip authoritative state tier.

Single-host view of the distributed design (SURVEY.md §2.2): the key table is
sharded over a ("region", "shard") mesh; every key has exactly one owner chip
(reference's owner-peer model, architecture.md:13-17) and one batch window
becomes one `shard_map`ped kernel launch where each chip applies the lanes
routed to it. The reference's non-owner -> owner gRPC forwarding
(peer_client.go:215-319) is replaced by host-side lane routing into the
[R, S, W] batch; its GLOBAL gRPC pipelines are replaced by the psum step in
parallel/global_sync.py.

Behavior=GLOBAL here (reference: gubernator.go:226-247):
- requests are answered from the replicated host-side mirror (the owner's
  last broadcast), with local hit deltas accumulated for the next sync;
- a key's FIRST touch (mirror miss) goes through the authoritative kernel
  synchronously and its hits are NOT queued — slightly stricter than the
  reference, which both queues the hit and processes it as-if-owner
  (double-counting one window's hits, gubernator.go:227-246);
- between syncs the local mirror's `remaining` is optimistically decremented
  by locally-queued hits — stricter than the reference, which returns the
  cached broadcast unmodified (gubernator.go:232-240) and so admits
  unbounded hits per peer per sync window; each broadcast overwrites the
  optimistic copy with the authoritative psum result.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gubernator_tpu.models.keyspace import KeyDirectory
from gubernator_tpu.models.prep import WorkItem, bucket_width, preprocess
from gubernator_tpu.ops.decide import TableState, decide_packed, pack_window
from gubernator_tpu.parallel.global_sync import (
    GlobalConfig,
    GlobalMirror,
    make_global_sync,
)
from gubernator_tpu.parallel.mesh import (
    REGION_AXIS,
    SHARD_AXIS,
    MeshPlan,
    make_mesh,
    make_sharded_table,
    shard_of_key,
)
from gubernator_tpu.types import (
    Behavior,
    RateLimitReq,
    RateLimitResp,
    Status,
    has_behavior,
)
from gubernator_tpu.utils.interval import millisecond_now


def make_decide_sharded(plan: MeshPlan, donate: bool = False):
    """Compile the batched decision kernel over the plan's mesh.

    fn(state [R,S,C], packed i64[R,S,9,W], now) -> (state, out i64[R,S,4,W]);
    each chip applies its own lane slice to its own table shard — no
    cross-chip traffic at all on the normal (non-GLOBAL) path, mirroring the
    reference's owner-local mutation. Requests ride ONE staging buffer up
    and one back (see ops/decide.py decide_packed; the host-side packer is
    ShardedEngine._apply_round — keep row orders in sync).
    """
    spec_state = P(REGION_AXIS, SHARD_AXIS, None)
    spec_io = P(REGION_AXIS, SHARD_AXIS, None, None)

    def _step(state: TableState, packed: jax.Array, now: jax.Array):
        local_state = TableState(*(c.reshape(c.shape[-1:]) for c in state))
        new_state, out = decide_packed(
            local_state, packed.reshape(packed.shape[-2:]), now
        )
        return (
            TableState(*(c.reshape(1, 1, -1) for c in new_state)),
            out.reshape(1, 1, *out.shape),
        )

    mapped = jax.shard_map(
        _step, mesh=plan.mesh,
        in_specs=(spec_state, spec_io, P()),
        out_specs=(spec_state, spec_io),
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


class _GlobalEntry:
    """Host record for one registered global key."""

    __slots__ = ("gidx", "owner", "req", "seen")

    def __init__(self, gidx: int, owner: int):
        self.gidx = gidx
        self.owner = owner
        self.req: Optional[RateLimitReq] = None
        self.seen = False  # at least one broadcast has populated the mirror


class ShardedEngine:
    """Authoritative rate-limit state sharded over a device mesh."""

    def __init__(
        self,
        mesh=None,
        n_shards: Optional[int] = None,
        n_regions: int = 1,
        capacity_per_shard: int = 1 << 17,
        global_capacity: int = 1024,
        min_width: int = 64,
        max_width: int = 4096,
        donate: Optional[bool] = None,
    ):
        if mesh is None:
            mesh = make_mesh(n_shards=n_shards, n_regions=n_regions)
        self.plan = MeshPlan(mesh=mesh, capacity_per_shard=capacity_per_shard)
        if donate is None:
            from gubernator_tpu.utils.platform import donation_supported

            donate = donation_supported()
        self.state = make_sharded_table(self.plan)
        self._decide = make_decide_sharded(self.plan, donate=donate)
        self._sync = make_global_sync(self.plan, donate=donate)
        from gubernator_tpu.native import make_key_directory

        self.directories = [
            make_key_directory(capacity_per_shard)
            for _ in range(self.plan.n_owners)
        ]
        self.min_width = min_width
        self.max_width = min(max_width, capacity_per_shard)
        self._lock = threading.Lock()

        # ---- GLOBAL-behavior host state --------------------------------
        self.global_capacity = global_capacity
        self._globals: Dict[str, _GlobalEntry] = {}
        self._gdelta = np.zeros((global_capacity,), np.int64)  # local hits
        self._mirror = GlobalMirror(  # host copy of last broadcast
            status=np.zeros((global_capacity,), np.int32),
            limit=np.zeros((global_capacity,), np.int64),
            remaining=np.zeros((global_capacity,), np.int64),
            reset_time=np.zeros((global_capacity,), np.int64),
        )
        self.stats = {
            "requests": 0,
            "batches": 0,
            "rounds": 0,
            "over_limit": 0,
            "errors": 0,
            "global_hits_queued": 0,
            "global_syncs": 0,
            "global_mirror_answers": 0,
        }

    # ------------------------------------------------------------------ API

    def owner_of(self, key: str) -> int:
        return shard_of_key(key, self.plan.n_owners)

    def get_rate_limits(
        self, requests: Sequence[RateLimitReq], now_ms: Optional[int] = None
    ) -> List[RateLimitResp]:
        if now_ms is None:
            now_ms = millisecond_now()
        responses, rounds, n_errors = preprocess(requests, now_ms)
        with self._lock:
            self.stats["requests"] += len(requests)
            self.stats["batches"] += 1
            self.stats["errors"] += n_errors
            for round_work in rounds:
                kernel_items = []
                for item in round_work:
                    if self._try_answer_global(item, responses):
                        continue
                    kernel_items.append(item)
                if kernel_items:
                    self.stats["rounds"] += 1
                    for start in range(0, len(kernel_items), self.max_width):
                        self._apply_round(
                            kernel_items[start : start + self.max_width],
                            now_ms,
                            responses,
                        )
        return responses  # type: ignore[return-value]

    def global_sync(self, now_ms: Optional[int] = None) -> int:
        """Run one psum sync window (reference: global.go runAsyncHits +
        runBroadcasts, collapsed). Returns the number of keys broadcast."""
        if now_ms is None:
            now_ms = millisecond_now()
        with self._lock:
            live = [e for e in self._globals.values() if e.req is not None]
            if not live:
                return 0
            cfg = self._build_global_config(now_ms)
            delta = self._place_delta()
            self.state, mirror, _ = self._sync(self.state, delta, cfg, now_ms)
            # np.array (not asarray): the host mirror must be writable for
            # optimistic deduction between syncs
            self._mirror = GlobalMirror(*(np.array(c) for c in mirror))
            self._gdelta[:] = 0
            for e in live:
                e.seen = True
            self.stats["global_syncs"] += 1
            return len(live)

    def global_pending_hits(self) -> int:
        return int(self._gdelta.sum())

    # ------------------------------------------------------------- internals

    def _try_answer_global(self, item: WorkItem, responses) -> bool:
        """Answer a GLOBAL request from the replicated mirror; queue its hits
        for the next sync. Returns False if the item must go to the kernel
        (not GLOBAL, or first touch)."""
        i, r, _ge, _gi = item
        if not has_behavior(r.behavior, Behavior.GLOBAL):
            return False
        key = r.hash_key()
        entry = self._globals.get(key)
        if entry is None:
            if len(self._globals) >= self.global_capacity:
                # registry full: serve authoritatively, skip async pipeline
                return False
            entry = _GlobalEntry(len(self._globals), self.owner_of(key))
            self._globals[key] = entry
        entry.req = r
        if not entry.seen:
            return False  # first touch: authoritative kernel path
        self._gdelta[entry.gidx] += r.hits
        self.stats["global_hits_queued"] += int(r.hits)
        self.stats["global_mirror_answers"] += 1
        # Optimistic local admission against the last broadcast: deduct hits
        # we can satisfy, reject the rest without deducting (token-bucket
        # response semantics, algorithms.go:107-133). Stricter than the
        # reference's frozen cached answer; authoritative state arrives with
        # the next broadcast.
        g = entry.gidx
        rem = int(self._mirror.remaining[g])
        st = int(self._mirror.status[g])
        if r.hits > 0:
            if rem == 0 or r.hits > rem:
                st = int(Status.OVER_LIMIT)
            else:
                rem -= r.hits
                self._mirror.remaining[g] = rem
        if st == Status.OVER_LIMIT:
            self.stats["over_limit"] += 1
        responses[i] = RateLimitResp(
            status=st,
            limit=int(self._mirror.limit[g]),
            remaining=rem,
            reset_time=int(self._mirror.reset_time[g]),
        )
        return True

    def _apply_round(self, round_work: List[WorkItem], now_ms, responses) -> None:
        R, S = self.plan.n_regions, self.plan.n_shards
        lanes: List[List[WorkItem]] = [[] for _ in range(R * S)]
        for item in round_work:
            lanes[self.owner_of(item[1].hash_key())].append(item)
        width = max(len(l) for l in lanes)
        w = bucket_width(width, self.min_width, self.max_width)

        # one i64[R,S,9,w] staging buffer up, one i64[R,S,4,w] back
        # (row order must match make_decide_sharded's unpack)
        packed = np.zeros((R, S, 9, w), np.int64)
        packed[:, :, 0, :] = -1  # vacant lanes
        placed: List[Tuple[int, int, int, int]] = []  # (resp idx, r, s, lane)
        for owner, items in enumerate(lanes):
            if not items:
                continue
            r_, s_ = self.plan.owner_coords(owner)
            keys = [it[1].hash_key() for it in items]
            slots, fresh = self.directories[owner].lookup(keys)
            packed[r_, s_] = pack_window(items, slots, fresh, w)
            for lane, item in enumerate(items):
                placed.append((item[0], r_, s_, lane))

        self.state, out = self._decide(self.state, packed, now_ms)

        out = np.asarray(out)
        for i, r_, s_, lane in placed:
            st = int(out[r_, s_, 0, lane])
            if st == Status.OVER_LIMIT:
                self.stats["over_limit"] += 1
            responses[i] = RateLimitResp(
                status=st,
                limit=int(out[r_, s_, 1, lane]),
                remaining=int(out[r_, s_, 2, lane]),
                reset_time=int(out[r_, s_, 3, lane]),
            )

    def _build_global_config(self, now_ms: int) -> GlobalConfig:
        import datetime as _dt

        from gubernator_tpu.utils.gregorian import (
            gregorian_duration,
            gregorian_expiration,
        )

        G = self.global_capacity
        slot = np.full((G,), -1, np.int32)
        owner = np.zeros((G,), np.int32)
        limit = np.zeros((G,), np.int64)
        duration = np.zeros((G,), np.int64)
        algorithm = np.zeros((G,), np.int32)
        behavior = np.zeros((G,), np.int32)
        greg_expire = np.zeros((G,), np.int64)
        greg_interval = np.zeros((G,), np.int64)
        fresh = np.zeros((G,), np.bool_)
        by_owner: Dict[int, List[Tuple[str, _GlobalEntry]]] = {}
        for key, e in self._globals.items():
            if e.req is not None:
                by_owner.setdefault(e.owner, []).append((key, e))
        local_now = _dt.datetime.fromtimestamp(now_ms / 1000.0)
        for own, entries in by_owner.items():
            slots, fr = self.directories[own].lookup([k for k, _ in entries])
            for (key, e), s_, f_ in zip(entries, slots, fr):
                g = e.gidx
                slot[g] = s_
                owner[g] = own
                limit[g] = e.req.limit
                duration[g] = e.req.duration
                algorithm[g] = int(e.req.algorithm)
                # the broadcast re-applies with the GLOBAL flag stripped
                # (reference: global.go:209-214)
                behavior[g] = int(e.req.behavior) & ~int(Behavior.GLOBAL)
                fresh[g] = f_
                if has_behavior(e.req.behavior, Behavior.DURATION_IS_GREGORIAN):
                    greg_expire[g] = gregorian_expiration(local_now, e.req.duration)
                    greg_interval[g] = gregorian_duration(local_now, e.req.duration)
        return GlobalConfig(
            slot=jnp.asarray(slot),
            owner=jnp.asarray(owner),
            limit=jnp.asarray(limit),
            duration=jnp.asarray(duration),
            algorithm=jnp.asarray(algorithm),
            behavior=jnp.asarray(behavior),
            greg_expire=jnp.asarray(greg_expire),
            greg_interval=jnp.asarray(greg_interval),
            fresh=jnp.asarray(fresh),
        )

    def _place_delta(self) -> jax.Array:
        """This host's deltas enter the mesh on device (0, 0); psum makes
        placement irrelevant. Multi-host processes each fill their local row."""
        R, S = self.plan.n_regions, self.plan.n_shards
        delta = np.zeros((R, S, self.global_capacity), np.int64)
        delta[0, 0, :] = self._gdelta
        return jnp.asarray(delta)
