"""Mesh-sharded rate-limit engine: the multi-chip authoritative state tier.

Single-host view of the distributed design (SURVEY.md §2.2): the key table is
sharded over a ("region", "shard") mesh; every key has exactly one owner chip
(reference's owner-peer model, architecture.md:13-17) and one batch window
becomes one `shard_map`ped kernel launch where each chip applies the lanes
routed to it. The reference's non-owner -> owner gRPC forwarding
(peer_client.go:215-319) is replaced by host-side lane routing into the
[R, S, W] batch; its GLOBAL gRPC pipelines are replaced by the psum step in
parallel/global_sync.py.

Behavior=GLOBAL here (reference: gubernator.go:226-247):
- requests are answered from the replicated host-side mirror (the owner's
  last broadcast), with local hit deltas accumulated for the next sync;
- a key's FIRST touch (mirror miss) goes through the authoritative kernel
  synchronously and its hits are NOT queued — slightly stricter than the
  reference, which both queues the hit and processes it as-if-owner
  (double-counting one window's hits, gubernator.go:227-246);
- between syncs the local mirror's `remaining` is optimistically decremented
  by locally-queued hits — stricter than the reference, which returns the
  cached broadcast unmodified (gubernator.go:232-240) and so admits
  unbounded hits per peer per sync window; each broadcast overwrites the
  optimistic copy with the authoritative psum result.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from gubernator_tpu.obs import witness
from gubernator_tpu.models.keyspace import KeyDirectory
from gubernator_tpu.models.prep import (
    WorkItem,
    bucket_pow2 as _bucket_pow2,
    bucket_width,
    preprocess,
)
from gubernator_tpu.ops.decide import (
    ROW_ALGO,
    ROW_DURATION,
    ROW_EXPIRE,
    ROW_LIMIT,
    ROW_REMAINING,
    ROW_STAMP,
    ROW_STATUS,
    TableState,
    decide_packed,
    decide_packed_lean,
    decide_scan_packed,
    decide_scan_packed_lean,
    lean_capacity_ok,
    lean_window,
    staging_policy,
    widen_compact_out,
    pack_window,
)
from gubernator_tpu.parallel.global_sync import (
    GlobalConfig,
    GlobalMirror,
    make_global_sync,
)
from gubernator_tpu.parallel.mesh import (
    REGION_AXIS,
    SHARD_AXIS,
    MeshPlan,
    make_mesh,
    make_sharded_table,
    shard_map as _shard_map,
    shard_of_key,
)
from gubernator_tpu.types import (
    Behavior,
    RateLimitReq,
    RateLimitResp,
    Status,
    has_behavior,
)
from gubernator_tpu.utils.interval import millisecond_now

from gubernator_tpu.native import PREP_OVERCOMMIT

# lanes the sharded native fast path must hand to the python pipeline:
# gregorian (host calendar math) and GLOBAL (mirror/psum tier)
_SLOW_MASK = int(Behavior.DURATION_IS_GREGORIAN) | int(Behavior.GLOBAL)


def make_decide_sharded(plan: MeshPlan, donate: bool = False):
    """Compile the batched decision kernel over the plan's mesh.

    fn(state [R,S,C], packed i64[R,S,9,W], now) -> (state, out i64[R,S,4,W]);
    each chip applies its own lane slice to its own table shard — no
    cross-chip traffic at all on the normal (non-GLOBAL) path, mirroring the
    reference's owner-local mutation. Requests ride ONE staging buffer up
    and one back (see ops/decide.py decide_packed; the host-side packer is
    ShardedEngine._apply_round — keep row orders in sync).
    """
    spec_state = P(REGION_AXIS, SHARD_AXIS, None, None)
    spec_io = P(REGION_AXIS, SHARD_AXIS, None, None)

    def _step(state: TableState, packed: jax.Array, now: jax.Array):
        local_state = state.reshape(state.shape[-2:])
        new_state, out = decide_packed(
            local_state, packed.reshape(packed.shape[-2:]), now
        )
        return (
            new_state.reshape((1, 1) + new_state.shape),
            out.reshape(1, 1, *out.shape),
        )

    mapped = _shard_map()(
        _step, mesh=plan.mesh,
        in_specs=(spec_state, spec_io, P()),
        out_specs=(spec_state, spec_io),
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def make_decide_sharded_scan(plan: MeshPlan, donate: bool = False):
    """Scan-coalesced variant of make_decide_sharded.

    fn(state [R,S,C], packed i64[R,S,K,9,W], now) -> (state, out
    i64[R,S,K,4,W]): each chip retires K windows over its own shard in ONE
    dispatch — `lax.scan` runs *inside* the shard_map, so the K windows cost
    one launch instead of K (launch overhead dominates; see
    ops/decide.py decide_scan_packed). Window k+1 observes window k's
    writes shard-locally, which is exactly the duplicate-key *rounds*
    ordering the engine needs.
    """
    spec_state = P(REGION_AXIS, SHARD_AXIS, None, None)
    spec_io = P(REGION_AXIS, SHARD_AXIS, None, None, None)

    def _step(state: TableState, packed_k: jax.Array, now: jax.Array):
        local_state = state.reshape(state.shape[-2:])
        new_state, out = decide_scan_packed(
            local_state, packed_k.reshape(packed_k.shape[-3:]), now
        )
        return (
            new_state.reshape((1, 1) + new_state.shape),
            out.reshape(1, 1, *out.shape),
        )

    mapped = _shard_map()(
        _step, mesh=plan.mesh,
        in_specs=(spec_state, spec_io, P()),
        out_specs=(spec_state, spec_io),
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def make_decide_sharded_lean(plan: MeshPlan, donate: bool = False):
    """Lean-lane variant of make_decide_sharded (r5): fn(state [R,S,C,8],
    lanes i32[R,S,W], cfg i64[128,4], now) -> (state, out i32[R,S,4,W]).

    The staging buffer drops from 72 B to 4 B per lane — on a multi-chip
    host the host->device transfer is the window's dominant byte cost,
    and the lean lane cuts it 18x for the dominant serving shape
    (hits=1, few configs; ops/decide.py "lean"). Slots are shard-LOCAL
    (each chip's lane slice indexes its own table shard, same as the
    wide path); the config table is fleet-global and replicated."""
    spec_state = P(REGION_AXIS, SHARD_AXIS, None, None)
    spec_lanes = P(REGION_AXIS, SHARD_AXIS, None)
    spec_out = P(REGION_AXIS, SHARD_AXIS, None, None)

    def _step(state: TableState, lanes: jax.Array, cfg: jax.Array,
              now: jax.Array):
        local_state = state.reshape(state.shape[-2:])
        new_state, out = decide_packed_lean(
            local_state, lanes.reshape(lanes.shape[-1:]), cfg, now)
        return (
            new_state.reshape((1, 1) + new_state.shape),
            out.reshape(1, 1, *out.shape),
        )

    mapped = _shard_map()(
        _step, mesh=plan.mesh,
        in_specs=(spec_state, spec_lanes, P(), P()),
        out_specs=(spec_state, spec_out),
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def make_decide_sharded_scan_lean(plan: MeshPlan, donate: bool = False):
    """Scan-coalesced lean variant: fn(state, lanes i32[R,S,K,W], cfg,
    now) -> (state, out i32[R,S,K,4,W]) — K lean windows per shard in one
    dispatch (see make_decide_sharded_scan for the rounds ordering)."""
    spec_state = P(REGION_AXIS, SHARD_AXIS, None, None)
    spec_lanes = P(REGION_AXIS, SHARD_AXIS, None, None)
    spec_out = P(REGION_AXIS, SHARD_AXIS, None, None, None)

    def _step(state: TableState, lanes_k: jax.Array, cfg: jax.Array,
              now: jax.Array):
        local_state = state.reshape(state.shape[-2:])
        new_state, out = decide_scan_packed_lean(
            local_state, lanes_k.reshape(lanes_k.shape[-2:]), cfg, now)
        return (
            new_state.reshape((1, 1) + new_state.shape),
            out.reshape(1, 1, *out.shape),
        )

    mapped = _shard_map()(
        _step, mesh=plan.mesh,
        in_specs=(spec_state, spec_lanes, P(), P()),
        out_specs=(spec_state, spec_out),
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


def make_gather_sharded(plan: MeshPlan):
    """Row gather over the mesh, for the Store hooks and snapshot deltas.

    fn(state [R,S,C], slot i32[R,S,W]) -> rows i64[R,S,7,W]: each chip reads
    its own slot lanes (lanes with slot -1 return garbage the caller must
    mask on `algo < 0` / its own bookkeeping). One staging buffer back, like
    the decide kernels — the host tier's cost is off-chip round trips.
    Row order is TableState field order; make_inject_sharded mirrors it.
    """
    spec_state = P(REGION_AXIS, SHARD_AXIS, None, None)
    spec_slot = P(REGION_AXIS, SHARD_AXIS, None)
    spec_out = P(REGION_AXIS, SHARD_AXIS, None, None)

    def _step(state: TableState, slot: jax.Array):
        local = state.reshape(state.shape[-2:])
        g = jnp.maximum(slot.reshape(slot.shape[-1:]), 0)
        # row fields 0..6 ARE the output row order (pad field dropped)
        rows = local[g][:, :7].T
        return rows.reshape(1, 1, *rows.shape)

    mapped = _shard_map()(
        _step, mesh=plan.mesh,
        in_specs=(spec_state, spec_slot), out_specs=spec_out,
    )
    return jax.jit(mapped)


def make_inject_sharded(plan: MeshPlan, donate: bool = False):
    """Row scatter over the mesh: the Store read-through's injection path.

    fn(state [R,S,C], slot i32[R,S,W], rows i64[R,S,7,W]) -> state; lanes
    with slot -1 are dropped. Mirrors models/engine.py _inject_rows for the
    single-table engine (reference: algorithms.go:26-33 read-through)."""
    from gubernator_tpu.ops.decide import pad_to_drop

    spec_state = P(REGION_AXIS, SHARD_AXIS, None, None)
    spec_slot = P(REGION_AXIS, SHARD_AXIS, None)
    spec_rows = P(REGION_AXIS, SHARD_AXIS, None, None)

    def _step(state: TableState, slot: jax.Array, rows: jax.Array):
        local = state.reshape(state.shape[-2:])
        s = pad_to_drop(slot.reshape(slot.shape[-1:]), local.shape[0])
        r = rows.reshape(rows.shape[-2:])  # [7, W], row field order
        w8 = jnp.concatenate(
            [r.T, jnp.zeros((r.shape[1], 1), r.dtype)], axis=1)
        new = local.at[s].set(w8, mode="drop")
        return new.reshape((1, 1) + new.shape)

    mapped = _shard_map()(
        _step, mesh=plan.mesh,
        in_specs=(spec_state, spec_slot, spec_rows), out_specs=spec_state,
    )
    return jax.jit(mapped, donate_argnums=(0,) if donate else ())


class _GlobalEntry:
    """Host record for one registered global key."""

    __slots__ = ("gidx", "owner", "req", "seen", "last_ms")

    def __init__(self, gidx: int, owner: int, now_ms: int):
        self.gidx = gidx
        self.owner = owner
        self.req: Optional[RateLimitReq] = None
        self.seen = False  # at least one broadcast has populated the mirror
        self.last_ms = now_ms  # last request touch (LRU / idle eviction)


class ShardedEngine:
    """Authoritative rate-limit state sharded over a device mesh."""

    def __init__(
        self,
        mesh=None,
        n_shards: Optional[int] = None,
        n_regions: int = 1,
        capacity_per_shard: int = 1 << 17,
        global_capacity: int = 1024,
        min_width: int = 64,
        max_width: int = 8192,
        donate: Optional[bool] = None,
        loader=None,
        store=None,
        collectives: str = "psum",
        global_idle_ms: int = 60_000,
    ):
        if mesh is None:
            mesh = make_mesh(n_shards=n_shards, n_regions=n_regions)
        self.plan = MeshPlan(mesh=mesh, capacity_per_shard=capacity_per_shard)
        if donate is None:
            from gubernator_tpu.utils.platform import donation_supported

            donate = donation_supported()
        self.state = make_sharded_table(self.plan)
        self._decide = make_decide_sharded(self.plan, donate=donate)
        self._decide_scan = make_decide_sharded_scan(self.plan, donate=donate)
        self._decide_lean = make_decide_sharded_lean(self.plan,
                                                     donate=donate)
        self._decide_scan_lean = make_decide_sharded_scan_lean(
            self.plan, donate=donate)
        # staging policy, same contract as models/engine.py: auto ships
        # eligible windows on the 4 B/lane lean wire; wide pins i64[9]
        self._staging = staging_policy()
        self._lean_ok = lean_capacity_ok(capacity_per_shard)
        self._sync = make_global_sync(self.plan, donate=donate,
                                      collectives=collectives)
        self.store = store
        if store is not None:
            self._gather = make_gather_sharded(self.plan)
            self._inject = make_inject_sharded(self.plan, donate=donate)
        from gubernator_tpu import native
        from gubernator_tpu.native import make_key_directory

        self.directories = [
            make_key_directory(capacity_per_shard)
            for _ in range(self.plan.n_owners)
        ]
        # native one-pass window prep + owner routing (see Engine._fast_window)
        self._prep_fast = (
            native.prep_route_sharded
            if all(isinstance(d, native.NativeKeyDirectory)
                   for d in self.directories)
            else None
        )
        self.min_width = min_width
        self.max_width = min(max_width, capacity_per_shard)
        self._lock = witness.make_lock("sharded.engine")
        self.loader = loader

        # ---- GLOBAL-behavior host state --------------------------------
        # The registry is an LRU within global_capacity (the reference routes
        # GLOBAL keys through its general 50k LRU, cache.go:82-84): gidx
        # slots are recycled through a free list, idle entries are swept
        # after each sync, and when the registry is full the
        # least-recently-touched zero-delta entry is evicted to make room.
        # Only when every slot still has unsynced hits does a NEW global key
        # fall back to the authoritative path (counted, never permanent).
        self.global_capacity = global_capacity
        self.global_idle_ms = global_idle_ms
        # recency-ordered (oldest first): touches move_to_end, so the LRU
        # victim is the first zero-delta entry in iteration order
        self._globals: "OrderedDict[str, _GlobalEntry]" = OrderedDict()
        self._gfree: List[int] = []  # recycled gidx slots
        self._gnext = 0  # high-water mark of allocated gidx
        self._gdelta = np.zeros((global_capacity,), np.int64)  # local hits
        self._mirror = GlobalMirror(  # host copy of last broadcast
            status=np.zeros((global_capacity,), np.int32),
            limit=np.zeros((global_capacity,), np.int64),
            remaining=np.zeros((global_capacity,), np.int64),
            reset_time=np.zeros((global_capacity,), np.int64),
        )
        self.stats = {
            "requests": 0,
            "batches": 0,
            "rounds": 0,
            "over_limit": 0,
            "errors": 0,
            "global_hits_queued": 0,
            "global_syncs": 0,
            "global_mirror_answers": 0,
            "global_evictions": 0,
            "global_registry_fallbacks": 0,
            "lean_windows": 0,  # windows shipped on the 4 B/lane wire
        }
        # per-stage wall clocks, same contract as models/engine.py
        # EngineStats (exposed as engine_stage_seconds_total in /metrics)
        from gubernator_tpu.models.engine import EngineStats

        for s in EngineStats.STAGES:
            self.stats[f"{s}_ns"] = 0

        if loader is not None:
            self.load_snapshot(loader.load())

    # ------------------------------------------------------------------ API

    def warmup(self) -> None:
        """Compile the mesh kernel for every width bucket and scan shape up
        front, so no serve-time request pays seconds of XLA compile (see
        Engine.warmup; daemons call this before reporting ready)."""
        R, S = self.plan.n_regions, self.plan.n_shards
        widths = []
        w = self.min_width
        while w < self.max_width:
            widths.append(w)
            w *= 2
        widths.append(self.max_width)
        resp = None
        with self._lock:
            lean_warm = self._staging != "wide" and self._lean_ok
            for width in widths:
                packed = np.zeros((R, S, 9, width), np.int64)
                packed[:, :, 0, :] = -1
                self.state, resp = self._decide(self.state, packed, 0)
                if lean_warm:  # auto mode serves either wire format
                    ln = lean_window(packed, self.plan.capacity_per_shard)
                    self.state, resp = self._decide_lean(
                        self.state, jnp.asarray(ln[0]),
                        jnp.asarray(ln[1]), 0)
            k = 2
            while k <= self._MAX_SCAN:
                packed = np.zeros((R, S, k, 9, self.min_width), np.int64)
                packed[:, :, :, 0, :] = -1
                self.state, resp = self._decide_scan(self.state, packed, 0)
                if lean_warm:
                    ln = lean_window(packed, self.plan.capacity_per_shard)
                    self.state, resp = self._decide_scan_lean(
                        self.state, jnp.asarray(ln[0]),
                        jnp.asarray(ln[1]), 0)
                k *= 2
            if self.store is not None:
                # the Store path adds two gathers + an inject per window
                # (_apply_round_store) and a gather per global sync
                # (_store_write_global, whose width ladder is capped by
                # global_capacity rather than max_width)
                gather_widths = set(widths)
                w = self.min_width
                while w < self.global_capacity:
                    gather_widths.add(w)
                    w *= 2
                gather_widths.add(
                    bucket_width(self.global_capacity, self.min_width,
                                 self.global_capacity))
                for width in sorted(gather_widths):
                    slotmat = np.full((R, S, width), -1, np.int32)
                    resp = self._gather(self.state, slotmat)
                    if width in widths:
                        self.state = self._inject(
                            self.state, slotmat,
                            np.zeros((R, S, 7, width), np.int64))
            # the GLOBAL sync kernel is one fixed-shape program; an
            # explicitly empty config + zero delta exercises it as a
            # guaranteed no-op — live host state (registered globals,
            # pending _gdelta) must NOT feed a warmup, or re-warming a
            # serving engine would apply queued hits here and again at the
            # next real sync
            G = self.global_capacity
            z32 = np.zeros((G,), np.int32)
            z64 = np.zeros((G,), np.int64)
            empty_cfg = GlobalConfig(
                slot=jnp.asarray(np.full((G,), -1, np.int32)),
                owner=jnp.asarray(z32), limit=jnp.asarray(z64),
                duration=jnp.asarray(z64), algorithm=jnp.asarray(z32),
                behavior=jnp.asarray(z32), greg_expire=jnp.asarray(z64),
                greg_interval=jnp.asarray(z64),
                fresh=jnp.asarray(np.zeros((G,), np.bool_)))
            self.state, _, _ = self._sync(
                self.state, np.zeros((R, S, G), np.int64), empty_cfg, 0)
            if resp is not None:
                jax.block_until_ready(resp)

    def owner_of(self, key: str) -> int:
        return shard_of_key(key, self.plan.n_owners)

    # ------------------------------------------------------- persistence SPI

    def snapshot(self, include_expired: bool = False):
        """Dump live rows across every shard (single-process meshes; a
        multi-host group snapshots per host, each daemon owning its local
        shards). Mirrors Engine.snapshot (reference: gubernator.go:86-105)."""
        from gubernator_tpu.store import BucketSnapshot
        from gubernator_tpu.utils.interval import millisecond_now

        out = []
        now = millisecond_now()
        with self._lock:
            tbl = np.asarray(self.state)  # [R, S, C, 8]
            for owner, directory in enumerate(self.directories):
                r_, s_ = self.plan.owner_coords(owner)
                for key, slot in directory.items():
                    row = tbl[r_, s_, slot]
                    algo = int(row[ROW_ALGO])
                    expire = int(row[ROW_EXPIRE])
                    if algo < 0:
                        continue
                    if not include_expired and now > expire:
                        continue
                    out.append(BucketSnapshot(
                        key=key, algo=algo,
                        limit=int(row[ROW_LIMIT]),
                        remaining=int(row[ROW_REMAINING]),
                        duration=int(row[ROW_DURATION]),
                        stamp=int(row[ROW_STAMP]),
                        expire_at=expire,
                        status=int(row[ROW_STATUS])))
        return out

    def load_snapshot(self, items) -> int:
        """Seed table rows from a Loader at boot (boot-time only: columns
        round-trip through the host). Reference: gubernator.go:75-83."""
        items = list(items)
        if not items:
            return 0
        with self._lock:
            tbl = np.array(self.state)  # writable host copy [R, S, C, 8]
            n = 0
            by_owner: Dict[int, list] = {}
            for it in items:
                by_owner.setdefault(self.owner_of(it.key), []).append(it)
            for owner, rows in by_owner.items():
                r_, s_ = self.plan.owner_coords(owner)
                # chunked lookups: a snapshot larger than the (possibly
                # resized-down) shard degrades via LRU eviction instead of
                # tripping the directory's over-commit guard, mirroring
                # Engine.load_snapshot
                for start in range(0, len(rows), self.max_width):
                    chunk = rows[start:start + self.max_width]
                    slots, _ = self.directories[owner].lookup(
                        [it.key for it in chunk])
                    for it, slot in zip(chunk, slots):
                        tbl[r_, s_, slot, :7] = (
                            it.algo, it.limit, it.remaining, it.duration,
                            it.stamp, it.expire_at, it.status)
                        n += 1
            self.state = jax.device_put(tbl, self.plan.state_sharding())
        return n

    def close(self) -> None:
        """Persist via the Loader, mirroring daemon shutdown
        (reference: gubernator.go:86-105). Pending GLOBAL hit deltas are
        flushed through one last sync first so the persisted rows — the
        Loader snapshot AND the Store's write-through copies — reflect every
        admitted hit, not just the last broadcast."""
        if ((self.loader is not None or self.store is not None)
                and self.global_pending_hits()):
            self.global_sync()
        if self.loader is not None:
            self.loader.save(self.snapshot())

    def get_rate_limits(
        self, requests: Sequence[RateLimitReq], now_ms: Optional[int] = None
    ) -> List[RateLimitResp]:
        if now_ms is None:
            now_ms = millisecond_now()
        if (self._prep_fast is not None and self.store is None
                and 0 < len(requests) <= self.max_width):
            fast = self._fast_window(requests, now_ms)
            if fast is not None:
                return fast
        return self._slow_window(requests, now_ms)

    def _fast_window(self, requests, now_ms) -> Optional[List[RateLimitResp]]:
        """Native one-pass window: validate + first-occurrence split + owner
        routing + per-owner directory lookup in one C call
        (native/keydir.cpp keydir_prep_route_sharded). Leftover lanes —
        invalid, gregorian, GLOBAL, duplicate occurrences — run through the
        python pipeline AFTER this round (same per-key order contract as
        Engine._fast_window)."""
        with self._lock:
            t0 = time.perf_counter_ns()  # excludes the lock wait
            n0, cols, lane_item, owner_count, leftover = self._prep_fast(
                self.directories, requests, _SLOW_MASK)
            if n0 == PREP_OVERCOMMIT:
                self._raise_overcommit()
            if n0 < 0:
                return None
            t1 = time.perf_counter_ns()
            self.stats["prep_ns"] += t1 - t0
            self.stats["requests"] += n0
            self.stats["batches"] += 1
            responses: List[Optional[RateLimitResp]] = [None] * len(requests)
            if n0:
                out, placed = self._pack_and_decide(
                    cols, lane_item, owner_count, now_ms, t1)
                t3 = time.perf_counter_ns()
                out = self._fetch_mesh(out)  # readback sync
                t4 = time.perf_counter_ns()
                self.stats["device_ns"] += t4 - t3
                self._demux(out, placed, responses)
                self.stats["demux_ns"] += time.perf_counter_ns() - t4
        if len(leftover):
            idxs = leftover.tolist()
            tail = self._slow_window(
                [requests[i] for i in idxs], now_ms, count_batch=False)
            for i, resp in zip(idxs, tail):
                responses[i] = resp
        return responses  # type: ignore[return-value]

    # ------------------------------------------------------- columnar path

    def supports_columnar(self) -> bool:
        """True when the zero-object columnar serving path is available
        (models/engine.py Engine.supports_columnar's mesh twin)."""
        return self._prep_fast is not None and self.store is None

    def submit_columnar(self, n: int, keys, key_off, name_len, hits, limit,
                        duration, algorithm, behavior, slow_mask: int,
                        now_ms: Optional[int] = None):
        """Dispatch one columnar window over the mesh: wire columns route
        to owner shards in one GIL-free C pass
        (native/keydir.cpp keydir_prep_route_columnar) and decide in one
        shard_map'ped launch. Same contract as Engine.submit_columnar —
        the peerlink server drives either backend through it."""
        if not 0 < n <= self.max_width:
            return None
        if now_ms is None:
            now_ms = millisecond_now()
        from gubernator_tpu import native

        with self._lock:
            t0 = time.perf_counter_ns()
            n0, cols, lane_item, owner_count, leftover = \
                native.prep_route_columnar(
                    self.directories, n, keys, key_off, name_len, hits,
                    limit, duration, algorithm, behavior,
                    slow_mask | _SLOW_MASK)
            if n0 == PREP_OVERCOMMIT:
                self._raise_overcommit()
            if n0 < 0:
                return None
            t1 = time.perf_counter_ns()
            self.stats["prep_ns"] += t1 - t0
            self.stats["requests"] += n0
            self.stats["batches"] += 1
            out, placed = None, []
            if n0:
                out, placed = self._pack_and_decide(
                    cols, lane_item, owner_count, now_ms, t1)
        return (out, placed, leftover, n0)

    def _raise_overcommit(self):
        raise RuntimeError(
            "key directory over-committed: "
            f">{self.plan.capacity_per_shard} distinct keys on one shard "
            "in one lookup")

    def _pack_and_decide(self, cols, lane_item, owner_count, now_ms, t1):
        """Pack owner-major staging cols into the [R,S,9,w] mesh buffer
        and dispatch one shard_map'ped window — the ONE copy of the mesh
        packing contract, shared by the object and columnar fast paths.
        Returns (_dispatch_mesh handle, placed) with placed rows
        (r, s, None, lanes); readback via _fetch_mesh. Caller holds the
        lock; `t1` is the pack-start clock; pack/rounds/dispatch stats
        recorded here, readback+demux by the caller."""
        R, S = self.plan.n_regions, self.plan.n_shards
        counts = owner_count.tolist()
        w = bucket_width(max(counts), self.min_width, self.max_width)
        packed = np.zeros((R, S, 9, w), np.int64)
        packed[:, :, 0, :] = -1
        placed = []
        lanes = lane_item.tolist()
        pos = 0
        for o, cnt in enumerate(counts):
            if not cnt:
                continue
            r_, s_ = self.plan.owner_coords(o)
            packed[r_, s_, :, :cnt] = cols[:, pos:pos + cnt]
            placed.append((r_, s_, None, lanes[pos:pos + cnt]))
            pos += cnt
        t2 = time.perf_counter_ns()
        self.stats["pack_ns"] += t2 - t1
        self.stats["rounds"] += 1
        handle = self._dispatch_mesh(packed, now_ms)
        self.stats["device_ns"] += time.perf_counter_ns() - t2
        return handle, placed

    def complete_columnar(self, handle, out_status, out_limit,
                          out_remaining, out_reset) -> np.ndarray:
        """Read back a submitted mesh window and scatter the owner blocks'
        response rows to their item positions. Returns leftover indices
        (run them through the request-object path AFTER this round)."""
        out, placed, leftover, n0 = handle
        if n0:
            t0 = time.perf_counter_ns()
            rows = self._fetch_mesh(out)  # device sync for THIS window
            t1 = time.perf_counter_ns()
            over = 0
            for r_, s_, _k, lanes in placed:
                blk = rows[r_, s_]
                cnt = len(lanes)
                li = np.asarray(lanes, np.int64)
                out_status[li] = blk[0, :cnt]
                out_limit[li] = blk[1, :cnt]
                out_remaining[li] = blk[2, :cnt]
                out_reset[li] = blk[3, :cnt]
                over += int(np.count_nonzero(
                    blk[0, :cnt] == int(Status.OVER_LIMIT)))
            t2 = time.perf_counter_ns()
            with self._lock:  # concurrent completers: counters stay exact
                self.stats["over_limit"] += over
                self.stats["device_ns"] += t1 - t0
                self.stats["demux_ns"] += t2 - t1
        return leftover

    # ------------------------------------------- pipelined columnar serving
    # Mesh twin of Engine.launch_columnar_windows (models/engine.py has
    # the full ordering argument): one shard_map launch per window, no
    # readback between launches, group cut on the first window that
    # yields leftovers.

    def launch_columnar_windows(self, windows, slow_mask: int,
                                now_ms: Optional[int] = None, staging=None):
        """Dispatch a PREFIX of 1..K columnar sub-windows over the mesh
        without blocking on any readback. Same wire layout and handle
        contract as Engine.launch_columnar_windows: handle[0] is the
        consumed-window meta list (each meta's last element the leftover
        indices), handle[1] an over-commit message or None. `staging` is
        accepted for contract parity (the mesh packer allocates per
        window)."""
        if not self.supports_columnar():
            return None
        if not windows or any(not 0 < wc[0] <= self.max_width
                              for wc in windows):
            return None
        if now_ms is None:
            now_ms = millisecond_now()
        from gubernator_tpu import native

        metas = []
        failed = None
        for k, wc in enumerate(windows):
            (n, keys, key_off, name_len, hits, limit, duration,
             algorithm, behavior) = wc
            with self._lock:
                t0 = time.perf_counter_ns()
                n0, cols, lane_item, owner_count, leftover = \
                    native.prep_route_columnar(
                        self.directories, n, keys, key_off, name_len,
                        hits, limit, duration, algorithm, behavior,
                        slow_mask | _SLOW_MASK)
                if n0 == PREP_OVERCOMMIT:
                    # earlier windows already dispatched; this one and the
                    # rest are not consumed (caller error-fills them)
                    failed = ("key directory over-committed: "
                              f">{self.plan.capacity_per_shard} distinct "
                              "keys on one shard in one lookup")
                    break
                if n0 < 0:
                    if k == 0:
                        return None  # nothing mutated: object fallback
                    # defensive: nothing committed for THIS window — it
                    # retires whole through the caller's leftover path
                    metas.append((0, None, [],
                                  np.arange(n, dtype=np.int32)))
                    break
                t1 = time.perf_counter_ns()
                self.stats["prep_ns"] += t1 - t0
                self.stats["requests"] += n0
                self.stats["batches"] += 1
                out, placed = None, []
                if n0:
                    out, placed = self._pack_and_decide(
                        cols, lane_item, owner_count, now_ms, t1)
                metas.append((n0, out, placed, leftover))
            if len(leftover):
                break  # group-cut barrier: leftovers retire first
        return (metas, failed)

    def collect_columnar_windows(self, handle, outs):
        """Block on a launched columnar group's mesh readbacks (in launch
        order) and scatter each window's owner blocks into the caller's
        column buffers. Same contract as Engine.collect_columnar_windows."""
        metas, _failed = handle
        over_status = int(Status.OVER_LIMIT)
        leftovers = []
        for (n0, out, placed, leftover), (o_st, o_li, o_re, o_rs) in zip(
                metas, outs):
            if n0:
                t0 = time.perf_counter_ns()
                rows = self._fetch_mesh(out)  # device sync, THIS window
                t1 = time.perf_counter_ns()
                over = 0
                for r_, s_, _k, lanes in placed:
                    blk = rows[r_, s_]
                    cnt = len(lanes)
                    li = np.asarray(lanes, np.int64)
                    o_st[li] = blk[0, :cnt]
                    o_li[li] = blk[1, :cnt]
                    o_re[li] = blk[2, :cnt]
                    o_rs[li] = blk[3, :cnt]
                    over += int(np.count_nonzero(
                        blk[0, :cnt] == over_status))
                t2 = time.perf_counter_ns()
                with self._lock:  # counters stay exact under concurrency
                    self.stats["over_limit"] += over
                    self.stats["device_ns"] += t1 - t0
                    self.stats["demux_ns"] += t2 - t1
            leftovers.append(leftover)
        return leftovers

    # ----------------------------------------------------- pipelined serving
    # Launch/collect split for the combiner's depth-N pipeline
    # (models/engine.py has the single-chip twin and the ordering
    # argument). Mesh groups launch one shard_map window per member —
    # still zero readbacks between launches, so depth cycles overlap.

    def supports_pipeline(self) -> bool:
        """True when the non-blocking launch/collect split is available
        (native routing prep, no Store hooks)."""
        return self._prep_fast is not None and self.store is None

    def launch_windows(self, windows, now_ms: Optional[int] = None,
                       staging=None):
        """Dispatch 1..K request-object windows without blocking on any
        readback (one mesh launch per window, state-chained). Returns an
        opaque handle for collect_windows, or None when the pipelined
        path cannot take the group at all (nothing mutated)."""
        if not self.supports_pipeline():
            return None
        if not windows or any(not 0 < len(wk) <= self.max_width
                              for wk in windows):
            return None
        if now_ms is None:
            now_ms = millisecond_now()
        meta = []
        tails = []
        for wk in windows:
            with self._lock:
                t0 = time.perf_counter_ns()
                n0, cols, lane_item, owner_count, leftover = self._prep_fast(
                    self.directories, wk, _SLOW_MASK)
                if n0 == PREP_OVERCOMMIT:
                    self._raise_overcommit()
                if n0 < 0:
                    # defensive: nothing committed for THIS window — it
                    # retires whole through the python tail below
                    n0, out, placed = 0, None, []
                    leftover = np.arange(len(wk), dtype=np.int32)
                else:
                    t1 = time.perf_counter_ns()
                    self.stats["prep_ns"] += t1 - t0
                    self.stats["requests"] += n0
                    self.stats["batches"] += 1
                    out, placed = (None, [])
                    if n0:
                        out, placed = self._pack_and_decide(
                            cols, lane_item, owner_count, now_ms, t1)
                meta.append((n0, out, placed, leftover))
            # Leftover tails retire NOW — after this window's dispatch,
            # BEFORE the next window preps — so a key pending in the tail
            # is never overtaken by its next arrival (per-key submission
            # order; models/engine.py has the full argument). Blocks on
            # its own readback; rare path.
            if leftover is not None and len(leftover):
                idxs = leftover.tolist()
                tails.append(self._slow_window(
                    [wk[i] for i in idxs], now_ms, count_batch=False))
            else:
                tails.append(None)
        return (windows, meta, tails)

    def collect_windows(self, handle):
        """Block on a launched group's readbacks (in launch order) and
        demux: one response list per window. Runs outside the engine lock
        except for the demux counter updates."""
        windows, meta, tails = handle
        results = []
        for k, wk in enumerate(windows):
            n0, out, placed, leftover = meta[k]
            responses: List[Optional[RateLimitResp]] = [None] * len(wk)
            if n0:
                t0 = time.perf_counter_ns()
                rows = self._fetch_mesh(out)  # device sync, THIS window
                t1 = time.perf_counter_ns()
                with self._lock:  # _demux mutates the stats counters
                    self.stats["device_ns"] += t1 - t0
                    self._demux(rows, placed, responses)
                    self.stats["demux_ns"] += time.perf_counter_ns() - t1
            tail = tails[k]
            if tail is not None:
                for i, resp in zip(leftover.tolist(), tail):
                    responses[i] = resp
            results.append(responses)
        return results

    def launch_noop(self, width: Optional[int] = None):
        """All-padding mesh window dispatch (mutates nothing) for the
        combiner's depth auto-probe."""
        R, S = self.plan.n_regions, self.plan.n_shards
        w = width or self.min_width
        packed = np.zeros((R, S, 9, w), np.int64)
        packed[:, :, 0, :] = -1
        with self._lock:
            return self._dispatch_mesh(packed, 0)

    def collect_noop(self, handle) -> None:
        """Block on a launch_noop readback."""
        self._fetch_mesh(handle)

    def _slow_window(self, requests, now_ms,
                     count_batch: bool = True) -> List[RateLimitResp]:
        """The python pipeline (full validation, gregorian, GLOBAL mirror,
        duplicate rounds). `count_batch` is False for a fast window's
        leftover tail — the client batch was already counted there."""
        t0 = time.perf_counter_ns()
        responses, rounds, n_errors = preprocess(requests, now_ms)
        prep_ns = time.perf_counter_ns() - t0  # excludes the lock wait below
        with self._lock:
            self.stats["prep_ns"] += prep_ns
            self.stats["requests"] += len(requests)
            self.stats["batches"] += 1 if count_batch else 0
            self.stats["errors"] += n_errors
            windows: List[List[WorkItem]] = []
            for round_work in rounds:
                kernel_items = []
                for item in round_work:
                    if self._try_answer_global(item, responses, now_ms):
                        continue
                    kernel_items.append(item)
                if kernel_items:
                    self.stats["rounds"] += 1
                    for start in range(0, len(kernel_items), self.max_width):
                        windows.append(
                            kernel_items[start : start + self.max_width])
            head, tail = self._split_scannable(windows)
            for wk in head:
                self._apply_round(wk, now_ms, responses)
            if tail:
                self._apply_rounds_scanned(tail, now_ms, responses)
        return responses  # type: ignore[return-value]

    def global_sync(self, now_ms: Optional[int] = None) -> int:
        """Run one psum sync window (reference: global.go runAsyncHits +
        runBroadcasts, collapsed). Returns the number of keys broadcast."""
        if now_ms is None:
            now_ms = millisecond_now()
        with self._lock:
            live = [(k, e) for k, e in self._globals.items()
                    if e.req is not None]
            if not live:
                return 0
            cfg = self._build_global_config(now_ms)
            delta = self._place_delta()
            # which keys actually carried hits this window, before zeroing:
            # the Store write-through below skips unchanged keys (the
            # reference fires OnChange only per applied hit, global.go:145)
            touched = {int(g) for g in np.nonzero(self._gdelta)[0]}
            self.state, mirror, _ = self._sync(self.state, delta, cfg, now_ms)
            # np.array (not asarray): the host mirror must be writable for
            # optimistic deduction between syncs
            self._mirror = GlobalMirror(*(np.array(c) for c in mirror))
            self._gdelta[:] = 0
            for _k, e in live:
                e.seen = True
            self.stats["global_syncs"] += 1
            if self.store is not None and touched:
                self._store_write_global(
                    [(k, e) for k, e in live if e.gidx in touched], cfg)
            self._sweep_globals(now_ms)
            return len(live)

    def global_pending_hits(self) -> int:
        return int(self._gdelta.sum())

    # ------------------------------------------------------------- internals

    def _try_answer_global(self, item: WorkItem, responses,
                           now_ms: int) -> bool:
        """Answer a GLOBAL request from the replicated mirror; queue its hits
        for the next sync. Returns False if the item must go to the kernel
        (not GLOBAL, or first touch)."""
        i, r, _ge, _gi = item
        if not has_behavior(r.behavior, Behavior.GLOBAL):
            return False
        key = r.hash_key()
        entry = self._globals.get(key)
        if entry is None:
            gidx = self._alloc_gidx(now_ms)
            if gidx < 0:
                # every slot has unsynced hits: serve this one
                # authoritatively and try again next touch
                self.stats["global_registry_fallbacks"] += 1
                return False
            entry = _GlobalEntry(gidx, self.owner_of(key), now_ms)
            self._globals[key] = entry
        else:
            self._globals.move_to_end(key)
        entry.req = r
        entry.last_ms = now_ms
        if not entry.seen:
            return False  # first touch: authoritative kernel path
        self._gdelta[entry.gidx] += r.hits
        self.stats["global_hits_queued"] += int(r.hits)
        self.stats["global_mirror_answers"] += 1
        # Optimistic local admission against the last broadcast: deduct hits
        # we can satisfy, reject the rest without deducting (token-bucket
        # response semantics, algorithms.go:107-133). Stricter than the
        # reference's frozen cached answer; authoritative state arrives with
        # the next broadcast.
        g = entry.gidx
        rem = int(self._mirror.remaining[g])
        st = int(self._mirror.status[g])
        if r.hits > 0:
            if rem == 0 or r.hits > rem:
                st = int(Status.OVER_LIMIT)
            else:
                rem -= r.hits
                self._mirror.remaining[g] = rem
        if st == Status.OVER_LIMIT:
            self.stats["over_limit"] += 1
        responses[i] = RateLimitResp(
            status=st,
            limit=int(self._mirror.limit[g]),
            remaining=rem,
            reset_time=int(self._mirror.reset_time[g]),
        )
        return True

    def _alloc_gidx(self, now_ms: int) -> int:
        """Claim a registry slot: free list, then high-water growth, then LRU
        eviction of a zero-delta entry. -1 when every slot holds unsynced
        hits (caller falls back to the authoritative path for one window)."""
        if self._gfree:
            return self._gfree.pop()
        if self._gnext < self.global_capacity:
            g = self._gnext
            self._gnext += 1
            return g
        # oldest-first iteration order: the first zero-delta entry IS the
        # LRU victim (entries with queued hits are skipped — evicting them
        # would lose hits); O(1) except when the oldest entries all hold
        # unsynced deltas
        for key, e in self._globals.items():
            if self._gdelta[e.gidx]:
                continue
            self._evict_global(key, e)
            return self._gfree.pop()
        return -1

    def _evict_global(self, key: str, entry: _GlobalEntry) -> None:
        """Drop one registered global key and recycle its gidx. The bucket
        row itself stays in the sharded table (its own expiry handles it);
        a re-registered key restarts on the first-touch authoritative path,
        exactly like a key evicted from the reference's LRU
        (cache.go:140-165)."""
        del self._globals[key]
        g = entry.gidx
        self._gdelta[g] = 0  # zero by precondition; keep it invariant
        self._mirror.status[g] = 0
        self._mirror.limit[g] = 0
        self._mirror.remaining[g] = 0
        self._mirror.reset_time[g] = 0
        self._gfree.append(g)
        self.stats["global_evictions"] += 1

    def _sweep_globals(self, now_ms: int) -> None:
        """Evict idle registered keys (no touch for global_idle_ms). Runs
        after a sync window, when every delta has just been flushed, so the
        zero-delta precondition holds for all live entries."""
        idle = [
            (k, e) for k, e in self._globals.items()
            if now_ms - e.last_ms > self.global_idle_ms
            and not self._gdelta[e.gidx]
        ]
        for k, e in idle:
            self._evict_global(k, e)

    def global_registry_size(self) -> int:
        return len(self._globals)

    def key_count(self) -> int:
        """Live key occupancy across every shard directory (the
        cache_size / engine_key_table_size gauge source)."""
        return sum(len(d) for d in self.directories)

    # Same fast-path bounds as models/engine.py: scan groups are capped at 32
    # windows of exactly min_width lanes, so warmup() can pre-compile every
    # shape this path dispatches, and the capacity guard keeps a group's
    # up-front directory lookups from recycling a slot an earlier window in
    # the group already claimed.
    _MAX_SCAN = 32

    def _split_scannable(self, windows: List[List[WorkItem]]):
        """Per-round head + scannable tail; see Engine._split_scannable.

        Round sizes only shrink, so the small duplicate-key rounds the scan
        path exists for always trail the list; wide windows keep the
        per-round path (already one amortized dispatch). A Store keeps the
        scan path (models/engine.py r3 parity): ONE read-through before
        the tail over the union of its keys, ONE write-through after with
        each key's FINAL row — resolved slot/fresh maps thread through
        _pack_lanes so no re-lookup strips a fresh flag (PARITY #8)."""
        if len(windows) <= 1:
            return windows, []
        split = len(windows)
        while split > 0 and len(windows[split - 1]) <= self.min_width:
            split -= 1
        tail = windows[split:]
        if (len(tail) < 2 or
                sum(len(w) for w in tail) * 4 > self.plan.capacity_per_shard):
            return windows, []
        return windows[:split], tail

    def _route_lanes(self, round_work: List[WorkItem]):
        """Split a window's items by owner chip (host-side lane routing)."""
        lanes: List[List[WorkItem]] = [[] for _ in range(self.plan.n_owners)]
        for item in round_work:
            lanes[self.owner_of(item[1].hash_key())].append(item)
        return lanes

    def _pack_lanes(self, lanes, w: int, packed, placed, k: Optional[int],
                    pre=None):
        """Fill one window's [R,S,9,w] slice (packed[..., k, :, :] when k is
        given) and record one (r, s, k, [resp indices]) demux group per
        owner lane-run (lanes 0..n-1 in index order — _demux's contract).

        `pre`, when given, maps owner -> (slots, fresh) already resolved by
        the caller (the Store path looks keys up before read-through)."""
        for owner, items in enumerate(lanes):
            if not items:
                continue
            r_, s_ = self.plan.owner_coords(owner)
            t = time.perf_counter_ns()
            if pre is None:
                keys = [it[1].hash_key() for it in items]
                slots, fresh = self.directories[owner].lookup(keys)
            else:
                slots, fresh = pre[owner]
            t2 = time.perf_counter_ns()
            self.stats["lookup_ns"] += t2 - t
            dst = packed[r_, s_] if k is None else packed[r_, s_, k]
            pack_window(items, slots, fresh, w, out=dst)
            self.stats["pack_ns"] += time.perf_counter_ns() - t2
            # one demux group per owner lane-run: lanes are 0..n-1 in item
            # order, so the group carries just the response indices
            placed.append((r_, s_, k, [item[0] for item in items]))

    def _demux(self, out, placed, responses) -> None:
        """Demux one readback buffer into responses.

        `placed` rows are (r, s, k, [resp indices]) — one group per owner
        lane-run, lanes 0..n-1 in index order; k is None outside the scan
        path. Response row order is decide_packed's output contract. One
        C-level tolist per group beats four per-element int() casts."""
        over = int(Status.OVER_LIMIT)
        for r_, s_, k, idxs in placed:
            row = out[r_, s_] if k is None else out[r_, s_, k]
            status, limit, remaining, reset = row[:, :len(idxs)].tolist()
            for j, i in enumerate(idxs):
                st = status[j]
                if st == over:
                    self.stats["over_limit"] += 1
                responses[i] = RateLimitResp(
                    status=st, limit=limit[j], remaining=remaining[j],
                    reset_time=reset[j])

    @staticmethod
    def _row_snapshot(rows, r_: int, s_: int, j: int, key: str):
        """One gathered-rows lane ([R,S,7,W] buffer, make_gather_sharded's
        row order = TableState field order) as a host BucketSnapshot."""
        from gubernator_tpu.store import BucketSnapshot

        return BucketSnapshot(
            key=key, algo=int(rows[r_, s_, 0, j]),
            limit=int(rows[r_, s_, 1, j]),
            remaining=int(rows[r_, s_, 2, j]),
            duration=int(rows[r_, s_, 3, j]),
            stamp=int(rows[r_, s_, 4, j]),
            expire_at=int(rows[r_, s_, 5, j]),
            status=int(rows[r_, s_, 6, j]))

    def _apply_rounds_scanned(self, windows, now_ms, responses) -> None:
        """Retire every scannable window in ⌈N/32⌉ mesh dispatches.

        The per-round path pays one full shard_map dispatch per duplicate-key
        round; a hot-key herd of d duplicates costs d launches. Here each
        chip scans up to 32 windows of its own lanes in one launch."""
        R, S = self.plan.n_regions, self.plan.n_shards
        w = self.min_width  # _split_scannable guarantees every window fits

        # Store hooks batch around the WHOLE tail (models/engine.py r3
        # parity): one read-through over the union of its keys, one
        # write-through after with final rows. Per-window slot/fresh come
        # from the union lookup's maps — a re-lookup would strip the fresh
        # flag of a first-occurrence key in a later tail window. `fresh`
        # is consumed by the key's first window.
        store_ctx = None
        slot_map = fresh_map = None
        if self.store is not None and windows:
            seen_items = {}
            for wk in windows:
                for item in wk:
                    seen_items.setdefault(item[1].hash_key(), item)
            union_items = list(seen_items.values())
            _lanes, per_owner, slotmat, _wu = \
                self._store_lookup_owners(union_items, unbounded=True)
            self._store_read_through_mesh(per_owner, slotmat, now_ms)
            slot_map, fresh_map = {}, {}
            for _o, _r, _s, _items, keys, slots, fresh in per_owner:
                for j, key in enumerate(keys):
                    slot_map[key] = slots[j]
                    if fresh[j]:
                        fresh_map[key] = True
            store_ctx = (per_owner, slotmat)

        def window_pre(lanes):
            if store_ctx is None:
                return None
            pre = {}
            for owner, items in enumerate(lanes):
                if not items:
                    continue
                ks = [it[1].hash_key() for it in items]
                pre[owner] = ([slot_map[k] for k in ks],
                              [fresh_map.pop(k, False) for k in ks])
            return pre

        for g0 in range(0, len(windows), self._MAX_SCAN):
            group = windows[g0:g0 + self._MAX_SCAN]
            if len(group) == 1:
                # trailing singleton rides the warmed single-window
                # program; inside a store tail it reuses the union's
                # resolved maps (its keys are covered by the batched hooks)
                lanes = self._route_lanes(group[0])
                self._apply_round(group[0], now_ms, responses,
                                  pre=window_pre(lanes), lanes=lanes)
                continue
            k_pad = _bucket_pow2(len(group))
            packed = np.zeros((R, S, k_pad, 9, w), np.int64)
            packed[:, :, :, 0, :] = -1  # vacant lanes (incl. pad windows)
            placed: List[Tuple[int, int, Optional[int], List[int]]] = []
            for k, wk in enumerate(group):
                lanes = self._route_lanes(wk)
                self._pack_lanes(lanes, w, packed, placed, k,
                                 pre=window_pre(lanes))

            t = time.perf_counter_ns()
            out = self._fetch_mesh(self._dispatch_mesh_scan(packed, now_ms))
            t2 = time.perf_counter_ns()
            self.stats["device_ns"] += t2 - t
            self._demux(out, placed, responses)
            self.stats["demux_ns"] += time.perf_counter_ns() - t2

        if store_ctx is not None:
            per_owner, slotmat = store_ctx
            self._store_write_through_mesh(per_owner, slotmat, now_ms)

    # -------------------------------------------------- staging dispatch
    # Every mesh window funnels through these helpers so the wide/lean
    # wire-format switch lives in one place (models/engine.py has the
    # single-chip twin). The handle defers the device sync: the columnar
    # path reads it back in complete_columnar, everyone else via
    # _fetch_mesh immediately.

    def _dispatch_mesh(self, packed: np.ndarray, now_ms):
        """One wide i64[R,S,9,w] window, shipped on the 4 B/lane lean
        wire when eligible. Returns an opaque handle for _fetch_mesh."""
        if self._staging != "wide" and self._lean_ok:
            ln = lean_window(packed, self.plan.capacity_per_shard)
            if ln is not None:
                self.stats["lean_windows"] += 1
                self.state, out = self._decide_lean(
                    self.state, jnp.asarray(ln[0]), jnp.asarray(ln[1]),
                    now_ms)
                return out, now_ms
        self.state, out = self._decide(self.state, packed, now_ms)
        return out, None

    def _dispatch_mesh_scan(self, stacked: np.ndarray, now_ms):
        """decide_scan dispatch of a wide i64[R,S,K,9,w] stack, shipped
        lean when eligible. Handle contract matches _dispatch_mesh."""
        if self._staging != "wide" and self._lean_ok:
            ln = lean_window(stacked, self.plan.capacity_per_shard)
            if ln is not None:
                self.stats["lean_windows"] += 1
                self.state, out = self._decide_scan_lean(
                    self.state, jnp.asarray(ln[0]), jnp.asarray(ln[1]),
                    now_ms)
                return out, now_ms
        self.state, out = self._decide_scan(self.state, stacked, now_ms)
        return out, None

    @staticmethod
    def _fetch_mesh(handle) -> np.ndarray:
        """Block on a dispatched mesh window and return the wide i64
        response rows regardless of which wire format carried it."""
        out, lean_now = handle
        if lean_now is not None:
            return widen_compact_out(np.asarray(out), lean_now)
        return np.asarray(out)

    def _apply_round(self, round_work: List[WorkItem], now_ms, responses,
                     pre=None, lanes=None) -> None:
        """One window, one mesh dispatch. `pre` (owner -> (slots, fresh))
        marks a tail singleton inside _apply_rounds_scanned's store tail,
        whose batched read/write-through already covers these keys
        (`lanes` carries the caller's routing so it isn't redone)."""
        if self.store is not None and pre is None:
            return self._apply_round_store(round_work, now_ms, responses)
        R, S = self.plan.n_regions, self.plan.n_shards
        if lanes is None:
            lanes = self._route_lanes(round_work)
        w = bucket_width(
            max(len(l) for l in lanes), self.min_width, self.max_width)

        # one i64[R,S,9,w] staging buffer up, one i64[R,S,4,w] back
        # (row order must match make_decide_sharded's unpack)
        packed = np.zeros((R, S, 9, w), np.int64)
        packed[:, :, 0, :] = -1  # vacant lanes
        placed: List[Tuple[int, int, Optional[int], List[int]]] = []
        self._pack_lanes(lanes, w, packed, placed, None, pre=pre)

        t = time.perf_counter_ns()
        out = self._fetch_mesh(self._dispatch_mesh(packed, now_ms))
        t2 = time.perf_counter_ns()
        self.stats["device_ns"] += t2 - t
        self._demux(out, placed, responses)
        self.stats["demux_ns"] += time.perf_counter_ns() - t2

    def _store_lookup_owners(self, work_items: List[WorkItem],
                             unbounded: bool = False):
        """Route + per-owner directory lookup for the Store paths.
        Returns (lanes, per_owner rows (owner, r, s, items, keys, slots,
        fresh), slotmat [R,S,w], w). `unbounded` lifts the max_width clamp:
        the scan tail's UNION spans many windows, and its slotmat only
        feeds the store gather/inject — never a decide window — so its
        lane width must fit the union, not the kernel."""
        R, S = self.plan.n_regions, self.plan.n_shards
        lanes = self._route_lanes(work_items)
        mx = max(len(l) for l in lanes)
        cap = max(self.max_width, _bucket_pow2(mx)) if unbounded \
            else self.max_width
        w = bucket_width(mx, self.min_width, cap)
        per_owner = []  # (owner, r, s, items, keys, slots, fresh)
        slotmat = np.full((R, S, w), -1, np.int32)
        t = time.perf_counter_ns()
        for owner, items in enumerate(lanes):
            if not items:
                continue
            r_, s_ = self.plan.owner_coords(owner)
            keys = [it[1].hash_key() for it in items]
            slots, fresh = self.directories[owner].lookup(keys)
            slotmat[r_, s_, :len(slots)] = slots
            per_owner.append((owner, r_, s_, items, keys, slots, list(fresh)))
        self.stats["lookup_ns"] += time.perf_counter_ns() - t
        return lanes, per_owner, slotmat, w

    def _store_read_through_mesh(self, per_owner, slotmat, now_ms) -> None:
        """Consult the store for rows the table can't serve (reference:
        algorithms.go:26-33); injects returned rows and flips their fresh
        flags (per_owner's fresh lists mutate in place)."""
        R, S = self.plan.n_regions, self.plan.n_shards
        w = slotmat.shape[-1]
        t = time.perf_counter_ns()
        rows = np.asarray(self._gather(self.state, slotmat))  # [R,S,7,w]
        inj_slot = np.full((R, S, w), -1, np.int32)
        inj_rows = np.zeros((R, S, 7, w), np.int64)
        inj_n = [0] * self.plan.n_owners
        for owner, r_, s_, items, keys, slots, fresh in per_owner:
            for j, (_i, r, _ge, _gi) in enumerate(items):
                algo = int(rows[r_, s_, 0, j])
                live = (not fresh[j] and algo >= 0
                        and now_ms <= int(rows[r_, s_, 5, j]))
                if live and algo != int(r.algorithm):
                    # algorithm switch discards the old bucket everywhere
                    # (reference: algorithms.go:54-62)
                    self.store.remove(keys[j])
                    live = False
                if live:
                    continue
                item = self.store.get(r)
                if item is None:
                    continue
                k = inj_n[owner]
                inj_n[owner] = k + 1
                inj_slot[r_, s_, k] = slots[j]
                inj_rows[r_, s_, :, k] = (
                    item.algo, item.limit, item.remaining, item.duration,
                    item.stamp, item.expire_at, item.status)
                fresh[j] = False  # the injected row is now live
        if any(inj_n):
            self.state = self._inject(self.state, inj_slot, inj_rows)
        self.stats["store_ns"] += time.perf_counter_ns() - t

    def _store_write_through_mesh(self, per_owner, slotmat, now_ms) -> None:
        """Report post-decision rows (reference: algorithms.go:64-68,
        175-177); discarded buckets get remove + directory drop."""
        t = time.perf_counter_ns()
        rows = np.asarray(self._gather(self.state, slotmat))
        for owner, r_, s_, items, keys, slots, fresh in per_owner:
            for j, (_i, r, _ge, _gi) in enumerate(items):
                if int(rows[r_, s_, 0, j]) < 0:
                    # token RESET_REMAINING cleared the row
                    # (reference: algorithms.go:37-39)
                    self.store.remove(keys[j])
                    self.directories[owner].drop(keys[j])
                    continue
                self.store.on_change(
                    r, self._row_snapshot(rows, r_, s_, j, keys[j]))
        self.stats["store_ns"] += time.perf_counter_ns() - t

    def _apply_round_store(self, round_work: List[WorkItem], now_ms,
                           responses) -> None:
        """Store-aware round: read-through before the kernel, write-through
        after, per owner lane. Mirrors models/engine.py
        _store_read_through/_store_write_through (reference:
        algorithms.go:26-33,64-68,175-177); the extra cost is two mesh row
        gathers and at most one row inject per window — all staged through
        single [R,S,...] buffers like the decide path itself."""
        R, S = self.plan.n_regions, self.plan.n_shards
        lanes, per_owner, slotmat, w = self._store_lookup_owners(round_work)
        self._store_read_through_mesh(per_owner, slotmat, now_ms)

        # ---- decide ------------------------------------------------------
        packed = np.zeros((R, S, 9, w), np.int64)
        packed[:, :, 0, :] = -1
        placed: List[Tuple[int, int, Optional[int], List[int]]] = []
        pre = {owner: (slots, fresh)
               for owner, _r, _s, _items, _keys, slots, fresh in per_owner}
        self._pack_lanes(lanes, w, packed, placed, None, pre=pre)
        t2 = time.perf_counter_ns()
        out = self._fetch_mesh(self._dispatch_mesh(packed, now_ms))
        t3 = time.perf_counter_ns()
        self.stats["device_ns"] += t3 - t2
        self._demux(out, placed, responses)
        self.stats["demux_ns"] += time.perf_counter_ns() - t3

        self._store_write_through_mesh(per_owner, slotmat, now_ms)

    def _build_global_config(self, now_ms: int) -> GlobalConfig:
        import datetime as _dt

        from gubernator_tpu.utils.gregorian import (
            gregorian_duration,
            gregorian_expiration,
        )

        G = self.global_capacity
        slot = np.full((G,), -1, np.int32)
        owner = np.zeros((G,), np.int32)
        limit = np.zeros((G,), np.int64)
        duration = np.zeros((G,), np.int64)
        algorithm = np.zeros((G,), np.int32)
        behavior = np.zeros((G,), np.int32)
        greg_expire = np.zeros((G,), np.int64)
        greg_interval = np.zeros((G,), np.int64)
        fresh = np.zeros((G,), np.bool_)
        by_owner: Dict[int, List[Tuple[str, _GlobalEntry]]] = {}
        for key, e in self._globals.items():
            if e.req is not None:
                by_owner.setdefault(e.owner, []).append((key, e))
        local_now = _dt.datetime.fromtimestamp(now_ms / 1000.0)
        for own, entries in by_owner.items():
            slots, fr = self.directories[own].lookup([k for k, _ in entries])
            for (key, e), s_, f_ in zip(entries, slots, fr):
                g = e.gidx
                slot[g] = s_
                owner[g] = own
                limit[g] = e.req.limit
                duration[g] = e.req.duration
                algorithm[g] = int(e.req.algorithm)
                # the broadcast re-applies with the GLOBAL flag stripped
                # (reference: global.go:209-214)
                behavior[g] = int(e.req.behavior) & ~int(Behavior.GLOBAL)
                fresh[g] = f_
                if has_behavior(e.req.behavior, Behavior.DURATION_IS_GREGORIAN):
                    greg_expire[g] = gregorian_expiration(local_now, e.req.duration)
                    greg_interval[g] = gregorian_duration(local_now, e.req.duration)
        return GlobalConfig(
            slot=jnp.asarray(slot),
            owner=jnp.asarray(owner),
            limit=jnp.asarray(limit),
            duration=jnp.asarray(duration),
            algorithm=jnp.asarray(algorithm),
            behavior=jnp.asarray(behavior),
            greg_expire=jnp.asarray(greg_expire),
            greg_interval=jnp.asarray(greg_interval),
            fresh=jnp.asarray(fresh),
        )

    def _store_write_global(self, live, cfg: GlobalConfig) -> None:
        """Write-through the rows a GLOBAL sync just rewrote.

        In the reference every hit an owner applies goes through getRateLimit
        and so fires Store.OnChange (algorithms.go:64-68 via global.go:145);
        here the sync applies aggregated deltas on device, so the hooks fire
        once per synced key per window — same persisted state, fewer calls."""
        R, S = self.plan.n_regions, self.plan.n_shards
        slot_np = np.asarray(cfg.slot)
        owner_np = np.asarray(cfg.owner)
        lanes = [0] * self.plan.n_owners
        placed = []  # (key, req, r, s, lane)
        width = bucket_width(
            max(1, len(live)), self.min_width, self.global_capacity)
        slotmat = np.full((R, S, width), -1, np.int32)
        for key, e in live:
            g = e.gidx
            if slot_np[g] < 0:
                continue
            r_, s_ = self.plan.owner_coords(int(owner_np[g]))
            k = lanes[int(owner_np[g])]
            lanes[int(owner_np[g])] = k + 1
            slotmat[r_, s_, k] = slot_np[g]
            placed.append((key, e.req, r_, s_, k))
        if not placed:
            return
        t = time.perf_counter_ns()
        rows = np.asarray(self._gather(self.state, slotmat))
        for key, req, r_, s_, k in placed:
            if int(rows[r_, s_, 0, k]) < 0:
                continue
            self.store.on_change(req, self._row_snapshot(rows, r_, s_, k, key))
        self.stats["store_ns"] += time.perf_counter_ns() - t

    def _place_delta(self) -> jax.Array:
        """This host's deltas enter the mesh on device (0, 0); psum makes
        placement irrelevant. Multi-host processes each fill their local row."""
        R, S = self.plan.n_regions, self.plan.n_shards
        delta = np.zeros((R, S, self.global_capacity), np.int64)
        delta[0, 0, :] = self._gdelta
        return jnp.asarray(delta)
