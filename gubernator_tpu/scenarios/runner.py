"""Drive a scenario against a live cluster and render the SLO verdict.

The runner paces a generated schedule onto real in-process daemons
(the same LocalCluster harness the drills use), fires the spec's
timeline events (kills, restarts, membership syncs, fault specs) on a
side thread so a multi-second node boot never stalls the arrival
clock, and then judges the run: client-observed latency percentiles
and goodput against the spec's envelope, plus the anomaly engine's
detector rising edges — a forbidden detector tripping during the run
fails the verdict, exactly as it would page an operator.

`render_verdict` is a pure function of the spec and the run's
aggregate stats, so tests can unit-drill the judgment (a forced SLO
burn must FAIL) without booting a cluster.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence

from gubernator_tpu.obs import witness
from gubernator_tpu.obs.anomaly import DETECTORS
from gubernator_tpu.scenarios.generator import WorkloadGenerator, windowed
from gubernator_tpu.scenarios.spec import (
    AUTOPILOT_PROFILES,
    SCENARIO_NAMES,
    ScenarioSpec,
    get_scenario,
)

VERDICT_SCHEMA_VERSION = 1

# Pacing granularity: arrivals inside one window submit as one batch —
# coarse enough to amortize the RPC, fine enough that a rate ramp is
# visible in the history ring.
BATCH_WINDOW_S = 0.05


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _cluster_behaviors(spec: ScenarioSpec):
    from gubernator_tpu.cluster.harness import test_behaviors

    beh = test_behaviors()
    for field, value in spec.behaviors.items():
        if not hasattr(beh, field):
            raise ValueError(
                f"scenario {spec.name}: unknown behavior field {field!r}")
        setattr(beh, field, value)
    return beh


def _trips(instance) -> Dict[str, int]:
    try:
        return dict(instance.anomaly.trips)
    except Exception:  # noqa: BLE001 — stub instances have no engine
        return {}


class _EventThread:
    """Fires the spec's timeline on its own clock so a blocking action
    (Engine boot on restart_node takes seconds) never stalls pacing.
    Owns the liveness map the driver routes around."""

    def __init__(self, cluster, spec: ScenarioSpec, behaviors,
                 anchor: float):
        self._cluster = cluster
        self._spec = spec
        self._behaviors = behaviors
        self._anchor = anchor
        self.lock = witness.make_lock("scenario.runner")
        self.dead: set = set()  # instance indices the driver must skip
        self.fired: List[dict] = []
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if not self._spec.events:
            return
        self._thread = threading.Thread(
            target=self._run, name="scenario-events", daemon=True)
        self._thread.start()

    def join(self, timeout_s: float = 30.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def _run(self) -> None:
        from gubernator_tpu.service import faults

        for ev in sorted(self._spec.events, key=lambda e: e.at_s):
            delay = self._anchor + ev.at_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t_fire = time.monotonic() - self._anchor
            try:
                self._fire(ev, faults)
                err = ""
            except Exception as e:  # noqa: BLE001 — record, keep the timeline
                err = repr(e)
            self.fired.append({"action": ev.action, "node": ev.node,
                               "at_s": round(ev.at_s, 3),
                               "fired_at_s": round(t_fire, 3),
                               "error": err})

    def _fire(self, ev, faults) -> None:
        cluster = self._cluster
        if ev.action == "kill_node":
            with self.lock:
                self.dead.add(ev.node)
            cluster.stop_instance_at(ev.node)
        elif ev.action == "restart_node":
            addr = cluster.instances[ev.node].address
            port = int(addr.rsplit(":", 1)[1])
            if ev.node not in self.dead:
                with self.lock:
                    self.dead.add(ev.node)
                cluster.stop_instance_at(ev.node)
            cluster.start_instance(
                fixed_port=port,
                behaviors=dataclasses.replace(self._behaviors))
            cluster.sync_peers()
            with self.lock:
                self.dead.discard(ev.node)
        elif ev.action == "add_node":
            cluster.start_instance(
                behaviors=dataclasses.replace(self._behaviors))
            cluster.sync_peers()
        elif ev.action == "sync_peers":
            cluster.sync_peers()
        elif ev.action == "inject_fault":
            faults.install(ev.arg)
        elif ev.action == "clear_faults":
            faults.clear()

    def live_instances(self):
        with self.lock:
            dead = set(self.dead)
        return [ci.instance for i, ci in enumerate(self._cluster.instances)
                if i not in dead]


def _knob_sample(instance) -> dict:
    """The controller-actuated knob values one node is serving with
    right now — the per-segment trajectory SCEN_r*.json records so a
    reviewer can see WHAT the autopilot did, not just the outcome."""
    beh = instance.conf.behaviors
    out = {
        "max_pending": getattr(beh, "max_pending", None),
        "brownout_fraction": getattr(beh, "brownout_fraction", None),
        "hot_lease_fraction": getattr(beh, "hot_lease_fraction", None),
        "hot_lease_ttl_s": getattr(beh, "hot_lease_ttl_s", None),
        "keyspace_interval_s": getattr(
            getattr(instance, "keyspace", None), "interval_s", None),
        "pipeline_depth": getattr(
            getattr(instance, "combiner", None), "depth", None),
    }
    ap = getattr(instance, "autopilot", None)
    if ap is not None:
        out["autopilot_moves"] = ap.moves
        out["autopilot_frozen"] = ap.frozen
    return out


def run_scenario(spec: ScenarioSpec, cluster=None, profile: str = "short",
                 window_s: float = BATCH_WINDOW_S,
                 autopilot: bool = False) -> dict:
    """Run one scenario and return its machine-readable verdict. Boots
    (and tears down) a LocalCluster of spec.nodes when none is given;
    a caller-provided cluster is reused and left running. `autopilot`
    layers the profile's AUTOPILOT_PROFILES overlay onto the cluster
    behaviors (arming the closed-loop controllers with dwell/cooldown
    clocks compressed to match the profile's time scale)."""
    scaled = spec.for_profile(profile)
    scaled.validate()
    schedule = WorkloadGenerator(scaled).schedule()

    own_cluster = cluster is None
    behaviors = _cluster_behaviors(scaled)
    if autopilot:
        overlay = AUTOPILOT_PROFILES.get(profile, AUTOPILOT_PROFILES["full"])
        for field, value in overlay.items():
            setattr(behaviors, field, value)
    if own_cluster:
        from gubernator_tpu.cluster.harness import LocalCluster

        cluster = LocalCluster().start(
            scaled.nodes, behaviors=dataclasses.replace(behaviors))
        time.sleep(0.3)  # boot grace: first peer RPCs past JIT warmup
    try:
        trips_before = {ci.address: _trips(ci.instance)
                        for ci in cluster.instances}
        anchor = time.monotonic()
        events = _EventThread(cluster, scaled, behaviors, anchor)
        events.start()

        latencies: List[float] = []
        ok = over_limit = errors = 0
        batches = 0
        max_lag_s = 0.0
        rr = 0
        last_sweep = anchor
        # per-segment knob trajectory: sampled at every segment boundary
        # (plus a final sample) so SCEN_r*.json shows what the autopilot
        # actually moved, window by window
        seg_ends: List[float] = []
        acc = 0.0
        for seg in scaled.segments:
            acc += seg.duration_s
            seg_ends.append(acc)
        seg_idx = 0
        knob_trajectory: List[dict] = []
        for start_s, arrivals in windowed(schedule, window_s):
            delay = anchor + start_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            else:
                max_lag_s = max(max_lag_s, -delay)
            live = events.live_instances()
            if autopilot:
                # controller sweeps ride the pacing clock the same way
                # scrapes drive them in production (maybe_tick self-gates
                # on the autopilot interval)
                for li in live:
                    try:
                        li.autopilot.maybe_tick()
                    except Exception:  # noqa: BLE001
                        pass
            while seg_idx < len(seg_ends) and start_s >= seg_ends[seg_idx]:
                seg_idx += 1
            if live and (not knob_trajectory
                         or knob_trajectory[-1]["segment"] != seg_idx):
                knob_trajectory.append({
                    "segment": seg_idx, "at_s": round(start_s, 3),
                    "knobs": _knob_sample(live[0])})
            if not live:
                errors += len(arrivals)
                continue
            inst = live[rr % len(live)]
            rr += 1
            reqs = [a.to_request() for a in arrivals]
            t0 = time.perf_counter()
            try:
                resps = inst.get_rate_limits(reqs)
            except Exception:  # noqa: BLE001 — a dying node fails a batch
                errors += len(reqs)
                continue
            latencies.append((time.perf_counter() - t0) * 1e3)
            batches += 1
            for resp in resps:
                if resp.error:
                    errors += 1
                elif resp.status == 1:  # Status.OVER_LIMIT
                    over_limit += 1
                else:
                    ok += 1
            now = time.monotonic()
            if now - last_sweep >= 0.25:
                last_sweep = now
                for li in events.live_instances():
                    try:
                        li.anomaly.check(now)
                    except Exception:  # noqa: BLE001
                        pass
        events.join()
        time.sleep(0.2)  # let in-flight async work land before the sweep
        now = time.monotonic()
        final_live = events.live_instances()
        if final_live:
            knob_trajectory.append({
                "segment": seg_idx,
                "at_s": round(now - anchor, 3),
                "final": True,
                "knobs": _knob_sample(final_live[0])})
        tripped: Dict[str, int] = {}
        conservation: Dict[str, int] = {
            "nodes_audited": 0, "windows_audited": 0, "violations": 0,
            "overshoot_hits": 0, "max_overshoot": 0}
        for ci in cluster.instances:
            inst = ci.instance
            # budget-conservation sweep: force-audit every node's decision
            # ledger (open windows included) so the verdict judges the
            # whole run's admits, not just windows that happened to close
            led = getattr(inst, "ledger", None)
            if led is not None and getattr(led, "enabled", False):
                try:
                    led.audit(getattr(inst, "backend", None), force=True)
                    t = led.totals()
                    conservation["nodes_audited"] += 1
                    conservation["windows_audited"] += \
                        int(t.get("windows_rolled", 0))
                    conservation["violations"] += int(t.get("violations", 0))
                    conservation["overshoot_hits"] += \
                        int(t.get("overshoot_hits", 0))
                    conservation["max_overshoot"] = max(
                        conservation["max_overshoot"],
                        int(t.get("max_overshoot", 0)))
                except Exception:  # noqa: BLE001 — stopped instance
                    pass
            try:
                inst.anomaly.check(now)
            except Exception:  # noqa: BLE001 — stopped instance
                continue
            before = trips_before.get(ci.address, {})
            for det, n in _trips(inst).items():
                delta = n - before.get(det, 0)
                if delta > 0:
                    tripped[det] = tripped.get(det, 0) + delta
    finally:
        if own_cluster:
            from gubernator_tpu.service import faults

            faults.clear()
            cluster.stop()

    latencies.sort()
    offered = len(schedule)
    stats = {
        "offered": offered,
        "ok": ok,
        "over_limit": over_limit,
        "errors": errors,
        "batches": batches,
        "max_lag_s": round(max_lag_s, 3),
        "latency_ms": {
            "p50": round(_percentile(latencies, 0.50), 3),
            "p95": round(_percentile(latencies, 0.95), 3),
            "p99": round(_percentile(latencies, 0.99), 3),
            "max": round(max(latencies), 3) if latencies else 0.0,
        },
        "detectors_tripped": tripped,
        "conservation": conservation,
        "events": events.fired,
        "autopilot": bool(autopilot),
        "knob_trajectory": knob_trajectory,
    }
    return render_verdict(scaled, stats, profile=profile)


def render_verdict(spec: ScenarioSpec, stats: dict,
                   profile: str = "") -> dict:
    """Judge aggregate run stats against the spec's envelope. Pure —
    the unit drills feed synthetic stats (a forced SLO burn, an
    inflated p99) and assert the verdict flips to FAIL."""
    env = spec.envelope
    offered = max(1, int(stats.get("offered", 0)))
    ok = int(stats.get("ok", 0))
    over_limit = int(stats.get("over_limit", 0))
    errors = int(stats.get("errors", 0))
    decided = ok + over_limit
    goodput = decided / offered
    error_share = errors / offered
    over_share = over_limit / decided if decided else 0.0
    p99 = float(stats.get("latency_ms", {}).get("p99", 0.0))
    tripped = dict(stats.get("detectors_tripped", {}))
    forbidden = sorted(d for d in tripped
                       if d in env.forbid_detectors)
    allowed = sorted(d for d in tripped
                     if d in env.allow_detectors)

    checks = [
        {"name": "p99_ms", "ok": p99 <= env.max_p99_ms,
         "observed": p99, "threshold": env.max_p99_ms},
        {"name": "goodput", "ok": goodput >= env.min_goodput,
         "observed": round(goodput, 6), "threshold": env.min_goodput},
        {"name": "error_share", "ok": error_share <= env.max_error_share,
         "observed": round(error_share, 6),
         "threshold": env.max_error_share},
        {"name": "forbidden_detectors", "ok": not forbidden,
         "observed": forbidden, "threshold": list(env.forbid_detectors)},
    ]
    if env.min_over_limit_share > 0:
        checks.append(
            {"name": "over_limit_share",
             "ok": over_share >= env.min_over_limit_share,
             "observed": round(over_share, 6),
             "threshold": env.min_over_limit_share})
    if env.max_over_admission is not None:
        cons = stats.get("conservation") or {}
        violations = int(cons.get("violations", 0))
        checks.append(
            {"name": "over_admission",
             "ok": violations <= env.max_over_admission,
             "observed": violations,
             "threshold": env.max_over_admission})
    unknown = sorted(d for d in tripped if d not in DETECTORS)
    if unknown:
        checks.append({"name": "known_detectors", "ok": False,
                       "observed": unknown, "threshold": list(DETECTORS)})

    return {
        "schema_version": VERDICT_SCHEMA_VERSION,
        "scenario": spec.name,
        "profile": profile,
        "seed": spec.seed,
        "duration_s": round(spec.duration_s(), 3),
        "passed": all(c["ok"] for c in checks),
        "checks": checks,
        "goodput": round(goodput, 6),
        "over_limit_share": round(over_share, 6),
        "error_share": round(error_share, 6),
        "allowed_detectors_seen": allowed,
        "stats": stats,
    }


def run_atlas(names: Optional[Sequence[str]] = None,
              profile: str = "short", autopilot: bool = False) -> dict:
    """Run (a subset of) the atlas, one fresh cluster per scenario, and
    return {"scenarios": {...}, "passed": bool}."""
    names = list(names or SCENARIO_NAMES)
    out: Dict[str, dict] = {}
    for name in names:
        out[name] = run_scenario(get_scenario(name), profile=profile,
                                 autopilot=autopilot)
    return {
        "schema_version": VERDICT_SCHEMA_VERSION,
        "profile": profile,
        "autopilot": bool(autopilot),
        "scenarios": out,
        "passed": all(v["passed"] for v in out.values()),
    }
