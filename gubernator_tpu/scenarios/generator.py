"""Seeded, deterministic workload generation.

A `WorkloadGenerator` turns a `ScenarioSpec` into a concrete arrival
schedule: a sorted list of `Arrival`s, each with a timestamp, tenant,
key, and the full rate-limit config the request carries. The same
(spec, seed) pair always yields the identical schedule — determinism is
a tested contract (tests/test_scenarios.py), because a verdict is only
comparable across commits if both commits judged the same traffic.

Arrivals are a Poisson process per segment (exponential inter-arrival
times at the segment's rate; ramping segments interpolate the rate
linearly across the segment, stepping the hazard as the clock moves).
Tenant choice is a cumulative-share draw; keys come from each tenant's
popularity model — Zipf via a precomputed CDF + bisect, uniform as the
exponent-zero special case of the same path.
"""

from __future__ import annotations

import bisect
import dataclasses
import itertools
import random
from typing import List, Optional

from gubernator_tpu.scenarios.spec import KeyModel, ScenarioSpec
from gubernator_tpu.types import RateLimitReq

# A schedule is generated fully in memory before the run paces it out;
# cap it so a mis-scaled spec fails loudly instead of swallowing RAM.
MAX_ARRIVALS = 2_000_000


@dataclasses.dataclass
class Arrival:
    """One generated request: when it arrives and what it carries."""

    t: float  # seconds from schedule start
    tenant: str
    key: str
    hits: int
    limit: int
    duration_ms: int
    algorithm: int
    behavior: int

    def to_request(self) -> RateLimitReq:
        return RateLimitReq(
            name=self.tenant, unique_key=self.key, hits=self.hits,
            limit=self.limit, duration=self.duration_ms,
            algorithm=self.algorithm, behavior=self.behavior)


class _KeySampler:
    """Popularity-model sampler: a precomputed CDF over ranks, walked
    with bisect. Uniform is the zipf-exponent-0 degenerate case."""

    def __init__(self, model: KeyModel):
        self._model = model
        expo = model.exponent if model.kind == "zipf" else 0.0
        weights = [1.0 / ((r + 1) ** expo) for r in range(model.n_keys)]
        total = sum(weights)
        acc = 0.0
        self._cdf: List[float] = []
        for w in weights:
            acc += w / total
            self._cdf.append(acc)
        self._cdf[-1] = 1.0  # float-sum slack never strands a draw

    def sample(self, rng: random.Random) -> str:
        rank = bisect.bisect_left(self._cdf, rng.random())
        return f"{self._model.prefix}{rank:05d}"


class WorkloadGenerator:
    """Deterministic arrival-schedule generation for one spec."""

    def __init__(self, spec: ScenarioSpec, seed: Optional[int] = None):
        spec.validate()
        self.spec = spec
        self.seed = spec.seed if seed is None else seed
        self._samplers = [_KeySampler(t.keys) for t in spec.tenants]
        total_share = sum(t.share for t in spec.tenants)
        acc = 0.0
        self._tenant_cdf: List[float] = []
        for t in spec.tenants:
            acc += t.share / total_share
            self._tenant_cdf.append(acc)
        self._tenant_cdf[-1] = 1.0

    def _pick_tenant(self, rng: random.Random) -> int:
        return bisect.bisect_left(self._tenant_cdf, rng.random())

    def schedule(self) -> List[Arrival]:
        """The full arrival schedule, sorted by time. Rate ramps step
        the exponential hazard at each draw (piecewise-exponential
        approximation of an inhomogeneous Poisson process — exact for
        flat segments, within a draw of exact for ramps)."""
        rng = random.Random(self.seed)
        out: List[Arrival] = []
        t0 = 0.0
        for seg in self.spec.segments:
            end = seg.end_rate_rps if seg.end_rate_rps is not None \
                else seg.rate_rps
            t = 0.0
            while t < seg.duration_s:
                frac = t / seg.duration_s
                rate = seg.rate_rps + (end - seg.rate_rps) * frac
                if rate <= 1e-9:
                    # a dead segment has no arrivals; skip to the next
                    # rate step a generator tick away
                    t += min(0.1, seg.duration_s - t) or seg.duration_s
                    continue
                t += rng.expovariate(rate)
                if t >= seg.duration_s:
                    break
                ti = self._pick_tenant(rng)
                tenant = self.spec.tenants[ti]
                out.append(Arrival(
                    t=t0 + t,
                    tenant=tenant.name,
                    key=self._samplers[ti].sample(rng),
                    hits=tenant.hits,
                    limit=tenant.limit,
                    duration_ms=tenant.duration_ms,
                    algorithm=tenant.algorithm,
                    behavior=tenant.behavior,
                ))
                if len(out) > MAX_ARRIVALS:
                    raise ValueError(
                        f"scenario {self.spec.name}: schedule exceeds "
                        f"{MAX_ARRIVALS} arrivals — scale it down")
            t0 += seg.duration_s
        return out

    def requests(self) -> List[RateLimitReq]:
        return [a.to_request() for a in self.schedule()]


def windowed(schedule: List[Arrival], window_s: float):
    """Group a schedule into consecutive (window_start_s, arrivals)
    batches — the unit the runner paces and submits together."""
    for start, group in itertools.groupby(
            schedule, key=lambda a: int(a.t / window_s)):
        yield start * window_s, list(group)
