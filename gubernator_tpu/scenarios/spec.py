"""Declarative scenario spec + the named-scenario registry.

A scenario is a complete, seeded description of a traffic shape and the
envelope it must be served within: an arrival-rate schedule (piecewise
segments, optionally ramping), per-tenant config mixes with their own
key-popularity models, fault/membership events on a timeline, and the
SLO envelope the verdict engine judges the run against. Everything is
plain data — the generator (generator.py) turns a spec into a
deterministic arrival schedule, the runner (runner.py) drives it
against a live cluster and renders the verdict.

The registry below is the operator-facing atlas: `SCENARIO_NAMES` is
the authoritative name tuple (guberlint `registry-drift` keeps it in
lock-step with the docs/observability.md "## Scenario atlas" table,
both directions, the same way flight-recorder kinds are pinned).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

# ---------------------------------------------------------------- data model


@dataclasses.dataclass
class KeyModel:
    """Key-popularity model for one tenant's traffic.

    kind "zipf": rank r drawn with weight 1/r^exponent over n_keys ranks
    (exponent ~0 degrades to uniform); kind "uniform" is the explicit
    uniform spelling. Keys render as f"{prefix}{rank:05d}" so rank 0 is
    always the hottest key — stable across runs and readable in the
    cartographer's top-K table.
    """

    kind: str = "zipf"
    n_keys: int = 1024
    exponent: float = 1.1
    prefix: str = "k"

    def validate(self) -> None:
        if self.kind not in ("zipf", "uniform"):
            raise ValueError(f"unknown key model kind {self.kind!r}")
        if self.n_keys < 1:
            raise ValueError("key model n_keys must be >= 1")
        if self.kind == "zipf" and self.exponent < 0:
            raise ValueError("zipf exponent cannot be negative")


@dataclasses.dataclass
class Tenant:
    """One tenant's slice of the mix: its share of arrivals and the
    rate-limit config its requests carry (the reference carries config in
    every request precisely so tenants differ — PAPER.md §0)."""

    name: str
    share: float = 1.0
    keys: KeyModel = dataclasses.field(default_factory=KeyModel)
    hits: int = 1
    limit: int = 1_000_000
    duration_ms: int = 3_600_000
    algorithm: int = 0  # TOKEN_BUCKET; 1 = LEAKY_BUCKET
    behavior: int = 0  # BATCHING; pipelines stay off unless a spec opts in

    def validate(self) -> None:
        if not self.name:
            raise ValueError("tenant name cannot be empty")
        if self.share <= 0:
            raise ValueError(f"tenant {self.name}: share must be positive")
        if self.hits < 1 or self.limit < 1 or self.duration_ms < 1:
            raise ValueError(
                f"tenant {self.name}: hits/limit/duration must be >= 1")
        self.keys.validate()


@dataclasses.dataclass
class Segment:
    """One piece of the arrival-rate schedule. rate_rps holds for
    duration_s; a non-None end_rate_rps ramps linearly across it."""

    duration_s: float
    rate_rps: float
    end_rate_rps: Optional[float] = None

    def validate(self) -> None:
        if self.duration_s <= 0:
            raise ValueError("segment duration_s must be positive")
        if self.rate_rps < 0 or (self.end_rate_rps or 0) < 0:
            raise ValueError("segment rates cannot be negative")


@dataclasses.dataclass
class TimelineEvent:
    """A fault/membership event fired when the (scaled) clock crosses
    at_s. Actions the runner knows: add_node, kill_node, restart_node,
    sync_peers, inject_fault (arg = a GUBER_FAULT_SPEC string),
    clear_faults. node is an index into the cluster's instance list."""

    at_s: float
    action: str
    node: int = 0
    arg: str = ""

    ACTIONS = ("add_node", "kill_node", "restart_node", "sync_peers",
               "inject_fault", "clear_faults")

    def validate(self) -> None:
        if self.action not in self.ACTIONS:
            raise ValueError(f"unknown timeline action {self.action!r}; "
                             f"choices are {list(self.ACTIONS)}")
        if self.at_s < 0:
            raise ValueError("event at_s cannot be negative")


@dataclasses.dataclass
class Envelope:
    """The SLO envelope a run must land inside to PASS. Latencies are
    client-observed per-batch decision latencies; goodput is decided
    responses (OK or OVER_LIMIT — an over-limit answer is the limiter
    WORKING) over offered requests. forbid_detectors are anomaly-engine
    detectors whose rising edge during the run fails the verdict;
    allow_detectors documents edges the scenario expects (a failover
    drill EXPECTS circuit_open) so the report can show them without
    failing. min_over_limit_share gives abuse scenarios teeth: a bot
    storm that never sees OVER_LIMIT means the limiter did not limit.
    max_over_admission arms the budget-conservation gate: a non-None
    bound makes the runner sweep every node's decision ledger after the
    run and fail the verdict when audited conservation violations
    (admits beyond limit + minted lease budget + declared degraded/
    reshard slack) exceed it — 0 is the 'never mint budget' spelling."""

    max_p99_ms: float = 250.0
    min_goodput: float = 0.999
    max_error_share: float = 0.0
    min_over_limit_share: float = 0.0
    max_over_admission: Optional[int] = None
    forbid_detectors: Tuple[str, ...] = ("slo_burn", "capacity")
    allow_detectors: Tuple[str, ...] = ()

    def validate(self) -> None:
        from gubernator_tpu.obs.anomaly import DETECTORS

        if self.max_p99_ms <= 0:
            raise ValueError("envelope max_p99_ms must be positive")
        if not 0.0 <= self.min_goodput <= 1.0:
            raise ValueError("envelope min_goodput must be in [0, 1]")
        if self.max_over_admission is not None \
                and self.max_over_admission < 0:
            raise ValueError(
                "envelope max_over_admission cannot be negative")
        for det in self.forbid_detectors + self.allow_detectors:
            if det not in DETECTORS:
                raise ValueError(f"envelope names unknown detector {det!r}")
        overlap = set(self.forbid_detectors) & set(self.allow_detectors)
        if overlap:
            raise ValueError(
                f"detectors both forbidden and allowed: {sorted(overlap)}")


@dataclasses.dataclass
class Profile:
    """How a named profile compresses the scenario: durations and event
    times multiply by time_scale, rates by rate_scale."""

    time_scale: float = 1.0
    rate_scale: float = 1.0


@dataclasses.dataclass
class ScenarioSpec:
    """The complete declarative scenario."""

    name: str
    description: str = ""
    seed: int = 1
    segments: List[Segment] = dataclasses.field(default_factory=list)
    tenants: List[Tenant] = dataclasses.field(default_factory=list)
    events: List[TimelineEvent] = dataclasses.field(default_factory=list)
    envelope: Envelope = dataclasses.field(default_factory=Envelope)
    nodes: int = 1  # cluster size the scenario wants (1 or 2 in-process)
    behaviors: Dict[str, object] = dataclasses.field(default_factory=dict)
    profiles: Dict[str, Profile] = dataclasses.field(default_factory=dict)

    def validate(self) -> None:
        if not self.name:
            raise ValueError("scenario name cannot be empty")
        if not self.segments:
            raise ValueError(f"scenario {self.name}: no rate segments")
        if not self.tenants:
            raise ValueError(f"scenario {self.name}: no tenants")
        if self.nodes < 1:
            raise ValueError(f"scenario {self.name}: nodes must be >= 1")
        for seg in self.segments:
            seg.validate()
        for t in self.tenants:
            t.validate()
        names = [t.name for t in self.tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"scenario {self.name}: duplicate tenant names")
        total = self.duration_s()
        for ev in self.events:
            ev.validate()
            if ev.at_s > total:
                raise ValueError(
                    f"scenario {self.name}: event {ev.action} at "
                    f"{ev.at_s}s lands past the {total}s schedule")
        self.envelope.validate()

    def duration_s(self) -> float:
        return sum(seg.duration_s for seg in self.segments)

    def scaled(self, time_scale: float = 1.0,
               rate_scale: float = 1.0) -> "ScenarioSpec":
        """A compressed copy: durations/event times x time_scale, rates
        x rate_scale. The envelope is untouched — per-batch latency and
        goodput targets do not change with compression."""
        out = dataclasses.replace(
            self,
            segments=[Segment(s.duration_s * time_scale,
                              s.rate_rps * rate_scale,
                              None if s.end_rate_rps is None
                              else s.end_rate_rps * rate_scale)
                      for s in self.segments],
            events=[dataclasses.replace(e, at_s=e.at_s * time_scale)
                    for e in self.events],
            tenants=[dataclasses.replace(
                t, keys=dataclasses.replace(t.keys)) for t in self.tenants],
            envelope=dataclasses.replace(self.envelope),
            behaviors=dict(self.behaviors),
            profiles=dict(self.profiles),
        )
        return out

    def for_profile(self, profile: str) -> "ScenarioSpec":
        p = self.profiles.get(profile, Profile())
        return self.scaled(p.time_scale, p.rate_scale)


# ------------------------------------------------------------- the atlas
#
# The authoritative name registry. guberlint `registry-drift` checks this
# tuple against the docs/observability.md "## Scenario atlas" table in
# both directions — a scenario without a doc row, or a doc row without a
# builder, is a lint finding.

SCENARIO_NAMES = (
    "diurnal-tide",
    "flash-crowd",
    "bot-storm",
    "multi-tenant-mix",
    "regional-failover",
    "rolling-restart",
)

# Autopilot overlays (service/autopilot.py): behavior fields layered on
# top of a scenario's own behaviors when the runner is asked to drive
# the shape with the autopilot armed. Compressed profiles need the
# control clocks compressed the same way the workload is — the "short"
# profile squeezes a minute-scale incident into ~2-4 s, so dwell and
# cooldown shrink with it or no controller could ever engage in-run.
AUTOPILOT_PROFILES: Dict[str, Dict[str, object]] = {
    "short": {"autopilot": True, "autopilot_interval_s": 0.05,
              "autopilot_dwell_s": 0.15, "autopilot_cooldown_s": 0.3,
              "autopilot_freeze_hold_s": 0.5},
    "medium": {"autopilot": True, "autopilot_interval_s": 0.25,
               "autopilot_dwell_s": 1.0, "autopilot_cooldown_s": 2.0,
               "autopilot_freeze_hold_s": 1.0},
    "full": {"autopilot": True, "autopilot_interval_s": 1.0,
             "autopilot_dwell_s": 5.0, "autopilot_cooldown_s": 10.0,
             "autopilot_freeze_hold_s": 5.0},
}


def _diurnal_tide() -> ScenarioSpec:
    # A compressed day: trough -> morning ramp -> plateau -> evening
    # peak -> ramp down. Shape-only stress: the envelope expects clean
    # serving end to end.
    return ScenarioSpec(
        name="diurnal-tide",
        description="24h sine compressed: trough, ramp, plateau, peak, "
                    "decay — the baseline 'normal day' shape",
        seed=11,
        segments=[
            Segment(20.0, 150.0),
            Segment(20.0, 150.0, 600.0),
            Segment(40.0, 600.0),
            Segment(20.0, 600.0, 900.0),
            Segment(20.0, 900.0, 150.0),
        ],
        tenants=[
            Tenant(name="api", share=0.8,
                   keys=KeyModel("zipf", n_keys=2048, exponent=0.9),
                   limit=1_000_000),
            Tenant(name="web", share=0.2,
                   keys=KeyModel("uniform", n_keys=512, prefix="w"),
                   limit=500_000),
        ],
        envelope=Envelope(max_p99_ms=200.0, min_goodput=0.999,
                          forbid_detectors=("slo_burn", "capacity",
                                            "deadline_burst", "shed_spike")),
        nodes=2,
        profiles={"short": Profile(time_scale=0.035, rate_scale=0.8),
                  "full": Profile()},
    )


def _flash_crowd() -> ScenarioSpec:
    # Steady state, then an 8x spike concentrated on a hot Zipf head,
    # then decay — the breaking-news shape the lease tier exists for.
    return ScenarioSpec(
        name="flash-crowd",
        description="8x arrival spike on a hot Zipf head over a steady "
                    "baseline, then decay",
        seed=23,
        segments=[
            Segment(30.0, 200.0),
            Segment(5.0, 200.0, 1600.0),
            Segment(25.0, 1600.0),
            Segment(20.0, 1600.0, 200.0),
        ],
        tenants=[
            Tenant(name="crowd", share=0.9,
                   keys=KeyModel("zipf", n_keys=512, exponent=1.3),
                   limit=2_000_000),
            Tenant(name="background", share=0.1,
                   keys=KeyModel("uniform", n_keys=1024, prefix="b"),
                   limit=1_000_000),
        ],
        envelope=Envelope(max_p99_ms=250.0, min_goodput=0.995,
                          forbid_detectors=("slo_burn", "capacity")),
        nodes=2,
        profiles={"short": Profile(time_scale=0.045, rate_scale=0.6),
                  "full": Profile()},
    )


def _bot_storm() -> ScenarioSpec:
    # An abusive tenant hammers a tiny key set with big hit counts
    # against a small limit: the limiter must answer OVER_LIMIT (that IS
    # goodput here — min_over_limit_share proves it actually limited)
    # while the well-behaved tenant stays clean.
    return ScenarioSpec(
        name="bot-storm",
        description="abusive tenant hammers a tiny hot set into a small "
                    "limit; the verdict demands OVER_LIMIT answers",
        seed=37,
        segments=[
            Segment(10.0, 300.0),
            Segment(40.0, 1200.0),
            Segment(10.0, 300.0),
        ],
        tenants=[
            Tenant(name="bots", share=0.7,
                   keys=KeyModel("zipf", n_keys=24, exponent=1.5,
                                 prefix="bot"),
                   hits=5, limit=500, duration_ms=3_600_000),
            Tenant(name="legit", share=0.3,
                   keys=KeyModel("zipf", n_keys=1024, exponent=0.9),
                   limit=1_000_000),
        ],
        envelope=Envelope(max_p99_ms=250.0, min_goodput=0.999,
                          min_over_limit_share=0.3,
                          max_over_admission=0,
                          forbid_detectors=("slo_burn", "capacity")),
        nodes=1,
        # leases armed: the hot bot keys are exactly the shape the lease
        # tier serves, and the conservation gate proves the slices it
        # mints never exceed the owner's declared budget
        behaviors={"hot_leases": True},
        profiles={"short": Profile(time_scale=0.05, rate_scale=0.7),
                  "full": Profile()},
    )


def _multi_tenant_mix() -> ScenarioSpec:
    # Four tenants with different algorithms, limits, durations, and
    # popularity models at once — the config-in-every-request property
    # the reference was built around, as one steady mixed stream.
    return ScenarioSpec(
        name="multi-tenant-mix",
        description="four tenants: token/leaky buckets, second-scale to "
                    "hour-scale windows, uniform to heavy-skew keys",
        seed=53,
        segments=[Segment(60.0, 800.0)],
        tenants=[
            Tenant(name="checkout", share=0.15,
                   keys=KeyModel("zipf", n_keys=256, exponent=1.1,
                                 prefix="c"),
                   limit=10_000, duration_ms=60_000, algorithm=0),
            Tenant(name="search", share=0.45,
                   keys=KeyModel("zipf", n_keys=4096, exponent=0.8,
                                 prefix="s"),
                   limit=1_000_000, duration_ms=3_600_000, algorithm=0),
            Tenant(name="stream", share=0.25,
                   keys=KeyModel("uniform", n_keys=512, prefix="v"),
                   hits=3, limit=100_000, duration_ms=600_000, algorithm=1),
            Tenant(name="admin", share=0.15,
                   keys=KeyModel("uniform", n_keys=64, prefix="a"),
                   limit=5_000, duration_ms=60_000, algorithm=1),
        ],
        envelope=Envelope(max_p99_ms=200.0, min_goodput=0.999,
                          forbid_detectors=("slo_burn", "capacity",
                                            "shed_spike")),
        nodes=2,
        profiles={"short": Profile(time_scale=0.05, rate_scale=0.6),
                  "full": Profile()},
    )


def _regional_failover() -> ScenarioSpec:
    # Kill the second node mid-run, serve through the survivor (circuit
    # opens, degraded-local absorbs the dead owner's keys), then revive
    # and rejoin. circuit_open is EXPECTED; the envelope tolerates the
    # pre-open error window but demands the fleet keep deciding.
    return ScenarioSpec(
        name="regional-failover",
        description="node killed under load, survivor degrades locally, "
                    "node revived and rejoined — availability over "
                    "strictness, bounded error window",
        seed=71,
        segments=[Segment(60.0, 500.0)],
        tenants=[
            Tenant(name="api", share=1.0,
                   keys=KeyModel("zipf", n_keys=1024, exponent=1.0),
                   limit=1_000_000),
        ],
        events=[
            TimelineEvent(at_s=20.0, action="kill_node", node=1),
            TimelineEvent(at_s=45.0, action="restart_node", node=1),
        ],
        envelope=Envelope(max_p99_ms=600.0, min_goodput=0.90,
                          max_error_share=0.10,
                          max_over_admission=0,
                          forbid_detectors=("slo_burn", "capacity"),
                          allow_detectors=("circuit_open", "shed_spike",
                                           "deadline_burst")),
        nodes=2,
        behaviors={"degraded_local": True, "circuit_threshold": 3,
                   "circuit_open_s": 0.4},
        profiles={"short": Profile(time_scale=0.06, rate_scale=0.5),
                  "full": Profile()},
    )


def _rolling_restart() -> ScenarioSpec:
    # The deploy shape: restart the non-driven node under load (stop,
    # boot a replacement on the same port, rejoin). Without GUBER_RESHARD
    # the restarted node's keys refill (documented amnesty) — the verdict
    # judges serving health, not counter continuity (that is
    # tests/test_reshard_drills.py's job).
    return ScenarioSpec(
        name="rolling-restart",
        description="restart a node under load: stop, boot a replacement "
                    "on the same port, rejoin — the deploy drill shape",
        seed=89,
        segments=[Segment(60.0, 400.0)],
        tenants=[
            Tenant(name="api", share=0.7,
                   keys=KeyModel("zipf", n_keys=1024, exponent=1.0),
                   limit=1_000_000),
            Tenant(name="batch", share=0.3,
                   keys=KeyModel("uniform", n_keys=256, prefix="j"),
                   limit=500_000),
        ],
        events=[
            TimelineEvent(at_s=25.0, action="restart_node", node=1),
        ],
        envelope=Envelope(max_p99_ms=600.0, min_goodput=0.95,
                          max_error_share=0.05,
                          max_over_admission=0,
                          forbid_detectors=("slo_burn", "capacity"),
                          allow_detectors=("circuit_open", "shed_spike",
                                           "deadline_burst")),
        nodes=2,
        behaviors={"degraded_local": True, "circuit_threshold": 3,
                   "circuit_open_s": 0.4},
        profiles={"short": Profile(time_scale=0.06, rate_scale=0.5),
                  "full": Profile()},
    )


_BUILDERS: Dict[str, Callable[[], ScenarioSpec]] = {
    "diurnal-tide": _diurnal_tide,
    "flash-crowd": _flash_crowd,
    "bot-storm": _bot_storm,
    "multi-tenant-mix": _multi_tenant_mix,
    "regional-failover": _regional_failover,
    "rolling-restart": _rolling_restart,
}

assert set(_BUILDERS) == set(SCENARIO_NAMES), (
    "SCENARIO_NAMES and the builder table drifted apart")


def scenario_names() -> Tuple[str, ...]:
    return SCENARIO_NAMES


def get_scenario(name: str) -> ScenarioSpec:
    """A fresh, validated spec for a named scenario."""
    try:
        builder = _BUILDERS[name]
    except KeyError:
        raise KeyError(f"unknown scenario {name!r}; the atlas has "
                       f"{list(SCENARIO_NAMES)}") from None
    spec = builder()
    spec.validate()
    return spec
