"""Captured-trace -> replayable ScenarioSpec.

The daemon-side capture (obs/capture.py) reduces what the obs plane
saw to a `derived` section: piecewise decision-rate segments and a
fitted key-popularity model. This module lifts that into a full
`ScenarioSpec`, so a production shape replays through exactly the same
generator/runner/verdict machinery as the hand-written atlas.

Fidelity contract (pinned by tests/test_scenarios.py):

- mean offered rate of the replayed schedule lands within ~25% of the
  captured mean (Poisson draw noise + segment quantization), and
- the replayed key skew reproduces the captured Zipf exponent within
  ~0.4 when re-fitted by the same cartographer estimator (rank-head
  slope fits are noisy at small key counts — the tolerance is the
  estimator's, not the generator's).

A replay is a *shape* reconstruction, not a log replay: per-request
identity (exact keys, exact timestamps) is deliberately discarded —
the obs plane stores curves, not requests, which is what keeps capture
inside the 2% observability budget.
"""

from __future__ import annotations

from typing import Optional

from gubernator_tpu.scenarios.spec import (
    Envelope,
    KeyModel,
    Profile,
    ScenarioSpec,
    Segment,
    Tenant,
)

# Replay compresses micro-segments below this span into their
# neighbors: ring ticks are ~5s in production but can be subsecond in
# tests, and a schedule of hundred-millisecond segments paces poorly.
MIN_REPLAY_SEGMENT_S = 0.5


def _coalesce(segments, min_span_s: float):
    """Merge adjacent derived segments until each spans at least
    min_span_s, rate-averaging by duration — the replayed schedule
    keeps the curve's area (total offered requests) exact."""
    out = []
    acc_s, acc_req = 0.0, 0.0
    for seg in segments:
        acc_s += float(seg["duration_s"])
        acc_req += float(seg["rate_rps"]) * float(seg["duration_s"])
        if acc_s >= min_span_s:
            out.append(Segment(acc_s, acc_req / acc_s))
            acc_s, acc_req = 0.0, 0.0
    if acc_s > 0 and acc_req > 0:
        out.append(Segment(acc_s, acc_req / acc_s))
    return out


def trace_to_spec(trace: dict, name: str = "replay",
                  seed: int = 1, nodes: int = 1,
                  envelope: Optional[Envelope] = None,
                  min_segment_s: float = MIN_REPLAY_SEGMENT_S,
                  ) -> ScenarioSpec:
    """Build a replayable spec from a capture-endpoint trace."""
    derived = trace.get("derived") or {}
    segments = _coalesce(derived.get("segments") or [], min_segment_s)
    if not segments:
        mean = float(derived.get("mean_rate_rps") or 0.0)
        if mean <= 0:
            raise ValueError(
                "trace has no live rate segments to replay — capture a "
                "window where the daemon actually served traffic")
        segments = [Segment(10.0, mean)]

    km = derived.get("key_model") or {}
    key_model = KeyModel(
        kind=km.get("kind", "zipf"),
        n_keys=max(1, int(km.get("n_keys", 1024))),
        exponent=float(km.get("exponent", 1.1)),
        prefix="r",
    )

    over_share = float(derived.get("over_limit_share") or 0.0)
    spec = ScenarioSpec(
        name=name,
        description=f"replay of {trace.get('node') or 'captured daemon'} "
                    f"at {trace.get('captured_at', 0):.0f}",
        seed=seed,
        segments=segments,
        tenants=[Tenant(name="replay", share=1.0, keys=key_model)],
        envelope=envelope or Envelope(
            # replay inherits the atlas default envelope, but an
            # observed over-limit share means the captured tenant mix
            # was being limited — don't fail the replay for matching it
            min_over_limit_share=0.0,
            max_error_share=0.0,
        ),
        nodes=max(1, int(nodes)),
        profiles={"short": Profile(), "full": Profile()},
    )
    spec.validate()
    return spec
