"""Scenario atlas: seeded workload generation, traffic capture/replay,
and the SLO verdict engine (ROADMAP item 4 — the observability stack
becomes the pass/fail judge for million-user traffic shapes).

- spec.py       — the declarative scenario spec + the named registry
- generator.py  — seeded, deterministic arrival-schedule generation
- replay.py     — captured-trace -> replayable ScenarioSpec
- runner.py     — drive a live cluster, judge with the anomaly engine

The daemon-side capture endpoint lives in obs/capture.py (it reads the
flight recorder, history ring, and keyspace cartography — all obs
surfaces); this package is the client side that replays what capture
recorded.
"""

from gubernator_tpu.scenarios.generator import WorkloadGenerator
from gubernator_tpu.scenarios.replay import trace_to_spec
from gubernator_tpu.scenarios.runner import run_atlas, run_scenario
from gubernator_tpu.scenarios.spec import (
    SCENARIO_NAMES,
    ScenarioSpec,
    get_scenario,
    scenario_names,
)

__all__ = [
    "SCENARIO_NAMES",
    "ScenarioSpec",
    "WorkloadGenerator",
    "get_scenario",
    "run_atlas",
    "run_scenario",
    "scenario_names",
    "trace_to_spec",
]
