"""Public wire-level types.

Mirrors the reference proto contract (reference: proto/gubernator.proto:56-220,
proto/peers.proto:28-57) so a gubernator client can talk to this service
unchanged. Field numbers and enum values are part of the wire contract and
must match; everything else here is our own.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional


class Algorithm(enum.IntEnum):
    """Bucket algorithm selector (reference: proto/gubernator.proto:56-62)."""

    TOKEN_BUCKET = 0
    LEAKY_BUCKET = 1


class Behavior(enum.IntFlag):
    """Per-request behavior bitflags (reference: proto/gubernator.proto:65-131).

    These ride on every request — the service itself is stateless with
    respect to rate-limit configuration.
    """

    BATCHING = 0  # default; no-op flag
    NO_BATCHING = 1
    GLOBAL = 2
    DURATION_IS_GREGORIAN = 4
    RESET_REMAINING = 8
    MULTI_REGION = 16


# Behaviors the native fast paths (columnar prep, lone-request decide_one)
# must hand to the request-object pipeline: gregorian needs host calendar
# math; GLOBAL / MULTI_REGION peel off to the host managers before the
# backend sees them. ONE definition — the engine gate, the columnar prep
# mask, and the peerlink IO-thread mask must never drift apart.
SLOW_PATH_BEHAVIOR_MASK = (int(Behavior.DURATION_IS_GREGORIAN)
                           | int(Behavior.GLOBAL)
                           | int(Behavior.MULTI_REGION))


class Status(enum.IntEnum):
    """Rate limit decision (reference: proto/gubernator.proto:161-164)."""

    UNDER_LIMIT = 0
    OVER_LIMIT = 1


def has_behavior(behavior: int, flag: Behavior) -> bool:
    """True if `flag` is set (reference: gubernator.go:456-461).

    int() both sides first: `int & IntFlag` dispatches through enum's
    reflected __rand__, which costs ~µs per call — real money at 4096
    requests per window."""
    return (int(behavior) & int(flag)) != 0


def set_behavior(behavior: int, flag: Behavior, on: bool) -> int:
    """Return `behavior` with `flag` set or cleared (reference: gubernator.go:463-468)."""
    return (behavior | flag) if on else (behavior & ~flag)


def without_behavior(req: "RateLimitReq", *flags: Behavior) -> "RateLimitReq":
    """A copy of `req` with the given behavior flags cleared — the shared
    idiom for handing a request down a tier that must not re-trigger
    owner-side pipelines (GLOBAL broadcast, MULTI_REGION replication)."""
    b = int(req.behavior)
    for f in flags:
        b = set_behavior(b, f, False)
    return dataclasses.replace(req, behavior=b)


def hash_key(name: str, unique_key: str) -> str:
    """The canonical rate-limit key: ``name + "_" + unique_key``
    (reference: client.go:33-35)."""
    return name + "_" + unique_key


@dataclasses.dataclass(slots=True)
class RateLimitReq:
    """One rate-limit request (reference: proto/gubernator.proto:134-159)."""

    name: str = ""
    unique_key: str = ""
    hits: int = 0
    limit: int = 0
    duration: int = 0  # milliseconds, or a Gregorian interval code when
    # Behavior.DURATION_IS_GREGORIAN is set
    algorithm: int = Algorithm.TOKEN_BUCKET
    behavior: int = 0

    def hash_key(self) -> str:
        return hash_key(self.name, self.unique_key)


@dataclasses.dataclass(slots=True)
class RateLimitResp:
    """One rate-limit decision (reference: proto/gubernator.proto:166-180)."""

    status: int = Status.UNDER_LIMIT
    limit: int = 0
    remaining: int = 0
    reset_time: int = 0  # unix ms when the limit span resets
    error: str = ""
    metadata: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class HealthCheckResp:
    """Service health (reference: proto/gubernator.proto:183-189)."""

    status: str = "healthy"  # 'healthy' | 'unhealthy'
    message: str = ""
    peer_count: int = 0


@dataclasses.dataclass
class PeerInfo:
    """One cluster member (reference: etcd.go:30-40)."""

    address: str = ""
    datacenter: str = ""
    is_owner: bool = False  # True only for the local instance's own entry


@dataclasses.dataclass
class UpdatePeerGlobal:
    """Owner-broadcast global rate-limit status (reference: proto/peers.proto:49-53)."""

    key: str = ""
    status: Optional[RateLimitResp] = None
    algorithm: int = Algorithm.TOKEN_BUCKET


# Batch caps (reference: gubernator.go:34, config.go:86-88).
MAX_BATCH_SIZE = 1000


ERR_EMPTY_UNIQUE_KEY = "field 'unique_key' cannot be empty"
ERR_EMPTY_NAME = "field 'namespace' cannot be empty"


def validate_request(req: RateLimitReq) -> str:
    """Return an error string for an invalid request, else "".

    (reference: gubernator.go:137-147 — empty unique_key / name are
    per-request errors, not call failures. models/prep.py inlines these
    checks in its hot loop — shared constants keep the strings in sync.)
    """
    if not req.unique_key:
        return ERR_EMPTY_UNIQUE_KEY
    if not req.name:
        return ERR_EMPTY_NAME
    return ""


def batch_error(n: int) -> Optional[str]:
    """Whole-call error when a batch exceeds the cap (reference: gubernator.go:113-116)."""
    if n > MAX_BATCH_SIZE:
        return f"Requests.RateLimits list too large; max size is '{MAX_BATCH_SIZE}'"
    return None


GetRateLimitsReq = List[RateLimitReq]
GetRateLimitsResp = List[RateLimitResp]
