"""etcd v3 discovery pool — a real implementation, no client library.

Speaks the etcd v3 gRPC API directly through generated stubs from a minimal
wire-compatible proto subset (proto/etcd.proto); works against a real etcd
server or the in-process fake in tests.

Behavior mirrors the reference pool (reference: etcd.go:49-329):

- register: grant a 30 s lease, put `base_key + address -> address` bound to
  the lease, and hold a keep-alive stream open (etcd.go:224-253);
- if the keep-alive stream is lost, re-register after a back-off
  (etcd.go:256-282);
- watch the prefix from the revision of the initial listing; PUT adds the
  peer, DELETE removes it (by prev_kv value), each event fires `on_update`
  (etcd.go:163-222);
- a failed watch is restarted after re-listing peers (etcd.go:198-219);
- close: delete our key and revoke the lease (etcd.go:283-301).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Iterator, List, Optional, Sequence

import grpc

from gubernator_tpu.service.pb import etcd_pb2 as epb
from gubernator_tpu.types import PeerInfo

log = logging.getLogger("gubernator_tpu.etcd")

UpdateFunc = Callable[[List[PeerInfo]], None]

ETCD_TIMEOUT_S = 10.0  # (reference: etcd.go:50)
BACKOFF_S = 5.0  # (reference: etcd.go:51)
LEASE_TTL_S = 30  # (reference: etcd.go:52)
DEFAULT_BASE_KEY = "/gubernator/peers/"  # (reference: etcd.go:53)


def prefix_range_end(prefix: bytes) -> bytes:
    """End of the range covering all keys with `prefix` (etcd clientv3
    GetPrefixRangeEnd semantics): last byte +1, carrying over 0xff."""
    b = bytearray(prefix)
    for i in reversed(range(len(b))):
        if b[i] < 0xFF:
            b[i] += 1
            return bytes(b[: i + 1])
    return b"\x00"  # all-0xff prefix: range to the end of keyspace


def _serialize(msg) -> bytes:
    return msg.SerializeToString()


class EtcdClient:
    """Thin generic-stub client for the KV/Lease/Watch services."""

    def __init__(self, channel: grpc.Channel):
        self.channel = channel
        self.range = channel.unary_unary(
            "/etcdserverpb.KV/Range",
            request_serializer=_serialize,
            response_deserializer=epb.RangeResponse.FromString,
        )
        self.put = channel.unary_unary(
            "/etcdserverpb.KV/Put",
            request_serializer=_serialize,
            response_deserializer=epb.PutResponse.FromString,
        )
        self.delete_range = channel.unary_unary(
            "/etcdserverpb.KV/DeleteRange",
            request_serializer=_serialize,
            response_deserializer=epb.DeleteRangeResponse.FromString,
        )
        self.lease_grant = channel.unary_unary(
            "/etcdserverpb.Lease/LeaseGrant",
            request_serializer=_serialize,
            response_deserializer=epb.LeaseGrantResponse.FromString,
        )
        self.lease_revoke = channel.unary_unary(
            "/etcdserverpb.Lease/LeaseRevoke",
            request_serializer=_serialize,
            response_deserializer=epb.LeaseRevokeResponse.FromString,
        )
        self.lease_keep_alive = channel.stream_stream(
            "/etcdserverpb.Lease/LeaseKeepAlive",
            request_serializer=_serialize,
            response_deserializer=epb.LeaseKeepAliveResponse.FromString,
        )
        self.watch = channel.stream_stream(
            "/etcdserverpb.Watch/Watch",
            request_serializer=_serialize,
            response_deserializer=epb.WatchResponse.FromString,
        )


class _StreamFeed:
    """Blocking request iterator for a bidi stream, closable from outside."""

    _CLOSE = object()

    def __init__(self):
        import queue

        self._q: "queue.Queue" = queue.Queue()

    def send(self, msg) -> None:
        self._q.put(msg)

    def close(self) -> None:
        self._q.put(self._CLOSE)

    def __iter__(self) -> Iterator:
        while True:
            item = self._q.get()
            if item is self._CLOSE:
                return
            yield item


class EtcdPool:
    """Register self + watch peers in etcd (reference: etcd.go EtcdPool)."""

    def __init__(
        self,
        endpoints: Sequence[str],
        advertise_address: str,
        on_update: UpdateFunc,
        base_key: str = DEFAULT_BASE_KEY,
        lease_ttl_s: int = LEASE_TTL_S,
        backoff_s: float = BACKOFF_S,
        timeout_s: float = ETCD_TIMEOUT_S,
        channel: Optional[grpc.Channel] = None,
        credentials: Optional[grpc.ChannelCredentials] = None,
    ):
        if not advertise_address:
            raise ValueError(
                "advertise address is required (GUBER_ADVERTISE_ADDRESS)"
            )
        if channel is None and not endpoints:
            raise ValueError("GUBER_ETCD_ENDPOINTS is required")
        self._endpoints = list(endpoints)
        self._endpoint_idx = 0
        self._credentials = credentials
        if channel is None:
            channel = self._dial(self._endpoints[0])
        self._own_channel = channel
        self.client = EtcdClient(channel)
        self.advertise_address = advertise_address
        self.base_key = base_key
        self.instance_key = (base_key + advertise_address).encode()
        self.on_update = on_update
        self.lease_ttl_s = lease_ttl_s
        self.backoff_s = backoff_s
        self.timeout_s = timeout_s

        self._peers: Dict[str, None] = {}
        self._peers_lock = threading.Lock()
        self._conn_lock = threading.Lock()
        self._closed = threading.Event()
        self._lease_id = 0
        self._ka_feed: Optional[_StreamFeed] = None
        self._ka_call = None
        self._watch_feed: Optional[_StreamFeed] = None
        self._watch_call = None

        # initial registration + listing are synchronous and fail loudly,
        # like the reference's NewEtcdPool (etcd.go:96-110) — after trying
        # every configured endpoint once
        for attempt in range(max(len(self._endpoints), 1)):
            try:
                self._register()
                break
            except grpc.RpcError:
                if attempt + 1 >= max(len(self._endpoints), 1):
                    raise
                self._rotate_endpoint()
        revision = self._collect_peers()
        self._watch_thread = threading.Thread(
            target=self._watch_loop, args=(revision,), name="etcd-watch",
            daemon=True,
        )
        self._ka_thread = threading.Thread(
            target=self._keepalive_loop, name="etcd-keepalive", daemon=True
        )
        self._watch_thread.start()
        self._ka_thread.start()

    def _dial(self, target: str) -> grpc.Channel:
        return (
            grpc.secure_channel(target, self._credentials)
            if self._credentials is not None
            else grpc.insecure_channel(target)
        )

    def _rotate_endpoint(self) -> None:
        """Fail over to the next configured endpoint (clientv3 balances
        across all endpoints; we fail over sequentially). Closing the old
        channel fails the other loop's in-flight stream, which then recovers
        through its own restart path on the fresh channel."""
        if len(self._endpoints) < 2:
            return
        with self._conn_lock:
            self._endpoint_idx = (self._endpoint_idx + 1) % len(self._endpoints)
            target = self._endpoints[self._endpoint_idx]
            log.info("failing over to etcd endpoint %s", target)
            old = self._own_channel
            self._own_channel = self._dial(target)
            self.client = EtcdClient(self._own_channel)
            try:
                old.close()
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------- register

    def _register(self) -> None:
        """Grant lease, put our key, open the keep-alive stream
        (reference: etcd.go:229-253)."""
        grant = self.client.lease_grant(
            epb.LeaseGrantRequest(TTL=self.lease_ttl_s), timeout=self.timeout_s
        )
        self._lease_id = grant.ID
        self.client.put(
            epb.PutRequest(
                key=self.instance_key,
                value=self.advertise_address.encode(),
                lease=grant.ID,
            ),
            timeout=self.timeout_s,
        )
        feed = _StreamFeed()
        call = self.client.lease_keep_alive(iter(feed))
        feed.send(epb.LeaseKeepAliveRequest(ID=grant.ID))
        self._ka_feed = feed
        self._ka_call = call
        log.info("registered peer '%s' with etcd", self.advertise_address)

    def _keepalive_loop(self) -> None:
        """Send a keep-alive every ttl/3; re-register if the stream dies
        (reference: etcd.go:256-282)."""
        interval = max(self.lease_ttl_s / 3.0, 0.05)
        while not self._closed.is_set():
            call, feed = self._ka_call, self._ka_feed
            try:
                for resp in call:
                    if self._closed.is_set():
                        return
                    if resp.TTL <= 0:
                        raise RuntimeError("lease expired")
                    if self._closed.wait(interval):
                        return
                    feed.send(epb.LeaseKeepAliveRequest(ID=self._lease_id))
                # server closed the stream
                raise RuntimeError("keep alive stream closed")
            except BaseException as e:  # noqa: BLE001 — includes RpcError
                if self._closed.is_set():
                    return
                log.warning(
                    "keep alive lost (%s), attempting to re-register peer", e
                )
                while not self._closed.is_set():
                    try:
                        self._register()
                        break
                    except BaseException as re:  # noqa: BLE001
                        log.error("while attempting to re-register peer: %s", re)
                        if self._closed.wait(self.backoff_s):
                            return
                        self._rotate_endpoint()

    # ---------------------------------------------------------------- watch

    def _collect_peers(self) -> int:
        """List the prefix, replacing our peer set; returns the store
        revision for the subsequent watch (reference: etcd.go:145-161)."""
        resp = self.client.range(
            epb.RangeRequest(
                key=self.base_key.encode(),
                range_end=prefix_range_end(self.base_key.encode()),
            ),
            timeout=self.timeout_s,
        )
        with self._peers_lock:
            self._peers = {kv.value.decode(): None for kv in resp.kvs}
        self._call_on_update()
        return resp.header.revision

    def _open_watch(self, revision: int):
        feed = _StreamFeed()
        call = self.client.watch(iter(feed))
        feed.send(
            epb.WatchRequest(
                create_request=epb.WatchCreateRequest(
                    key=self.base_key.encode(),
                    range_end=prefix_range_end(self.base_key.encode()),
                    start_revision=revision + 1,
                    prev_kv=True,
                )
            )
        )
        self._watch_feed = feed
        self._watch_call = call
        log.info(
            "watching for peer changes '%s' at revision %d",
            self.base_key, revision,
        )
        return call

    def _watch_loop(self, revision: int) -> None:
        """Apply watch events; restart the watch (after re-listing) on any
        error (reference: etcd.go:163-222)."""
        call = self._open_watch(revision)
        while not self._closed.is_set():
            try:
                for resp in call:
                    if resp.canceled:
                        if self._closed.is_set():
                            log.info("graceful watch shutdown")
                            return
                        # server-side cancel (e.g. requested revision was
                        # compacted away): re-list and re-watch — the
                        # reference wrongly treats every cancel as graceful
                        # shutdown and freezes membership (etcd.go:171-174)
                        raise RuntimeError(
                            f"watch canceled by server "
                            f"(compact_revision={resp.compact_revision}, "
                            f"reason={resp.cancel_reason!r})"
                        )
                    changed = False
                    with self._peers_lock:
                        for ev in resp.events:
                            if ev.type == epb.Event.PUT and ev.kv.value:
                                self._peers[ev.kv.value.decode()] = None
                                changed = True
                            elif ev.type == epb.Event.DELETE and ev.prev_kv.value:
                                self._peers.pop(ev.prev_kv.value.decode(), None)
                                changed = True
                    if changed:
                        self._call_on_update()
                # stream ended without cancel
                raise RuntimeError("watch stream closed")
            except BaseException as e:  # noqa: BLE001
                if self._closed.is_set():
                    return
                log.error("watch error: %s; restarting watch", e)
                while not self._closed.is_set():
                    try:
                        revision = self._collect_peers()
                        call = self._open_watch(revision)
                        break
                    except BaseException as re:  # noqa: BLE001
                        log.error("while attempting to restart watch: %s", re)
                        if self._closed.wait(self.backoff_s):
                            return
                        self._rotate_endpoint()

    def _call_on_update(self) -> None:
        """(reference: etcd.go:321-329)"""
        peers = [PeerInfo(address=a) for a in sorted(self._peers)]
        try:
            self.on_update(peers)
        except Exception:  # noqa: BLE001
            log.exception("peer update callback failed")

    # ---------------------------------------------------------------- close

    def close(self) -> None:
        """Deregister: delete our key, revoke the lease
        (reference: etcd.go:283-301)."""
        if self._closed.is_set():
            return
        self._closed.set()
        for call in (self._watch_call, self._ka_call):
            if call is not None:
                try:
                    call.cancel()
                except Exception:  # noqa: BLE001
                    pass
        for feed in (self._watch_feed, self._ka_feed):
            if feed is not None:
                feed.close()
        try:
            self.client.delete_range(
                epb.DeleteRangeRequest(key=self.instance_key),
                timeout=self.timeout_s,
            )
            if self._lease_id:
                self.client.lease_revoke(
                    epb.LeaseRevokeRequest(ID=self._lease_id),
                    timeout=self.timeout_s,
                )
        except grpc.RpcError as e:
            log.warning("during etcd deregister: %s", e)
        self._watch_thread.join(timeout=2.0)
        self._ka_thread.join(timeout=2.0)
