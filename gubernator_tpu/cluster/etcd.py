"""etcd v3 discovery pool — a real implementation, no client library.

Speaks the etcd v3 gRPC API directly through generated stubs from a minimal
wire-compatible proto subset (proto/etcd.proto); works against a real etcd
server or the in-process fake in tests.

Behavior mirrors the reference pool (reference: etcd.go:49-329):

- register: grant a 30 s lease, put `base_key + address -> address` bound to
  the lease, and hold a keep-alive stream open (etcd.go:224-253);
- if the keep-alive stream is lost, re-register after a back-off
  (etcd.go:256-282);
- watch the prefix from the revision of the initial listing; PUT adds the
  peer, DELETE removes it (by prev_kv value), each event fires `on_update`
  (etcd.go:163-222);
- a failed watch is restarted after re-listing peers (etcd.go:198-219);
- close: delete our key and revoke the lease (etcd.go:283-301).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Iterator, List, Optional, Sequence

import grpc

from gubernator_tpu.obs import witness
from gubernator_tpu.service.pb import etcd_pb2 as epb
from gubernator_tpu.types import PeerInfo

log = logging.getLogger("gubernator_tpu.etcd")

UpdateFunc = Callable[[List[PeerInfo]], None]

ETCD_TIMEOUT_S = 10.0  # (reference: etcd.go:50)
BACKOFF_S = 5.0  # (reference: etcd.go:51)
LEASE_TTL_S = 30  # (reference: etcd.go:52)
DEFAULT_BASE_KEY = "/gubernator/peers/"  # (reference: etcd.go:53)


def prefix_range_end(prefix: bytes) -> bytes:
    """End of the range covering all keys with `prefix` (etcd clientv3
    GetPrefixRangeEnd semantics): last byte +1, carrying over 0xff."""
    b = bytearray(prefix)
    for i in reversed(range(len(b))):
        if b[i] < 0xFF:
            b[i] += 1
            return bytes(b[: i + 1])
    return b"\x00"  # all-0xff prefix: range to the end of keyspace


def _serialize(msg) -> bytes:
    return msg.SerializeToString()


def build_tls_credentials(
    ca_file: str = "",
    cert_file: str = "",
    key_file: str = "",
    skip_verify: bool = False,
    endpoint: str = "",
):
    """Channel credentials + options for TLS to etcd, mirroring the
    reference's GUBER_ETCD_TLS_* assembly (reference: config.go:216-259).

    Returns (grpc.ChannelCredentials, [channel options]). gRPC cannot
    disable certificate-chain validation, so GUBER_ETCD_TLS_SKIP_VERIFY is
    implemented as trust-on-first-use: the server's presented certificate
    is fetched over a raw TLS handshake and pinned as the root CA, with the
    target name overridden to the certificate's subject CN (deviation noted
    in PARITY.md — same "don't verify against a configured CA" intent,
    strictly stronger than the reference's InsecureSkipVerify because the
    pinned certificate can't be swapped mid-session).
    """
    import ssl

    def _read(path):
        if not path:
            return None
        with open(path, "rb") as f:
            return f.read()

    root = _read(ca_file)
    options = []
    if skip_verify and endpoint:
        pem = ssl.get_server_certificate(host_port(endpoint))
        root = pem.encode()
        cn = _cert_common_name(pem)
        if cn:
            options.append(("grpc.ssl_target_name_override", cn))
    creds = grpc.ssl_channel_credentials(
        root_certificates=root,
        private_key=_read(key_file),
        certificate_chain=_read(cert_file),
    )
    return creds, options


def host_port(endpoint: str, default_port: int = 2379):
    """Split host:port, defaulting the port like etcd clients do."""
    if ":" in endpoint:
        host, _, port = endpoint.rpartition(":")
        return host, int(port)
    return endpoint, default_port


def _cert_common_name(pem: str) -> Optional[str]:
    """Subject CN of a PEM certificate, via the stdlib's decoder (no
    third-party x509 parser in the image); None when undecodable."""
    import ssl
    import tempfile

    try:
        with tempfile.NamedTemporaryFile("w", suffix=".pem") as f:
            f.write(pem)
            f.flush()
            info = ssl._ssl._test_decode_cert(f.name)  # noqa: SLF001
        for rdn in info.get("subject", ()):
            for k, v in rdn:
                if k == "commonName":
                    return v
    except Exception:  # noqa: BLE001
        log.warning("could not decode server certificate CN", exc_info=True)
    return None


class EtcdClient:
    """Thin generic-stub client for the KV/Lease/Watch services."""

    def __init__(self, channel: grpc.Channel):
        self.channel = channel
        self.range = channel.unary_unary(
            "/etcdserverpb.KV/Range",
            request_serializer=_serialize,
            response_deserializer=epb.RangeResponse.FromString,
        )
        self.put = channel.unary_unary(
            "/etcdserverpb.KV/Put",
            request_serializer=_serialize,
            response_deserializer=epb.PutResponse.FromString,
        )
        self.delete_range = channel.unary_unary(
            "/etcdserverpb.KV/DeleteRange",
            request_serializer=_serialize,
            response_deserializer=epb.DeleteRangeResponse.FromString,
        )
        self.lease_grant = channel.unary_unary(
            "/etcdserverpb.Lease/LeaseGrant",
            request_serializer=_serialize,
            response_deserializer=epb.LeaseGrantResponse.FromString,
        )
        self.lease_revoke = channel.unary_unary(
            "/etcdserverpb.Lease/LeaseRevoke",
            request_serializer=_serialize,
            response_deserializer=epb.LeaseRevokeResponse.FromString,
        )
        self.lease_keep_alive = channel.stream_stream(
            "/etcdserverpb.Lease/LeaseKeepAlive",
            request_serializer=_serialize,
            response_deserializer=epb.LeaseKeepAliveResponse.FromString,
        )
        self.authenticate = channel.unary_unary(
            "/etcdserverpb.Auth/Authenticate",
            request_serializer=_serialize,
            response_deserializer=epb.AuthenticateResponse.FromString,
        )
        self.watch = channel.stream_stream(
            "/etcdserverpb.Watch/Watch",
            request_serializer=_serialize,
            response_deserializer=epb.WatchResponse.FromString,
        )


class _StreamFeed:
    """Blocking request iterator for a bidi stream, closable from outside."""

    _CLOSE = object()

    def __init__(self):
        import queue

        self._q: "queue.Queue" = queue.Queue()

    def send(self, msg) -> None:
        self._q.put(msg)

    def close(self) -> None:
        self._q.put(self._CLOSE)

    def __iter__(self) -> Iterator:
        while True:
            item = self._q.get()
            if item is self._CLOSE:
                return
            yield item


class EtcdPool:
    """Register self + watch peers in etcd (reference: etcd.go EtcdPool)."""

    def __init__(
        self,
        endpoints: Sequence[str],
        advertise_address: str,
        on_update: UpdateFunc,
        base_key: str = DEFAULT_BASE_KEY,
        lease_ttl_s: int = LEASE_TTL_S,
        backoff_s: float = BACKOFF_S,
        timeout_s: float = ETCD_TIMEOUT_S,
        dial_timeout_s: Optional[float] = None,
        channel: Optional[grpc.Channel] = None,
        credentials: Optional[grpc.ChannelCredentials] = None,
        channel_options: Sequence = (),
        credentials_factory: Optional[Callable] = None,
        username: str = "",
        password: str = "",
    ):
        if not advertise_address:
            raise ValueError(
                "advertise address is required (GUBER_ADVERTISE_ADDRESS)"
            )
        if channel is None and not endpoints:
            raise ValueError("GUBER_ETCD_ENDPOINTS is required")
        self._endpoints = list(endpoints)
        self._endpoint_idx = 0
        self._credentials = credentials
        self._channel_options = list(channel_options)
        # per-target credentials (skip-verify pinning must fetch each
        # endpoint's own certificate, not reuse endpoints[0]'s)
        self._credentials_factory = credentials_factory
        # etcd user/password auth (reference: GUBER_ETCD_USER/PASSWORD fed
        # to clientv3, cmd/gubernator/config.go:122-123): Authenticate
        # issues a token carried as "token" metadata; a token invalidated
        # server-side is re-acquired lazily (_meta)
        self._username = username
        self._password = password
        self._auth_token: Optional[str] = None
        if channel is None:
            # GUBER_ETCD_DIAL_TIMEOUT analogue (reference: config.go:121,
            # clientv3 DialTimeout spans all endpoints): try each endpoint
            # until one dials (and, when a timeout is set, becomes ready)
            last_err: Optional[BaseException] = None
            for _ in range(max(len(self._endpoints), 1)):
                target = self._endpoints[self._endpoint_idx]
                try:
                    channel = self._dial(target)
                    if dial_timeout_s:
                        grpc.channel_ready_future(channel).result(
                            timeout=dial_timeout_s)
                    break
                except BaseException as e:  # noqa: BLE001 — incl. TOFU I/O
                    log.warning("etcd endpoint %s unreachable: %s", target, e)
                    last_err = e
                    if channel is not None:
                        channel.close()
                        channel = None
                    self._endpoint_idx = (
                        (self._endpoint_idx + 1) % len(self._endpoints))
            if channel is None:
                raise last_err  # every endpoint failed
        self._own_channel = channel
        self.client = EtcdClient(channel)
        self.advertise_address = advertise_address
        self.base_key = base_key
        self.instance_key = (base_key + advertise_address).encode()
        self.on_update = on_update
        self.lease_ttl_s = lease_ttl_s
        self.backoff_s = backoff_s
        self.timeout_s = timeout_s

        self._peers: Dict[str, None] = {}
        self._peers_lock = witness.make_lock("etcd.peers")
        self._conn_lock = witness.make_lock("etcd.conn")
        self._closed = threading.Event()
        self._lease_id = 0
        self._ka_feed: Optional[_StreamFeed] = None
        self._ka_call = None
        self._watch_feed: Optional[_StreamFeed] = None
        self._watch_call = None

        # initial registration + listing are synchronous and fail loudly,
        # like the reference's NewEtcdPool (etcd.go:96-110) — after trying
        # every configured endpoint once
        for attempt in range(max(len(self._endpoints), 1)):
            try:
                self._register()
                break
            except grpc.RpcError as e:
                self._maybe_reauth(e)
                if attempt + 1 >= max(len(self._endpoints), 1):
                    raise
                self._rotate_endpoint()
        revision = self._collect_peers()
        self._watch_thread = threading.Thread(
            target=self._watch_loop, args=(revision,), name="etcd-watch",
            daemon=True,
        )
        self._ka_thread = threading.Thread(
            target=self._keepalive_loop, name="etcd-keepalive", daemon=True
        )
        self._watch_thread.start()
        self._ka_thread.start()

    def _dial(self, target: str) -> grpc.Channel:
        creds, opts = self._credentials, self._channel_options
        if self._credentials_factory is not None:
            creds, opts = self._credentials_factory(target)
        opts = opts or None
        return (
            grpc.secure_channel(target, creds, options=opts)
            if creds is not None
            else grpc.insecure_channel(target, options=opts)
        )

    def _meta(self):
        """Per-call metadata: the auth token, acquired lazily."""
        if not self._username:
            return None
        if self._auth_token is None:
            resp = self.client.authenticate(
                epb.AuthenticateRequest(
                    name=self._username, password=self._password),
                timeout=self.timeout_s,
            )
            self._auth_token = resp.token
        return (("token", self._auth_token),)

    def _maybe_reauth(self, e: BaseException) -> None:
        """An UNAUTHENTICATED failure invalidates the cached token so the
        retry path re-authenticates (etcd rotates tokens on restart)."""
        if (isinstance(e, grpc.RpcError)
                and e.code() == grpc.StatusCode.UNAUTHENTICATED):
            self._auth_token = None

    def _rotate_endpoint(self) -> None:
        """Fail over to the next configured endpoint (clientv3 balances
        across all endpoints; we fail over sequentially). Closing the old
        channel fails the other loop's in-flight stream, which then recovers
        through its own restart path on the fresh channel."""
        if len(self._endpoints) < 2:
            return
        with self._conn_lock:
            self._endpoint_idx = (self._endpoint_idx + 1) % len(self._endpoints)
            target = self._endpoints[self._endpoint_idx]
            log.info("failing over to etcd endpoint %s", target)
            try:
                fresh = self._dial(target)
            except BaseException as e:  # noqa: BLE001 — e.g. TOFU cert fetch
                # keep the old channel; the caller's retry loop will rotate
                # again (the index already advanced to the next endpoint)
                log.warning("could not dial etcd endpoint %s: %s", target, e)
                return
            old = self._own_channel
            self._own_channel = fresh
            self.client = EtcdClient(self._own_channel)
            # simple tokens are per-node; re-authenticate against the new one
            self._auth_token = None
            try:
                old.close()
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------------- register

    def _register(self) -> None:
        """Grant lease, put our key, open the keep-alive stream
        (reference: etcd.go:229-253)."""
        grant = self.client.lease_grant(
            epb.LeaseGrantRequest(TTL=self.lease_ttl_s),
            timeout=self.timeout_s, metadata=self._meta(),
        )
        self._lease_id = grant.ID
        self.client.put(
            epb.PutRequest(
                key=self.instance_key,
                value=self.advertise_address.encode(),
                lease=grant.ID,
            ),
            timeout=self.timeout_s,
            metadata=self._meta(),
        )
        feed = _StreamFeed()
        call = self.client.lease_keep_alive(iter(feed), metadata=self._meta())
        feed.send(epb.LeaseKeepAliveRequest(ID=grant.ID))
        self._ka_feed = feed
        self._ka_call = call
        log.info("registered peer '%s' with etcd", self.advertise_address)

    def _keepalive_loop(self) -> None:
        """Send a keep-alive every ttl/3; re-register if the stream dies
        (reference: etcd.go:256-282)."""
        interval = max(self.lease_ttl_s / 3.0, 0.05)
        while not self._closed.is_set():
            call, feed = self._ka_call, self._ka_feed
            try:
                for resp in call:
                    if self._closed.is_set():
                        return
                    if resp.TTL <= 0:
                        raise RuntimeError("lease expired")
                    if self._closed.wait(interval):
                        return
                    feed.send(epb.LeaseKeepAliveRequest(ID=self._lease_id))
                # server closed the stream
                raise RuntimeError("keep alive stream closed")
            except BaseException as e:  # noqa: BLE001 — includes RpcError
                if self._closed.is_set():
                    return
                log.warning(
                    "keep alive lost (%s), attempting to re-register peer", e
                )
                self._maybe_reauth(e)
                while not self._closed.is_set():
                    try:
                        self._register()
                        break
                    except BaseException as re:  # noqa: BLE001
                        log.error("while attempting to re-register peer: %s", re)
                        self._maybe_reauth(re)
                        if self._closed.wait(self.backoff_s):
                            return
                        self._rotate_endpoint()

    # ---------------------------------------------------------------- watch

    def _collect_peers(self) -> int:
        """List the prefix, replacing our peer set; returns the store
        revision for the subsequent watch (reference: etcd.go:145-161)."""
        resp = self.client.range(
            epb.RangeRequest(
                key=self.base_key.encode(),
                range_end=prefix_range_end(self.base_key.encode()),
            ),
            timeout=self.timeout_s,
            metadata=self._meta(),
        )
        with self._peers_lock:
            self._peers = {kv.value.decode(): None for kv in resp.kvs}
        self._call_on_update()
        return resp.header.revision

    def _open_watch(self, revision: int):
        feed = _StreamFeed()
        call = self.client.watch(iter(feed), metadata=self._meta())
        feed.send(
            epb.WatchRequest(
                create_request=epb.WatchCreateRequest(
                    key=self.base_key.encode(),
                    range_end=prefix_range_end(self.base_key.encode()),
                    start_revision=revision + 1,
                    prev_kv=True,
                )
            )
        )
        self._watch_feed = feed
        self._watch_call = call
        log.info(
            "watching for peer changes '%s' at revision %d",
            self.base_key, revision,
        )
        return call

    def _watch_loop(self, revision: int) -> None:
        """Apply watch events; restart the watch (after re-listing) on any
        error (reference: etcd.go:163-222)."""
        call = self._open_watch(revision)
        while not self._closed.is_set():
            try:
                for resp in call:
                    if resp.canceled:
                        if self._closed.is_set():
                            log.info("graceful watch shutdown")
                            return
                        # server-side cancel (e.g. requested revision was
                        # compacted away): re-list and re-watch — the
                        # reference wrongly treats every cancel as graceful
                        # shutdown and freezes membership (etcd.go:171-174)
                        raise RuntimeError(
                            f"watch canceled by server "
                            f"(compact_revision={resp.compact_revision}, "
                            f"reason={resp.cancel_reason!r})"
                        )
                    changed = False
                    with self._peers_lock:
                        for ev in resp.events:
                            if ev.type == epb.Event.PUT and ev.kv.value:
                                self._peers[ev.kv.value.decode()] = None
                                changed = True
                            elif ev.type == epb.Event.DELETE and ev.prev_kv.value:
                                self._peers.pop(ev.prev_kv.value.decode(), None)
                                changed = True
                    if changed:
                        self._call_on_update()
                # stream ended without cancel
                raise RuntimeError("watch stream closed")
            except BaseException as e:  # noqa: BLE001
                if self._closed.is_set():
                    return
                log.error("watch error: %s; restarting watch", e)
                self._maybe_reauth(e)
                while not self._closed.is_set():
                    try:
                        revision = self._collect_peers()
                        call = self._open_watch(revision)
                        break
                    except BaseException as re:  # noqa: BLE001
                        log.error("while attempting to restart watch: %s", re)
                        self._maybe_reauth(re)
                        if self._closed.wait(self.backoff_s):
                            return
                        self._rotate_endpoint()

    def _call_on_update(self) -> None:
        """(reference: etcd.go:321-329)"""
        peers = [PeerInfo(address=a) for a in sorted(self._peers)]
        try:
            self.on_update(peers)
        except Exception:  # noqa: BLE001
            log.exception("peer update callback failed")

    # ---------------------------------------------------------------- close

    def close(self) -> None:
        """Deregister: delete our key, revoke the lease
        (reference: etcd.go:283-301)."""
        if self._closed.is_set():
            return
        self._closed.set()
        for call in (self._watch_call, self._ka_call):
            if call is not None:
                try:
                    call.cancel()
                except Exception:  # noqa: BLE001
                    pass
        for feed in (self._watch_feed, self._ka_feed):
            if feed is not None:
                feed.close()
        try:
            self.client.delete_range(
                epb.DeleteRangeRequest(key=self.instance_key),
                timeout=self.timeout_s,
                metadata=self._meta(),
            )
            if self._lease_id:
                self.client.lease_revoke(
                    epb.LeaseRevokeRequest(ID=self._lease_id),
                    timeout=self.timeout_s,
                    metadata=self._meta(),
                )
        except grpc.RpcError as e:
            log.warning("during etcd deregister: %s", e)
        self._watch_thread.join(timeout=2.0)
        self._ka_thread.join(timeout=2.0)
