"""Peer discovery pools (L0): who is in the cluster.

The reference ships three pools — etcd lease/watch, kubernetes Endpoints
informer, and hashicorp memberlist gossip (reference: etcd.go:56-329,
kubernetes.go:36-162, memberlist.go:17-226) — each reduced to one contract:
call `on_update(List[PeerInfo])` whenever membership changes, and `close()`.

This build ships:

- StaticPool: fixed peer list (what the in-process harness and tests use;
  the reference injects peers the same way, cluster/cluster.go:124-127).
- FilePool: watch a JSON peers file by mtime — operational middle ground.
- MemberlistPool (cluster/memberlist.py): hashicorp/memberlist-v0.2.0-
  wire-compatible SWIM gossip — joins existing reference fleets; the
  GUBER_MEMBERLIST_* default since r4 (PARITY #11).
- GossipPool: a dependency-free UDP heartbeat gossip carrying
  {grpc_address, datacenter} metadata, the same role with a leaner
  wire format (GUBER_MEMBERLIST_COMPAT=0); like MemberlistPool it
  feeds DataCenter and thus enables MULTI_REGION
  (reference: memberlist.go:17-34).
- EtcdPool (cluster/etcd.py): real etcd v3 lease/watch registration over a
  wire-level gRPC client — no etcd3 package needed; pairs with the
  embeddable etcdlite server (cluster/etcdlite.py).
- K8sPool (cluster/k8s.py): real Endpoints-API informer over stdlib
  HTTP(S) — no kubernetes package needed.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from gubernator_tpu.obs import witness
from gubernator_tpu.types import PeerInfo

log = logging.getLogger("gubernator_tpu.discovery")

UpdateFunc = Callable[[List[PeerInfo]], None]


class Pool:
    """Discovery contract (reference: etcd.go:56-58 PoolInterface)."""

    def close(self) -> None:
        raise NotImplementedError


class StaticPool(Pool):
    """Fixed membership pushed once."""

    def __init__(self, peers: Sequence[PeerInfo], on_update: UpdateFunc):
        self.peers = list(peers)
        on_update(self.peers)

    def close(self) -> None:
        pass


class FilePool(Pool):
    """Watch a JSON file of [{"address": ..., "datacenter": ...}] by mtime."""

    def __init__(self, path: str, on_update: UpdateFunc, poll_s: float = 1.0):
        self.path = path
        self.on_update = on_update
        self.poll_s = poll_s
        self._mtime = 0.0
        self._closed = threading.Event()
        self._load()
        self._thread = threading.Thread(
            target=self._watch, name="file-pool", daemon=True
        )
        self._thread.start()

    def _load(self) -> None:
        try:
            mtime = os.stat(self.path).st_mtime
            if mtime == self._mtime:
                return
            self._mtime = mtime
            with open(self.path) as f:
                data = json.load(f)
            peers = [
                PeerInfo(
                    address=p["address"], datacenter=p.get("datacenter", "")
                )
                for p in data
            ]
            self.on_update(peers)
        except FileNotFoundError:
            pass
        except Exception:  # noqa: BLE001
            log.exception("while loading peers file %s", self.path)

    def _watch(self) -> None:
        while not self._closed.wait(self.poll_s):
            self._load()

    def close(self) -> None:
        self._closed.set()
        self._thread.join(timeout=2.0)


class GossipPool(Pool):
    """UDP heartbeat gossip, the memberlist role (reference: memberlist.go).

    Every `heartbeat_s` each node sends its {grpc_address, datacenter,
    peers-i-know} to `fanout` random known peers. Liveness is two-tier
    (the SWIM idea behind memberlist's suspicion mechanism,
    memberlist.go:17-34, without the full protocol): a member unseen for
    `timeout_s` becomes SUSPECT — still a member, now receiving DIRECT
    probes every heartbeat (a probed node answers immediately with a
    unicast heartbeat, independent of its own fanout choices) — and only
    drops after a further `timeout_s` of silence. On a lossy network this
    matters enormously: at 30% packet loss a single-tier design false-
    expires a pair after ~5 lost heartbeats (~0.3^5 per window — minutes
    to the first ring-rehashing flap), while the probe/ack round trips of
    the suspicion window push false expiry below ~1e-5 per window
    (verified by tests/test_control_plane.py's lossy-network test).
    Worst-case detection of a REALLY dead node is bounded at
    2 x timeout_s + heartbeat_s. Membership changes call on_update.
    Convergence is O(log n) rounds of heartbeat dissemination.
    """

    MAGIC = b"gtpu1"

    def __init__(
        self,
        bind_address: str,
        grpc_address: str,
        on_update: UpdateFunc,
        known_nodes: Sequence[str] = (),
        datacenter: str = "",
        heartbeat_s: float = 1.0,
        timeout_s: float = 5.0,
        fanout: int = 3,
    ):
        host, _, port = bind_address.rpartition(":")
        self.bind = (host or "0.0.0.0", int(port))
        self.grpc_address = grpc_address
        self.datacenter = datacenter
        self.on_update = on_update
        self.heartbeat_s = heartbeat_s
        self.timeout_s = timeout_s
        self.fanout = fanout
        # gossip address -> (grpc_address, datacenter, last_seen)
        self._members: Dict[str, tuple] = {}
        # SUSPECT members: gossip address -> drop deadline (monotonic)
        self._suspects: Dict[str, float] = {}
        # freshly-DROPPED members: gossip address -> tombstone deadline.
        # Peers with skewed drop timers keep relaying a dead member for a
        # while; resurrecting it from a relay would flap the ring
        # 3->2->3->2 and double the detection bound. Only a DIRECT
        # heartbeat from the member itself (it is alive after all, or
        # restarted) clears the tombstone early.
        self._tombstones: Dict[str, float] = {}
        self._lock = witness.make_lock("cluster.discovery")
        self._closed = threading.Event()
        self._last_pushed: Optional[List[PeerInfo]] = None

        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind(self.bind)
        self._sock.settimeout(0.2)
        self.gossip_address = f"{self._sock.getsockname()[0]}:{self._sock.getsockname()[1]}"

        with self._lock:
            self._members[self.gossip_address] = (
                grpc_address, datacenter, time.monotonic(),
            )
        self._seeds = list(known_nodes)

        self._rx = threading.Thread(target=self._recv_loop, daemon=True,
                                    name="gossip-rx")
        self._tx = threading.Thread(target=self._send_loop, daemon=True,
                                    name="gossip-tx")
        self._rx.start()
        self._tx.start()
        self._push_update()

    # ------------------------------------------------------------ internals

    def _payload(self, probe: bool = False) -> bytes:
        with self._lock:
            members = {
                addr: {"grpc": g, "dc": dc}
                for addr, (g, dc, _) in self._members.items()
            }
        msg = {"from": self.gossip_address, "members": members}
        if probe:
            msg["probe"] = True  # receiver acks with a direct heartbeat
        return self.MAGIC + json.dumps(msg).encode()

    def _targets(self) -> List[str]:
        import random

        with self._lock:
            others = [a for a in self._members if a != self.gossip_address]
        pool = list(set(others + self._seeds))
        random.shuffle(pool)
        return pool[: max(self.fanout, len(self._seeds))]

    def _send_to(self, target, payload: bytes) -> None:
        # the target may come off the WIRE (probe acks reply to msg
        # "from"): any malformed value must be a no-op, never an escape
        # that kills the rx/tx thread
        try:
            host, _, port = target.rpartition(":")
            self._sock.sendto(payload, (host, int(port)))
        except (OSError, ValueError, AttributeError, TypeError):
            pass

    def _send_loop(self) -> None:
        while not self._closed.wait(self.heartbeat_s):
            payload = self._payload()
            for target in self._targets():
                self._send_to(target, payload)
            with self._lock:
                suspects = list(self._suspects)
            if suspects:
                # direct probes: the ack (an immediate unicast heartbeat)
                # refreshes last_seen without depending on the suspect's
                # random fanout happening to pick us
                probe = self._payload(probe=True)
                for target in suspects:
                    self._send_to(target, probe)
            self._expire()

    def _recv_loop(self) -> None:
        while not self._closed.is_set():
            try:
                data, _ = self._sock.recvfrom(65536)
            except socket.timeout:
                continue
            except OSError:
                return
            if not data.startswith(self.MAGIC):
                continue
            try:
                msg = json.loads(data[len(self.MAGIC):])
            except json.JSONDecodeError:
                continue
            now = time.monotonic()
            if msg.get("probe") and msg.get("from"):
                # answer NOW with a unicast heartbeat: the prober's
                # suspicion clears on any direct packet from us
                self._send_to(msg["from"], self._payload())
            changed = False
            with self._lock:
                for addr, meta in msg.get("members", {}).items():
                    cur = self._members.get(addr)
                    if addr == self.gossip_address:
                        continue
                    direct = addr == msg.get("from")
                    if not direct and cur is None and \
                            self._tombstones.get(addr, 0) > now:
                        continue  # relayed ghost of a dropped member
                    if direct:
                        self._tombstones.pop(addr, None)
                    fresh = (meta.get("grpc", ""), meta.get("dc", ""), now)
                    if cur is None or cur[:2] != fresh[:2]:
                        changed = True
                    # only bump last_seen for the direct sender; relayed
                    # entries keep their own aging
                    if direct or cur is None:
                        self._members[addr] = fresh
                    else:
                        self._members[addr] = (fresh[0], fresh[1], cur[2])
            if changed:
                self._push_update()

    def _expire(self) -> None:
        now = time.monotonic()
        cutoff = now - self.timeout_s
        dropped = False
        with self._lock:
            for addr in list(self._members):
                if addr == self.gossip_address:
                    continue
                if self._members[addr][2] >= cutoff:
                    self._suspects.pop(addr, None)  # heard again: clear
                    continue
                deadline = self._suspects.get(addr)
                if deadline is None:
                    # tier 1: unseen past timeout_s -> SUSPECT, probed
                    # directly for one more timeout_s before any drop
                    self._suspects[addr] = now + self.timeout_s
                elif now >= deadline:
                    del self._members[addr]
                    del self._suspects[addr]
                    # hold the tombstone long enough for every peer's own
                    # (suspicion-delayed, clock-skewed) drop to complete
                    self._tombstones[addr] = now + 2 * self.timeout_s \
                        + self.heartbeat_s
                    dropped = True
            for addr in [a for a, t in self._tombstones.items() if t <= now]:
                del self._tombstones[addr]
        if dropped:
            self._push_update()

    def suspects(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._suspects)

    def _push_update(self) -> None:
        with self._lock:
            peers = sorted(
                (
                    PeerInfo(address=g, datacenter=dc)
                    for g, dc, _ in self._members.values()
                    if g
                ),
                key=lambda p: p.address,
            )
        if peers != self._last_pushed:
            self._last_pushed = peers
            try:
                self.on_update(list(peers))
            except Exception:  # noqa: BLE001
                log.exception("peer update callback failed")

    def members(self) -> Dict[str, tuple]:
        with self._lock:
            return dict(self._members)

    def close(self) -> None:
        self._closed.set()
        self._rx.join(timeout=1.0)
        self._tx.join(timeout=2.0)
        self._sock.close()


# Real etcd v3 pool (wire-level client, no etcd3 package needed) lives in
# cluster/etcd.py; re-exported here so all pools share one import point.
from gubernator_tpu.cluster.etcd import EtcdPool  # noqa: E402,F401


# Real Endpoints-API pool (stdlib HTTP informer, no kubernetes package
# needed) lives in cluster/k8s.py.
from gubernator_tpu.cluster.k8s import K8sPool  # noqa: E402,F401
