"""Host-tier peer pickers: which *process* owns a rate-limit key.

Two ownership tiers exist in this framework (SURVEY.md §2.2): within a
process, keys map to mesh shards by `parallel.mesh.shard_of_key`; across
processes, these pickers map keys to host peers, exactly mirroring the
reference's consistent-hash rings so that routing behavior (and its tests)
carry over:

- ConsistentHashPicker: one ring point per peer, crc32 default, binary
  search with wraparound (reference: hash.go:31-99).
- ReplicatedConsistentHashPicker: `replicas` ring points per peer
  (DefaultReplicas=512), 64-bit fnv1 default, point hash of
  ``str(i) + address`` (reference: replicated_hash.go:27-116).
- RegionPicker: one sub-picker per datacenter; GetClients returns one owner
  per region for MULTI_REGION fan-out (reference: region_picker.go:17-95).

Peers are any object carrying an `info: PeerInfo` attribute.
"""

from __future__ import annotations

import bisect
import zlib
from typing import Any, Callable, Dict, List, Optional

from gubernator_tpu.types import PeerInfo
from gubernator_tpu.utils.fnv import fnv1_64, fnv1a_64

HashFunc = Callable[[bytes], int]

DEFAULT_REPLICAS = 512  # reference: replicated_hash.go:27


def crc32_hash(data: bytes) -> int:
    """Default 32-bit ring hash (reference: hash.go:43-45)."""
    return zlib.crc32(data) & 0xFFFFFFFF


def fnv1_32(data: bytes) -> int:
    h = 2166136261
    for b in data:
        h = ((h * 16777619) & 0xFFFFFFFF) ^ b
    return h


def fnv1a_32(data: bytes) -> int:
    h = 2166136261
    for b in data:
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


class PickerEmptyError(RuntimeError):
    def __init__(self):
        super().__init__("unable to pick a peer; pool is empty")


class ConsistentHashPicker:
    """Single-point consistent-hash ring (reference: hash.go:31-99)."""

    def __init__(self, hash_func: Optional[HashFunc] = None):
        self.hash_func = hash_func or crc32_hash
        self._ring: List[int] = []  # sorted point hashes
        self._by_hash: Dict[int, Any] = {}

    def new(self) -> "ConsistentHashPicker":
        """Empty picker with the same configuration (reference: hash.go:48-53)."""
        return ConsistentHashPicker(self.hash_func)

    def add(self, peer: Any) -> None:
        h = self.hash_func(peer.info.address.encode())
        bisect.insort(self._ring, h)
        self._by_hash[h] = peer

    def size(self) -> int:
        return len(self._ring)

    def peers(self) -> List[Any]:
        return list(self._by_hash.values())

    def get_by_peer_info(self, info: PeerInfo) -> Optional[Any]:
        return self._by_hash.get(self.hash_func(info.address.encode()))

    def get(self, key: str) -> Any:
        """Owner of `key`: first ring point >= hash(key), wrapping to the
        smallest (reference: hash.go:83-99)."""
        if not self._ring:
            raise PickerEmptyError()
        h = self.hash_func(key.encode())
        idx = bisect.bisect_left(self._ring, h)
        if idx == len(self._ring):
            idx = 0
        return self._by_hash[self._ring[idx]]


class ReplicatedConsistentHashPicker:
    """Virtual-node ring: `replicas` points per peer for smooth key spread
    (reference: replicated_hash.go:34-116)."""

    def __init__(
        self,
        hash_func: Optional[HashFunc] = None,
        replicas: int = DEFAULT_REPLICAS,
    ):
        self.hash_func = hash_func or fnv1_64
        self.replicas = replicas
        self._points: List[int] = []  # sorted
        self._point_peer: List[Any] = []  # parallel to _points
        self._by_address: Dict[str, Any] = {}

    def new(self) -> "ReplicatedConsistentHashPicker":
        return ReplicatedConsistentHashPicker(self.hash_func, self.replicas)

    def add(self, peer: Any) -> None:
        addr = peer.info.address
        self._by_address[addr] = peer
        pts = [
            (self.hash_func((str(i) + addr).encode()), peer)
            for i in range(self.replicas)
        ]
        merged = sorted(
            list(zip(self._points, self._point_peer)) + pts, key=lambda t: t[0]
        )
        self._points = [h for h, _ in merged]
        self._point_peer = [p for _, p in merged]

    def size(self) -> int:
        return len(self._by_address)

    def peers(self) -> List[Any]:
        return list(self._by_address.values())

    def get_by_peer_info(self, info: PeerInfo) -> Optional[Any]:
        return self._by_address.get(info.address)

    def get(self, key: str) -> Any:
        if not self._by_address:
            raise PickerEmptyError()
        h = self.hash_func(key.encode())
        idx = bisect.bisect_left(self._points, h)
        if idx == len(self._points):
            idx = 0
        return self._point_peer[idx]


class RegionPicker:
    """Two-level picker for multi-datacenter deployments: one sub-picker per
    region (reference: region_picker.go:17-95)."""

    def __init__(self, picker: Optional[Any] = None):
        self._template = picker or ConsistentHashPicker()
        self._regions: Dict[str, Any] = {}

    def new(self) -> "RegionPicker":
        return RegionPicker(self._template.new())

    def add(self, peer: Any) -> None:
        dc = peer.info.datacenter
        if dc not in self._regions:
            self._regions[dc] = self._template.new()
        self._regions[dc].add(peer)

    def pickers(self) -> Dict[str, Any]:
        return self._regions

    def peers(self) -> List[Any]:
        return [p for picker in self._regions.values() for p in picker.peers()]

    def size(self) -> int:
        return sum(p.size() for p in self._regions.values())

    def get_by_peer_info(self, info: PeerInfo) -> Optional[Any]:
        for picker in self._regions.values():
            peer = picker.get_by_peer_info(info)
            if peer is not None:
                return peer
        return None

    def get_clients(self, key: str) -> List[Any]:
        """One owner per region, for MULTI_REGION hit replication
        (reference: region_picker.go:47-59)."""
        return [picker.get(key) for picker in self._regions.values()]
