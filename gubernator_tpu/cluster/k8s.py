"""Kubernetes discovery pool — a real implementation, no client library.

Watches the core/v1 Endpoints API with a label selector, the same surface
the reference consumes through client-go's SharedIndexInformer (reference:
kubernetes.go:36-162), over plain HTTP(S) with the standard library:

- in-cluster config: KUBERNETES_SERVICE_HOST/PORT + the service-account
  token/CA/namespace files (what client-go's rest.InClusterConfig reads,
  reference: kubernetes.go:57-66);
- list + watch with resourceVersion continuation; 410 Gone or any stream
  error re-lists and re-watches (the informer's behavior);
- peers = every subset address of every matching Endpoints object, as
  `ip:pod_port`, with `is_owner` set when the ip equals our pod ip
  (reference: kubernetes.go:136-158).
"""

from __future__ import annotations

import json
import logging
import ssl
import threading
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional

from gubernator_tpu.obs import witness
from gubernator_tpu.types import PeerInfo

log = logging.getLogger("gubernator_tpu.k8s")

UpdateFunc = Callable[[List[PeerInfo]], None]

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class K8sPool:
    """Peer discovery from the Endpoints API (reference: kubernetes.go)."""

    def __init__(
        self,
        on_update: UpdateFunc,
        selector: str,
        pod_ip: str,
        pod_port: str,
        namespace: Optional[str] = None,
        api_server: Optional[str] = None,
        token: Optional[str] = None,
        ssl_context: Optional[ssl.SSLContext] = None,
        backoff_s: float = 5.0,
        request_timeout_s: float = 30.0,
        watch_timeout_s: float = 240.0,
    ):
        if api_server is None:
            import os

            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not running in-cluster: KUBERNETES_SERVICE_HOST unset "
                    "and no api_server given (reference: rest.InClusterConfig)"
                )
            api_server = f"https://{host}:{port}"
            if token is None:
                with open(f"{SERVICE_ACCOUNT_DIR}/token") as f:
                    token = f.read().strip()
            if ssl_context is None:
                ssl_context = ssl.create_default_context(
                    cafile=f"{SERVICE_ACCOUNT_DIR}/ca.crt"
                )
            if namespace is None:
                with open(f"{SERVICE_ACCOUNT_DIR}/namespace") as f:
                    namespace = f.read().strip()
        self.api_server = api_server.rstrip("/")
        self.token = token
        self.ssl_context = ssl_context
        self.namespace = namespace or "default"
        self.selector = selector
        self.pod_ip = pod_ip
        self.pod_port = pod_port
        self.on_update = on_update
        self.backoff_s = backoff_s
        self.request_timeout_s = request_timeout_s
        self.watch_timeout_s = watch_timeout_s

        # informer store: "namespace/name" -> Endpoints object
        self._store: Dict[str, dict] = {}
        self._lock = witness.make_lock("k8s.watch")
        self._closed = threading.Event()
        self._last_pushed: Optional[List[PeerInfo]] = None

        # initial list is synchronous and fails loudly, mirroring
        # WaitForCacheSync (reference: kubernetes.go:128-131)
        rv = self._list()
        self._push()
        self._thread = threading.Thread(
            target=self._watch_loop, args=(rv,), name="k8s-watch", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------ http

    def _request(self, query: Dict[str, str], stream: bool):
        qs = urllib.parse.urlencode(query)
        url = (
            f"{self.api_server}/api/v1/namespaces/{self.namespace}"
            f"/endpoints?{qs}"
        )
        req = urllib.request.Request(url)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        # streams get a socket timeout too: a black-holed connection must
        # raise rather than block recv() forever (client-go sets a
        # server-side timeoutSeconds per watch for the same reason)
        timeout = (
            self.watch_timeout_s + 30.0 if stream else self.request_timeout_s
        )
        return urllib.request.urlopen(
            req, timeout=timeout, context=self.ssl_context
        )

    def _list(self) -> str:
        """Full re-list; returns the collection resourceVersion."""
        query = {}
        if self.selector:
            query["labelSelector"] = self.selector
        with self._request(query, stream=False) as resp:
            body = json.load(resp)
        with self._lock:
            self._store = {
                self._key(item): item for item in body.get("items", [])
            }
        return body.get("metadata", {}).get("resourceVersion", "")

    def _watch_loop(self, resource_version: str) -> None:
        while not self._closed.is_set():
            try:
                query = {
                    "watch": "1",
                    "allowWatchBookmarks": "true",
                    # ask the server to end the watch periodically so a
                    # silent connection can't freeze discovery forever
                    "timeoutSeconds": str(int(self.watch_timeout_s)),
                }
                if self.selector:
                    query["labelSelector"] = self.selector
                if resource_version:
                    query["resourceVersion"] = resource_version
                with self._request(query, stream=True) as resp:
                    for line in resp:
                        if self._closed.is_set():
                            return
                        if not line.strip():
                            continue
                        event = json.loads(line)
                        resource_version = self._apply(event, resource_version)
            except _Expired:
                log.info("watch expired (410 Gone); re-listing")
                resource_version = ""
            except Exception as e:  # noqa: BLE001
                if self._closed.is_set():
                    return
                log.warning("endpoints watch error: %s; re-listing", e)
                if self._closed.wait(self.backoff_s):
                    return
            if self._closed.is_set():
                return
            # stream ended or failed: informer semantics — re-list, then
            # continue watching from the fresh resourceVersion
            try:
                resource_version = self._list()
                self._push()
            except Exception as e:  # noqa: BLE001
                log.warning("endpoints re-list failed: %s", e)
                if self._closed.wait(self.backoff_s):
                    return

    def _apply(self, event: dict, resource_version: str) -> str:
        etype = event.get("type")
        obj = event.get("object", {})
        rv = obj.get("metadata", {}).get("resourceVersion", resource_version)
        if etype == "BOOKMARK":
            return rv
        if etype == "ERROR":
            if obj.get("code") == 410:
                raise _Expired()
            raise RuntimeError(f"watch error event: {obj}")
        key = self._key(obj)
        with self._lock:
            if etype == "DELETED":
                self._store.pop(key, None)
            else:  # ADDED / MODIFIED
                self._store[key] = obj
        # the reference pushes on update/delete events
        # (kubernetes.go:97-124: Add logs only; Update/Delete call updatePeers)
        self._push()
        return rv

    # --------------------------------------------------------------- updates

    @staticmethod
    def _key(obj: dict) -> str:
        meta = obj.get("metadata", {})
        return f"{meta.get('namespace', '')}/{meta.get('name', '')}"

    def _peers(self) -> List[PeerInfo]:
        """(reference: kubernetes.go:136-158 updatePeers)"""
        peers = []
        with self._lock:
            for obj in self._store.values():
                for subset in obj.get("subsets") or []:
                    for addr in subset.get("addresses") or []:
                        ip = addr.get("ip", "")
                        if not ip:
                            continue
                        peers.append(
                            PeerInfo(
                                address=f"{ip}:{self.pod_port}",
                                is_owner=ip == self.pod_ip,
                            )
                        )
        peers.sort(key=lambda p: p.address)
        return peers

    def _push(self) -> None:
        peers = self._peers()
        if peers == self._last_pushed:
            return
        self._last_pushed = peers
        try:
            self.on_update(list(peers))
        except Exception:  # noqa: BLE001
            log.exception("peer update callback failed")

    def close(self) -> None:
        self._closed.set()
        self._thread.join(timeout=2.0)


class _Expired(Exception):
    """HTTP 410: the watch resourceVersion was compacted away."""
