"""MemberlistPool: hashicorp/memberlist-v0.2.0-wire-compatible discovery.

The reference's memberlist-backed pool (reference: memberlist.go:17-106)
is the one discovery option round-3 review recorded as genuinely absent:
GossipPool (cluster/discovery.py) fills the ROLE but speaks its own
wire format, so a gubernator_tpu node could not join an existing
memberlist fleet.  This pool speaks the library's actual protocol
(cluster/mlwire.py) and its SWIM state machine:

- UDP failure detection: round-robin probe -> ack, indirect probes
  through `indirect_checks` relays (with nacks), TCP fallback ping, then
  a SUSPECT broadcast; suspicion expires into DEAD after
  `suspicion_mult * log10(n+1) * probe_interval` seconds.
- dissemination: alive/suspect/dead broadcasts piggyback on every UDP
  send through a transmit-limited queue (`retransmit_mult * log10(n+1)`
  sends per broadcast, newest-about-a-node invalidates queued older).
- refutation: suspect/dead claims about ourselves bump our incarnation
  and re-broadcast alive, exactly the SWIM liveness rule.
- state sync: TCP push/pull of the full node table on join and every
  `push_pull_interval` (both sides merge; streams may be LZW-wrapped).
- metadata: Node.Meta carries the reference's gob-encoded
  {DataCenter, GubernatorPort} (reference: memberlist.go:193-209), so
  peers learn each other's *gubernator* endpoint through the gossip
  fleet itself; `on_update` receives PeerInfo(address=ip:guber_port,
  datacenter=dc) just like the reference's event handler
  (reference: memberlist.go:119-149).

Timing defaults mirror DefaultWANConfig, the config the reference picks
(reference: memberlist.go:43); tests shrink them.  Not implemented (and
refused loudly rather than mis-spoken): encrypted fleets (SecretKey —
the reference never sets one) and user-level delegate messages.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import random
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import msgpack

from gubernator_tpu.obs import witness
from gubernator_tpu.cluster import mlwire as wire
from gubernator_tpu.cluster.discovery import Pool
from gubernator_tpu.types import PeerInfo

log = logging.getLogger("gubernator_tpu.memberlist")

UpdateFunc = Callable[[List[PeerInfo]], None]

_TICK = 0.05  # scheduler granularity; every interval is measured, not counted
_UDP_BUDGET = 1400  # memberlist UDPBufferSize: max datagram it assembles


class JoinError(RuntimeError):
    """No seed node could be push/pull-synced."""


@dataclasses.dataclass
class NodeState:
    name: str
    addr: bytes  # 4 (IPv4) or 16 (IPv6) bytes, the alive.Addr payload
    port: int
    meta: bytes
    incarnation: int
    state: int  # wire.STATE_*
    state_change: float = 0.0
    suspicion_deadline: float = 0.0

    def endpoint(self) -> Tuple[str, int]:
        host = socket.inet_ntoa(self.addr) if len(self.addr) == 4 else \
            socket.inet_ntop(socket.AF_INET6, self.addr)
        return host, self.port


class MemberlistPool(Pool):
    def __init__(
        self,
        bind_address: str,
        node_name: str,
        on_update: UpdateFunc,
        gubernator_port: int,
        known_nodes: Sequence[str] = (),
        datacenter: str = "",
        advertise_address: str = "",
        probe_interval: float = 5.0,
        probe_timeout: float = 3.0,
        gossip_interval: float = 0.5,
        gossip_nodes: int = 4,
        push_pull_interval: float = 60.0,
        suspicion_mult: float = 6.0,
        retransmit_mult: float = 4.0,
        indirect_checks: int = 3,
        join_required: bool = True,
        secret_key: bytes = b"",
        secret_keys: Sequence[bytes] = (),
    ):
        host, _, port = bind_address.rpartition(":")
        self.bind = (host or "0.0.0.0", int(port))
        self.name = node_name
        self.on_update = on_update
        self.datacenter = datacenter
        self.probe_interval = probe_interval
        self.probe_timeout = probe_timeout
        self.gossip_interval = gossip_interval
        self.gossip_nodes = gossip_nodes
        self.push_pull_interval = push_pull_interval
        self.suspicion_mult = suspicion_mult
        self.retransmit_mult = retransmit_mult
        self.indirect_checks = indirect_checks
        # AES-GCM packet encryption, as hashicorp/memberlist's SecretKey/
        # Keyring: `secret_key` is the primary (encrypt) key, `secret_keys`
        # additional decrypt-only keys for rotation. An encrypted fleet
        # refuses plaintext both ways (GossipVerify{In,Out}going defaults).
        ring = [k for k in [secret_key, *secret_keys] if k]
        for k in ring:
            if len(k) not in (16, 24, 32):
                raise ValueError(
                    "memberlist secret keys must be 16/24/32 bytes")
        self._keyring: Optional[List[bytes]] = ring or None
        self._primary_key: Optional[bytes] = ring[0] if ring else None

        self._lock = witness.make_rlock("memberlist.state")
        self._closed = threading.Event()
        self._nodes: Dict[str, NodeState] = {}
        self._incarnation = 1
        self._seq = 0
        # seqno -> (deadline, callback(payload) or None); fired on ack
        self._acks: Dict[int, Tuple[float, Optional[Callable[[bytes], None]]]] = {}
        # broadcast queue: node name -> [framed bytes, transmits so far]
        self._bcast: Dict[str, List[Any]] = {}
        # pending indirect-ping nack timers: cancelled on close so a
        # dying pool neither delays interpreter exit nor fires a nack
        # after its sockets are gone
        self._nack_timers: List[threading.Timer] = []
        self._probe_ring: List[str] = []
        self._push_lock = witness.make_lock("memberlist.push")
        self._last_pushed: Optional[List[PeerInfo]] = None
        self._leaving = False

        # --- sockets (UDP + TCP share the port, like memberlist). With
        # an ephemeral bind (port 0) the kernel picks the UDP port first
        # and the matching TCP port may already belong to someone else —
        # retry with a fresh ephemeral pick instead of failing the pool.
        for attempt in range(16):
            self._udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            self._udp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._udp.bind(self.bind)
            self._udp.settimeout(0.2)
            self._tcp = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._tcp.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                self._tcp.bind((self.bind[0], self._udp.getsockname()[1]))
            except OSError:
                self._udp.close()
                self._tcp.close()
                if self.bind[1] != 0 or attempt == 15:
                    raise  # a FIXED port in use is the operator's error
                continue
            break
        self._tcp.listen(16)
        self._tcp.settimeout(0.2)
        self.bound_port = self._udp.getsockname()[1]

        adv_host = advertise_address or self._advertise_ip()
        self.advertise = (adv_host, self.bound_port)
        try:
            self._addr_bytes = socket.inet_pton(socket.AF_INET, adv_host)
        except OSError:  # IPv6 advertise hosts ride the 16-byte form
            self._addr_bytes = socket.inet_pton(socket.AF_INET6, adv_host)

        meta = wire.gob_encode_metadata(datacenter, gubernator_port)
        if len(meta) > 512:  # memberlist MetaMaxSize
            raise ValueError("gob metadata over memberlist's 512-byte cap")
        with self._lock:
            self._nodes[self.name] = NodeState(
                name=self.name, addr=self._addr_bytes, port=self.bound_port,
                meta=meta, incarnation=self._incarnation,
                state=wire.STATE_ALIVE, state_change=time.monotonic(),
            )
        self._queue_broadcast(self.name, self._alive_msg(self._nodes[self.name]))

        self._threads = [
            threading.Thread(target=self._udp_loop, daemon=True, name="ml-udp"),
            threading.Thread(target=self._tcp_loop, daemon=True, name="ml-tcp"),
            threading.Thread(target=self._sched_loop, daemon=True, name="ml-tick"),
        ]
        for t in self._threads:
            t.start()

        if known_nodes:
            joined = self.join(known_nodes)
            if joined == 0 and join_required:
                self.close()
                raise JoinError(f"could not join any of {list(known_nodes)}")
        self._push_update()

    # ------------------------------------------------------------- identity

    def _advertise_ip(self) -> str:
        ip = self.bind[0]
        if ip not in ("0.0.0.0", ""):
            return ip
        try:  # routing trick: no packet is sent for a connected UDP socket
            probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            probe.connect(("198.51.100.1", 9))
            ip = probe.getsockname()[0]
            probe.close()
            return ip
        except OSError:
            return "127.0.0.1"

    def _next_seq(self) -> int:
        with self._lock:
            self._seq = (self._seq + 1) & 0xFFFFFFFF
            return self._seq

    def _alive_msg(self, n: NodeState) -> bytes:
        return wire.encode_msg(wire.ALIVE, {
            "Incarnation": n.incarnation, "Node": n.name, "Addr": n.addr,
            "Port": n.port, "Meta": n.meta, "Vsn": wire.DEFAULT_VSN,
        })

    # ------------------------------------------------------------ broadcasts

    def _queue_broadcast(self, about: str, framed: bytes) -> None:
        with self._lock:
            self._bcast[about] = [framed, 0]

    def _transmit_limit(self) -> int:
        with self._lock:
            n = len(self._nodes)
        return max(1, int(self.retransmit_mult * math.ceil(math.log10(n + 1))))

    def _take_broadcasts(self, budget: int) -> List[bytes]:
        """Pop up to `budget` bytes of queued broadcasts, fewest-transmits
        first, charging each 2 bytes of compound overhead."""
        limit = self._transmit_limit()
        out: List[bytes] = []
        with self._lock:
            order = sorted(self._bcast.items(), key=lambda kv: kv[1][1])
            for about, entry in order:
                framed = entry[0]
                if len(framed) + 2 > budget:
                    continue
                budget -= len(framed) + 2
                out.append(framed)
                entry[1] += 1
                if entry[1] >= limit:
                    del self._bcast[about]
        return out

    def _send_udp(self, dest: Tuple[str, int], *parts: bytes) -> None:
        head = b"".join(parts)
        overhead = 7 if self._primary_key is None else \
            7 + wire.encrypted_length(wire.ENC_V1, 0)
        piggyback = self._take_broadcasts(_UDP_BUDGET - len(head) - overhead)
        try:
            self._udp.sendto(
                wire.assemble_packet(list(parts) + piggyback,
                                     key=self._primary_key), dest
            )
        except OSError:
            pass

    def _stream_out(self, payload: bytes) -> bytes:
        """Frame one outbound TCP stream body: encryptMsg-wrapped on an
        encrypted fleet, plaintext otherwise."""
        if self._primary_key is None:
            return payload
        return wire.encrypt_stream_frame(self._primary_key, payload)

    # ------------------------------------------------------------- UDP loop

    def _udp_loop(self) -> None:
        while not self._closed.is_set():
            try:
                data, src = self._udp.recvfrom(wire.MAX_UDP_PACKET)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                msgs = wire.ingest_packet(data, keyring=self._keyring)
            except wire.WireError as exc:
                log.debug("bad packet from %s: %s", src, exc)
                continue
            for t, body in msgs:
                try:
                    self._handle(t, body, src)
                except (wire.WireError, ValueError, TypeError, KeyError,
                        OverflowError) as exc:
                    # a peer-controlled field of the wrong msgpack type
                    # (int() on bytes, a non-addr Addr) must never kill
                    # the receive thread
                    log.debug("bad %d msg from %s: %s", t, src, exc)

    def _handle(self, t: int, m: Dict[str, Any], src: Tuple[str, int]) -> None:
        if t == wire.PING:
            node = m.get("Node", "")
            if node and node != self.name:
                log.warning("ping for %r arrived at %r", node, self.name)
                return
            dest = self._reply_addr(m, src)
            self._send_udp(dest, wire.encode_msg(
                wire.ACK_RESP, {"SeqNo": m.get("SeqNo", 0), "Payload": b""}))
        elif t == wire.INDIRECT_PING:
            self._on_indirect_ping(m, src)
        elif t == wire.ACK_RESP:
            self._on_ack(m)
        elif t == wire.NACK_RESP:
            pass  # informational: the relay answered but the target did not
        elif t == wire.SUSPECT:
            self._on_suspect(int(m.get("Incarnation", 0)), m.get("Node", ""))
        elif t == wire.ALIVE:
            self._on_alive(m)
        elif t == wire.DEAD:
            self._on_dead(int(m.get("Incarnation", 0)), m.get("Node", ""),
                          m.get("From", ""))
        elif t in (wire.USER, wire.ERR):
            pass
        else:
            log.debug("unhandled msg type %d", t)

    @staticmethod
    def _ntop(addr: Any) -> Optional[str]:
        """4- or 16-byte wire address -> presentation form (None when
        neither) — IPv6 members carry 16-byte Addr/SourceAddr/Target."""
        if isinstance(addr, bytes):
            if len(addr) == 4:
                return socket.inet_ntoa(addr)
            if len(addr) == 16:
                return socket.inet_ntop(socket.AF_INET6, addr)
        return None

    @classmethod
    def _reply_addr(cls, m: Dict[str, Any], src: Tuple[str, int]) -> Tuple[str, int]:
        host = cls._ntop(m.get("SourceAddr"))
        sp = m.get("SourcePort")
        if host and sp:
            return host, int(sp)
        return src

    def _on_ack(self, m: Dict[str, Any]) -> None:
        seq = int(m.get("SeqNo", 0))
        with self._lock:
            entry = self._acks.pop(seq, None)
        if entry and entry[1]:
            payload = m.get("Payload", b"")
            entry[1](payload if isinstance(payload, bytes) else b"")

    def _on_indirect_ping(self, m: Dict[str, Any], src: Tuple[str, int]) -> None:
        target_host = self._ntop(m.get("Target", b""))
        if target_host is None:
            return
        dest = (target_host, int(m.get("Port", 0)))
        requester = self._reply_addr(m, src)
        orig_seq = int(m.get("SeqNo", 0))
        want_nack = bool(m.get("Nack", False))
        my_seq = self._next_seq()

        def relay(_payload: bytes, _req=requester, _orig=orig_seq) -> None:
            self._send_udp(_req, wire.encode_msg(
                wire.ACK_RESP, {"SeqNo": _orig, "Payload": b""}))

        deadline = time.monotonic() + self.probe_timeout
        with self._lock:
            self._acks[my_seq] = (deadline, relay)
        if want_nack:
            def nack_if_unanswered(_seq=my_seq, _req=requester, _orig=orig_seq):
                with self._lock:
                    missed = _seq in self._acks
                if missed:
                    self._send_udp(_req, wire.encode_msg(
                        wire.NACK_RESP, {"SeqNo": _orig}))
            timer = threading.Timer(self.probe_timeout, nack_if_unanswered)
            timer.daemon = True
            with self._lock:
                self._nack_timers = [
                    t for t in self._nack_timers if t.is_alive()]
                self._nack_timers.append(timer)
            timer.start()
        self._send_udp(dest, wire.encode_msg(wire.PING, {
            "SeqNo": my_seq, "Node": m.get("Node", ""),
            "SourceAddr": self._addr_bytes, "SourcePort": self.bound_port,
            "SourceNode": self.name,
        }))

    # --------------------------------------------------------- state machine

    def _refute(self, claimed_inc: int) -> None:
        with self._lock:
            self._incarnation = max(self._incarnation, claimed_inc) + 1
            me = self._nodes[self.name]
            me.incarnation = self._incarnation
            me.state = wire.STATE_ALIVE
            framed = self._alive_msg(me)
        self._queue_broadcast(self.name, framed)

    def _on_alive(self, m: Dict[str, Any]) -> None:
        name = m.get("Node", "")
        inc = int(m.get("Incarnation", 0))
        addr, port = m.get("Addr", b""), int(m.get("Port", 0))
        meta = m.get("Meta", b"") or b""
        if not name or not isinstance(addr, bytes) or len(addr) not in (4, 16):
            return
        if name == self.name:
            with self._lock:  # compare under the lock: a concurrent
                me = self._nodes[self.name]  # _refute must not race the
                same = addr == me.addr and port == me.port and meta == me.meta
                stale = inc < me.incarnation  # incarnation read
            if not stale and not same:
                self._refute(inc)  # someone is gossiping a stale identity
            return
        changed = False
        with self._lock:
            cur = self._nodes.get(name)
            if cur is None:
                self._nodes[name] = NodeState(
                    name=name, addr=addr, port=port, meta=bytes(meta),
                    incarnation=inc, state=wire.STATE_ALIVE,
                    state_change=time.monotonic(),
                )
                changed = True
            elif inc > cur.incarnation:
                cur.addr, cur.port, cur.meta = addr, port, bytes(meta)
                cur.incarnation = inc
                if cur.state != wire.STATE_ALIVE:
                    cur.state = wire.STATE_ALIVE
                    cur.state_change = time.monotonic()
                changed = True
        if changed:
            self._queue_broadcast(name, wire.encode_msg(wire.ALIVE, m))
            self._push_update()

    def _on_suspect(self, inc: int, name: str) -> None:
        if not name:
            return
        if name == self.name:
            # staleness rule: a claim older than our incarnation is a
            # replay of an already-refuted rumor — ignoring it (as the
            # Go state machine does) stops incarnation churn. Read the
            # incarnation under the lock so a concurrent _refute cannot
            # race the comparison.
            with self._lock:
                stale = inc < self._incarnation
            if not stale:
                self._refute(inc)
            return
        now = time.monotonic()
        with self._lock:
            cur = self._nodes.get(name)
            if cur is None or inc < cur.incarnation or \
                    cur.state != wire.STATE_ALIVE:
                return
            cur.state = wire.STATE_SUSPECT
            cur.incarnation = inc
            cur.state_change = now
            n = len(self._nodes)
            # fractional nodeScale, exactly hashicorp/memberlist's
            # suspicionTimeout (state.go): max(1, log10(max(1, n))) — the
            # earlier ceil(log10(n+1)) overshot the reference's window up
            # to ~2x at small clusters while claiming parity
            cur.suspicion_deadline = now + (
                self.suspicion_mult
                * max(1.0, math.log10(max(n, 1)))
                * self.probe_interval
            )
        self._queue_broadcast(name, wire.encode_msg(wire.SUSPECT, {
            "Incarnation": inc, "Node": name, "From": self.name,
        }))
        self._push_update()

    def _on_dead(self, inc: int, name: str, from_: str) -> None:
        if not name:
            return
        if name == self.name:
            if not self._leaving and inc >= self._incarnation:
                self._refute(inc)
            return
        with self._lock:
            cur = self._nodes.get(name)
            if cur is None or inc < cur.incarnation or \
                    cur.state == wire.STATE_DEAD:
                return
            cur.state = wire.STATE_DEAD
            cur.incarnation = inc
            cur.state_change = time.monotonic()
        self._queue_broadcast(name, wire.encode_msg(wire.DEAD, {
            "Incarnation": inc, "Node": name, "From": from_ or self.name,
        }))
        self._push_update()

    # ------------------------------------------------------------ scheduler

    def _sched_loop(self) -> None:
        now = time.monotonic()
        next_probe = now + self.probe_interval
        next_gossip = now + self.gossip_interval
        next_push_pull = now + self.push_pull_interval
        while not self._closed.wait(_TICK):
            now = time.monotonic()
            self._expire_acks(now)
            self._expire_suspicion(now)
            if now >= next_gossip:
                next_gossip = now + self.gossip_interval
                self._gossip_tick()
            if now >= next_probe:
                next_probe = now + self.probe_interval
                target = self._next_probe_target()
                if target:
                    threading.Thread(
                        target=self._probe, args=(target,), daemon=True,
                        name="ml-probe",
                    ).start()
            if now >= next_push_pull:
                next_push_pull = now + self.push_pull_interval
                peer = self._random_alive_endpoint()
                if peer:
                    threading.Thread(
                        target=self._push_pull_safely, args=(peer,),
                        daemon=True, name="ml-pushpull",
                    ).start()

    def _expire_acks(self, now: float) -> None:
        with self._lock:
            stale = [s for s, (dl, _) in self._acks.items() if now > dl]
            for s in stale:
                del self._acks[s]

    def _expire_suspicion(self, now: float) -> None:
        expired: List[NodeState] = []
        with self._lock:
            for n in self._nodes.values():
                if n.state == wire.STATE_SUSPECT and \
                        now >= n.suspicion_deadline:
                    expired.append(n)
        for n in expired:
            self._on_dead(n.incarnation, n.name, self.name)

    def _gossip_tick(self) -> None:
        with self._lock:
            if not self._bcast:
                return
            candidates = [
                n for n in self._nodes.values()
                if n.name != self.name and (
                    n.state != wire.STATE_DEAD
                    or time.monotonic() - n.state_change < 30.0
                )
            ]
        random.shuffle(candidates)
        for n in candidates[: self.gossip_nodes]:
            parts = self._take_broadcasts(_UDP_BUDGET - 7)
            if not parts:
                return
            try:
                self._udp.sendto(wire.assemble_packet(parts), n.endpoint())
            except OSError:
                pass

    # ---------------------------------------------------------------- probe

    def _next_probe_target(self) -> Optional[NodeState]:
        with self._lock:
            while True:
                if not self._probe_ring:
                    self._probe_ring = [
                        n for n in self._nodes if n != self.name
                    ]
                    random.shuffle(self._probe_ring)
                    if not self._probe_ring:
                        return None
                name = self._probe_ring.pop()
                node = self._nodes.get(name)
                if node and node.state != wire.STATE_DEAD:
                    return node
                if not self._probe_ring:
                    return None

    def _ping_once(self, node: NodeState, timeout: float) -> bool:
        seq = self._next_seq()
        got = threading.Event()
        with self._lock:
            self._acks[seq] = (
                time.monotonic() + timeout, lambda _p: got.set()
            )
        self._send_udp(node.endpoint(), wire.encode_msg(wire.PING, {
            "SeqNo": seq, "Node": node.name,
            "SourceAddr": self._addr_bytes, "SourcePort": self.bound_port,
            "SourceNode": self.name,
        }))
        return got.wait(timeout)

    def _probe(self, node: NodeState) -> None:
        if self._ping_once(node, self.probe_timeout):
            return
        # indirect probes through up to `indirect_checks` alive relays
        with self._lock:
            relays = [
                n for n in self._nodes.values()
                if n.state == wire.STATE_ALIVE
                and n.name not in (self.name, node.name)
            ]
        random.shuffle(relays)
        got = threading.Event()
        seq = self._next_seq()
        with self._lock:
            self._acks[seq] = (
                time.monotonic() + self.probe_interval, lambda _p: got.set()
            )
        for relay in relays[: self.indirect_checks]:
            self._send_udp(relay.endpoint(), wire.encode_msg(
                wire.INDIRECT_PING, {
                    "SeqNo": seq, "Target": node.addr, "Port": node.port,
                    "Node": node.name, "Nack": True,
                    "SourceAddr": self._addr_bytes,
                    "SourcePort": self.bound_port, "SourceNode": self.name,
                }))
        # TCP fallback ping, the way memberlist covers UDP-hostile paths
        tcp_ok = self._tcp_ping(node)
        if got.wait(self.probe_timeout) or tcp_ok:
            return
        if self._closed.is_set():
            return
        self._on_suspect(node.incarnation, node.name)

    def _tcp_ping(self, node: NodeState) -> bool:
        seq = self._next_seq()
        try:
            with socket.create_connection(
                node.endpoint(), timeout=self.probe_timeout
            ) as conn:
                conn.sendall(self._stream_out(wire.encode_msg(wire.PING, {
                    "SeqNo": seq, "Node": node.name,
                    "SourceAddr": self._addr_bytes,
                    "SourcePort": self.bound_port, "SourceNode": self.name,
                })))
                conn.settimeout(self.probe_timeout)
                t, parsed = _read_stream_message(conn, self.probe_timeout,
                                                 keyring=self._keyring)
                if t != wire.ACK_RESP:
                    return False
                return int(parsed.get("SeqNo", -1)) == seq
        except (OSError, wire.WireError, ValueError, TypeError):
            return False

    # ------------------------------------------------------------ push/pull

    def _local_states(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {
                    "Name": n.name, "Addr": n.addr, "Port": n.port,
                    "Meta": n.meta, "Incarnation": n.incarnation,
                    "State": n.state, "Vsn": wire.DEFAULT_VSN,
                }
                for n in self._nodes.values()
            ]

    def _merge_states(self, states: List[Dict[str, Any]]) -> None:
        for s in states:
            state = int(s.get("State", wire.STATE_ALIVE))
            alive_shaped = {
                "Incarnation": s.get("Incarnation", 0),
                "Node": s.get("Name", ""), "Addr": s.get("Addr", b""),
                "Port": s.get("Port", 0), "Meta": s.get("Meta", b""),
                "Vsn": s.get("Vsn", wire.DEFAULT_VSN),
            }
            if state == wire.STATE_ALIVE:
                self._on_alive(alive_shaped)
            elif state == wire.STATE_SUSPECT:
                self._on_alive(alive_shaped)
                self._on_suspect(int(s.get("Incarnation", 0)),
                                 str(s.get("Name", "")))
            elif state == wire.STATE_DEAD:
                # make the node known first so the death can be recorded
                self._on_alive(alive_shaped)
                self._on_dead(int(s.get("Incarnation", 0)),
                              str(s.get("Name", "")), "")

    def push_pull(self, host: str, port: int, join: bool = False) -> int:
        """One TCP state exchange with host:port; returns nodes merged."""
        with socket.create_connection((host, port), timeout=5.0) as conn:
            conn.sendall(self._stream_out(
                wire.encode_push_pull(self._local_states(), join)))
            t, parsed = _read_stream_message(conn, 5.0,
                                             keyring=self._keyring)
        if t != wire.PUSH_PULL:
            raise wire.WireError(f"push/pull reply was msg type {t}")
        states, _join, _user = parsed
        self._merge_states(states)
        self._push_update()
        return len(states)

    def _push_pull_safely(self, peer: Tuple[str, int]) -> None:
        try:
            self.push_pull(peer[0], peer[1])
        except (OSError, wire.WireError, ValueError, TypeError,
                KeyError, OverflowError) as exc:
            log.debug("push/pull with %s failed: %s", peer, exc)

    def _random_alive_endpoint(self) -> Optional[Tuple[str, int]]:
        with self._lock:
            alive = [
                n for n in self._nodes.values()
                if n.name != self.name and n.state == wire.STATE_ALIVE
            ]
        return random.choice(alive).endpoint() if alive else None

    def join(self, known_nodes: Sequence[str]) -> int:
        """Push/pull every seed (host or host:port; bare hosts get our
        bind port, reference: config.go:186-190).  Returns successes."""
        ok = 0
        for seed in known_nodes:
            host, _, port = seed.rpartition(":") if ":" in seed else (seed, "", "")
            try:
                self.push_pull(host or seed, int(port or self.bound_port),
                               join=True)
                ok += 1
            except (OSError, wire.WireError, ValueError, TypeError,
                    KeyError, OverflowError) as exc:
                log.warning("join %s failed: %s", seed, exc)
        return ok

    # ------------------------------------------------------------- TCP loop

    def _tcp_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _src = self._tcp.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="ml-conn",
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(5.0)
                t, parsed = _read_stream_message(conn, 5.0,
                                                 keyring=self._keyring)
                if t == wire.PUSH_PULL:
                    states, _join, _user = parsed
                    # reply first: the peer reads our state before merging
                    conn.sendall(self._stream_out(
                        wire.encode_push_pull(self._local_states(), False)))
                    self._merge_states(states)
                    self._push_update()
                elif t == wire.PING:
                    conn.sendall(self._stream_out(wire.encode_msg(
                        wire.ACK_RESP, {
                            "SeqNo": parsed.get("SeqNo", 0), "Payload": b"",
                        })))
        except (OSError, wire.WireError, msgpack.OutOfData, ValueError,
                TypeError, KeyError, OverflowError) as exc:
            log.debug("stream conn failed: %s", exc)

    # ------------------------------------------------------------ membership

    def _push_update(self) -> None:
        # _push_lock serializes compute -> compare -> callback across the
        # rx/tick/push-pull threads; without it a stale peer list could be
        # published LAST and stick until the next membership change
        with self._push_lock:
            peers: List[PeerInfo] = []
            with self._lock:
                for n in self._nodes.values():
                    if n.state == wire.STATE_DEAD:
                        continue
                    try:
                        dc, gport = wire.gob_decode_metadata(n.meta)
                    except wire.WireError as exc:
                        # same stance as the reference: a member with
                        # unreadable metadata is logged and not routed to
                        # (reference: memberlist.go:138-143)
                        log.warning("bad metadata from %r: %s", n.name, exc)
                        continue
                    if not gport:
                        continue
                    peers.append(PeerInfo(
                        address=f"{n.endpoint()[0]}:{gport}", datacenter=dc))
            peers.sort(key=lambda p: p.address)
            if peers == self._last_pushed:
                return
            self._last_pushed = peers
            try:
                self.on_update(list(peers))
            except Exception:  # noqa: BLE001
                log.exception("peer update callback failed")

    def members(self) -> Dict[str, NodeState]:
        with self._lock:
            return {k: dataclasses.replace(v) for k, v in self._nodes.items()}

    def leave(self, timeout: float = 1.0) -> None:
        """Graceful exit: broadcast dead-about-self (Node == From means
        intentional, reference semantics) and give gossip a moment."""
        self._leaving = True
        framed = wire.encode_msg(wire.DEAD, {
            "Incarnation": self._incarnation, "Node": self.name,
            "From": self.name,
        })
        self._queue_broadcast(self.name, framed)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                pending = self.name in self._bcast
            if not pending:
                break
            self._gossip_tick()
            time.sleep(min(0.05, self.gossip_interval))

    def close(self) -> None:
        if self._closed.is_set():
            return
        if not self._leaving:
            try:
                self.leave(timeout=0.5)
            except Exception:  # noqa: BLE001
                pass
        self._closed.set()
        with self._lock:
            timers, self._nack_timers = self._nack_timers, []
        for timer in timers:  # pending nacks must not outlive the sockets
            timer.cancel()
        for sock in (self._udp, self._tcp):
            try:
                sock.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=1.0)


# ---------------------------------------------------------------- streams

class _StreamBuf:
    """Buffered socket reader over one persistent Unpacker: each object
    is parsed exactly once and only NEW bytes are ever fed (linear in
    stream size, even for a 4096-state push/pull)."""

    def __init__(self, conn: socket.socket, deadline: float):
        self.conn = conn
        self.deadline = deadline
        self.up = msgpack.Unpacker(
            raw=True, strict_map_key=False, max_buffer_size=1 << 26)

    def _fill(self) -> None:
        if time.monotonic() > self.deadline:
            raise wire.WireError("stream read timed out")
        chunk = self.conn.recv(65536)
        if not chunk:
            raise wire.WireError("stream closed mid-message")
        self.up.feed(chunk)

    def next_obj(self) -> Any:
        while True:
            try:
                return self.up.unpack()
            except msgpack.OutOfData:
                self._fill()

    def read_exact(self, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            got = self.up.read_bytes(n - len(out))
            if got:
                out.extend(got)
            else:
                self._fill()
        return bytes(out)


_MAX_STREAM_ENC = 1 << 25  # like memberlist's maxPushStateBytes bound


def _parse_stream_bytes(data: bytes, depth: int = 0) -> Tuple[int, Any]:
    """Parse one fully-buffered stream message (the decrypted form) ->
    (type, parsed); same contract as _read_stream_message."""
    if not data:
        raise wire.WireError("empty stream message")
    if depth > 2:
        raise wire.WireError("stream nesting too deep")
    t = data[0]
    if t == wire.COMPRESS:
        body = wire.decode_body(t, data[1:])
        if body.get("Algo", 0) != 0:
            raise wire.WireError("unknown stream compression algo")
        raw = body.get("Buf", b"")
        if not isinstance(raw, bytes) or not raw:
            raise wire.WireError("empty compressed stream")
        return _parse_stream_bytes(wire.lzw_decompress(raw), depth + 1)
    if t == wire.ENCRYPT:
        raise wire.WireError("nested encrypted stream")
    if t == wire.PUSH_PULL:
        return t, wire.decode_push_pull(data[1:])
    return t, wire.decode_body(t, data[1:])


def _read_stream_message(
    conn: socket.socket, timeout: float,
    keyring: Optional[List[bytes]] = None,
) -> Tuple[int, Any]:
    """Read one framed message off a TCP stream -> (type, parsed).

    parsed is (states, join, user_state) for PUSH_PULL and the body dict
    for everything else.  Handles the compressMsg wrapping a
    default-config Go sender applies to whole streams:
    [0x09][msgpack compress{Algo,Buf}] where Buf decompresses to
    [real type][real body], and — under a keyring — the encryptMsg
    stream frame [0x0a][u32 length][vsn|nonce|ct] whose 5-byte header is
    the GCM AAD (security.go decryptRemoteState). An encrypted fleet
    refuses plaintext streams (GossipVerifyIncoming's default)."""
    r = _StreamBuf(conn, time.monotonic() + timeout)
    first = r.read_exact(1)[0]
    if first == wire.ENCRYPT:
        if not keyring:
            raise wire.WireError("encrypted stream (no keyring configured)")
        size_bytes = r.read_exact(4)
        n = struct.unpack(">I", size_bytes)[0]
        if not 0 < n <= _MAX_STREAM_ENC:
            raise wire.WireError("encrypted stream length out of range")
        aad = bytes([wire.ENCRYPT]) + size_bytes
        plain = wire.decrypt_payload(keyring, r.read_exact(n), aad=aad)
        return _parse_stream_bytes(plain)
    if keyring:
        raise wire.WireError("plaintext stream on an encrypted fleet")
    if first == wire.COMPRESS:
        body = wire._norm(wire.COMPRESS, r.next_obj())
        if body.get("Algo", 0) != 0:
            raise wire.WireError("unknown stream compression algo")
        raw = body.get("Buf", b"")
        if not isinstance(raw, bytes) or not raw:
            raise wire.WireError("empty compressed stream")
        return _parse_stream_bytes(wire.lzw_decompress(raw))
    if first == wire.PUSH_PULL:
        header = wire._norm(wire.PUSH_PULL, r.next_obj())
        n = int(header.get("Nodes", 0))
        user_len = int(header.get("UserStateLen", 0))
        if not 0 <= n <= 4096 or not 0 <= user_len <= (1 << 24):
            raise wire.WireError("push/pull header out of range")
        states = [wire._norm(wire.PUSH_PULL, r.next_obj()) for _ in range(n)]
        user = r.read_exact(user_len) if user_len else b""
        return first, (states, bool(header.get("Join", False)), user)
    # fixed single-object messages (stream ping / ack / err)
    return first, wire._norm(first, r.next_obj())
