"""hashicorp/memberlist v0.2.0 wire codec — pure functions, no sockets.

The reference's MemberlistPool (reference: memberlist.go:36-78) delegates
membership to github.com/hashicorp/memberlist v0.2.0 (reference:
go.mod:9).  Interop therefore needs that library's exact wire format, NOT
its Go API.  This module implements the format from the protocol's
published structure so a gubernator_tpu node can join an existing
memberlist fleet:

- message framing: one type byte, then a go-msgpack (codec) body.
  go-msgpack v0.5.3 (reference: go.sum:98) speaks the OLD msgpack spec:
  structs are maps keyed by exported field name, strings AND []byte both
  use the raw family (0xa0-0xbf/0xda/0xdb) — never bin8/str8.  msgpack-
  python produces exactly that with use_bin_type=False, and raw=True on
  decode keeps []byte fields (Addr, Meta, Vsn) byte-exact.
- compound packets: [0x07][count u8][count × u16be lengths][parts].
- CRC framing: [0x0c][crc32-ieee u32be][payload] (verified + stripped).
- compression: compress{Algo: 0 (lzw), Buf} wrapping, where Buf is
  compress/lzw LSB litWidth=8 — variable 9..12-bit codes, clear=256,
  eof=257, "late" width change, clear-code reset at 4095 — implemented
  here byte-compatibly (tests/test_memberlist.py pins golden vectors).
- node metadata: the reference gob-encodes {DataCenter, GubernatorPort}
  into Node.Meta (reference: memberlist.go:193-226); gob_encode_metadata/
  gob_decode_metadata speak that stream (typedef message + value message,
  validated against the gob wire spec's published struct example).

Every decoder here is fed attacker-reachable bytes from UDP/TCP; all of
them bound allocations and raise WireError (never segfault, never hang)
on malformed input.
"""

from __future__ import annotations

import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

import msgpack

# ---------------------------------------------------------------- messages

PING = 0
INDIRECT_PING = 1
ACK_RESP = 2
SUSPECT = 3
ALIVE = 4
DEAD = 5
PUSH_PULL = 6
COMPOUND = 7
USER = 8
COMPRESS = 9
ENCRYPT = 10
NACK_RESP = 11
HAS_CRC = 12
ERR = 13

# node states carried in pushNodeState.State (memberlist v0.2.0)
STATE_ALIVE = 0
STATE_SUSPECT = 1
STATE_DEAD = 2

# alive.Vsn layout: [pmin, pmax, pcur, dmin, dmax, dcur]; defaults for a
# config that sets none of the protocol knobs (the reference sets none).
DEFAULT_VSN = bytes([1, 5, 2, 0, 0, 0])

MAX_UDP_PACKET = 65536
MAX_DECOMPRESSED = 1 << 22


class WireError(ValueError):
    """Malformed or unsupported memberlist wire bytes."""


def pack(obj: Any) -> bytes:
    """Old-spec msgpack bytes (what go-msgpack v0.5.3 decodes)."""
    return msgpack.packb(obj, use_bin_type=False)


# Fields whose values are UTF-8 text in the Go structs; everything else
# raw stays bytes (Addr/Target/Meta/Vsn/Payload/Buf are []byte in Go).
_TEXT_FIELDS = {"Node", "SourceNode", "From", "Name", "Error"}


def _norm(t: int, obj: Any) -> Dict[str, Any]:
    if not isinstance(obj, dict):
        raise WireError(f"msg type {t}: body is not a struct map")
    out: Dict[str, Any] = {}
    for k, v in obj.items():
        if isinstance(k, bytes):
            k = k.decode("utf-8", errors="replace")
        if not isinstance(k, str):
            raise WireError(f"msg type {t}: non-string field key")
        if k in _TEXT_FIELDS and isinstance(v, bytes):
            v = v.decode("utf-8", errors="replace")
        out[k] = v
    return out


def encode_msg(msg_type: int, body: Dict[str, Any]) -> bytes:
    """[type byte][old-spec msgpack body] — the unit every framing wraps."""
    return bytes([msg_type]) + pack(body)


def decode_body(msg_type: int, body: bytes) -> Dict[str, Any]:
    try:
        obj = msgpack.unpackb(body, raw=True, strict_map_key=False)
    except Exception as exc:  # noqa: BLE001 - any unpack failure is WireError
        raise WireError(f"msgpack: {exc}") from exc
    return _norm(msg_type, obj)


# ---------------------------------------------------------------- compound

def make_compound(parts: List[bytes]) -> bytes:
    if not 0 < len(parts) <= 255:
        raise WireError(f"compound of {len(parts)} parts")
    out = [bytes([COMPOUND, len(parts)])]
    for p in parts:
        if len(p) > 0xFFFF:
            raise WireError("compound part over 64KiB")
        out.append(struct.pack(">H", len(p)))
    out.extend(parts)
    return b"".join(out)


def split_compound(buf: bytes) -> List[bytes]:
    if len(buf) < 1:
        raise WireError("truncated compound")
    n, off = buf[0], 1
    if len(buf) < off + 2 * n:
        raise WireError("truncated compound lengths")
    lens = struct.unpack(f">{n}H", buf[off:off + 2 * n])
    off += 2 * n
    parts = []
    for ln in lens:
        if len(buf) < off + ln:
            raise WireError("truncated compound part")
        parts.append(buf[off:off + ln])
        off += ln
    return parts


# ---------------------------------------------------------------- LZW (Go compress/lzw, LSB, litWidth=8)

_CLEAR = 256
_EOF = 257
_MAX_CODE = (1 << 12) - 1


def lzw_compress(data: bytes) -> bytes:
    out = bytearray()
    acc = nbits = 0
    width = 9
    hi = _EOF
    overflow = 1 << 9
    table: Dict[int, int] = {}

    def emit(code: int) -> None:
        nonlocal acc, nbits
        acc |= code << nbits
        nbits += width
        while nbits >= 8:
            out.append(acc & 0xFF)
            acc >>= 8
            nbits -= 8

    def inc_hi() -> bool:
        # Returns False when the table was reset (no new entry may be
        # added this step) — Go's errOutOfCodes path.
        nonlocal hi, width, overflow, table
        hi += 1
        if hi == overflow:
            width += 1
            overflow <<= 1
        if hi == _MAX_CODE:
            emit(_CLEAR)
            width, hi, overflow = 9, _EOF, 1 << 9
            table = {}
            return False
        return True

    seq = -1
    for b in data:
        if seq < 0:
            seq = b
            continue
        key = (seq << 8) | b
        nxt = table.get(key)
        if nxt is not None:
            seq = nxt
            continue
        emit(seq)
        if inc_hi():
            table[key] = hi
        seq = b
    if seq >= 0:
        emit(seq)
        inc_hi()
    emit(_EOF)
    if nbits > 0:
        out.append(acc & 0xFF)
    return bytes(out)


def lzw_decompress(data: bytes, max_out: int = MAX_DECOMPRESSED) -> bytes:
    out = bytearray()
    acc = nbits = 0
    width = 9
    hi = _EOF
    overflow = 1 << 9
    last = -1
    # code -> (prefix code, suffix byte); literals implicit
    prefix = {}
    suffix = {}
    pos = 0
    n = len(data)
    while True:
        while nbits < width:
            if pos >= n:
                # Go returns io.ErrUnexpectedEOF here; trailing padding
                # after the eof code never reaches this loop.
                raise WireError("lzw: truncated stream")
            acc |= data[pos] << nbits
            pos += 1
            nbits += 8
        code = acc & ((1 << width) - 1)
        acc >>= width
        nbits -= width

        if code < _CLEAR:
            out.append(code)
            if last >= 0:
                prefix[hi] = last
                suffix[hi] = code
        elif code == _CLEAR:
            width, hi, overflow, last = 9, _EOF, 1 << 9, -1
            prefix.clear()
            suffix.clear()
            continue
        elif code == _EOF:
            return bytes(out)
        elif code <= hi:
            chunk = bytearray()
            c = code
            if code == hi and last >= 0:
                # KwKwK: expands to last expansion + its first byte
                c = last
                while c >= _CLEAR:
                    c = prefix[c]
                chunk.append(c)
                c = last
            while c >= _CLEAR:
                chunk.append(suffix[c])
                c = prefix[c]
            chunk.append(c)
            chunk.reverse()
            first = chunk[0]
            out.extend(chunk)
            if last >= 0:
                prefix[hi] = last
                suffix[hi] = first
        else:
            raise WireError("lzw: invalid code")
        last = code
        hi += 1
        if hi >= overflow:
            if width == 12:
                # writer is obliged to send a clear before overflowing
                last = -1
                hi -= 1
            else:
                width += 1
                overflow <<= 1
        if len(out) > max_out:
            raise WireError("lzw: output over limit")


# ---------------------------------------------------------------- encryption
# hashicorp/memberlist packet encryption (security.go): AES-GCM under a
# keyring, payload = [version byte][12-byte nonce][ciphertext || 16-byte
# tag]. Version 0 PKCS7-pads the plaintext to the AES block; version 1
# (what protocol >= 2 speaks — our DEFAULT_VSN advertises protocol 2+)
# sends it raw. On UDP the whole assembled packet is encrypted as the
# OUTERMOST layer (AAD empty, v0.2.0 predates packet labels); on TCP the
# stream body rides an [encryptMsg][u32 length][payload] frame whose
# 5-byte header is the GCM AAD (security.go encryptLocalState /
# decryptRemoteState).

NONCE_SIZE = 12
TAG_SIZE = 16
_AES_BLOCK = 16
ENC_V0 = 0
ENC_V1 = 1


def _aesgcm(key: bytes):
    if len(key) not in (16, 24, 32):
        raise WireError(
            f"memberlist SecretKey must be 16, 24 or 32 bytes (AES-128/"
            f"192/256), got {len(key)}")
    try:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    except ImportError as exc:  # pragma: no cover - baked into the image
        raise WireError(f"AES-GCM unavailable: {exc}") from exc
    return AESGCM(key)


def encrypted_length(vsn: int, msg_len: int) -> int:
    """Size of encrypt_payload's output for a plaintext of msg_len."""
    if vsn == ENC_V0:  # PKCS7 always pads 1..16 bytes
        pad = _AES_BLOCK - (msg_len % _AES_BLOCK)
        return 1 + NONCE_SIZE + msg_len + pad + TAG_SIZE
    return 1 + NONCE_SIZE + msg_len + TAG_SIZE


def encrypt_payload(key: bytes, plaintext: bytes, aad: bytes = b"",
                    vsn: int = ENC_V1, _nonce: Optional[bytes] = None
                    ) -> bytes:
    """[vsn][nonce][GCM ct||tag] with the keyring's primary key.
    `_nonce` pins the nonce for golden-vector tests ONLY."""
    if vsn not in (ENC_V0, ENC_V1):
        raise WireError(f"unsupported encryption version {vsn}")
    import os as _os
    nonce = _os.urandom(NONCE_SIZE) if _nonce is None else _nonce
    if len(nonce) != NONCE_SIZE:
        raise WireError("bad nonce size")
    if vsn == ENC_V0:
        pad = _AES_BLOCK - (len(plaintext) % _AES_BLOCK)
        plaintext = plaintext + bytes([pad]) * pad
    ct = _aesgcm(key).encrypt(nonce, plaintext, aad or None)
    return bytes([vsn]) + nonce + ct


def decrypt_payload(keys: List[bytes], payload: bytes,
                    aad: bytes = b"") -> bytes:
    """Try every keyring key (newest-first, like memberlist's keyring)
    against one [vsn][nonce][ct||tag] payload."""
    if len(payload) < 1 + NONCE_SIZE + TAG_SIZE:
        raise WireError("encrypted payload truncated")
    vsn = payload[0]
    if vsn not in (ENC_V0, ENC_V1):
        raise WireError(f"unsupported encryption version {vsn}")
    nonce = payload[1:1 + NONCE_SIZE]
    ct = payload[1 + NONCE_SIZE:]
    from cryptography.exceptions import InvalidTag
    for key in keys:
        try:
            plain = _aesgcm(key).decrypt(nonce, ct, aad or None)
            break
        except InvalidTag:
            continue
    else:
        raise WireError("no keyring key decrypts this payload")
    if vsn == ENC_V0:
        if not plain:
            raise WireError("empty padded plaintext")
        pad = plain[-1]
        if not 1 <= pad <= _AES_BLOCK or len(plain) < pad:
            raise WireError("bad PKCS7 padding")
        plain = plain[:-pad]
    return plain


def encrypt_stream_frame(key: bytes, body: bytes, vsn: int = ENC_V1
                         ) -> bytes:
    """TCP framing: [encryptMsg][u32 BE encrypted-length][payload], the
    5-byte header doubling as GCM AAD (security.go encryptLocalState)."""
    header = bytes([ENCRYPT]) + struct.pack(
        ">I", encrypted_length(vsn, len(body)))
    return header + encrypt_payload(key, body, aad=header, vsn=vsn)


# ---------------------------------------------------------------- packet assembly / ingest

def wrap_compress(payload: bytes) -> bytes:
    """compress{Algo: lzw(0), Buf} framing — used only when smaller."""
    return encode_msg(COMPRESS, {"Algo": 0, "Buf": lzw_compress(payload)})


def wrap_crc(payload: bytes) -> bytes:
    return bytes([HAS_CRC]) + struct.pack(">I", zlib.crc32(payload)) + payload


def assemble_packet(
    parts: List[bytes], compress: bool = True, crc: bool = True,
    key: Optional[bytes] = None
) -> bytes:
    """One UDP datagram from framed messages, the sender-side pipeline:
    compound (if >1) -> lzw (kept only if smaller, matching the Go
    sender) -> crc (receivers with protocol max >= 5 verify it) ->
    AES-GCM under `key` as the OUTERMOST layer (rawSendMsgPacket order)."""
    buf = parts[0] if len(parts) == 1 else make_compound(parts)
    if compress:
        comp = wrap_compress(buf)
        if len(comp) < len(buf):
            buf = comp
    if crc:
        buf = wrap_crc(buf)
    if key is not None:
        buf = encrypt_payload(key, buf)
    return buf


def ingest_packet(
    buf: bytes, depth: int = 0, budget: Optional[List[int]] = None,
    keyring: Optional[List[bytes]] = None
) -> List[Tuple[int, Dict[str, Any]]]:
    """Decode one UDP datagram into [(msg_type, body), ...], unwrapping
    crc / compress / compound recursively the way the Go receiver does.
    A `keyring` decrypts the whole datagram FIRST (encryption is the
    outermost layer; an encrypted fleet rejects plaintext, matching
    GossipVerifyIncoming's default).

    `budget` is a shared one-element mutable cell of decompressed bytes
    remaining for the WHOLE datagram: without it, a compound of 255
    compress parts could turn one 64 KB datagram into ~1 GB of
    sequential LZW work and stall the single receive thread."""
    if depth > 4:
        raise WireError("packet nesting too deep")
    if not buf:
        return []
    if depth == 0 and keyring:
        buf = decrypt_payload(keyring, buf)
        if not buf:
            return []
    if budget is None:
        budget = [MAX_DECOMPRESSED]
    t = buf[0]
    if t == HAS_CRC:
        if len(buf) < 5:
            raise WireError("truncated crc header")
        want = struct.unpack(">I", buf[1:5])[0]
        if zlib.crc32(buf[5:]) != want:
            raise WireError("crc mismatch")
        return ingest_packet(buf[5:], depth + 1, budget)
    if t == COMPRESS:
        body = decode_body(t, buf[1:])
        if body.get("Algo", 0) != 0:
            raise WireError(f"unknown compression algo {body.get('Algo')}")
        raw = body.get("Buf", b"")
        if not isinstance(raw, bytes):
            raise WireError("compress.Buf is not bytes")
        if budget[0] <= 0:
            raise WireError("datagram decompression budget exhausted")
        out = lzw_decompress(raw, max_out=budget[0])
        budget[0] -= len(out)
        return ingest_packet(out, depth + 1, budget)
    if t == COMPOUND:
        msgs: List[Tuple[int, Dict[str, Any]]] = []
        for part in split_compound(buf[1:]):
            msgs.extend(ingest_packet(part, depth + 1, budget))
        return msgs
    if t == ENCRYPT:
        raise WireError("encrypted packet (no keyring configured)")
    return [(t, decode_body(t, buf[1:]))]


# ---------------------------------------------------------------- push/pull stream bodies

def encode_push_pull(
    states: List[Dict[str, Any]], join: bool, user_state: bytes = b""
) -> bytes:
    """[pushPullMsg][header][N node states][user state] — the TCP state
    sync body both sides exchange (join=True on the joining side)."""
    out = [bytes([PUSH_PULL])]
    out.append(pack({
        "Nodes": len(states), "UserStateLen": len(user_state), "Join": join,
    }))
    for s in states:
        out.append(pack(s))
    out.append(user_state)
    return b"".join(out)


def decode_push_pull(body: bytes) -> Tuple[List[Dict[str, Any]], bool, bytes]:
    """Parse everything after the pushPullMsg type byte."""
    up = msgpack.Unpacker(raw=True, strict_map_key=False,
                          max_buffer_size=1 << 26)
    up.feed(body)
    try:
        header = _norm(PUSH_PULL, up.unpack())
        n = int(header.get("Nodes", 0))
        user_len = int(header.get("UserStateLen", 0))
        if not 0 <= n <= 4096 or not 0 <= user_len <= (1 << 24):
            raise WireError("push/pull header out of range")
        states = [_norm(PUSH_PULL, up.unpack()) for _ in range(n)]
        user = up.read_bytes(user_len) if user_len else b""
    except WireError:
        raise
    except Exception as exc:  # noqa: BLE001
        raise WireError(f"push/pull: {exc}") from exc
    if len(user) != user_len:
        raise WireError("truncated user state")
    return states, bool(header.get("Join", False)), bytes(user)


# ---------------------------------------------------------------- gob metadata
#
# encoding/gob stream for the single struct the reference stores in
# Node.Meta (reference: memberlist.go:193-209):
#
#   type memberlistMetadata struct { DataCenter string; GubernatorPort int }
#
# Stream = [typedef message for user type 65][value message].  Each
# message is uint(length) + payload; a typedef payload is int(-65) + the
# wireType descriptor; a value payload is int(+65) + the struct fields as
# (field delta, value) pairs with zero fields omitted and a 0 terminator.

_GOB_TSTRING = 6
_GOB_TINT = 2
_GOB_USER_ID = 65


def _gob_uint(n: int) -> bytes:
    if n < 0:
        raise WireError("gob uint < 0")
    if n < 128:
        return bytes([n])
    raw = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([256 - len(raw)]) + raw


def _gob_int(i: int) -> bytes:
    u = (i << 1) if i >= 0 else (((-i) << 1) - 1)
    return _gob_uint(u)


def _gob_string(s: str) -> bytes:
    raw = s.encode("utf-8")
    return _gob_uint(len(raw)) + raw


def _gob_field_type(name: str, type_id: int) -> bytes:
    # fieldType{Name(0), Id(1)} + terminator
    return b"\x01" + _gob_string(name) + b"\x01" + _gob_int(type_id) + b"\x00"


def _gob_message(payload: bytes) -> bytes:
    return _gob_uint(len(payload)) + payload


def gob_encode_metadata(datacenter: str, gubernator_port: int) -> bytes:
    # typedef: wireType{StructT(2): StructType{CommonType{Name, Id},
    #                                          Field: []fieldType}}
    struct_t = (
        b"\x01"  # StructType field 0: CommonType
        + b"\x01" + _gob_string("memberlistMetadata")
        + b"\x01" + _gob_int(_GOB_USER_ID)
        + b"\x00"
        + b"\x01"  # StructType field 1: Field slice
        + _gob_uint(2)
        + _gob_field_type("DataCenter", _GOB_TSTRING)
        + _gob_field_type("GubernatorPort", _GOB_TINT)
        + b"\x00"  # end StructType
    )
    typedef = _gob_int(-_GOB_USER_ID) + b"\x03" + struct_t + b"\x00"

    fields = b""
    delta = 1
    if datacenter:
        fields += bytes([delta]) + _gob_string(datacenter)
        delta = 1
    else:
        delta = 2
    if gubernator_port:
        fields += bytes([delta]) + _gob_int(gubernator_port)
    value = _gob_int(_GOB_USER_ID) + fields + b"\x00"
    return _gob_message(typedef) + _gob_message(value)


class _GobReader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def uint(self) -> int:
        if self.pos >= len(self.buf):
            raise WireError("gob: truncated uint")
        b = self.buf[self.pos]
        self.pos += 1
        if b < 128:
            return b
        n = 256 - b
        if n > 8 or self.pos + n > len(self.buf):
            raise WireError("gob: bad uint length")
        v = int.from_bytes(self.buf[self.pos:self.pos + n], "big")
        self.pos += n
        return v

    def int_(self) -> int:
        u = self.uint()
        return -( (u + 1) >> 1) if (u & 1) else (u >> 1)

    def string(self) -> str:
        n = self.uint()
        if n > 1 << 16 or self.pos + n > len(self.buf):
            raise WireError("gob: bad string length")
        s = self.buf[self.pos:self.pos + n]
        self.pos += n
        return s.decode("utf-8", errors="replace")


def _gob_parse_typedef(r: _GobReader) -> Dict[int, Tuple[str, int]]:
    """Parse a wireType struct -> {field number: (name, type id)}."""
    fields: Dict[int, Tuple[str, int]] = {}
    wt_field = -1
    while True:
        delta = r.uint()
        if delta == 0:
            break
        wt_field += delta
        if wt_field != 2:  # only StructT is expected / supported
            raise WireError(f"gob: unsupported wireType field {wt_field}")
        st_field = -1
        while True:
            d = r.uint()
            if d == 0:
                break
            st_field += d
            if st_field == 0:  # CommonType {Name, Id}
                ct_field = -1
                while True:
                    dd = r.uint()
                    if dd == 0:
                        break
                    ct_field += dd
                    if ct_field == 0:
                        r.string()
                    elif ct_field == 1:
                        r.int_()
                    else:
                        raise WireError("gob: bad CommonType")
            elif st_field == 1:  # Field []fieldType
                count = r.uint()
                if count > 256:
                    raise WireError("gob: too many fields")
                for i in range(count):
                    name, tid = "", 0
                    ft_field = -1
                    while True:
                        dd = r.uint()
                        if dd == 0:
                            break
                        ft_field += dd
                        if ft_field == 0:
                            name = r.string()
                        elif ft_field == 1:
                            tid = r.int_()
                        else:
                            raise WireError("gob: bad fieldType")
                    fields[i] = (name, tid)
            else:
                raise WireError("gob: bad StructType")
    return fields


def gob_decode_metadata(buf: bytes) -> Tuple[str, int]:
    """Tolerant decode of the reference's gob metadata -> (datacenter,
    gubernator_port).  Raises WireError on anything else."""
    fields: Dict[int, Tuple[str, int]] = {}
    r = _GobReader(buf)
    for _ in range(8):  # bounded number of messages
        if r.pos >= len(r.buf):
            break
        length = r.uint()
        end = r.pos + length
        if length > len(r.buf) - r.pos:
            raise WireError("gob: truncated message")
        type_id = r.int_()
        if type_id < 0:
            fields = _gob_parse_typedef(r)
            if r.pos != end:
                raise WireError("gob: typedef trailing bytes")
            continue
        # value message: struct fields by (delta, typed value)
        dc, port = "", 0
        fnum = -1
        while True:
            delta = r.uint()
            if delta == 0:
                break
            fnum += delta
            name, tid = fields.get(fnum, ("", 0))
            if name == "DataCenter" or (not fields and fnum == 0):
                dc = r.string()
            elif name == "GubernatorPort" or (not fields and fnum == 1):
                port = r.int_()
            elif tid == _GOB_TSTRING:
                r.string()
            elif tid == _GOB_TINT:
                r.int_()
            else:
                raise WireError(f"gob: unknown field {fnum}")
        return dc, port
    raise WireError("gob: no value message")
