from gubernator_tpu.cluster.pickers import (
    ConsistentHashPicker,
    RegionPicker,
    ReplicatedConsistentHashPicker,
    crc32_hash,
    fnv1_32,
    fnv1a_32,
)

__all__ = [
    "ConsistentHashPicker",
    "ReplicatedConsistentHashPicker",
    "RegionPicker",
    "crc32_hash",
    "fnv1_32",
    "fnv1a_32",
]
