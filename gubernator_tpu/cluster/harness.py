"""In-process multi-instance cluster harness.

Mirrors the reference's test strategy (reference: cluster/cluster.go:104-165,
functional_test.go:35-49): N real gRPC servers + Instances on loopback in one
process, peer lists injected directly (discovery bypassed), sync windows
tuned down to 50 ms so GLOBAL tests settle fast
(reference: cluster/cluster.go:57-66). `stop_instance_at` kills one server
WITHOUT updating peer lists, for fault-injection tests
(reference: cluster/cluster.go:93-96).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import grpc

from gubernator_tpu.models.engine import Engine
from gubernator_tpu.service.config import BehaviorConfig, InstanceConfig
from gubernator_tpu.service.grpc_api import close_channels
from gubernator_tpu.service.instance import Instance
from gubernator_tpu.service.metrics import Metrics
from gubernator_tpu.service.server import make_server
from gubernator_tpu.types import PeerInfo


def test_behaviors() -> BehaviorConfig:
    """Batch fast, sync at 50 ms (reference: cluster/cluster.go:57-66)."""
    # Wait windows are tuned down so async tests settle fast; RPC *timeouts*
    # stay generous — a first-touch XLA compile or CPU contention from N
    # in-process servers can exceed 500 ms, and a timed-out forward records a
    # peer error with a 5-minute TTL that poisons HealthCheck for the rest of
    # the cluster's life.
    return BehaviorConfig(
        batch_timeout_s=10.0,
        batch_wait_s=0.01,
        global_timeout_s=10.0,
        global_sync_wait_s=0.05,
        multi_region_timeout_s=10.0,
        multi_region_sync_wait_s=0.05,
        # gRPC ports are dynamic here, so a fixed link offset could collide
        # with another instance's port; peerlink tests wire it explicitly
        peer_link_offset=0,
        # breaker cooldown tracks the bounded channel-reconnect backoff
        # (grpc_api.CHANNEL_OPTIONS, ~1 s): a kill/restart harness reuses
        # PeerClients across the restart, so the production 5 s cooldown
        # would stall recovery past the soak's settle grace
        circuit_open_s=0.5,
    )


@dataclasses.dataclass
class ClusterInstance:
    address: str
    datacenter: str
    instance: Instance
    server: grpc.Server
    # per-instance registry so tests can assert histogram samples the way
    # the reference's GLOBAL test reads Collect() (functional_test.go:311-343)
    metrics: Optional[Metrics] = None

    def stop(self) -> None:
        # wait for full termination: stop() returns before the listening
        # socket closes, so a fault-injection test could still reach a
        # "dead" server for a few ms and flake
        self.server.stop(grace=0.2).wait()
        self.instance.close()
        # drop any cached client channel so a restart on the same port isn't
        # hit through a channel stuck in reconnect backoff
        close_channels(self.address)


def wire_peerlink(cluster: "LocalCluster"):
    """Attach a peerlink service to every instance at grpc port + one
    shared offset (the daemon's production convention) and point the
    instances' peer clients at it. Returns the service list (callers own
    closing them), or [] when no offset binds cleanly — gRPC then carries
    every peer call, exactly like a fleet with the link disabled."""
    from gubernator_tpu.service.peerlink import PeerLinkError, PeerLinkService

    ports = [int(ci.address.rsplit(":", 1)[1]) for ci in cluster.instances]
    for offset in (1000, 2000, 3000, 5000):
        attempt: List[PeerLinkService] = []
        try:
            for i, ci in enumerate(cluster.instances):
                attempt.append(
                    PeerLinkService(
                        ci.instance, port=ports[i] + offset,
                        wire_v2=getattr(
                            ci.instance.conf.behaviors, "wire_v2", None)))
        except PeerLinkError:
            for svc in attempt:
                svc.close()
            continue
        for ci in cluster.instances:
            ci.instance.conf.behaviors.peer_link_offset = offset
        return attempt
    return []


class LocalCluster:
    """A loopback cluster of real servers (reference: cluster/cluster.go)."""

    def __init__(self):
        self.instances: List[ClusterInstance] = []

    # ------------------------------------------------------------ lifecycle

    def start(self, n: int, datacenters: Optional[Sequence[str]] = None,
              capacity: int = 4096,
              behaviors: Optional[BehaviorConfig] = None) -> "LocalCluster":
        """Boot n instances on dynamic loopback ports and wire full peer
        lists (reference: cluster/cluster.go:104-128)."""
        datacenters = list(datacenters or [""] * n)
        for i in range(n):
            self.start_instance(datacenter=datacenters[i], capacity=capacity,
                                behaviors=behaviors)
        self.sync_peers()
        return self

    def start_instance(self, datacenter: str = "", capacity: int = 4096,
                       fixed_port: int = 0,
                       behaviors: Optional[BehaviorConfig] = None
                       ) -> ClusterInstance:
        """(reference: cluster/cluster.go:138-165)"""
        backend = Engine(capacity=capacity, min_width=32, max_width=256)
        backend.warmup()  # compile all width buckets before serving
        metrics = Metrics()
        backend.metrics = metrics  # engine phase histograms, as the daemon
        inst = Instance(
            InstanceConfig(
                behaviors=dataclasses.replace(behaviors) if behaviors
                else test_behaviors(),
                data_center=datacenter,
                backend=backend,
                metrics=metrics,
            ),
            advertise_address="pending",
        )
        server, port = make_server(inst, f"127.0.0.1:{fixed_port}")
        address = f"127.0.0.1:{port}"
        inst.advertise_address = address
        ci = ClusterInstance(
            address=address, datacenter=datacenter, instance=inst,
            server=server, metrics=metrics,
        )
        server.start()
        # a restart on a fixed port replaces the stopped entry, so
        # sync_peers/instance_for_host never see a dead duplicate address
        for i, old in enumerate(self.instances):
            if old.address == address:
                self.instances[i] = ci
                return ci
        self.instances.append(ci)
        return ci

    def sync_peers(self) -> None:
        """Push the full membership to every live instance
        (reference: cluster/cluster.go:124-127)."""
        infos = [
            PeerInfo(address=ci.address, datacenter=ci.datacenter)
            for ci in self.instances
        ]
        for ci in self.instances:
            ci.instance.set_peers(infos)

    def stop(self) -> None:
        for ci in self.instances:
            ci.stop()
        self.instances = []

    # -------------------------------------------------------------- helpers

    def peers(self) -> List[PeerInfo]:
        return [
            PeerInfo(address=ci.address, datacenter=ci.datacenter)
            for ci in self.instances
        ]

    def instance_for_host(self, address: str) -> Optional[ClusterInstance]:
        """(reference: cluster/cluster.go:84-91)"""
        for ci in self.instances:
            if ci.address == address:
                return ci
        return None

    def stop_instance_at(self, idx: int) -> None:
        """Kill one instance WITHOUT updating peers — fault injection
        (reference: cluster/cluster.go:93-96)."""
        self.instances[idx].stop()

    def owner_of(self, key: str) -> ClusterInstance:
        """The instance whose picker owns `key`."""
        peer = self.instances[0].instance.get_peer(key)
        ci = self.instance_for_host(peer.info.address)
        assert ci is not None
        return ci
