"""etcdlite: an embeddable server speaking the etcd v3 API subset we use.

Implements KV Range/Put/DeleteRange, Lease Grant/Revoke/KeepAlive, and
prefix Watch with prev_kv — the exact surface EtcdPool (cluster/etcd.py)
consumes — over the real etcd wire protocol (proto/etcd.proto). Two roles:

- test double: discovery tests run the full register/watch/lease-expiry
  lifecycle in-process with no external etcd (the reference never tests its
  etcd pool at all; reference: etcd.go has no _test.go);
- embedded membership server: a cluster without an etcd deployment can point
  every node's EtcdPool at one etcdlite (e.g. `gubernator-cluster --etcd`),
  accepting that it is a single-node, in-memory store — the same accepted
  tradeoff as the rate-limit state itself (reference: architecture.md:5-11).

Leases expire for real: a lapsed keep-alive deletes the lease's keys and
notifies watchers, so peer death is observable end to end.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from concurrent import futures
from typing import Dict, Iterator, List, Optional, Tuple

import grpc

from gubernator_tpu.obs import witness
from gubernator_tpu.service.pb import etcd_pb2 as epb

log = logging.getLogger("gubernator_tpu.etcdlite")


@dataclasses.dataclass
class _KV:
    value: bytes
    lease: int
    create_revision: int
    mod_revision: int
    version: int


@dataclasses.dataclass
class _Lease:
    id: int
    ttl_s: float
    expires_at: float  # monotonic


class _Watcher:
    def __init__(self, watch_id: int, key: bytes, range_end: bytes):
        self.watch_id = watch_id
        self.key = key
        self.range_end = range_end
        self.events: "queue.Queue[Optional[List[epb.Event]]]" = queue.Queue()

    def matches(self, key: bytes) -> bool:
        if self.range_end:
            return self.key <= key < self.range_end
        return key == self.key


class EtcdLite:
    """In-memory etcd-subset server. `address` of "127.0.0.1:0" picks a port;
    the bound address is in `.address` after start()."""

    def __init__(self, address: str = "127.0.0.1:0",
                 min_lease_ttl_s: float = 0.0,
                 users: Optional[Dict[str, str]] = None,
                 credentials: Optional[grpc.ServerCredentials] = None):
        self._kvs: Dict[bytes, _KV] = {}
        self._leases: Dict[int, _Lease] = {}
        self._watchers: List[_Watcher] = []
        # (revision, event) log so watches can replay from start_revision,
        # like real etcd's mvcc history — trimmed to the newest
        # `max_history` entries; replays from before the trim point are
        # answered with canceled+compact_revision, like compacted etcd
        self._events: List[Tuple[int, epb.Event]] = []
        self._compacted_rev = 0
        self.max_history = 4096
        self._revision = 0
        self._next_lease = 1000
        self._next_watch = 1
        self._lock = witness.make_lock("etcdlite.store")
        self._closed = threading.Event()
        self.min_lease_ttl_s = min_lease_ttl_s
        # test hook: when set, keep-alive streams terminate immediately and
        # grants/renewals are refused, simulating a dead etcd
        self.refuse_keepalives = False
        # auth mirrors etcd's: Authenticate issues a token, every other RPC
        # must carry it as "token" metadata (etcd rpc interceptor semantics)
        self.users = dict(users) if users else {}
        self._tokens: Dict[str, str] = {}

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16),
            options=[("grpc.so_reuseport", 0)],
        )
        self._server.add_generic_rpc_handlers((self._handlers(),))
        if credentials is not None:
            port = self._server.add_secure_port(address, credentials)
        else:
            port = self._server.add_insecure_port(address)
        host = address.rsplit(":", 1)[0]
        self.address = f"{host}:{port}"
        self._reaper = threading.Thread(
            target=self._reap_loop, name="etcdlite-reaper", daemon=True
        )

    def start(self) -> "EtcdLite":
        self._server.start()
        self._reaper.start()
        return self

    def stop(self) -> None:
        self._closed.set()
        with self._lock:
            for w in self._watchers:
                w.events.put(None)
            self._watchers = []
        self._server.stop(grace=0.2)
        self._reaper.join(timeout=2.0)

    # -------------------------------------------------------------- handlers

    def _handlers(self):
        def unary(fn, req_cls):
            return grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )

        def stream(fn, req_cls):
            return grpc.stream_stream_rpc_method_handler(
                fn,
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )

        def guarded(fn):
            """Require a valid auth token when users are configured
            (etcd's per-RPC auth interceptor)."""
            def inner(req_or_it, ctx):
                self._check_auth(ctx)
                return fn(req_or_it, ctx)
            return inner

        method_map = {
            "/etcdserverpb.KV/Range": unary(
                guarded(self._range), epb.RangeRequest),
            "/etcdserverpb.KV/Put": unary(
                guarded(self._put), epb.PutRequest),
            "/etcdserverpb.KV/DeleteRange": unary(
                guarded(self._delete_range), epb.DeleteRangeRequest
            ),
            "/etcdserverpb.Lease/LeaseGrant": unary(
                guarded(self._lease_grant), epb.LeaseGrantRequest
            ),
            "/etcdserverpb.Lease/LeaseRevoke": unary(
                guarded(self._lease_revoke), epb.LeaseRevokeRequest
            ),
            "/etcdserverpb.Lease/LeaseKeepAlive": stream(
                guarded(self._lease_keep_alive), epb.LeaseKeepAliveRequest
            ),
            "/etcdserverpb.Watch/Watch": stream(
                guarded(self._watch), epb.WatchRequest),
            "/etcdserverpb.Auth/Authenticate": unary(
                self._authenticate, epb.AuthenticateRequest),
        }

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                return method_map.get(handler_call_details.method)

        return Handler()

    def _header(self) -> epb.ResponseHeader:
        return epb.ResponseHeader(revision=self._revision)

    # ----------------------------------------------------------------- auth

    def _check_auth(self, ctx) -> None:
        if not self.users:
            return
        md = dict(ctx.invocation_metadata() or ())
        if md.get("token") not in self._tokens:
            ctx.abort(grpc.StatusCode.UNAUTHENTICATED,
                      "etcdserver: invalid auth token")

    def _authenticate(self, req: epb.AuthenticateRequest,
                      ctx) -> epb.AuthenticateResponse:
        import uuid

        if not self.users or self.users.get(req.name) != req.password:
            ctx.abort(
                grpc.StatusCode.UNAUTHENTICATED,
                "etcdserver: authentication failed, "
                "invalid user ID or password")
        token = uuid.uuid4().hex
        with self._lock:
            self._tokens[token] = req.name
        return epb.AuthenticateResponse(header=self._header(), token=token)

    # ------------------------------------------------------------------- KV

    def _in_range(self, key: bytes, start: bytes, end: bytes) -> bool:
        if end:
            return start <= key < end
        return key == start

    def _range(self, req: epb.RangeRequest, ctx) -> epb.RangeResponse:
        with self._lock:
            kvs = [
                epb.KeyValue(
                    key=k, value=kv.value, lease=kv.lease,
                    create_revision=kv.create_revision,
                    mod_revision=kv.mod_revision, version=kv.version,
                )
                for k, kv in sorted(self._kvs.items())
                if self._in_range(k, req.key, req.range_end)
            ]
            return epb.RangeResponse(
                header=self._header(), kvs=kvs, count=len(kvs)
            )

    def _put(self, req: epb.PutRequest, ctx) -> epb.PutResponse:
        with self._lock:
            self._revision += 1
            old = self._kvs.get(req.key)
            kv = _KV(
                value=req.value,
                lease=req.lease,
                create_revision=old.create_revision if old else self._revision,
                mod_revision=self._revision,
                version=(old.version + 1) if old else 1,
            )
            self._kvs[req.key] = kv
            self._notify(
                epb.Event(
                    type=epb.Event.PUT,
                    kv=epb.KeyValue(
                        key=req.key, value=req.value, lease=req.lease,
                        create_revision=kv.create_revision,
                        mod_revision=kv.mod_revision, version=kv.version,
                    ),
                )
            )
            return epb.PutResponse(header=self._header())

    def _delete_range(
        self, req: epb.DeleteRangeRequest, ctx
    ) -> epb.DeleteRangeResponse:
        with self._lock:
            deleted = self._delete_keys_locked(
                [
                    k
                    for k in list(self._kvs)
                    if self._in_range(k, req.key, req.range_end)
                ]
            )
            return epb.DeleteRangeResponse(
                header=self._header(), deleted=deleted
            )

    def _delete_keys_locked(self, keys: List[bytes]) -> int:
        n = 0
        for k in keys:
            kv = self._kvs.pop(k, None)
            if kv is None:
                continue
            n += 1
            self._revision += 1
            self._notify(
                epb.Event(
                    type=epb.Event.DELETE,
                    kv=epb.KeyValue(key=k, mod_revision=self._revision),
                    prev_kv=epb.KeyValue(
                        key=k, value=kv.value, lease=kv.lease,
                        create_revision=kv.create_revision,
                        mod_revision=kv.mod_revision, version=kv.version,
                    ),
                )
            )
        return n

    # ---------------------------------------------------------------- leases

    def _lease_grant(self, req: epb.LeaseGrantRequest, ctx) -> epb.LeaseGrantResponse:
        if self.refuse_keepalives:
            ctx.abort(grpc.StatusCode.UNAVAILABLE, "etcdlite: refusing leases")
        with self._lock:
            self._next_lease += 1
            lease_id = req.ID or self._next_lease
            ttl = max(float(req.TTL), self.min_lease_ttl_s)
            self._leases[lease_id] = _Lease(
                id=lease_id, ttl_s=ttl, expires_at=time.monotonic() + ttl
            )
            return epb.LeaseGrantResponse(
                header=self._header(), ID=lease_id, TTL=int(ttl)
            )

    def _lease_revoke(self, req: epb.LeaseRevokeRequest, ctx) -> epb.LeaseRevokeResponse:
        with self._lock:
            self._leases.pop(req.ID, None)
            self._delete_keys_locked(
                [k for k, kv in self._kvs.items() if kv.lease == req.ID]
            )
            return epb.LeaseRevokeResponse(header=self._header())

    def _lease_keep_alive(
        self, request_iterator: Iterator[epb.LeaseKeepAliveRequest], ctx
    ) -> Iterator[epb.LeaseKeepAliveResponse]:
        for req in request_iterator:
            if self.refuse_keepalives or self._closed.is_set():
                return  # stream closes; client must re-register
            with self._lock:
                lease = self._leases.get(req.ID)
                if lease is None:
                    yield epb.LeaseKeepAliveResponse(
                        header=self._header(), ID=req.ID, TTL=0
                    )
                    continue
                lease.expires_at = time.monotonic() + lease.ttl_s
                yield epb.LeaseKeepAliveResponse(
                    header=self._header(), ID=req.ID, TTL=int(lease.ttl_s)
                )

    def _reap_loop(self) -> None:
        while not self._closed.wait(0.05):
            now = time.monotonic()
            with self._lock:
                dead = [l.id for l in self._leases.values() if l.expires_at < now]
                for lease_id in dead:
                    log.info("lease %d expired", lease_id)
                    del self._leases[lease_id]
                    self._delete_keys_locked(
                        [k for k, kv in self._kvs.items() if kv.lease == lease_id]
                    )

    # ----------------------------------------------------------------- watch

    def _watch(
        self, request_iterator: Iterator[epb.WatchRequest], ctx
    ) -> Iterator[epb.WatchResponse]:
        create = None
        for req in request_iterator:
            if req.HasField("create_request"):
                create = req.create_request
                break
            return
        if create is None:
            return
        with self._lock:
            self._next_watch += 1
            watcher = _Watcher(self._next_watch, create.key, create.range_end)
            if 0 < create.start_revision <= self._compacted_rev:
                yield epb.WatchResponse(
                    header=self._header(),
                    watch_id=watcher.watch_id,
                    created=True,
                )
                yield epb.WatchResponse(
                    header=self._header(),
                    watch_id=watcher.watch_id,
                    canceled=True,
                    compact_revision=self._compacted_rev + 1,
                    cancel_reason="required revision has been compacted",
                )
                return
            if create.start_revision > 0:
                replay = [
                    ev
                    for rev, ev in self._events
                    if rev >= create.start_revision and watcher.matches(ev.kv.key)
                ]
                if replay:
                    watcher.events.put(replay)
            self._watchers.append(watcher)
        yield epb.WatchResponse(
            header=self._header(), watch_id=watcher.watch_id, created=True
        )
        try:
            while True:
                events = watcher.events.get()
                if events is None:
                    yield epb.WatchResponse(
                        header=self._header(),
                        watch_id=watcher.watch_id,
                        canceled=True,
                    )
                    return
                yield epb.WatchResponse(
                    header=self._header(),
                    watch_id=watcher.watch_id,
                    events=events,
                )
        finally:
            with self._lock:
                if watcher in self._watchers:
                    self._watchers.remove(watcher)

    def _notify(self, event: epb.Event) -> None:
        """Callers hold self._lock."""
        self._events.append((self._revision, event))
        if len(self._events) > self.max_history:
            drop = len(self._events) - self.max_history
            self._compacted_rev = self._events[drop - 1][0]
            del self._events[:drop]
        for w in self._watchers:
            if w.matches(event.kv.key):
                w.events.put([event])

    # ------------------------------------------------------------- test hooks

    def expire_all_leases(self) -> None:
        """Force every lease to lapse now (fault injection)."""
        with self._lock:
            for lease in self._leases.values():
                lease.expires_at = 0.0

    def keys(self) -> Dict[bytes, bytes]:
        with self._lock:
            return {k: kv.value for k, kv in self._kvs.items()}
