# Parity with the reference's Makefile (Makefile:1-18): `test` runs the
# whole suite with concurrency hygiene, plus this repo's bench/proto targets.

.PHONY: test test-fast lint lockmap sanitize bench bench-skew bench-wire bench-reshard bench-suite bench-check scenarios capacity-report profile-report ledger-report soak chaos proto docker clean native

# the suite runs on a virtual 8-device CPU mesh (tests/conftest.py)
test:
	python -m pytest tests/ -q

test-fast: lint
	python -m pytest tests/ -q -x -m "not slow"

# guberlint: AST-driven invariant analyzer (docs/static-analysis.md).
# Zero unwaived findings is a tier-1 gate (tests/test_lint.py runs the
# same check in-process).
lint: lockmap
	python -m gubernator_tpu.analysis

# lock acquisition-order graph: drift-gate the built graph against the
# committed lockmap.json in both directions and fail on any unwaived
# lock-order/donation-flow finding (docs/static-analysis.md "Reading a
# lockmap"); after a reviewed ordering change:
# `python scripts/lockmap_report.py --write` and commit
lockmap:
	python scripts/lockmap_report.py --check

# TSan/ASan/UBSan builds of native/*.cpp into the same mtime-keyed .so
# cache `make native` uses; the TSan variants load under
# TSAN_OPTIONS=suppressions=native/tsan.supp (tests/test_tsan.py)
sanitize:
	python scripts/build_native.py --sanitize

bench:
	python bench.py

# Zipf-1.1 skew through a 2-node loopback cluster: uniform vs leases-off
# vs leases-on rows (client p99 + hot-owner work share, BENCH_r09)
bench-skew:
	python bench.py --skew

# wire contract v1 vs v2 over a loopback peerlink, bare CPU rig plus a
# link-emulated (BENCH_r05-class tunnel latency) regime (BENCH_r10)
bench-wire:
	python bench.py --wire

# live resharding at scale: 1M-row evacuate() handoff duration plus the
# importer's foreground p50/p99 quiet vs mid-handoff (BENCH_r13)
bench-reshard:
	python bench.py --reshard

bench-suite:
	python scripts/bench_suite.py

# diff the two newest BENCH_r*.json rounds; fails on a >25% cliff in a
# throughput/latency key both rounds measured (see scripts/bench_check.py)
bench-check:
	python scripts/bench_check.py

# scenario atlas: seeded workload drills against live 1-2 node clusters,
# SLO verdicts written to the round's SCEN_r<NN>.json; exits 1 on any
# FAIL (docs/OPERATIONS.md "Scenario drills"); PROFILE=full for the
# real-length shapes
scenarios:
	python scripts/scenario_report.py --profile $(or $(PROFILE),short)

# occupancy, headroom forecast, hit-mass concentration and top-K heavy
# hitters from a running node's /v1/debug/{keyspace,history} endpoints
# (docs/OPERATIONS.md "Capacity planning"); ADDR defaults to 127.0.0.1:80
capacity-report:
	python scripts/capacity_report.py $(ADDR)

# serving-cycle decomposition, lock-wait sites and kernel cost table
# from a running node's /v1/debug/{profile,kernels} endpoints
# (docs/OPERATIONS.md "Performance triage"); ADDR defaults to 127.0.0.1:80
profile-report:
	python scripts/profile_report.py $(ADDR)

# decision-ledger conservation digest: admits-by-authority, minted lease
# budget, over-admission distribution and the device ground-truth check
# (docs/OPERATIONS.md "Over-admission triage"); ADDR defaults to 127.0.0.1:80
ledger-report:
	python scripts/ledger_report.py $(ADDR)

# 30s fault-injection soak: kill/restart chaos under load, invariant-judged
soak:
	PYTHONPATH=. python scripts/soak.py

# deterministic fault-injection drills (circuit breaker, degraded-local,
# recovery) with a randomized seed; -s keeps the seed line visible —
# reproduce any failure with GUBER_CHAOS_SEED=<seed> make chaos
chaos:
	@seed=$${GUBER_CHAOS_SEED:-$$(od -An -N2 -tu2 /dev/urandom | tr -d ' ')}; \
	echo "chaos seed: $$seed"; \
	GUBER_CHAOS_SEED=$$seed python -m pytest tests/ -q -s -m chaos

# rebuild both native components (keydir.cpp, peerlink.cpp) plus their
# tsan variants from source into the mtime-keyed .so cache names the
# loaders expect; stale caches are deleted. tests/test_native_build.py is
# the tier-1 drift check (a cached .so older than its source fails).
native:
	python scripts/build_native.py

proto:
	bash scripts/genproto.sh

docker:
	docker build -t gubernator-tpu:latest .

clean:
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null; true
	rm -f gubernator_tpu/native/_keydir_*.so \
	      gubernator_tpu/native/_peerlink_*.so \
	      gubernator_tpu/native/_tsan_*.so
