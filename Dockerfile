# gubernator_tpu serving image (CPU/JAX base; swap the base image for a TPU
# runtime image on TPU VMs). Role parity: reference Dockerfile builds a
# static Go binary into a scratch image; here the daemon is Python+JAX with
# a C++ native module compiled at build time.
FROM python:3.11-slim

RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY setup.py README.md ./
COPY proto ./proto
COPY gubernator_tpu ./gubernator_tpu
RUN pip install --no-cache-dir "jax[cpu]" grpcio protobuf prometheus_client numpy \
    && pip install --no-cache-dir -e . \
    && python -c "from gubernator_tpu.native import available; assert available()"

# reference ports: 81 gRPC, 80 HTTP (Dockerfile:24-27); gossip on 7946
EXPOSE 81 80 7946/udp
ENV GUBER_GRPC_ADDRESS=0.0.0.0:81 \
    GUBER_HTTP_ADDRESS=0.0.0.0:80

ENTRYPOINT ["python", "-m", "gubernator_tpu.cmd.daemon"]
