#!/usr/bin/env bash
# Regenerate the Python protobuf modules from proto/.
# (reference equivalent: scripts/proto.sh — but we need only message code;
# gRPC service registration is hand-written in service/grpc_api.py)
set -euo pipefail
cd "$(dirname "$0")/.."
protoc -I proto --python_out=gubernator_tpu/service/pb \
    proto/gubernator.proto proto/peers.proto proto/etcd.proto
echo "generated gubernator_tpu/service/pb/{gubernator,peers,etcd}_pb2.py"
