"""Device-time-vs-width curve for the latency story (VERDICT r4 item 3).

BASELINE.md's p99 < 2 ms target is a LATENCY-mode bar: a locally-attached
chip serving one flat-combining window synchronously. The tunneled rig
cannot measure that end-to-end (every dispatch pays ~100+ ms of link RTT),
but the ON-CHIP term is measurable here: time a K-deep `lax.scan` of the
decision kernel in ONE dispatch, difference two depths, and the
dispatch/link overhead cancels:

    device_per_window(W) = (t(scan K2, W) - t(scan K1, W)) / (K2 - K1)

Every timed quantity is completion-forced (data-dependent scalar fetch).
The curve feeds DESIGN.md "Latency mode" and OPERATIONS.md's
window-width guidance: p99 on local hardware composes as
device_per_window + PCIe transfer (12 B/decision round trip, ~µs) +
local dispatch overhead (~100-300 µs PJRT launch).

Prints one JSON line: {"widths": {...}, "table_capacity": N, ...}.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TABLE_CAPACITY = 10_000_000
WIDTHS = (512, 1024, 2048, 4096, 8192)
REPS = 3  # per measurement; median-of-reps kills link-weather outliers


def depths_for(width: int):
    """Differencing depths scaled so the K2-K1 device term (~1M decisions)
    dwarfs the tunnel's ±10 ms dispatch jitter at every width."""
    k2 = max(64, (1_000_000 + width - 1) // width)
    return max(8, k2 // 8), k2


def main() -> None:
    import jax
    import jax.numpy as jnp

    from gubernator_tpu.ops.decide import (
        decide_scan_packed_lean,
        lean_window,
        make_table,
    )
    from gubernator_tpu.utils.platform import donation_supported

    dargs = dict(donate_argnums=(0,)) if donation_supported() else {}
    step = jax.jit(decide_scan_packed_lean, **dargs)
    now = 1_700_000_000_000
    rng = np.random.RandomState(11)

    def force(x) -> int:
        return int(np.asarray(x[(0,) * x.ndim]))

    # ONE shared permutation; each window takes a disjoint slice — same
    # collision-free-window contract as rng.choice(replace=False) per
    # window, without paying a fresh 10M permutation per window
    perm = rng.permutation(TABLE_CAPACITY)
    perm_pos = [0]

    def windows(k: int, w: int):
        p = np.zeros((k, 9, w), np.int64)
        for i in range(k):
            if perm_pos[0] + w > TABLE_CAPACITY:
                perm_pos[0] = 0
            p[i, 0] = perm[perm_pos[0]:perm_pos[0] + w]
            perm_pos[0] += w
            p[i, 1] = 1
            p[i, 2] = rng.choice([100, 1000, 10000], w)
            p[i, 3] = 60_000
            p[i, 4] = rng.randint(0, 2, w)
        lanes, cfg = lean_window(p, TABLE_CAPACITY)
        return jnp.asarray(lanes), jnp.asarray(cfg)

    state = make_table(TABLE_CAPACITY)
    out = {"bench": "latency_curve", "table_capacity": TABLE_CAPACITY,
           "reps": REPS,
           "completion_barrier": "data-dependent fetch", "widths": {}}

    for w in WIDTHS:
        K1, K2 = depths_for(w)
        l1, cfg = windows(K1, w)
        l2, _ = windows(K2, w)
        # warm both shapes
        state, r = step(state, l1, cfg, now)
        force(r)
        state, r = step(state, l2, cfg, now)
        force(r)
        t1s, t2s = [], []
        for rep in range(REPS):
            t0 = time.perf_counter()
            state, r = step(state, l1, cfg, now + rep)
            force(r)
            t1s.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            state, r = step(state, l2, cfg, now + 100 + rep)
            force(r)
            t2s.append(time.perf_counter() - t0)
        t1 = float(np.median(t1s))
        t2 = float(np.median(t2s))
        dev_ms = max(t2 - t1, 0.0) / (K2 - K1) * 1e3
        out["widths"][str(w)] = {
            "scan_depths": [K1, K2],
            "device_ms_per_window": round(dev_ms, 4),
            "device_us_per_decision": round(dev_ms * 1e3 / w, 4),
            "device_decisions_per_sec": round(w / (dev_ms / 1e3), 1)
            if dev_ms > 0 else None,
            # local-chip p99 composition: on-chip + PCIe transfer of
            # 12 B/dec at >=10 GB/s + PJRT launch overhead
            "p99_ms_local_estimate": round(
                dev_ms + (12 * w) / 10e9 * 1e3 + 0.3, 3),
            "scan_k1_s": round(t1, 4), "scan_k2_s": round(t2, 4),
        }

    print(json.dumps({**out, "device": str(jax.devices()[0])}), flush=True)


if __name__ == "__main__":
    main()
