"""Random row-access ceiling microbenchmark (VERDICT r3 item 3).

Isolates the decide kernel's memory access pattern — gather B random
i64[8] rows from a C-row table, scatter B rows back — WITHOUT the decide
math, to measure how far the kernel sits from the chip's random-access
ceiling. Variants:

  gather+scatter   the kernel's exact access pattern (touch both ways)
  gather_only      read side alone
  scatter_only     write side alone
  sorted           slots sorted ON DEVICE before the gather/scatter
                   (locality probe: does HBM row locality buy anything?)
  decide           the real kernel (ops/decide.py) for comparison

All completion-forced (data-dependent fetch), scan-coalesced K-deep like
bench.py's headline, donated state. Prints one JSON line per variant.
"""

from __future__ import annotations

import json
import time

import numpy as np

TABLE_CAPACITY = 10_000_000
BATCH = 8_192
SCAN_K = 128
N_VARIANTS = 4
TARGET_S = 3.0


def main() -> None:
    import jax
    import jax.numpy as jnp

    from gubernator_tpu.ops.decide import I64, decide_scan_packed, make_table
    from gubernator_tpu.utils.platform import donation_supported

    donate = donation_supported()
    dargs = dict(donate_argnums=(0,)) if donate else {}

    def force(x) -> int:
        return int(np.asarray(x[(0,) * x.ndim]))

    rng = np.random.RandomState(3)
    slot_sets = [
        jnp.asarray(np.stack([
            rng.choice(TABLE_CAPACITY, BATCH, replace=False)
            for _ in range(SCAN_K)]).astype(np.int32))
        for _ in range(N_VARIANTS)
    ]

    # ---- raw gather+scatter: the kernel's access pattern, no math ------
    def gs_scan(state, slots_k, bump):
        def body(st, slots):
            rows = st[slots]                      # [B, 8] random gather
            st2 = st.at[slots].set(rows + bump)   # [B, 8] random scatter
            return st2, rows[:, 0]
        return jax.lax.scan(body, state, slots_k)

    def g_scan(state, slots_k, bump):
        def body(st, slots):
            rows = st[slots]
            return st, rows[:, 0] + bump
        return jax.lax.scan(body, state, slots_k)

    def s_scan(state, slots_k, bump):
        def body(st, slots):
            st2 = st.at[slots].set(
                jnp.full((slots.shape[0], 8), bump, I64))
            return st2, slots[:1].astype(I64)
        return jax.lax.scan(body, state, slots_k)

    def sorted_scan(state, slots_k, bump):
        def body(st, slots):
            order = jnp.argsort(slots)
            s_sorted = slots[order]
            rows = st[s_sorted]
            st2 = st.at[s_sorted].set(rows + bump)
            # un-sort the per-lane result (the serving contract)
            out = jnp.zeros_like(rows[:, 0]).at[order].set(rows[:, 0])
            return st2, out
        return jax.lax.scan(body, state, slots_k)

    variants = {
        "gather_scatter": gs_scan,
        "gather_only": g_scan,
        "scatter_only": s_scan,
        "sorted_gather_scatter": sorted_scan,
    }
    results = {}
    for name, fn in variants.items():
        step = jax.jit(fn, **dargs)
        state = make_table(TABLE_CAPACITY)
        state, out = step(state, slot_sets[0], 1)
        force(out)
        t0 = time.perf_counter()
        state, out = step(state, slot_sets[1], 2)
        force(out)
        per_call = max(time.perf_counter() - t0, 1e-6)
        iters = max(4, min(200, int(TARGET_S / per_call)))
        t0 = time.perf_counter()
        for i in range(iters):
            state, out = step(state, slot_sets[i % N_VARIANTS], 3 + i)
        force(out)
        el = time.perf_counter() - t0
        rate = iters * SCAN_K * BATCH / el
        results[name] = round(rate, 1)
        print(json.dumps({"variant": name, "rows_per_s": round(rate, 1),
                          "iters": iters}), flush=True)
        del state

    # ---- the real kernel for comparison --------------------------------
    def make_windows(seed: int) -> np.ndarray:
        r = np.random.RandomState(seed)
        p = np.zeros((SCAN_K, 9, BATCH), np.int64)
        for i in range(SCAN_K):
            p[i, 0] = r.choice(TABLE_CAPACITY, BATCH, replace=False)
            p[i, 1] = 1
            p[i, 2] = 1000
            p[i, 3] = 60_000
        return p
    scans = [jnp.asarray(make_windows(s)) for s in range(N_VARIANTS)]
    step = jax.jit(decide_scan_packed, **dargs)
    state = make_table(TABLE_CAPACITY)
    state, out = step(state, scans[0], 1)
    force(out)
    t0 = time.perf_counter()
    state, out = step(state, scans[1], 2)
    force(out)
    per_call = max(time.perf_counter() - t0, 1e-6)
    iters = max(4, min(200, int(TARGET_S / per_call)))
    t0 = time.perf_counter()
    for i in range(iters):
        state, out = step(state, scans[i % N_VARIANTS], 3 + i)
    force(out)
    rate = iters * SCAN_K * BATCH / (time.perf_counter() - t0)
    results["decide_kernel"] = round(rate, 1)
    print(json.dumps({"variant": "decide_kernel",
                      "rows_per_s": round(rate, 1), "iters": iters}),
          flush=True)
    print(json.dumps({"summary": results,
                      "device": str(jax.devices()[0]),
                      "capacity": TABLE_CAPACITY,
                      "batch": BATCH, "scan_k": SCAN_K}), flush=True)


if __name__ == "__main__":
    main()
