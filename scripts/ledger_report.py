"""Render a node's decision ledger as a terminal report.

Fetches /v1/debug/ledger from a running node's HTTP gateway (or reads a
saved endpoint body / diagnostic bundle from a file) and prints the
operator-facing digest: the admit-by-authority split, minted lease
budget, the conservation audit's violation count and over-admission
distribution, the recent-violation ring, and the device-counter ground
truth comparison. This is the evidence the "Over-admission triage"
runbook (docs/OPERATIONS.md) walks — the report exists so a human can
see WHO admitted the traffic before (or after) the `over_admission`
detector trips.

Usage:
    python scripts/ledger_report.py [host:port]     # default 127.0.0.1:80
    python scripts/ledger_report.py --file body.json  # offline artifact
    make ledger-report [ADDR=host:port]

Rendering is a pure function over the endpoint body (render_report), so
tests exercise it offline; only main() touches the network. Exit
status: 0 rendered, 1 on fetch/shape failure.
"""

import json
import sys
import urllib.request


def _bar(fraction, width=28):
    fraction = min(max(float(fraction or 0.0), 0.0), 1.0)
    filled = int(round(fraction * width))
    return "[" + "#" * filled + "." * (width - filled) + "]"


def render_report(body):
    """Pure renderer: /v1/debug/ledger body in, report text out."""
    lines = []
    lines.append("decision ledger & budget-conservation audit")
    lines.append("=" * 58)
    if not body.get("enabled", True):
        lines.append("ledger DISABLED (GUBER_LEDGER=0) — counters frozen "
                     "at the values below")
    totals = body.get("totals") or {}
    admits = dict(totals.get("admits") or {})
    other = int(totals.get("admits_other", 0))
    if other:
        admits["other"] = other
    admitted = sum(admits.values())
    attempted = int(totals.get("attempted", 0))
    if not attempted and not admitted:
        lines.append("no decisions observed yet")
        return "\n".join(lines) + "\n"

    lines.append("admits by authority (who let each hit through)")
    lines.append("-" * 58)
    for auth, n in sorted(admits.items(), key=lambda kv: -kv[1]):
        share = n / admitted if admitted else 0.0
        lines.append(f"{auth:<13} {_bar(share)} {share:>6.1%}  {n} hits")
    lines.append(f"{'admitted':<13} {admitted} of {attempted} attempted "
                 f"({int(totals.get('rejected', 0))} rejected)")
    lines.append("")

    lines.append("conservation audit")
    lines.append("-" * 58)
    lines.append(f"windows rolled   {int(totals.get('windows_rolled', 0))}"
                 f"  (audits: {int(totals.get('audits', 0))}, keys live: "
                 f"{int(totals.get('keys_tracked', 0))})")
    lines.append(f"minted budget    {int(totals.get('minted_budget', 0))} "
                 "hits (lease slices granted by owners)")
    violations = int(totals.get("violations", 0))
    verdict = "INVARIANT HELD" if violations == 0 else "BUDGET MINTED"
    lines.append(f"violations       {violations}  -> {verdict}")
    over = body.get("overshoot") or {}
    if int(over.get("n", 0)):
        lines.append(
            f"over-admission   {int(over.get('n', 0))} windows overshot: "
            f"p50 {int(over.get('p50_hits', 0))} / "
            f"p99 {int(over.get('p99_hits', 0))} / "
            f"max {int(over.get('max_hits', 0))} hits "
            f"(total {int(over.get('total_hits', 0))})")
    else:
        lines.append("over-admission   none observed")
    lines.append("")

    recent = body.get("recent_violations") or []
    if recent:
        lines.append("recent violations (newest last)")
        lines.append("-" * 58)
        for v in recent:
            auths = v.get("authorities") or {}
            split = " ".join(f"{a}={n}" for a, n in sorted(auths.items()))
            lines.append(
                f"{v.get('key', '?'):<24} overshoot "
                f"{int(v.get('overshoot', 0)):>6} beyond slack "
                f"{int(v.get('slack', 0))} (limit "
                f"{int(v.get('limit', 0))}, minted "
                f"{int(v.get('minted', 0))})  {split}")
        lines.append("")

    gt = body.get("ground_truth") or {}
    checked = int(gt.get("keys_checked", 0))
    lines.append("device ground truth (table col-7 attempted-hit counters)")
    lines.append("-" * 58)
    if checked:
        breaches = int(gt.get("breaches", 0))
        lines.append(
            f"{checked} owner-resident keys compared: ledger "
            f"{int(gt.get('ledger_hits', 0))} vs device "
            f"{int(gt.get('device_hits', 0))} hits, "
            f"{breaches} breach(es)"
            + ("" if breaches == 0 else
               "  <- ledger counted hits the device never saw"))
    else:
        lines.append("(no owner-resident keys compared yet)")
    return "\n".join(lines) + "\n"


def _fetch(addr, path, timeout=5.0):
    return json.loads(urllib.request.urlopen(
        f"http://{addr}{path}", timeout=timeout).read())


def main(argv):
    if len(argv) > 2 and argv[1] == "--file":
        try:
            with open(argv[2], encoding="utf-8") as f:
                body = json.load(f)
            # a full diagnostic bundle carries the body under "ledger"
            if "ledger" in body and "totals" not in body:
                body = body["ledger"]
        except Exception as e:  # noqa: BLE001 — operator tool
            print(f"ledger_report: reading {argv[2]} failed: {e}",
                  file=sys.stderr)
            return 1
    else:
        addr = argv[1] if len(argv) > 1 else "127.0.0.1:80"
        try:
            body = _fetch(addr, "/v1/debug/ledger?audit=1")
        except Exception as e:  # noqa: BLE001 — operator tool
            print(f"ledger_report: fetch from {addr} failed: {e}",
                  file=sys.stderr)
            return 1
    try:
        sys.stdout.write(render_report(body))
    except Exception as e:  # noqa: BLE001
        print(f"ledger_report: unexpected endpoint shape: {e}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
