"""Device-directory vs host-directory serving, measured on the real device.

VERDICT r3 weak #4: the device directory (models/devdir_engine.py) was
graduated on an r2 measurement of a PROTOTYPE path (2.2x through the
tunnel, when the host path still staged ~72 B/decision wide).  Round 4's
interned i32[2] serving staging ships 8 B/decision on the HOST path too,
so the devdir's wire advantage is gone by construction — what remains is
the host-CPU question: keydir lookup+prep (~100 ns/item, GIL held in
parts) vs a C fnv batch alone (measured 89.8 ns/item on this host — the
string hashing both paths pay dominates either way).  This bench measures
both engines through the SAME front door (get_rate_limits), same widths,
same resident keyset, on whatever platform JAX gives (the tunneled chip
under axon; CPU JAX under JAX_PLATFORMS=cpu), plus the host-side cost in
isolation.

Usage: python scripts/bench_devdir.py [--keys 1000000] [--width 4096]
       [--rounds 8]
Emits one JSON line per scenario (bench_suite.py conventions).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _requests(names, start, count):
    from gubernator_tpu.types import RateLimitReq

    return [
        RateLimitReq(
            name="bench", unique_key=names[(start + i) % len(names)],
            hits=1, limit=1 << 30, duration=3_600_000,
        )
        for i in range(count)
    ]


def _seed(engine, names, width):
    for off in range(0, len(names), width):
        chunk = names[off:off + width]
        engine.get_rate_limits(_requests(chunk, 0, len(chunk)))


def _serve_rounds(engine, names, width, rounds, rng):
    """Sequential serving windows of `width` random resident keys;
    responses are materialized host-side every call (completion-forced
    by construction).  Returns (req/s, per-window seconds)."""
    # one warm call per width bucket so no timed window pays a compile
    engine.get_rate_limits(_requests(names, 0, width))
    t0 = time.perf_counter()
    n = 0
    for _ in range(rounds):
        start = int(rng.integers(0, len(names)))
        out = engine.get_rate_limits(_requests(names, start, width))
        n += len(out)
        assert out[0].error == ""
    dt = time.perf_counter() - t0
    return n / dt, dt / rounds


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=1_000_000)
    ap.add_argument("--width", type=int, default=4096)
    ap.add_argument("--rounds", type=int, default=8)
    args = ap.parse_args()
    if args.keys < args.width:
        ap.error("--keys must be >= --width (duplicate keys in one window "
                 "decide in sequential rounds and would skew req/s)")

    import jax

    # honor JAX_PLATFORMS even against a platform plugin (the axon TPU
    # plugin outranks the env default; only the config update wins)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from gubernator_tpu import native
    from gubernator_tpu.models.devdir_engine import DevDirEngine
    from gubernator_tpu.models.engine import Engine

    platform = jax.devices()[0].platform
    cap = 1 << max(20, (args.keys * 2 - 1).bit_length())
    names = [f"k:{i:012d}" for i in range(args.keys)]
    rng = np.random.default_rng(7)

    rows = []
    for label, ctor in (("hostdir", Engine), ("devdir", DevDirEngine)):
        eng = ctor(capacity=cap, min_width=64, max_width=8192)
        t0 = time.perf_counter()
        _seed(eng, names, 8192)
        seed_s = time.perf_counter() - t0
        rate, per_window = _serve_rounds(
            eng, names, args.width, args.rounds, rng)
        rows.append({
            "scenario": f"devdir_bench_{label}",
            "platform": platform,
            "resident_keys": args.keys,
            "width": args.width,
            "req_per_sec": round(rate, 1),
            "window_ms": round(per_window * 1e3, 2),
            "seed_s": round(seed_s, 1),
        })
        print(json.dumps(rows[-1]), flush=True)
        del eng

    # host-side per-item cost in isolation: what each directory charges
    # the serving CPU before any dispatch
    native.load_library()
    key_sample = names[: args.width]
    t0 = time.perf_counter()
    reps = 200
    for _ in range(reps):
        native.fingerprint_batch(key_sample)
    fnv_ns = (time.perf_counter() - t0) / (reps * args.width) * 1e9
    print(json.dumps({
        "scenario": "devdir_bench_host_cost",
        "fnv_hash_ns_per_item": round(fnv_ns, 1),
        "note": "hostdir path adds directory lookup+pin (~100 ns/item, "
                "measured in DESIGN.md 'Native host tier'); devdir ships "
                "only this hash",
    }), flush=True)


if __name__ == "__main__":
    main()
