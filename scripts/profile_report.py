"""Render a node's serving-cycle profile as a terminal report.

Fetches /v1/debug/profile and /v1/debug/kernels from a running node's
HTTP gateway and prints the operator-facing digest: the per-phase
decomposition of the serial serving cycle (boot-cumulative shares plus
the last-minute window), per-call-site engine-lock wait, and the kernel
cost/dispatch table. This is the same data the `profile_shift` anomaly
detector reads from the history ring — the report exists so a human can
see WHERE the cycle's time went before (or after) the detector trips
(see docs/OPERATIONS.md "Performance triage").

Usage:
    python scripts/profile_report.py [host:port]   # default 127.0.0.1:80
    make profile-report [ADDR=host:port]

Rendering is a pure function over the two endpoint bodies
(render_report), so tests exercise it offline; only main() touches the
network. Exit status: 0 rendered, 1 on fetch/shape failure.
"""

import json
import sys
import urllib.request


def _fmt_ns(ns):
    if ns is None:
        return "n/a"
    ns = float(ns)
    if ns >= 1e9:
        return f"{ns / 1e9:.2f}s"
    if ns >= 1e6:
        return f"{ns / 1e6:.2f}ms"
    if ns >= 1e3:
        return f"{ns / 1e3:.1f}us"
    return f"{ns:.0f}ns"


def _bar(fraction, width=28):
    fraction = min(max(float(fraction or 0.0), 0.0), 1.0)
    filled = int(round(fraction * width))
    return "[" + "#" * filled + "." * (width - filled) + "]"


def _phase_block(lines, title, phases_dec):
    lines.append(title)
    lines.append("-" * 58)
    for p, d in phases_dec.items():
        share = d.get("share", 0.0)
        n = d.get("count", d.get("n", 0))
        total_ns = d.get("total_ns")
        total_s = d.get("total_s", (total_ns or 0) / 1e9)
        caveat = "  (pipeline residency)" if p == "queue_wait" else ""
        lines.append(f"{p:<11} {_bar(share)} {share:>6.1%}  "
                     f"{total_s:>9.3f}s / {n} windows{caveat}")


def render_report(profile_body, kernels_body=None):
    """Pure renderer: endpoint bodies in, report text out."""
    lines = []
    lines.append("serving-cycle profile")
    lines.append("=" * 58)
    if not profile_body.get("enabled", True):
        lines.append("profiler DISABLED (GUBER_PROFILE=0) — counters "
                     "frozen at the values below")
    dec = profile_body.get("decomposition") or {}
    if not any((d.get("count") or 0) for d in dec.values()):
        lines.append("no serving cycles observed yet")
        return "\n".join(lines) + "\n"

    _phase_block(lines, "cycle decomposition (boot-cumulative, share of "
                        "serial cycle)", dec)
    lines.append("")

    recent = profile_body.get("recent") or {}
    rp = recent.get("phases") or {}
    if any((d.get("n") or 0) for d in rp.values()):
        win = recent.get("window_s")
        _phase_block(
            lines,
            f"last {win:.0f}s" if win else "since boot (ring still filling)",
            rp)
        lines.append("")

    sites = profile_body.get("lock_sites") or {}
    lines.append("engine-lock wait by call site")
    lines.append("-" * 58)
    if sites:
        for s, h in sorted(sites.items(),
                           key=lambda kv: -(kv[1].get("total_ns") or 0)):
            lines.append(f"{s:<24} {h.get('n'):>9} waits  "
                         f"p50 {_fmt_ns(h.get('p50_ns')):>9}  "
                         f"p99 {_fmt_ns(h.get('p99_ns')):>9}  "
                         f"total {_fmt_ns(h.get('total_ns'))}")
    else:
        lines.append("(none recorded)")
    lines.append("")

    cap = profile_body.get("capture") or {}
    lines.append(f"deep captures  {cap.get('count', 0)} taken "
                 f"(min {cap.get('min_interval_s')}s apart; "
                 "?capture=1 to trigger)")
    if cap.get("last_path"):
        lines.append(f"  last: {cap['last_path']} ({cap.get('last_mode')})")

    if kernels_body is not None:
        lines.append("")
        lines.append("kernel dispatch & cost")
        lines.append("-" * 58)
        kernels = kernels_body.get("kernels") or {}
        if not kernels:
            lines.append("(no kernels dispatched yet)")
        for name, rec in kernels.items():
            hist = rec.get("dispatch_ns") or {}
            cost = rec.get("cost") or {}
            cost_txt = (f"flops {cost['flops']:.3g} "
                        f"bytes {cost['bytes_accessed']:.3g}"
                        if "flops" in cost
                        else cost.get("error") or cost.get("cost_error")
                        or "cost n/a")
            lines.append(f"{name:<22} {rec.get('windows'):>9} windows  "
                         f"dispatch p99 {_fmt_ns(hist.get('p99_ns')):>9}  "
                         f"{cost_txt}")
        lines.append(f"lanes total    {kernels_body.get('lanes_total')}")
    return "\n".join(lines) + "\n"


def _fetch(addr, path, timeout=5.0):
    return json.loads(urllib.request.urlopen(
        f"http://{addr}{path}", timeout=timeout).read())


def main(argv):
    addr = argv[1] if len(argv) > 1 else "127.0.0.1:80"
    try:
        prof = _fetch(addr, "/v1/debug/profile")
        # the kernels body may pay first-call cost compiles; give it room
        kern = _fetch(addr, "/v1/debug/kernels", timeout=30.0)
    except Exception as e:  # noqa: BLE001 — operator tool, report and exit
        print(f"profile_report: fetch from {addr} failed: {e}",
              file=sys.stderr)
        return 1
    try:
        sys.stdout.write(render_report(prof, kern))
    except Exception as e:  # noqa: BLE001
        print(f"profile_report: unexpected endpoint shape: {e}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
