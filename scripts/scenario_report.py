"""Run the scenario atlas and write the round's SCEN_r<NN>.json verdict.

Each scenario boots its own in-process LocalCluster (1-2 nodes per the
spec), paces the seeded schedule onto it, fires the spec's timeline
events, and records the SLO verdict the anomaly engine + envelope
render. The artifact is the scenario counterpart of BENCH_r<NN>.json:
machine-readable, diffable across rounds, and gated — exit status 1
when any scenario FAILs, so `make scenarios` is red exactly when an
operator would have been paged.

Usage:
    python scripts/scenario_report.py                  # short atlas
    python scripts/scenario_report.py --profile full   # 870s-scale drills
    python scripts/scenario_report.py --scenario bot-storm --scenario ...
    python scripts/scenario_report.py --autopilot both # off + on per shape
    python scripts/scenario_report.py --replay trace.json
    python scripts/scenario_report.py --list
    python scripts/scenario_report.py --out SCEN_r02.json

With --autopilot both, each shape runs twice on the same seed — static
knobs, then GUBER_AUTOPILOT-armed via the spec overlay — and the armed
run is keyed "<name>@autopilot", which bench_check gates at the same
zero tolerance as the plain verdicts.
"""

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _next_round_path() -> str:
    rounds = []
    for p in glob.glob(os.path.join(REPO, "SCEN_r*.json")):
        m = re.match(r"SCEN_r(\d+)\.json$", os.path.basename(p))
        if m:
            rounds.append(int(m.group(1)))
    return os.path.join(REPO, f"SCEN_r{(max(rounds) + 1 if rounds else 1):02d}.json")


def main(argv=None) -> int:
    from gubernator_tpu.scenarios import (
        SCENARIO_NAMES,
        get_scenario,
        run_scenario,
        trace_to_spec,
    )

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--profile", default="short",
                    choices=("short", "full"),
                    help="short: seconds-scale tier-1-safe drills; "
                         "full: the real-length shapes (marked slow)")
    ap.add_argument("--scenario", action="append", default=[],
                    help="run only these (repeatable; default: whole atlas)")
    ap.add_argument("--replay", metavar="TRACE.json",
                    help="also replay a /v1/debug/capture trace file as "
                         "an extra scenario")
    ap.add_argument("--autopilot", default="off",
                    choices=("off", "on", "both"),
                    help="arm the closed-loop controllers: on = every "
                         "shape runs autopilot-armed; both = each shape "
                         "runs off AND on (same seed), the armed verdict "
                         "keyed '<name>@autopilot'")
    ap.add_argument("--out", help="artifact path (default: next SCEN_r<NN>)")
    ap.add_argument("--list", action="store_true",
                    help="print the atlas and exit")
    args = ap.parse_args(argv)

    if args.list:
        for name in SCENARIO_NAMES:
            spec = get_scenario(name)
            print(f"{name:20s} {spec.nodes}n "
                  f"{spec.duration_s():6.0f}s  {spec.description}")
        return 0

    names = args.scenario or list(SCENARIO_NAMES)
    verdicts = {}
    for name in names:
        if args.autopilot in ("off", "both"):
            print(f"scenario {name} [{args.profile}] ...", flush=True)
            v = run_scenario(get_scenario(name), profile=args.profile)
            verdicts[name] = v
            _print_verdict(v)
        if args.autopilot in ("on", "both"):
            key = name if args.autopilot == "on" else f"{name}@autopilot"
            print(f"scenario {key} [{args.profile}] autopilot ...",
                  flush=True)
            v = run_scenario(get_scenario(name), profile=args.profile,
                             autopilot=True)
            verdicts[key] = v
            _print_verdict(v)
    if args.replay:
        from gubernator_tpu.obs.capture import load_trace

        spec = trace_to_spec(load_trace(args.replay), name="replay")
        print(f"scenario replay [{args.replay}] ...", flush=True)
        v = run_scenario(spec, profile="short")
        verdicts["replay"] = v
        _print_verdict(v)

    doc = {
        "schema_version": 1,
        "profile": args.profile,
        "autopilot": args.autopilot,
        "scenarios": verdicts,
        "passed": all(v["passed"] for v in verdicts.values()),
    }
    out = args.out or _next_round_path()
    with open(out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    n_pass = sum(v["passed"] for v in verdicts.values())
    print(f"\n{n_pass}/{len(verdicts)} scenarios PASS -> {out}")
    return 0 if doc["passed"] else 1


def _print_verdict(v: dict) -> None:
    mark = "PASS" if v["passed"] else "FAIL"
    lat = v["stats"]["latency_ms"]
    print(f"  {mark}  goodput={v['goodput']:.4f} "
          f"over_limit={v['over_limit_share']:.3f} "
          f"err={v['error_share']:.4f} "
          f"p50={lat['p50']:.1f}ms p99={lat['p99']:.1f}ms")
    for c in v["checks"]:
        if not c["ok"]:
            print(f"        check {c['name']}: observed {c['observed']} "
                  f"vs threshold {c['threshold']}")
    if v["allowed_detectors_seen"]:
        print(f"        expected detectors seen: "
              f"{', '.join(v['allowed_detectors_seen'])}")


if __name__ == "__main__":
    sys.exit(main())
