#!/usr/bin/env bash
# Memberlist live-interop proof (VERDICT r4 item 6): a reference Go
# gubernator and a gubernator_tpu node must discover each other over the
# real hashicorp/memberlist wire and route a GLOBAL limit across the
# implementation boundary.
#
# Usage:  GUBER_REFERENCE_PATH=/path/to/mailgun-gubernator \
#           ./scripts/interop/run_interop.sh
#
# Requires Docker + docker compose and network egress to build the two
# images. Exits 0 on proof, non-zero with a diagnostic otherwise.
set -euo pipefail
cd "$(dirname "$0")"

: "${GUBER_REFERENCE_PATH:?set GUBER_REFERENCE_PATH to the reference Go checkout}"

cleanup() { docker compose down -v --remove-orphans >/dev/null 2>&1 || true; }
trap cleanup EXIT

echo "== building images"
docker compose build

echo "== starting the mixed fleet"
docker compose up -d

REF=http://127.0.0.1:8180
TPU=http://127.0.0.1:8280

peers() {  # $1 = base url -> peer count from the health check
  curl -sf "$1/v1/HealthCheck" | python3 -c \
    'import json,sys; d=json.load(sys.stdin); print(d.get("peerCount", d.get("peer_count", 0)))' \
    2>/dev/null || echo 0
}

echo "== waiting for mutual discovery (both health checks at 2 peers)"
for i in $(seq 1 60); do
  R=$(peers "$REF"); T=$(peers "$TPU")
  [ "$R" = 2 ] && [ "$T" = 2 ] && break
  sleep 2
done
R=$(peers "$REF"); T=$(peers "$TPU")
if [ "$R" != 2 ] || [ "$T" != 2 ]; then
  echo "FAIL: discovery incomplete (reference sees $R peers, tpu sees $T)"
  docker compose logs --tail 50
  exit 1
fi
echo "ok: each side lists the other as a peer"

echo "== driving a GLOBAL limit across the boundary"
BODY='{"requests":[{"name":"interop","uniqueKey":"k1","hits":"1","limit":"10","duration":"60000","behavior":2}]}'
for i in $(seq 1 6); do
  curl -sf -X POST "$TPU/v1/GetRateLimits" \
    -H 'Content-Type: application/json' -d "$BODY" >/dev/null
done
sleep 3  # let the async GLOBAL pipeline broadcast
PEEK='{"requests":[{"name":"interop","uniqueKey":"k1","hits":"0","limit":"10","duration":"60000","behavior":2}]}'
REMAIN=$(curl -sf -X POST "$REF/v1/GetRateLimits" \
  -H 'Content-Type: application/json' -d "$PEEK" | python3 -c \
  'import json,sys; print(json.load(sys.stdin)["responses"][0]["remaining"])')
if [ "$REMAIN" -ge 10 ]; then
  echo "FAIL: reference never saw the tpu node's GLOBAL hits (remaining=$REMAIN)"
  exit 1
fi
echo "ok: GLOBAL hits from the tpu node visible at the reference node (remaining=$REMAIN)"
echo "PASS: memberlist wire interop + cross-impl GLOBAL"
