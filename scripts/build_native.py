#!/usr/bin/env python
"""Rebuild every native component from source (`make native`).

Produces the exact mtime-keyed cache names the runtime loaders
(gubernator_tpu/native/__init__.py) and the TSan suite (tests/test_tsan.py)
expect, deleting stale caches — so after editing keydir.cpp or peerlink.cpp
one command restores a verifiable binary set:

    _keydir_<mtime>.so          g++ -O2            (runtime)
    _peerlink_<mtime>.so        g++ -O2            (runtime)
    _tsan_keydir_<mtime>.so     g++ -O1 -g -fsanitize=thread
    _tsan_peerlink_<mtime>.so   g++ -O1 -g -fsanitize=thread

tests/test_native_build.py is the matching drift check: it fails when a
cached .so predates its source or misses the exported symbol surface.
"""

import os
import subprocess
import sys
import sysconfig

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
sys.path.insert(0, ROOT)

NATIVE = os.path.join(ROOT, "gubernator_tpu", "native")
PYINC = f"-I{sysconfig.get_paths()['include']}"

# warnings are errors for the native tier: the sources must stay clean
# under the same -Wall -Wextra sweep guberlint's native-warnings rule
# runs (gubernator_tpu/analysis/rules/native.py) — keep both flag sets
# in lockstep
WARN = ["-Wall", "-Wextra", "-Werror"]

# (source, cache prefix, extra flags) for each build flavor
BUILDS = [
    ("keydir.cpp", "_keydir_", [*WARN, "-O2", PYINC]),
    ("peerlink.cpp", "_peerlink_", [*WARN, "-O2"]),
    ("keydir.cpp", "_tsan_keydir_",
     [*WARN, "-O1", "-g", "-fsanitize=thread", "-pthread", PYINC]),
    ("peerlink.cpp", "_tsan_peerlink_",
     [*WARN, "-O1", "-g", "-fsanitize=thread", "-pthread"]),
]


def build(src_name: str, prefix: str, flags) -> str:
    src = os.path.join(NATIVE, src_name)
    mtime = int(os.stat(src).st_mtime)
    path = os.path.join(NATIVE, f"{prefix}{mtime}.so")
    fresh = not os.path.exists(path)
    if fresh:
        tmp = path + ".tmp"
        subprocess.run(
            ["g++", *flags, "-shared", "-fPIC", "-std=c++17",
             "-o", tmp, src],
            check=True)
        os.replace(tmp, path)
    for name in os.listdir(NATIVE):
        if name.startswith(prefix) and name.endswith(".so") and \
                os.path.join(NATIVE, name) != path:
            os.unlink(os.path.join(NATIVE, name))
    print(f"{'built' if fresh else 'cached'}  {os.path.relpath(path, ROOT)}")
    return path


def main() -> int:
    for src, prefix, flags in BUILDS:
        build(src, prefix, flags)
    return 0


if __name__ == "__main__":
    sys.exit(main())
