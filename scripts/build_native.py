#!/usr/bin/env python
"""Rebuild every native component from source (`make native`).

Produces the exact mtime-keyed cache names the runtime loaders
(gubernator_tpu/native/__init__.py) and the TSan suite (tests/test_tsan.py)
expect, deleting stale caches — so after editing keydir.cpp or peerlink.cpp
one command restores a verifiable binary set:

    _keydir_<mtime>.so          g++ -O2            (runtime)
    _peerlink_<mtime>.so        g++ -O2            (runtime)
    _tsan_keydir_<mtime>.so     g++ -O1 -g -fsanitize=thread
    _tsan_peerlink_<mtime>.so   g++ -O1 -g -fsanitize=thread

`--sanitize` (`make sanitize`) builds the full sanitizer matrix instead:
the TSan pair above (pre-warming the exact cache tests/test_tsan.py
keys on) plus ASan and UBSan variants of both sources —

    _asan_keydir_<mtime>.so     g++ -O1 -g -fsanitize=address
    _asan_peerlink_<mtime>.so   g++ -O1 -g -fsanitize=address
    _ubsan_keydir_<mtime>.so    g++ -O1 -g -fsanitize=undefined
    _ubsan_peerlink_<mtime>.so  g++ -O1 -g -fsanitize=undefined

(TSan and ASan are mutually exclusive instrumentation, hence separate
.so flavors; all share the mtime cache keying so a rebuild is a no-op
until the source changes.)

tests/test_native_build.py is the matching drift check: it fails when a
cached .so predates its source or misses the exported symbol surface.
"""

import os
import subprocess
import sys
import sysconfig

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
sys.path.insert(0, ROOT)

NATIVE = os.path.join(ROOT, "gubernator_tpu", "native")
PYINC = f"-I{sysconfig.get_paths()['include']}"

# warnings are errors for the native tier: the sources must stay clean
# under the same -Wall -Wextra sweep guberlint's native-warnings rule
# runs (gubernator_tpu/analysis/rules/native.py) — keep both flag sets
# in lockstep
WARN = ["-Wall", "-Wextra", "-Werror"]

# (source, cache prefix, extra flags) for each build flavor
TSAN_BUILDS = [
    ("keydir.cpp", "_tsan_keydir_",
     [*WARN, "-O1", "-g", "-fsanitize=thread", "-pthread", PYINC]),
    ("peerlink.cpp", "_tsan_peerlink_",
     [*WARN, "-O1", "-g", "-fsanitize=thread", "-pthread"]),
]

BUILDS = [
    ("keydir.cpp", "_keydir_", [*WARN, "-O2", PYINC]),
    ("peerlink.cpp", "_peerlink_", [*WARN, "-O2"]),
    *TSAN_BUILDS,
]

# ASan catches what TSan structurally cannot (heap overflow,
# use-after-free on the single-threaded paths); UBSan the arithmetic /
# alignment traps in the frame codecs. -fno-omit-frame-pointer keeps
# ASan stacks honest at -O1.
SANITIZE_BUILDS = [
    *TSAN_BUILDS,
    ("keydir.cpp", "_asan_keydir_",
     [*WARN, "-O1", "-g", "-fsanitize=address", "-fno-omit-frame-pointer",
      "-pthread", PYINC]),
    ("peerlink.cpp", "_asan_peerlink_",
     [*WARN, "-O1", "-g", "-fsanitize=address", "-fno-omit-frame-pointer",
      "-pthread"]),
    ("keydir.cpp", "_ubsan_keydir_",
     [*WARN, "-O1", "-g", "-fsanitize=undefined", "-pthread", PYINC]),
    ("peerlink.cpp", "_ubsan_peerlink_",
     [*WARN, "-O1", "-g", "-fsanitize=undefined", "-pthread"]),
]


def build(src_name: str, prefix: str, flags) -> str:
    src = os.path.join(NATIVE, src_name)
    mtime = int(os.stat(src).st_mtime)
    path = os.path.join(NATIVE, f"{prefix}{mtime}.so")
    fresh = not os.path.exists(path)
    if fresh:
        tmp = path + ".tmp"
        subprocess.run(
            ["g++", *flags, "-shared", "-fPIC", "-std=c++17",
             "-o", tmp, src],
            check=True)
        os.replace(tmp, path)
    for name in os.listdir(NATIVE):
        if name.startswith(prefix) and name.endswith(".so") and \
                os.path.join(NATIVE, name) != path:
            os.unlink(os.path.join(NATIVE, name))
    print(f"{'built' if fresh else 'cached'}  {os.path.relpath(path, ROOT)}")
    return path


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    builds = SANITIZE_BUILDS if "--sanitize" in argv else BUILDS
    for src, prefix, flags in builds:
        build(src, prefix, flags)
    return 0


if __name__ == "__main__":
    sys.exit(main())
