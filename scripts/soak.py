"""Fault-injection soak: a live cluster under sustained concurrent load
while nodes are killed and restarted.

The reference's fault story is a one-shot test (stop 5 of 6 instances,
assert unhealthy, restart — functional_test.go:507-569). This harness runs
the same machinery continuously: worker threads hammer every node with
mixed traffic while a chaos thread stops and restarts instances on their
original ports, and the whole run is judged on invariants rather than
scripted steps:

- SAFETY (never violated): for every key epoch — the life of one bucket
  between state losses — admitted hits never exceed the limit. Killing a
  node loses its buckets (the reference's accepted tradeoff,
  architecture.md:5-11), which RESETS an epoch, never inflates one.
- LIVENESS: errors are allowed only while a node is down (connection
  refused / deadline toward the dead owner); after the last restart the
  cluster must settle back to fully-successful traffic.
- RECOVERY: keys owned by a killed node come back fresh (full limit) and
  drain correctly again.

Usage: python scripts/soak.py [--seconds 30] [--nodes 4] [--threads 8]
Exit code 0 = all invariants held; prints one JSON line per phase.
"""

from __future__ import annotations

import argparse
import collections
import json
import random
import sys
import threading
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("soak")
    ap.add_argument("--seconds", type=float, default=30.0)
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--keys", type=int, default=24)
    ap.add_argument("--limit", type=int, default=1000)
    ap.add_argument("--chaos-period", type=float, default=3.0,
                    help="seconds between kill/restart cycles")
    args = ap.parse_args(argv)

    import os

    import jax

    # CPU by default: the soak measures the serving stack, and merely
    # probing the default backend would initialize a possibly-absent TPU.
    # Set SOAK_PLATFORM=tpu (or any JAX platform) to override.
    jax.config.update("jax_platforms", os.environ.get("SOAK_PLATFORM", "cpu"))

    import grpc

    from gubernator_tpu.cluster.harness import LocalCluster
    from gubernator_tpu.service.grpc_api import dial_v1
    from gubernator_tpu.service.pb import gubernator_pb2 as pb
    from gubernator_tpu.types import Behavior

    cluster = LocalCluster().start(args.nodes)
    keys = [f"soak_{i}" for i in range(args.keys)]
    # ~25% of traffic drives Behavior=GLOBAL keys — the reference's own
    # fault test targets GLOBAL (functional_test.go:507-569); judged by
    # post-chaos convergence, not per-epoch admission (eventual consistency
    # admits bounded overshoot by design, PARITY.md #3)
    gkeys = [f"gsoak_{i}" for i in range(max(2, args.keys // 4))]
    stop = threading.Event()
    chaos_done = threading.Event()
    settled = threading.Event()  # 2s after the last restart: reconnect grace
    lock = threading.Lock()
    # admissions per (key, reset_time) epoch — see the SAFETY note below
    admitted = collections.Counter()
    violations = []
    errors_during_chaos = 0
    errors_after_chaos = 0
    error_samples = []
    total = 0

    def worker(wid: int):
        nonlocal errors_during_chaos, errors_after_chaos, total
        rng = random.Random(wid)
        while not stop.is_set():
            addr = cluster.instances[rng.randrange(args.nodes)].address
            is_global = rng.random() < 0.25
            key = rng.choice(gkeys if is_global else keys)
            behavior = int(Behavior.GLOBAL) if is_global else 0
            try:
                stub = dial_v1(addr)
                r = stub.GetRateLimits(pb.GetRateLimitsReq(requests=[
                    pb.RateLimitReq(name="soak", unique_key=key, hits=1,
                                    limit=args.limit, duration=3_600_000,
                                    behavior=behavior)
                ]), timeout=10,
                    # settle-phase liveness is judged on the serving stack,
                    # not on grpc client reconnect races
                    wait_for_ready=chaos_done.is_set()).responses[0]
            except grpc.RpcError as e:
                with lock:
                    if settled.is_set():
                        errors_after_chaos += 1
                        if len(error_samples) < 5:
                            error_samples.append(f"rpc:{e.code()}")
                    else:
                        errors_during_chaos += 1
                continue
            with lock:
                total += 1
                if r.error:
                    if settled.is_set():
                        errors_after_chaos += 1
                        if len(error_samples) < 5:
                            error_samples.append(r.error[:120])
                    else:
                        errors_during_chaos += 1
                elif r.status == 0 and not is_global:
                    # SAFETY: within one epoch, admissions <= limit. The
                    # epoch is identified by reset_time — a restarted owner
                    # recreates the bucket with a fresh CreatedAt, so its
                    # reset_time moves. Counting per (key, reset_time) is
                    # immune to response-reordering races that a
                    # "remaining jumped back up" heuristic trips over:
                    # admission order and response-processing order differ
                    # under concurrency.
                    epoch = (key, r.reset_time)
                    admitted[epoch] += 1
                    if admitted[epoch] > args.limit:
                        violations.append(
                            f"{key}@{r.reset_time}: "
                            f"{admitted[epoch]} admissions > limit")

    def chaos():
        rng = random.Random(99)
        deadline = time.monotonic() + args.seconds * 0.7
        cycles = 0
        while time.monotonic() < deadline and not stop.is_set():
            time.sleep(args.chaos_period)
            idx = rng.randrange(args.nodes)
            victim = cluster.instances[idx]
            port = int(victim.address.rsplit(":", 1)[1])
            cluster.stop_instance_at(idx)
            time.sleep(args.chaos_period / 2)
            cluster.start_instance(fixed_port=port)
            cluster.sync_peers()
            cycles += 1
        chaos_done.set()
        print(json.dumps({"phase": "chaos", "kill_restart_cycles": cycles}),
              flush=True)

    workers = [threading.Thread(target=worker, args=(w,))
               for w in range(args.threads)]
    chaos_thread = threading.Thread(target=chaos)
    for t in workers:
        t.start()
    chaos_thread.start()

    chaos_thread.join()
    time.sleep(2.0)  # reconnect grace: bounded backoff reconnects within ~1s
    settled.set()
    settle = time.monotonic()
    # settle phase: post-chaos traffic must succeed
    with lock:
        errors_after_chaos = 0
    while time.monotonic() - settle < max(args.seconds * 0.3, 8.0):
        time.sleep(0.5)
    stop.set()
    for t in workers:
        t.join(timeout=30)

    # CONVERGENCE: with traffic quiesced, every node's view of every GLOBAL
    # key — owner authoritative or non-owner mirror — must agree. Broadcasts
    # are request-triggered, so a key idle through the settle phase can hold
    # a legitimately stale mirror: the first probe pass touches every
    # (key, node) pair (a hits=0 GLOBAL request queues through the async
    # pipelines and the owner rebroadcasts), then a few 50 ms test sync
    # windows elapse, then the judged pass runs. Any error — application or
    # RPC, uniform or not — fails the check; ignoring them could false-pass
    # a cluster-wide GLOBAL breakage as "converged".
    def probe(key):
        views = {}
        for ci in cluster.instances:
            try:
                r = dial_v1(ci.address).GetRateLimits(
                    pb.GetRateLimitsReq(requests=[
                        pb.RateLimitReq(name="soak", unique_key=key, hits=0,
                                        limit=args.limit,
                                        duration=3_600_000,
                                        behavior=int(Behavior.GLOBAL))
                    ]), timeout=10, wait_for_ready=True).responses[0]
                views[ci.address] = (f"err:{r.error[:80]}" if r.error
                                     else r.remaining)
            except grpc.RpcError as e:
                views[ci.address] = f"rpc:{e.code()}"
        return views

    global_divergence = []
    for key in gkeys:
        probe(key)  # refresh pass: trigger owner rebroadcast to every peer
    time.sleep(1.0)
    for key in gkeys:
        views = probe(key)
        errs = [v for v in views.values() if isinstance(v, str)]
        if errs or len(set(views.values())) > 1:
            global_divergence.append({key: views})
    cluster.stop()

    ok = (not violations and errors_after_chaos == 0
          and not global_divergence)
    print(json.dumps({
        "phase": "result",
        "ok": ok,
        "total_decisions": total,
        "admission_violations": violations[:5],
        "errors_during_chaos": errors_during_chaos,
        "errors_after_chaos": errors_after_chaos,
        "error_samples": error_samples,
        "global_divergence": global_divergence[:3],
    }), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
