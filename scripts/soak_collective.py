"""Chaos soak for the cross-host collective GLOBAL tier (VERDICT r2 item 8).

Two REAL daemons form a jax.distributed process group and exchange GLOBAL
aggregates over the collective (50 ms lockstep ticks). A SIGKILL takes one
daemon down MID-TICK, and the run is judged on the defined degradation
behavior rather than scripted recovery:

- STALL -> HEALTH: the survivor's blocked tick flips its /v1/HealthCheck
  to unhealthy within the stall timeout (+ grace).
- FALLBACK WITHOUT DOUBLE COUNT: traffic at the survivor keeps being
  admitted through the gRPC tier; per-epoch admissions never exceed the
  limit (the in-flight collective contribution is delivery-uncertain and
  must NOT be re-sent; queued-but-uncontributed hits re-route once).
- CLEAN RE-JOIN: the dead daemon restarts (standalone — a broken
  jax.distributed group is not elastic; the restart rejoins the gRPC
  fleet), serves its keys again, and reports healthy. The survivor keeps
  serving through its gRPC pipelines; its health keeps reporting the
  stalled collective (the group IS broken — an operator signal, not an
  outage: correctness rides the fallback).

Usage: python scripts/soak_collective.py [--seconds 20]
Exit 0 = all invariants held; prints one JSON line per phase.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn(env_overrides, log_path, ready_timeout=240.0):
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(REPO, "tests", ".jax_cache"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    env.update(env_overrides)
    stderr = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cmd.daemon"],
        env=env, cwd=REPO, stdout=subprocess.PIPE, stderr=stderr, text=True)
    stderr.close()
    ready = threading.Event()

    def wait_ready():
        while True:
            line = proc.stdout.readline()
            if not line:
                return
            if "Ready" in line:
                ready.set()
                return

    threading.Thread(target=wait_ready, daemon=True).start()
    if not ready.wait(ready_timeout):
        proc.kill()
        raise RuntimeError(f"daemon not ready in {ready_timeout}s")
    return proc


def post(port, body, timeout=10.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/GetRateLimits",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=timeout).read())


def health(port, timeout=5.0):
    try:
        raw = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/v1/HealthCheck", timeout=timeout).read()
        return json.loads(raw)
    except Exception as e:  # noqa: BLE001
        return {"status": f"unreachable: {e}"}


def metric(port, name):
    try:
        txt = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
    except Exception:  # noqa: BLE001
        return None
    for line in txt.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            return float(line.rsplit(" ", 1)[1])
    return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("soak_collective")
    ap.add_argument("--seconds", type=float, default=20.0)
    ap.add_argument("--limit", type=int, default=100_000)
    args = ap.parse_args(argv)

    coord = f"127.0.0.1:{free_port()}"
    grpc_ports = [free_port(), free_port()]
    http_ports = [free_port(), free_port()]
    addrs = [f"127.0.0.1:{p}" for p in grpc_ports]
    stall_s = 2.0
    base_env = {
        "JAX_PLATFORMS": "cpu",
        "GUBER_BACKEND": "engine",
        "GUBER_PEERS": ",".join(addrs),
        "GUBER_CACHE_SIZE": "4096",
        "GUBER_MIN_BATCH_WIDTH": "16",
        "GUBER_MAX_BATCH_WIDTH": "128",
        "GUBER_CROSS_HOST_SYNC": "50ms",
        "GUBER_CROSS_HOST_STALL": "2s",
        "GUBER_CROSS_HOST_CAPACITY": "1024",
    }

    def boot(i, group=True):
        env = dict(base_env)
        env.update({
            "GUBER_GRPC_ADDRESS": addrs[i],
            "GUBER_HTTP_ADDRESS": f"127.0.0.1:{http_ports[i]}",
        })
        if group:
            env.update({
                "GUBER_COORDINATOR_ADDRESS": coord,
                "GUBER_NUM_HOSTS": "2",
                "GUBER_HOST_ID": str(i),
            })
        return spawn(env, f"/tmp/soak_collective_d{i}.log")

    # keys owned by the SURVIVOR (daemon 0), computed with the daemons' own
    # picker (default replicated-hash over the static peer list): traffic on
    # these must stay clean while daemon 1 is dead
    sys.path.insert(0, REPO)
    from gubernator_tpu.cluster.pickers import (  # noqa: E402
        ReplicatedConsistentHashPicker,
    )
    from gubernator_tpu.types import PeerInfo  # noqa: E402

    picker = ReplicatedConsistentHashPicker(None, replicas=512)
    for a in addrs:
        picker.add(type("P", (), {"info": PeerInfo(address=a)})())
    d0_keys = []
    i = 0
    while len(d0_keys) < 4:
        k = f"p{i}"
        if picker.get(f"sc_{k}").info.address == addrs[0]:
            d0_keys.append(k)
        i += 1

    procs = [None, None]
    boots = [threading.Thread(target=lambda i=i: procs.__setitem__(
        i, boot(i)), daemon=True) for i in range(2)]
    for t in boots:
        t.start()
    for t in boots:
        t.join(timeout=300)
    assert all(procs), "daemon pair failed to boot"

    failures = []
    admitted = collections.Counter()  # (key, reset_time) -> admissions

    def ok(cond, msg):
        if not cond:
            failures.append(msg)
        return cond

    def drive(port, keys, n, behavior="GLOBAL", allow_errors=False):
        """n admission attempts round-robin over keys; SAFETY-counted."""
        errs = 0
        for i in range(n):
            body = {"requests": [{
                "name": "sc", "uniqueKey": keys[i % len(keys)], "hits": "1",
                "limit": str(args.limit), "duration": "3600000",
                "behavior": behavior}]}
            try:
                r = post(port, body)["responses"][0]
            except Exception:  # noqa: BLE001
                errs += 1
                continue
            if r.get("error"):
                errs += 1
                continue
            if int(r.get("status", 0) or 0) == 0:
                epoch = (keys[i % len(keys)], r.get("resetTime"))
                admitted[epoch] += 1
                if admitted[epoch] > args.limit:
                    failures.append(f"DOUBLE COUNT: {epoch}")
        if errs and not allow_errors:
            failures.append(f"{errs}/{n} errors on port {port}")
        return errs

    # ---- phase 1: converge over the collective --------------------------
    drive(http_ports[0], ["g0", "g1", "g2"], 60)
    drive(http_ports[1], ["g0", "g1", "g2"], 60)
    time.sleep(1.0)  # ~20 ticks
    drive(http_ports[1], ["g0", "g1", "g2"], 60)
    time.sleep(0.5)
    synced = (metric(http_ports[0], "cross_host_hits_synced_total") or 0) + \
             (metric(http_ports[1], "cross_host_hits_synced_total") or 0)
    ok(synced > 0, f"collective moved no hits (synced={synced})")
    ok(health(http_ports[0]).get("status") == "healthy", "d0 not healthy")
    ok(health(http_ports[1]).get("status") == "healthy", "d1 not healthy")
    print(json.dumps({"phase": "converged", "hits_synced": synced}),
          flush=True)

    # exact-accounting key: owned by the SURVIVOR, driven only at the
    # survivor, small limit — any double-apply (e.g. a stall requeue
    # re-sending an in-flight collective contribution) shows up as
    # remaining < limit - admitted. This gives the double-count invariant
    # teeth; the per-epoch counter alone could never reach args.limit.
    acct_key, acct_limit, acct_admitted = None, 300, 0
    i = 0
    while acct_key is None:
        k = f"acct{i}"
        if picker.get(f"sc_{k}").info.address == addrs[0]:
            acct_key = k
        i += 1

    def drive_acct(n):
        nonlocal acct_admitted
        for _ in range(n):
            body = {"requests": [{
                "name": "sc", "uniqueKey": acct_key, "hits": "1",
                "limit": str(acct_limit), "duration": "3600000",
                "behavior": "GLOBAL"}]}
            try:
                r = post(http_ports[0], body)["responses"][0]
            except Exception:  # noqa: BLE001
                continue
            if not r.get("error") and int(r.get("status", 0) or 0) == 0:
                acct_admitted += 1

    drive_acct(40)

    # ---- phase 2: SIGKILL daemon 1 mid-tick -----------------------------
    procs[1].send_signal(signal.SIGKILL)
    procs[1].wait()
    t_kill = time.monotonic()
    # survivor keeps serving its OWN keys through the gRPC tier the whole
    # time (forwards to the dead peer may error: allowed)
    flip_deadline = t_kill + stall_s + 6.0
    flipped = False
    while time.monotonic() < flip_deadline:
        drive(http_ports[0], ["g0", "g1", "g2"], 10, allow_errors=True)
        h = health(http_ports[0])
        if h.get("status") == "unhealthy":
            flipped = True
            break
        time.sleep(0.25)
    ok(flipped, "survivor health never flipped after peer death")
    flip_s = time.monotonic() - t_kill
    # degraded-but-correct: survivor-OWNED traffic is clean
    errs = drive(http_ports[0], d0_keys, 40, behavior="BATCHING",
                 allow_errors=True)
    ok(errs == 0, f"survivor plain traffic errored while degraded ({errs})")
    drive_acct(40)  # admissions THROUGH the chaos window
    print(json.dumps({"phase": "killed", "health_flip_s": round(flip_s, 2)}),
          flush=True)

    # ---- phase 3: restart daemon 1 standalone (gRPC fleet re-join) ------
    procs[1] = boot(1, group=False)
    settle = time.monotonic() + 5.0
    while time.monotonic() < settle:
        drive(http_ports[0], ["g0", "g1", "g2"], 10, allow_errors=True)
        drive(http_ports[1], ["g0", "g1", "g2"], 10, allow_errors=True)
        time.sleep(0.2)
    ok(health(http_ports[1]).get("status") == "healthy",
       "restarted daemon not healthy")
    # settled: traffic anywhere succeeds (the fleet is whole again on gRPC)
    e0 = drive(http_ports[0], ["g0", "g1", "g2", "p0"], 40,
               allow_errors=True)
    e1 = drive(http_ports[1], ["g0", "g1", "g2", "p0"], 40,
               allow_errors=True)
    ok(e0 == 0, f"post-rejoin errors at survivor ({e0})")
    ok(e1 == 0, f"post-rejoin errors at restarted daemon ({e1})")
    drive_acct(40)
    time.sleep(0.5)  # let async pipelines settle before the exact peek
    peek = post(http_ports[0], {"requests": [{
        "name": "sc", "uniqueKey": acct_key, "hits": "0",
        "limit": str(acct_limit), "duration": "3600000",
        "behavior": "GLOBAL"}]})["responses"][0]
    got_rem = int(peek.get("remaining", -1) or 0)
    want_rem = acct_limit - acct_admitted
    ok(got_rem == want_rem,
       f"EXACT ACCOUNTING: remaining {got_rem} != "
       f"{acct_limit} - {acct_admitted} admitted = {want_rem} "
       "(double- or under-count through the chaos)")
    print(json.dumps({"phase": "rejoined", "acct_admitted": acct_admitted,
                      "acct_remaining": got_rem}), flush=True)

    for p in procs:
        if p and p.poll() is None:
            p.terminate()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    result = {"phase": "result", "ok": not failures, "failures": failures[:5]}
    print(json.dumps(result), flush=True)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
