"""One-off tunnel calibration: upload/download bandwidth + fixed RTT.

Run on the axon rig to size the serving-path byte budget (DESIGN.md
"Off-chip transfers"). Not part of the bench suite.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

dev = jax.devices()[0]
print("device:", dev)


def t(f, n=3):
    best = 1e9
    for _ in range(n):
        t0 = time.perf_counter()
        f()
        best = min(best, time.perf_counter() - t0)
    return best


# --- fixed RTT: tiny scalar round trip
x = jnp.zeros((), jnp.int32)
f = jax.jit(lambda a: a + 1)
y = f(x); _ = int(y)
rtt = t(lambda: int(f(x)))
print(f"scalar round trip: {rtt*1e3:.1f} ms")

for mb in (2, 8, 32):
    n = mb * (1 << 20) // 4
    host = np.random.randint(0, 100, n, np.int32)
    up = t(lambda: jax.device_put(host, dev).block_until_ready())
    devarr = jax.device_put(host, dev)
    g = jax.jit(lambda a: a + 1)
    devarr2 = g(devarr); devarr2.block_until_ready()
    down = t(lambda: np.asarray(devarr2))
    print(f"{mb:3d} MB  up {up:6.3f}s ({mb/up:6.1f} MB/s)   "
          f"down {down:6.3f}s ({mb/down:6.1f} MB/s)")
