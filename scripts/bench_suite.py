"""Serving-stack benchmark suite — ports of the reference's shipped
benchmarks plus BASELINE.json's scenario configs, against a real in-process
loopback cluster (the reference's own rig: benchmark_test.go:28-135 over
cluster/cluster.go).

Scenarios:
  get_rate_limit             BenchmarkServer_GetRateLimit (single-req RPC)
  get_peer_no_batching       BenchmarkServer_GetPeerRateLimitNoBatching
  health_check               BenchmarkServer_Ping
  thundering_herd            BenchmarkServer_ThunderingHeard (100-wide fanout)
  thundering_herd_mp         same herd from 4 client PROCESSES (server capacity,
                             not the bench process's GIL)
  grpc_native_wire_rps       the native gRPC/HTTP/2 front under a lean raw-h2
                             pipelined client (h2load methodology): the
                             wire-compatible surface's server capacity
  grpc_native_unbatched_rps  same front, pipelined grpcio client futures
  grpc_native_herd_mp        same front, 4-process grpcio herd (1-node)
  grpc_native_routed_herd_mp same herd against the multi-node cluster (full
                             routing: most keys forward to their owner)
  leaky_bucket               LEAKY_BUCKET drain (BASELINE.json configs[1])
  global_mode                Behavior=GLOBAL aggregation (configs[2])
  gregorian                  DURATION_IS_GREGORIAN resets (configs[3])
  multi_region               2-DC cluster, MULTI_REGION hits (configs[4])

Each scenario prints one JSON line {"bench", "ops_per_s", "p50_ms",
"p99_ms", "n", ...}. The serving tier is host code: by default the suite
pins JAX to CPU so the numbers measure the gRPC/batching/host path the way
the reference's Go benchmarks do (the device-kernel headline is bench.py's
job; on a tunneled TPU every dispatch pays ~270 ms RTT, which would measure
the tunnel, not the framework). Pass --platform=default to keep the ambient
device.

Usage: python scripts/bench_suite.py [--seconds 2.0] [--nodes 3]
       [--only name[,name...]] [--platform cpu|default]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import string
import sys
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentile(sorted_ms, q: float) -> float:
    if not sorted_ms:
        return 0.0
    idx = min(len(sorted_ms) - 1, int(q * (len(sorted_ms) - 1) + 0.5))
    return sorted_ms[idx]


def _rand_key(rng, n=10) -> str:
    # reference: client.go RandomString(10)
    return "".join(rng.choice(string.ascii_lowercase) for _ in range(n))


def run_serial(fn, seconds: float, warmup: int = 50):
    """b.N-style loop: run fn for `seconds` after warmup; returns stats."""
    for _ in range(warmup):
        fn()
    lat = []
    t_end = time.perf_counter() + seconds
    t0 = time.perf_counter()
    while time.perf_counter() < t_end:
        s = time.perf_counter()
        fn()
        lat.append((time.perf_counter() - s) * 1e3)
    elapsed = time.perf_counter() - t0
    lat.sort()
    return {
        "ops_per_s": round(len(lat) / elapsed, 1),
        "p50_ms": round(_percentile(lat, 0.50), 3),
        "p99_ms": round(_percentile(lat, 0.99), 3),
        "n": len(lat),
    }


def run_fanout(fn, seconds: float, width: int = 100, warmup: int = 50):
    """ThunderingHeard rig: `width` concurrent callers
    (reference: benchmark_test.go:108-135 syncutil.NewFanOut(100))."""
    for _ in range(warmup):
        fn()
    lat = []
    pool = ThreadPoolExecutor(max_workers=width)
    t_end = time.perf_counter() + seconds

    def timed():
        s = time.perf_counter()
        fn()
        return (time.perf_counter() - s) * 1e3

    t0 = time.perf_counter()
    futures = [pool.submit(timed) for _ in range(width)]
    while True:
        done, futures = futures, []
        for f in done:
            lat.append(f.result())
            if time.perf_counter() < t_end:
                futures.append(pool.submit(timed))
        if not futures:
            break
    elapsed = time.perf_counter() - t0
    pool.shutdown()
    lat.sort()
    return {
        "ops_per_s": round(len(lat) / elapsed, 1),
        "p50_ms": round(_percentile(lat, 0.50), 3),
        "p99_ms": round(_percentile(lat, 0.99), 3),
        "n": len(lat),
        "fanout": width,
    }


def _herd_worker(address: str, seconds: float, threads: int, seed: int, out_q):
    """One client PROCESS of the multiprocess herd (spawned): `threads`
    concurrent single-request callers against `address` for `seconds`.
    Runs in its own interpreter so the parent's GIL stops capping the
    offered load — the in-process thread herd (run_fanout) measures the
    client as much as the server."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # never touch a device
    import time as _time
    from concurrent.futures import ThreadPoolExecutor as _Pool

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.types import RateLimitReq

    try:
        client = V1Client(address)

        def loop(tid: int):
            rng = random.Random(seed * 1000 + tid)
            lat = []
            mk = lambda: RateLimitReq(
                name="get_rate_limit_benchmark", unique_key=_rand_key(rng),
                hits=1, limit=10, duration=5_000)
            client.get_rate_limits([mk()], timeout=30)  # connect + warm
            t_end = _time.perf_counter() + seconds
            while _time.perf_counter() < t_end:
                s = _time.perf_counter()
                client.get_rate_limits([mk()], timeout=30)
                lat.append((_time.perf_counter() - s) * 1e3)
            return lat

        out = []
        t0 = _time.perf_counter()
        with _Pool(max_workers=threads) as pool:
            for chunk in pool.map(loop, range(threads)):
                out.extend(chunk)
        out_q.put((out, _time.perf_counter() - t0))
    except Exception as e:  # noqa: BLE001 — a dead worker must not wedge
        out_q.put(("error", repr(e)))  # the parent (cf. bench.py watchdog)


def run_herd_mp(address: str, seconds: float, procs: int = 4,
                threads: int = 25):
    """ThunderingHeard with the client herd spread over `procs` real
    processes (procs*threads concurrent callers) so the measurement is
    server capacity, not the benchmarking process's GIL."""
    import multiprocessing as mp

    import queue as _queue

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    workers = [
        ctx.Process(target=_herd_worker,
                    args=(address, seconds, threads, p, q), daemon=True)
        for p in range(procs)
    ]
    for w in workers:
        w.start()
    lat, spans, failures = [], [], []
    pending = len(workers)
    deadline = time.monotonic() + seconds + 90
    while pending and time.monotonic() < deadline:
        try:
            item = q.get(timeout=1.0)
        except _queue.Empty:
            # a worker that died without reporting must not wedge the suite
            if not any(w.is_alive() for w in workers):
                break
            continue
        pending -= 1
        if isinstance(item, tuple) and item and item[0] == "error":
            failures.append(item[1])
        else:
            chunk, span = item
            lat.extend(chunk)
            spans.append(span)
    for w in workers:
        w.join(timeout=10)
        if w.is_alive():
            w.terminate()
    lat.sort()
    # completions over the measured window, same methodology as
    # run_serial/run_fanout (dividing by nominal `seconds` would count
    # requests still in flight at the cutoff)
    elapsed = max(spans) if spans else seconds
    out = {
        "ops_per_s": round(len(lat) / elapsed, 1),
        "p50_ms": round(_percentile(lat, 0.50), 3),
        "p99_ms": round(_percentile(lat, 0.99), 3),
        "n": len(lat),
        "fanout": procs * threads,
        "client_procs": procs,
    }
    if failures or pending:
        out["worker_failures"] = len(failures) + pending
        out["first_failure"] = failures[0] if failures else "no report"
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=2.0)
    ap.add_argument("--nodes", type=int, default=3)
    ap.add_argument("--only", type=str, default="")
    ap.add_argument("--platform", choices=["cpu", "default"], default="cpu")
    args = ap.parse_args(argv)

    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.cluster.harness import LocalCluster
    from gubernator_tpu.service.peer_client import PeerClient
    from gubernator_tpu.types import Algorithm, Behavior, PeerInfo, RateLimitReq
    from gubernator_tpu.utils.gregorian import GREGORIAN_MINUTES

    rng = random.Random(42)

    def req(name, key, **kw):
        defaults = dict(hits=1, limit=10, duration=5_000)
        defaults.update(kw)
        return RateLimitReq(name=name, unique_key=key, **defaults)

    print(
        f"# bench_suite: {args.nodes}-node loopback cluster, "
        f"{args.seconds:.1f}s/scenario, platform={args.platform}",
        file=sys.stderr,
    )
    cluster = LocalCluster().start(
        args.nodes, datacenters=["dc-a"] * (args.nodes - 1) + ["dc-b"]
    )
    # wire peerlink between the nodes, as the daemon does by default
    # (GUBER_PEER_LINK_OFFSET=1000): inter-node forwarding rides the native
    # transport; scenarios that fail to wire it fall back to gRPC silently
    node_links = []
    try:
        from gubernator_tpu.cluster.harness import wire_peerlink

        node_links = wire_peerlink(cluster)
        print(f"# peerlink between nodes: "
              f"{'wired' if node_links else 'DISABLED'}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — bench must run without native
        print(f"# peerlink between nodes unavailable: {e}", file=sys.stderr)
    try:
        client = V1Client(rng.choice(cluster.instances).address)

        def bench_get_rate_limit():
            # reference: benchmark_test.go:53-77
            return run_serial(
                lambda: client.get_rate_limits(
                    [req("get_rate_limit_benchmark", _rand_key(rng))]
                ),
                args.seconds,
            )

        def bench_get_peer_no_batching():
            # reference: benchmark_test.go:28-51 — direct PeerClient unary
            ci = rng.choice(cluster.instances)
            peer = PeerClient(
                cluster.instances[0].instance.conf.behaviors,
                PeerInfo(address=ci.address, datacenter=ci.datacenter),
            )
            try:
                return run_serial(
                    lambda: peer.get_peer_rate_limit(
                        req(
                            "get_peer_rate_limits_benchmark",
                            _rand_key(rng),
                            behavior=Behavior.NO_BATCHING,
                            duration=5,
                        )
                    ),
                    args.seconds,
                )
            finally:
                peer.shutdown()

        def bench_get_rate_limit_batch():
            # the design point: clients batch (reference README.md:113-115 —
            # production traffic rides 500µs windows up to 1000 wide).
            # ops_per_s here counts CALLS; requests/s = ops_per_s * 100.
            def call():
                client.get_rate_limits(
                    [
                        req("get_rate_limit_benchmark", _rand_key(rng))
                        for _ in range(100)
                    ],
                    timeout=30,
                )

            stats = run_serial(call, args.seconds, warmup=10)
            stats["requests_per_s"] = round(stats["ops_per_s"] * 100, 1)
            return stats

        def bench_health_check():
            # reference: benchmark_test.go:80-97
            return run_serial(lambda: client.health_check(), args.seconds)

        def bench_thundering_herd():
            # reference: benchmark_test.go:108-135
            return run_fanout(
                lambda: client.get_rate_limits(
                    [req("get_rate_limit_benchmark", _rand_key(rng))]
                ),
                args.seconds,
            )

        def bench_thundering_herd_mp():
            # same herd, client spread over real processes: server capacity
            return run_herd_mp(
                rng.choice(cluster.instances).address, args.seconds)

        def bench_leaky_bucket():
            return run_serial(
                lambda: client.get_rate_limits(
                    [
                        req(
                            "leaky_benchmark",
                            _rand_key(rng),
                            algorithm=Algorithm.LEAKY_BUCKET,
                            limit=100,
                            duration=60_000,
                        )
                    ]
                ),
                args.seconds,
            )

        def bench_global_mode():
            return run_serial(
                lambda: client.get_rate_limits(
                    [
                        req(
                            "global_benchmark",
                            _rand_key(rng),
                            behavior=Behavior.GLOBAL,
                            limit=1_000_000,
                        )
                    ]
                ),
                args.seconds,
            )

        def bench_gregorian():
            return run_serial(
                lambda: client.get_rate_limits(
                    [
                        req(
                            "gregorian_benchmark",
                            _rand_key(rng),
                            behavior=Behavior.DURATION_IS_GREGORIAN,
                            duration=GREGORIAN_MINUTES,
                            limit=1_000_000,
                        )
                    ]
                ),
                args.seconds,
            )

        def bench_peerlink_hop():
            # the native peer transport vs get_peer_no_batching's gRPC hop
            # (VERDICT r1 item 1: the reference's forwarded hop is ~30 µs,
            # README.md:104; python gRPC pays ~0.4-0.8 ms)
            from gubernator_tpu.service.peerlink import (
                METHOD_GET_PEER_RATE_LIMITS,
                PeerLinkClient,
                PeerLinkService,
            )

            ci = rng.choice(cluster.instances)
            svc = PeerLinkService(ci.instance, port=0)
            cli = PeerLinkClient(f"127.0.0.1:{svc.port}")
            try:
                return run_serial(
                    lambda: cli.call(
                        METHOD_GET_PEER_RATE_LIMITS,
                        [req("peerlink_benchmark", _rand_key(rng),
                             duration=5)],
                        5.0,
                    ),
                    args.seconds,
                )
            finally:
                cli.close()
                svc.close()

        def bench_peerlink_unbatched_rps():
            # server capacity under pipelined UNBATCHED load: every RPC is
            # one single-request frame; WINDOW outstanding keeps the link
            # busy the way a fleet of independent callers would. Done bar
            # (VERDICT r1 item 1): >= 20k unbatched RPC/s/node.
            from gubernator_tpu.service import peerlink as pl

            ci = rng.choice(cluster.instances)
            svc = pl.PeerLinkService(ci.instance, port=0)
            cli = pl.PeerLinkClient(f"127.0.0.1:{svc.port}")
            try:
                WINDOW = 64
                done = 0
                inflight = []
                deadline = time.perf_counter() + args.seconds
                t0 = time.perf_counter()
                while time.perf_counter() < deadline or inflight:
                    while (len(inflight) < WINDOW
                           and time.perf_counter() < deadline):
                        fut, _rid = cli.call_async(
                            pl.METHOD_GET_PEER_RATE_LIMITS,
                            [req("peerlink_rps", _rand_key(rng), duration=5)])
                        inflight.append(fut)
                    inflight.pop(0).result(timeout=30.0)
                    done += 1
                el = time.perf_counter() - t0
                return {"ops": done, "ops_per_s": round(done / el, 1),
                        "pipeline_window": WINDOW}
            finally:
                cli.close()
                svc.close()

        def bench_peerlink_herd():
            # VERDICT r1 item 5 done bar: p99 < 10 ms at 100 concurrent
            # single-request callers. Over gRPC the herd queues behind the
            # ~2.3k RPC/s GIL-bound tier (Little's law: 100/2300 = 43 ms
            # p50); over peerlink the same herd aggregates server-side.
            from gubernator_tpu.service.peerlink import (
                METHOD_GET_RATE_LIMITS,
                PeerLinkClient,
                PeerLinkService,
            )

            ci = rng.choice(cluster.instances)
            svc = PeerLinkService(ci.instance, port=0)
            clients = [PeerLinkClient(f"127.0.0.1:{svc.port}")
                       for _ in range(8)]  # 100 callers share 8 links
            k = 0
            try:
                def call():
                    nonlocal k
                    k += 1
                    clients[k % len(clients)].call(
                        METHOD_GET_RATE_LIMITS,
                        [req("peerlink_herd", _rand_key(rng))], 30.0)

                return run_fanout(call, args.seconds)
            finally:
                for c in clients:
                    c.close()
                svc.close()

        def bench_peerlink_batch100():
            # VERDICT r1 item 5 done bar: batched clients see p99 < 2 ms
            from gubernator_tpu.service.peerlink import (
                METHOD_GET_RATE_LIMITS,
                PeerLinkClient,
                PeerLinkService,
            )

            ci = rng.choice(cluster.instances)
            svc = PeerLinkService(ci.instance, port=0)
            cli = PeerLinkClient(f"127.0.0.1:{svc.port}")
            try:
                def call():
                    cli.call(
                        METHOD_GET_RATE_LIMITS,
                        [req("peerlink_b100", _rand_key(rng))
                         for _ in range(100)], 30.0)

                stats = run_serial(call, args.seconds, warmup=10)
                stats["requests_per_s"] = round(stats["ops_per_s"] * 100, 1)
                return stats
            finally:
                cli.close()
                svc.close()

        def _start_grpc_front(ci):
            from gubernator_tpu.service.peerlink import PeerLinkService

            return PeerLinkService(ci.instance, port=0, grpc_port=0)

        def _one_node_front():
            """A dedicated single-node instance + native gRPC front: the
            per-NODE capacity of the wire-compatible surface (the
            reference's >2k req/s/node headline is per node too,
            README.md:94-100). On one node the front's method-0 frames
            ride the zero-object columnar path end to end."""
            from gubernator_tpu.cluster.harness import LocalCluster

            one = LocalCluster().start(1)
            return one, _start_grpc_front(one.instances[0])

        def bench_grpc_native_unbatched_rps():
            # The WIRE-COMPATIBLE surface under pipelined unbatched load
            # (VERDICT r3 item 2 done bar: >= 5k RPC/s). Every call is a
            # real gRPC unary RPC from grpcio; WINDOW outstanding futures
            # keep the server busy the way independent callers would.
            import grpc as _grpc

            from gubernator_tpu.service.grpc_api import V1Stub
            from gubernator_tpu.service.pb import gubernator_pb2 as _pb

            one, svc = _one_node_front()
            ch = _grpc.insecure_channel(f"127.0.0.1:{svc.grpc_port}")
            stub = V1Stub(ch)
            try:
                def mk():
                    return _pb.GetRateLimitsReq(requests=[_pb.RateLimitReq(
                        name="grpc_native_rps", unique_key=_rand_key(rng),
                        hits=1, limit=10, duration=5_000)])

                stub.GetRateLimits(mk(), timeout=30)  # connect + warm
                WINDOW = 64
                done = 0
                inflight = []
                deadline = time.perf_counter() + args.seconds
                t0 = time.perf_counter()
                while time.perf_counter() < deadline or inflight:
                    while (len(inflight) < WINDOW
                           and time.perf_counter() < deadline):
                        inflight.append(
                            stub.GetRateLimits.future(mk(), timeout=30))
                    inflight.pop(0).result()
                    done += 1
                el = time.perf_counter() - t0
                return {"ops": done, "ops_per_s": round(done / el, 1),
                        "pipeline_window": WINDOW,
                        "native_hits": svc.native_hits()}
            finally:
                ch.close()
                svc.close()
                one.stop()

        def bench_grpc_native_herd_mp():
            # Wire-compatible gRPC herd from 4 client PROCESSES against
            # a single-node native front — per-node server capacity +
            # herd p99 on the surface existing gubernator clients speak
            # (done bar: herd p99 <= 10 ms).
            one, svc = _one_node_front()
            try:
                out = run_herd_mp(f"127.0.0.1:{svc.grpc_port}",
                                  args.seconds)
                out["native_hits"] = svc.native_hits()
                return out
            finally:
                svc.close()
                one.stop()

        def bench_grpc_native_wire_rps():
            # Server-side capacity of the wire-compatible surface with a
            # LEAN load generator (h2load methodology): a hand-rolled
            # HTTP/2 client pipelines unary gRPC calls over one
            # connection, costing ~10 µs/RPC client-side — on this 1-core
            # rig the grpcio client library costs ~0.2 ms/RPC and caps
            # the herd scenarios well below the server's capacity. The
            # bytes on the wire are exactly what a gRPC client sends.
            import socket
            import struct as _s

            from gubernator_tpu.service.pb import gubernator_pb2 as _pb

            one, svc = _one_node_front()
            sk = socket.create_connection(("127.0.0.1", svc.grpc_port))
            sk.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                def frame(t, flags, sid, payload=b""):
                    return (_s.pack(">I", len(payload))[1:]
                            + bytes([t, flags]) + _s.pack(">I", sid)
                            + payload)

                def lit(n, v):
                    return bytes([0, len(n)]) + n + bytes([len(v)]) + v

                hdrs = (lit(b":method", b"POST") + lit(b":scheme", b"http")
                        + lit(b":path", b"/pb.gubernator.V1/GetRateLimits")
                        + lit(b":authority", b"bench")
                        + lit(b"content-type", b"application/grpc")
                        + lit(b"te", b"trailers"))
                # distinct keys like every herd scenario — a tiny key
                # pool turns each pull into duplicate-key ROUNDS (one
                # kernel dispatch per duplicate) and measures that
                # instead of the serving path
                bodies = []
                for i in range(16384):
                    msg = _pb.GetRateLimitsReq(requests=[_pb.RateLimitReq(
                        name="grpc_wire", unique_key=_rand_key(rng),
                        hits=1, limit=10, duration=5_000,
                    )]).SerializeToString()
                    bodies.append(b"\x00" + _s.pack(">I", len(msg)) + msg)
                sk.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
                           + frame(4, 0, 0))
                WINDOW = 100  # the thundering-herd shape
                sid = 1
                inflight = 0
                done = 0
                consumed = 0
                buf = b""
                starts = {}
                lat = []
                sk.setblocking(False)
                deadline = time.perf_counter() + args.seconds
                t0 = time.perf_counter()
                while True:
                    now_t = time.perf_counter()
                    if now_t >= deadline and inflight == 0:
                        break
                    while inflight < WINDOW and now_t < deadline:
                        sk.setblocking(True)
                        sk.sendall(frame(1, 0x4, sid, hdrs)
                                   + frame(0, 0x1, sid,
                                           bodies[(sid >> 1) % 16384]))
                        sk.setblocking(False)
                        starts[sid] = time.perf_counter()
                        sid += 2
                        inflight += 1
                    try:
                        d = sk.recv(1 << 18)
                        if not d:
                            break
                        buf += d
                    except BlockingIOError:
                        time.sleep(0)
                    off = 0
                    now_t = time.perf_counter()
                    while len(buf) - off >= 9:
                        ln = int.from_bytes(buf[off:off + 3], "big")
                        if len(buf) - off - 9 < ln:
                            break
                        t = buf[off + 3]
                        fl = buf[off + 4]
                        if t == 0:
                            consumed += ln
                        if t == 1 and (fl & 0x1):  # trailers END_STREAM
                            rsid = int.from_bytes(
                                buf[off + 5:off + 9], "big") & 0x7fffffff
                            s0 = starts.pop(rsid, None)
                            if s0 is not None:
                                lat.append((now_t - s0) * 1e3)
                            done += 1
                            inflight -= 1
                        off += 9 + ln
                    buf = buf[off:]
                    if consumed > 32768:  # keep the server's send window fed
                        sk.setblocking(True)
                        sk.sendall(frame(8, 0, 0, _s.pack(">I", consumed)))
                        sk.setblocking(False)
                        consumed = 0
                el = time.perf_counter() - t0
                lat.sort()
                pulls = max(svc.stats["batches"], 1)
                return {"ops": done, "ops_per_s": round(done / el, 1),
                        "p50_ms": round(_percentile(lat, 0.50), 3),
                        "p99_ms": round(_percentile(lat, 0.99), 3),
                        "pipeline_window": WINDOW,
                        "items_per_pull": round(
                            svc.stats["requests"] / pulls, 1),
                        "client": "raw-h2 (h2load methodology)"}
            finally:
                sk.close()
                svc.close()
                one.stop()

        def bench_grpc_native_routed_herd_mp():
            # The same herd against a front on the SHARED multi-node
            # cluster: every RPC pays real routing (2/3 of keys forward
            # to the owner over peerlink) — the fleet-topology picture.
            ci = rng.choice(cluster.instances)
            svc = _start_grpc_front(ci)
            try:
                out = run_herd_mp(f"127.0.0.1:{svc.grpc_port}",
                                  args.seconds)
                out["native_hits"] = svc.native_hits()
                return out
            finally:
                svc.close()

        def bench_grpc_herd_fairness():
            # VERDICT r4 item 8: are the ~50 ms grpcio herd p99s a server
            # fairness problem or client-library queuing? On this 1-core
            # rig the herd processes cannot be pinned off the server's
            # core, so the discriminating experiment runs a LEAN probe
            # client (native LinkClient, ~10 µs client cost) through the
            # SAME server at low offered load DURING the grpcio herd:
            # an unfair/slow server would collapse the probe's p99 along
            # with the herd's; a fair server serving self-queued grpcio
            # clients keeps the probe fast while grpcio reports ~50 ms.
            import threading as _t

            from gubernator_tpu.service.peerlink import (
                METHOD_GET_RATE_LIMITS,
                PeerLinkClient,
            )

            ci = rng.choice(cluster.instances)
            svc = _start_grpc_front(ci)
            probe_lat = []
            stop = _t.Event()

            def probe_once(cli, r, sink):
                t0 = time.perf_counter()
                cli.call(METHOD_GET_RATE_LIMITS, r, 30.0)
                sink.append((time.perf_counter() - t0) * 1e3)

            def prober():
                cli = PeerLinkClient(f"127.0.0.1:{svc.port}")
                try:
                    r = [req("fair_probe", "probe_key", limit=1 << 30,
                             duration=3_600_000)]
                    cli.call(METHOD_GET_RATE_LIMITS, r, 30.0)  # warm
                    while not stop.is_set():
                        probe_once(cli, r, probe_lat)
                        stop.wait(0.005)  # low offered load
                finally:
                    cli.close()

            # baseline: the same probe ALONE (no herd) — the un-contended
            # floor the mixed-load numbers are read against
            base_lat = []
            cli0 = PeerLinkClient(f"127.0.0.1:{svc.port}")
            try:
                r0 = [req("fair_probe", "probe_key", limit=1 << 30,
                          duration=3_600_000)]
                cli0.call(METHOD_GET_RATE_LIMITS, r0, 30.0)
                t_end = time.perf_counter() + min(2.0, args.seconds)
                while time.perf_counter() < t_end:
                    probe_once(cli0, r0, base_lat)
                    time.sleep(0.005)
            finally:
                cli0.close()

            th = _t.Thread(target=prober, daemon=True)
            th.start()
            try:
                out = run_herd_mp(f"127.0.0.1:{svc.grpc_port}",
                                  args.seconds)
            finally:
                stop.set()
                th.join(timeout=10)
                svc.close()
            lat = sorted(probe_lat)
            base = sorted(base_lat)
            out["probe_rpcs"] = len(lat)
            out["probe_alone_p50_ms"] = round(_percentile(base, 0.50), 3)
            out["probe_alone_p99_ms"] = round(_percentile(base, 0.99), 3)
            out["probe_during_herd_p50_ms"] = round(
                _percentile(lat, 0.50), 3)
            out["probe_during_herd_p99_ms"] = round(
                _percentile(lat, 0.99), 3)
            out["client"] = "4-proc grpcio herd + concurrent lean probe"
            return out

        def bench_multi_region():
            return run_serial(
                lambda: client.get_rate_limits(
                    [
                        req(
                            "multi_region_benchmark",
                            _rand_key(rng),
                            behavior=Behavior.MULTI_REGION,
                            limit=1_000_000,
                        )
                    ]
                ),
                args.seconds,
            )

        def bench_native_lone_hop():
            # r3: 1-item peer-hop frames decided in the C++ IO thread
            # against the directory row mirror (keydir.cpp decide_one) —
            # no Python worker, no kernel dispatch. The first call misses
            # (kernel path) and seeds; the timed loop runs native.
            from gubernator_tpu.service.peerlink import (
                METHOD_GET_PEER_RATE_LIMITS,
                PeerLinkClient,
                PeerLinkService,
            )

            ci = rng.choice(cluster.instances)
            svc = PeerLinkService(ci.instance, port=0)
            cli = PeerLinkClient(f"127.0.0.1:{svc.port}")
            try:
                r = [req("native_hop", "hot", duration=3_600_000,
                         limit=1 << 40)]
                cli.call(METHOD_GET_PEER_RATE_LIMITS, r, 5.0)  # miss+seed
                out = run_serial(
                    lambda: cli.call(METHOD_GET_PEER_RATE_LIMITS, r, 5.0),
                    args.seconds)
                out["native_hits"] = svc.native_hits()
                return out
            finally:
                cli.close()
                svc.close()

        def bench_public_link_serial():
            # r3: the PUBLIC lean surface over the columnar link
            # (client.LinkClient, method 0 — full router semantics). On
            # this multi-node cluster frames take the routed object path
            # server-side; the standalone IO-thread fast path is measured
            # in BENCH_SUITE.md's round-3 rows.
            from gubernator_tpu.client import LinkClient

            if not node_links:
                return {"skipped": "peerlink not wired"}
            # SAME entry node as bench_get_rate_limit's V1Client, so the
            # two rows compare the transports, not the key-ownership mix
            idx = next(i for i, x in enumerate(cluster.instances)
                       if x.address == client.address)
            ci = cluster.instances[idx]
            off = node_links[idx].port - int(
                ci.address.rsplit(":", 1)[1])
            cli = LinkClient(ci.address, link_offset=off)
            try:
                if cli._link is None:
                    return {"skipped": "link did not connect"}
                return run_serial(
                    lambda: cli.get_rate_limits(
                        [req("public_link", _rand_key(rng),
                             limit=1_000_000)]),
                    args.seconds)
            finally:
                cli.close()

        def bench_herd_with_store():
            # r2 verdict item 5 'done' bar: a Store no longer disables the
            # scan-coalesced dispatch. A hot-key herd (d duplicates = d
            # rounds) against a store-attached engine retires in ~d/32
            # dispatches with ONE batched read-through + write-through,
            # vs one dispatch + two hook passes PER ROUND before.
            from gubernator_tpu.models.engine import Engine as _Engine
            from gubernator_tpu.store import MockStore

            store = MockStore()
            eng = _Engine(capacity=4096, min_width=16, max_width=256,
                          store=store)
            eng.warmup()
            herd = [req("herd_store", "hot", limit=10**9,
                        duration=3_600_000) for _ in range(64)]
            out = run_serial(lambda: eng.get_rate_limits(herd),
                             args.seconds, warmup=5)
            out["req_per_s"] = round(out["ops_per_s"] * len(herd), 1)
            out["scan_rounds"] = eng.stats.rounds
            out["on_change_calls"] = store.called["on_change"]
            return out

        scenarios = {
            "get_rate_limit": bench_get_rate_limit,
            "get_rate_limit_batch100": bench_get_rate_limit_batch,
            "get_peer_no_batching": bench_get_peer_no_batching,
            "peerlink_hop": bench_peerlink_hop,
            "peerlink_unbatched_rps": bench_peerlink_unbatched_rps,
            "peerlink_herd": bench_peerlink_herd,
            "peerlink_batch100": bench_peerlink_batch100,
            "native_lone_hop": bench_native_lone_hop,
            "public_link_serial": bench_public_link_serial,
            "herd_with_store": bench_herd_with_store,
            "health_check": bench_health_check,
            "thundering_herd": bench_thundering_herd,
            "thundering_herd_mp": bench_thundering_herd_mp,
            "grpc_native_unbatched_rps": bench_grpc_native_unbatched_rps,
            "grpc_native_wire_rps": bench_grpc_native_wire_rps,
            "grpc_native_herd_mp": bench_grpc_native_herd_mp,
            "grpc_native_routed_herd_mp": bench_grpc_native_routed_herd_mp,
            "grpc_herd_fairness": bench_grpc_herd_fairness,
            "leaky_bucket": bench_leaky_bucket,
            "global_mode": bench_global_mode,
            "gregorian": bench_gregorian,
            "multi_region": bench_multi_region,
        }
        selected = (
            [s.strip() for s in args.only.split(",") if s.strip()]
            if args.only
            else list(scenarios)
        )
        unknown = [s for s in selected if s not in scenarios]
        if unknown:
            print(f"unknown scenarios: {unknown}", file=sys.stderr)
            return 2

        for name in selected:
            stats = scenarios[name]()
            print(json.dumps({"bench": name, **stats}), flush=True)
    finally:
        for svc in node_links:
            svc.close()
        cluster.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
