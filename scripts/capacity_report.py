"""Render a node's capacity & keyspace cartography as a terminal report.

Fetches /v1/debug/keyspace and /v1/debug/history from a running node's
HTTP gateway and prints the operator-facing digest: occupancy vs
capacity, the headroom forecast (time-to-full / time-to-pressure from
the linear net-growth fit over the metrics-history ring), hit-mass
concentration, HBM footprint, and the top-K heavy hitters. This is the
same data the `capacity` anomaly detector reads — the report exists so
a human can see the run-up BEFORE the detector trips (see
docs/OPERATIONS.md "Capacity planning").

Usage:
    python scripts/capacity_report.py [host:port]   # default 127.0.0.1:80
    make capacity-report [ADDR=host:port]

Rendering is a pure function over the two endpoint bodies
(render_report), so tests exercise it offline; only main() touches the
network. Exit status: 0 rendered, 1 on fetch/shape failure.
"""

import json
import sys
import urllib.request


def _fmt_secs(s):
    if s is None:
        return "n/a"
    s = float(s)
    if s >= 86400:
        return f"{s / 86400:.1f}d"
    if s >= 3600:
        return f"{s / 3600:.1f}h"
    if s >= 60:
        return f"{s / 60:.1f}m"
    return f"{s:.0f}s"


def _fmt_bytes(n):
    if n is None:
        return "n/a"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n:.0f}B"
        n /= 1024
    return f"{n:.1f}GiB"


def _bar(fraction, width=40):
    fraction = min(max(float(fraction or 0.0), 0.0), 1.0)
    filled = int(round(fraction * width))
    return "[" + "#" * filled + "." * (width - filled) + "]"


def render_report(keyspace_body, history_body=None):
    """Pure renderer: endpoint bodies in, report text out."""
    lines = []
    rep = keyspace_body.get("report") or {}
    fc = keyspace_body.get("forecast") or {}
    occ = rep.get("occupancy") or {}
    hm = rep.get("hit_mass") or {}
    hbm = rep.get("hbm") or {}

    lines.append("capacity & keyspace cartography")
    lines.append("=" * 47)
    if not keyspace_body.get("enabled", True):
        lines.append("keyspace scan DISABLED (GUBER_KEYSPACE_SCAN=0) — "
                     "report may be stale or absent")
    if not rep:
        lines.append("no harvest yet; retry after GUBER_KEYSPACE_INTERVAL "
                     "or hit /v1/debug/keyspace?refresh=1")
        return "\n".join(lines) + "\n"

    cap = occ.get("capacity")
    fill = occ.get("fill_fraction") or 0.0
    lines.append(f"backend        {rep.get('backend', '?')}   "
                 f"(harvest {rep.get('harvest_ms', '?')} ms)")
    lines.append(f"occupancy      {occ.get('key_count')} / {cap} keys  "
                 f"{_bar(fill)} {fill:.1%}")
    lines.append(f"free slots     {occ.get('free_slots')}")
    ev = (rep.get("evictions") or {}).get("total")
    lines.append(f"evictions      {ev if ev is not None else 'n/a'} lifetime")
    lines.append(f"hbm table      {_fmt_bytes(hbm.get('total_bytes'))}")
    lines.append("")

    lines.append("headroom forecast")
    lines.append("-" * 47)
    if fc.get("projectable"):
        g = fc.get("growth_keys_per_s")
        lines.append(f"net growth     {g:+.2f} keys/s over "
                     f"{_fmt_secs(fc.get('span_s'))} "
                     f"({fc.get('samples')} ring samples)")
        lines.append(f"time to full   {_fmt_secs(fc.get('time_to_full_s'))}")
        lines.append("time to evict  "
                     f"{_fmt_secs(fc.get('time_to_pressure_s'))} "
                     f"(pressure at {fc.get('pressure_fraction', 0.9):.0%})")
    else:
        lines.append("not projectable — table shrinking/flat, already "
                     "evicting, or too few ring samples "
                     f"({fc.get('samples', 0)} so far)")
    lines.append("")

    lines.append("hit-mass concentration")
    lines.append("-" * 47)
    if hm:
        for b in ("top1", "top10", "top100"):
            share = hm.get(f"{b}_share")
            if share is not None:
                lines.append(f"{b:<9}      {share:.1%} of lifetime hits")
        z = hm.get("zipf_exponent")
        lines.append("zipf exponent  "
                     + (f"{z:.2f}" if z is not None
                        else "n/a (too few keys)"))
    else:
        lines.append("n/a")
    lines.append("")

    top = rep.get("top_keys") or []
    lines.append(f"top {len(top)} heavy hitters"
                 + ("" if rep.get("keys_resolvable", True)
                    else "  (keys unresolvable on this backend; "
                         "fingerprints shown)"))
    lines.append("-" * 47)
    for i, e in enumerate(top, 1):
        name = e.get("key")
        if name is None:
            name = f"fp=0x{e.get('fp', 0):x}"
        lines.append(f"{i:>3}. {name:<32} {e.get('hits')} hits"
                     + (f"  ({e.get('share'):.1%})"
                        if e.get("share") is not None else ""))
    if not top:
        lines.append("(none)")

    if history_body is not None:
        lines.append("")
        lines.append("metrics-history ring")
        lines.append("-" * 47)
        if not history_body.get("enabled", True):
            lines.append("ring DISABLED (GUBER_HISTORY=0) — forecaster "
                         "is blind; only instantaneous gauges remain")
        samples = history_body.get("samples") or []
        lines.append(f"{history_body.get('sample_count', 0)} samples @ "
                     f"{history_body.get('tick_s')}s tick, "
                     f"{_fmt_secs(history_body.get('retention_s'))} "
                     "retention")
        if len(samples) >= 2:
            first, last = samples[0], samples[-1]
            span = last["t"] - first["t"]
            lines.append(f"tail window    {_fmt_secs(span)}: key_count "
                         f"{first.get('key_count')} -> "
                         f"{last.get('key_count')}, decisions "
                         f"+{last.get('decisions', 0) - first.get('decisions', 0)}")
    return "\n".join(lines) + "\n"


def _fetch(addr, path, timeout=5.0):
    return json.loads(urllib.request.urlopen(
        f"http://{addr}{path}", timeout=timeout).read())


def main(argv):
    addr = argv[1] if len(argv) > 1 else "127.0.0.1:80"
    try:
        ks = _fetch(addr, "/v1/debug/keyspace")
        # n=24 keeps the tail line cheap; the ring itself holds ~2h
        hist = _fetch(addr, "/v1/debug/history?n=24")
    except Exception as e:  # noqa: BLE001 — operator tool, report and exit
        print(f"capacity_report: fetch from {addr} failed: {e}",
              file=sys.stderr)
        return 1
    try:
        sys.stdout.write(render_report(ks, hist))
    except Exception as e:  # noqa: BLE001
        print(f"capacity_report: unexpected endpoint shape: {e}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
