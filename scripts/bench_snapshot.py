"""Snapshot/restore at production scale (VERDICT r3 item 6).

10M keys through the STREAMED paths end to end:

  seed      synthetic BucketSnapshot generator -> Engine.load_snapshot
            (chunked directory insert + row inject; nothing materialized)
  save      Engine.snapshot_stream -> FileLoader.save (slab row fetches,
            vectorized filter, rows stream straight into the file)
  restore   FileLoader.load (streamed JSONL) -> fresh Engine.load_snapshot
  verify    spot peeks through the public API

Reports seconds per phase, snapshot file size, and peak host RSS.
Pins JAX to CPU by default (this measures the HOST persistence path;
through a tunneled device every slab fetch would measure the tunnel —
pass --platform=default to keep the ambient device).
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=10_000_000)
    ap.add_argument("--path", default="/tmp/guber_snapshot_bench.snap")
    ap.add_argument("--platform", default="cpu", choices=["cpu", "default"])
    ap.add_argument("--format", default="binary",
                    choices=["binary", "jsonl"])
    args = ap.parse_args()

    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    from gubernator_tpu.models.engine import Engine
    from gubernator_tpu.store import (
        BinarySnapshotLoader,
        BucketSnapshot,
        FileLoader,
    )

    N = args.keys
    NOW = 4_000_000_000_000  # far future: nothing expires mid-bench

    def synthetic():
        for i in range(N):
            yield BucketSnapshot(
                key=f"sb_{i}", algo=i & 1, limit=100, remaining=100 - (i % 7),
                duration=3_600_000, stamp=NOW - 1000, expire_at=NOW,
                status=0)

    out = {"bench": "snapshot_10m", "keys": N, "format": args.format,
           "rss0_mb": round(rss_mb(), 1)}

    eng = Engine(capacity=N, min_width=64, max_width=8192)
    t0 = time.perf_counter()
    n = eng.load_snapshot(synthetic())
    out["seed_s"] = round(time.perf_counter() - t0, 2)
    assert n == N

    if args.format == "binary":
        loader = BinarySnapshotLoader(args.path)
        t0 = time.perf_counter()
        loader.save_slabs(eng.snapshot_slabs())
    else:
        loader = FileLoader(args.path)
        t0 = time.perf_counter()
        loader.save(eng.snapshot_stream())
    out["save_s"] = round(time.perf_counter() - t0, 2)
    out["file_mb"] = round(os.path.getsize(args.path) / 1e6, 1)
    out["rss_after_save_mb"] = round(rss_mb(), 1)
    del eng

    eng2 = Engine(capacity=N, min_width=64, max_width=8192)
    t0 = time.perf_counter()
    if args.format == "binary":
        n2 = eng2.load_snapshot_slabs(loader.load_slabs())
    else:
        n2 = eng2.load_snapshot(loader.load())
    out["restore_s"] = round(time.perf_counter() - t0, 2)
    assert n2 == N, (n2, N)

    # spot-verify through the public API
    from gubernator_tpu.types import RateLimitReq

    for i in (0, N // 2, N - 1):
        key = f"sb_{i}"
        r = eng2.get_rate_limits([RateLimitReq(
            name="sb", unique_key=key[3:], hits=0, limit=100,
            duration=3_600_000, algorithm=i & 1)],  # match the row's
            now_ms=NOW - 500)[0]  # algo: a mismatch resets the bucket
        assert r.remaining == 100 - (i % 7), (key, r)
    out["peak_rss_mb"] = round(rss_mb(), 1)
    os.unlink(args.path)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
