"""Bench regression gate: diff the two newest BENCH_r*.json artifacts.

Every PR round records its bench run as BENCH_r<NN>.json, but nothing
reads consecutive rounds against each other — a throughput cliff only
surfaces when a human happens to eyeball the notes. This gate makes the
comparison mechanical: flatten each round's `parsed` section, intersect
the numeric keys, and flag

- throughput keys (`value`, `*_decisions_per_sec`, `*_speedup*`) that
  DROPPED by more than the tolerance, and
- latency keys (`*_ms`, `*p50*`/`*p99*`) that ROSE by more than the
  latency tolerance AND by more than 1 ms absolute (relative change on
  sub-millisecond samples is pure scheduler noise), and
- serving-decomposition keys (`serving_decomposition.*_s` /
  `*_s_est`) that ROSE by more than the latency tolerance AND by more
  than 1 ms absolute — a phase of the serving cycle quietly doubling is
  exactly the cliff the profiling plane exists to catch. These are only
  gated when both rounds record the same
  `serving_decomposition.derivation_version` (the r14 move from
  kernel-tier estimates to profiler-measured phases changed what the
  keys MEAN; cross-version deltas are printed informationally).

Scenario-atlas keys are split: `scenarios.<name>.verdict_pass` is gated
HARD with zero tolerance (a shape that passed its SLO envelope last
round and fails it now is a regression regardless of rig weather),
while the rest of `scenarios.*` (per-scenario latency/goodput numbers)
is operating-point context — the envelope judgment already happened
inside the verdict itself. Autopilot-armed verdicts
(`scenarios.<name>@autopilot.verdict_pass`) are gated at the SAME zero
tolerance as the static-knob ones.

Baseline keys (`serial_*`, `lockstep*`, `baseline_*`) are excluded — a
slower comparison baseline is not a product regression. The whole
`overload.*` section is excluded: each round offers load at 2x its OWN
probed capacity, so shed rate, goodput, and accepted percentiles are
responses at different operating points across rounds — and the probe
itself (`capacity_decisions_per_sec`, a 24-thread closed loop) measures
the rig's concurrent-scheduling conditions as much as the code. The
recorded band is 23.9k-90.8k across rounds 8-13, and re-running the
r13 commit unchanged on the r14 rig measured 26.5k against its
recorded 90.8k — a 3.4x swing with zero code delta, far outside any
usable tolerance (the within-round admission-vs-queueing claim is the
bench's own acceptance check, not this gate's). Everything else
overlapping is printed informationally. The default tolerances are
deliberately loose (25% throughput, 60% latency): these are shared-CPU
rig numbers whose run-to-run noise band is wide; the gate exists to
catch cliffs, not to turn scheduler jitter into red builds.

Usage:
    python scripts/bench_check.py                 # two newest rounds
    python scripts/bench_check.py --tolerance 0.4
    python scripts/bench_check.py --base BENCH_r09.json --head BENCH_r11.json

Exit status: 0 clean (or fewer than two artifacts), 1 on regression.
"""

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _flatten(obj, prefix=""):
    """Dotted-path numeric leaves; lists contribute only their length-
    independent aggregates elsewhere, so they are skipped."""
    out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(_flatten(v, f"{prefix}{k}."))
    elif isinstance(obj, bool):
        pass
    elif isinstance(obj, (int, float)):
        out[prefix[:-1]] = float(obj)
    return out


def _load(path):
    with open(path) as f:
        doc = json.load(f)
    return _flatten(doc.get("parsed", {}))


def _is_baseline(key):
    return any(tag in key for tag in ("serial", "lockstep", "baseline"))


def _is_operating_point(key):
    """Overload responses measured at that round's own 2x-capacity
    operating point — cross-round deltas reflect the operating point,
    not the code. The capacity probe itself rides along: it is a
    24-thread closed loop whose result tracks the shared rig's
    concurrent-scheduling conditions (r13's commit re-measured 3.4x
    lower on the r14 rig with zero code delta), so gating it turns rig
    weather into red builds."""
    return key.startswith("overload.")


def _is_throughput(key):
    leaf = key.rsplit(".", 1)[-1]
    return (leaf == "value" or leaf.endswith("_decisions_per_sec")
            or "speedup" in leaf)


def _is_latency(key):
    leaf = key.rsplit(".", 1)[-1]
    return leaf.endswith("_ms") or "p50" in leaf or "p99" in leaf


def _is_decomposition(key):
    """Per-phase serving-cycle seconds from the profiler-derived
    decomposition; shares/byte counts stay informational."""
    if "serving_decomposition." not in key:
        return False
    leaf = key.rsplit(".", 1)[-1]
    return leaf.endswith("_s") or leaf.endswith("_s_est")


def _is_scenario_verdict(key):
    """scenarios.<name>.verdict_pass — the atlas PASS/FAIL bit. Gated
    hard with zero tolerance: a scenario flipping 1 -> 0 across rounds
    means a traffic shape the last round served inside its SLO envelope
    no longer does, which is a regression regardless of rig weather.
    Autopilot-armed runs (`scenarios.<name>@autopilot.verdict_pass`,
    scripts/scenario_report.py --autopilot both) match this same
    pattern DELIBERATELY: a shape the closed-loop controllers served
    inside its envelope last round gets exactly the zero tolerance the
    static-knob verdicts get — the autopilot is not allowed to be a
    flakiness excuse."""
    return key.startswith("scenarios.") and key.endswith(".verdict_pass")


def _is_scenario_envelope(key):
    """Everything else under scenarios.* (latency percentiles, goodput,
    offered counts): measured at each round's own pacing on a shared
    rig, so cross-round deltas are operating-point context — the
    binding judgment already happened inside the verdict."""
    return key.startswith("scenarios.")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--base", help="older artifact (default: 2nd newest)")
    ap.add_argument("--head", help="newer artifact (default: newest)")
    ap.add_argument("--tolerance", type=float, default=0.25,
                    help="allowed relative throughput drop before "
                         "failing (default 0.25 — CPU-rig noise band)")
    ap.add_argument("--latency-tolerance", type=float, default=0.60,
                    help="allowed relative latency rise (default 0.60; "
                         "tail latencies are noisier than throughput)")
    args = ap.parse_args(argv)

    if args.base and args.head:
        base_path, head_path = args.base, args.head
    else:
        rounds = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
        if len(rounds) < 2:
            print("bench-check: fewer than two BENCH_r*.json artifacts; "
                  "nothing to compare")
            return 0
        base_path, head_path = rounds[-2], rounds[-1]

    base, head = _load(base_path), _load(head_path)
    shared = sorted(set(base) & set(head))
    if not shared:
        print(f"bench-check: no overlapping numeric keys between "
              f"{os.path.basename(base_path)} and "
              f"{os.path.basename(head_path)}")
        return 0

    print(f"bench-check: {os.path.basename(base_path)} -> "
          f"{os.path.basename(head_path)}  "
          f"(tolerance {args.tolerance:.0%})")
    ver_key = "serving_decomposition.derivation_version"
    same_derivation = base.get(ver_key) == head.get(ver_key)
    regressions = []
    for key in shared:
        b, h = base[key], head[key]
        if b == 0:
            continue
        delta = (h - b) / abs(b)
        verdict = ""
        if _is_baseline(key):
            verdict = "(baseline)"
        elif _is_scenario_verdict(key):
            verdict = "REGRESSION" if h < b else "(scenario-verdict)"
        elif _is_scenario_envelope(key):
            verdict = "(operating-point)"
        elif _is_operating_point(key):
            verdict = "(operating-point)"
        elif _is_decomposition(key):
            if not same_derivation:
                verdict = "(decomposition: re-derived)"
            elif delta > args.latency_tolerance and h - b > 1e-3:
                verdict = "REGRESSION"
            else:
                verdict = "(decomposition)"
        elif _is_throughput(key) and delta < -args.tolerance:
            verdict = "REGRESSION"
        elif (_is_latency(key) and delta > args.latency_tolerance
                and h - b > 1.0):
            verdict = "REGRESSION"
        elif not (_is_throughput(key) or _is_latency(key)):
            verdict = "(info)"
        if verdict == "REGRESSION":
            regressions.append(key)
        print(f"  {key:58s} {b:>14.4g} -> {h:>14.4g}  "
              f"{delta:+7.1%}  {verdict}")

    if regressions:
        print(f"\nbench-check FAILED: {len(regressions)} regression(s) "
              f"beyond {args.tolerance:.0%}: {', '.join(regressions)}")
        return 1
    print("\nbench-check OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
