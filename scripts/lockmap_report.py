#!/usr/bin/env python
"""lockmap_report: render, pin, and drift-check the lock-order graph.

Three modes (docs/static-analysis.md "Reading a lockmap"):

  python scripts/lockmap_report.py            # render the graph
  python scripts/lockmap_report.py --write    # (re)write lockmap.json
  python scripts/lockmap_report.py --check    # drift gate: `make lockmap`

`--check` is the CI face: it fails when the built graph and the
committed lockmap.json disagree in EITHER direction (a new acquisition
edge must be committed deliberately; a vanished edge must be removed
deliberately — same two-direction discipline as `registry-drift`), and
when any unwaived `lock-order` / `donation-flow` finding exists. The
runtime witness (obs/witness.py) loads the same baseline and fails
tier-1 on any order inversion or unknown edge observed live.

`runtime_edges` in lockmap.json are edges only the runtime witness can
see (through C callbacks, thread trampolines, or calls the bounded
static walk under-approximates); they are added by hand, each with a
`why`, and join the committed order the witness enforces.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from gubernator_tpu.analysis import core, lockmap  # noqa: E402


def render(graph: lockmap.LockGraph, verbose: bool) -> None:
    reg = sum(1 for c in graph.classes.values() if c.registered)
    print(f"lock classes: {len(graph.classes)} ({reg} witness-registered, "
          f"{len(graph.classes) - reg} auto-named)")
    for name, c in sorted(graph.classes.items()):
        tag = "" if c.registered else "  [auto]"
        print(f"  {name:28s} {c.kind:10s} {c.sites[0].render()}{tag}")
    print(f"\nacquisition-order edges: {len(graph.edges)}")
    for (src, dst), chains in sorted(graph.edges.items()):
        print(f"  {src} -> {dst}")
        shown = chains if verbose else chains[:1]
        for chain in shown:
            print(f"      {' -> '.join(chain)}")
    if graph.unresolved:
        print(f"\nunresolved lock-ish scopes: {len(graph.unresolved)} "
              "(holes in the proof — the witness is the only cover here)")
        for path, line, expr in graph.unresolved:
            print(f"  {path}:{line}: with {expr}")
    cycles = graph.cycles()
    if cycles:
        print(f"\nCYCLES: {cycles}")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        "lockmap_report",
        description="whole-repo lock acquisition-order graph")
    parser.add_argument("--root", default=REPO_ROOT)
    parser.add_argument("--write", action="store_true",
                        help="write lockmap.json (preserves runtime_edges)")
    parser.add_argument("--check", action="store_true",
                        help="drift-gate against committed lockmap.json and "
                             "fail on unwaived lock-order/donation-flow "
                             "findings (make lockmap)")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="print every witness chain per edge")
    opts = parser.parse_args(argv)

    repo = core.RepoIndex(opts.root)
    graph = lockmap.build(repo)

    if opts.write:
        prior = lockmap.load_baseline(opts.root)
        payload = lockmap.render_baseline(graph, prior)
        with open(lockmap.baseline_path(opts.root), "w",
                  encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {lockmap.baseline_path(opts.root)}: "
              f"{len(payload['classes'])} classes, "
              f"{len(payload['static_edges'])} static edges, "
              f"{len(payload['runtime_edges'])} runtime edges")
        return 0

    if not opts.check:
        render(graph, opts.verbose)
        return 0

    rc = 0
    baseline = lockmap.load_baseline(opts.root)
    if baseline is None:
        print("lockmap: no committed lockmap.json — run "
              "`python scripts/lockmap_report.py --write` and commit it")
        rc = 1
    else:
        new, gone = lockmap.diff_baseline(graph, baseline)
        for src, dst in new:
            chain = graph.edges[(src, dst)][0]
            print(f"lockmap: NEW edge {src} -> {dst} not in committed "
                  f"lockmap.json (via {' -> '.join(chain)}) — review the "
                  "ordering, then --write and commit")
            rc = 1
        for src, dst in gone:
            print(f"lockmap: committed edge {src} -> {dst} no longer "
                  "produced by the analysis — --write and commit the "
                  "removal")
            rc = 1

    findings, suppressed = core.run(opts.root,
                                    only=["lock-order", "donation-flow"])
    for f in findings:
        print(f.render())
        rc = 1
    if rc == 0:
        print(f"lockmap: clean — {len(graph.classes)} classes, "
              f"{len(graph.edges)} edges pinned, acyclic "
              f"({len(suppressed)} waived finding(s), "
              f"{len(graph.unresolved)} unresolved scope(s))")
    return rc


if __name__ == "__main__":
    sys.exit(main())
