"""Pallas row-DMA probe (VERDICT r3 item 3): can hand-issued per-row DMAs
beat XLA's gather/scatter lowering for the decide kernel's access pattern?

One grid program loops over B random rows with a DEPTH-deep pipeline of
async HBM->VMEM row copies, bumps each row, and DMAs it back. This is the
"Pallas would have to issue per-element HBM DMAs" path DESIGN.md argues
against — measured here instead of asserted. Table stays in ANY/HBM;
slots ride scalar prefetch (SMEM).

Prints one JSON line. Compare rows_per_s against
scripts/bench_access_ceiling.py's gather_scatter variant.
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np

CAP = 10_000_000
BATCH = 8_192
DEPTH = 16  # DMA pipeline depth
TARGET_S = 3.0


def main() -> None:
    import sys
    sys.setrecursionlimit(100_000)
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(slots_ref, table_ref, table_out_ref, out_ref, rbuf, wbuf,
               rsems, wsems):
        del table_out_ref  # aliased to table_ref (in-place rows)

        def i32(x):  # loop bounds are i32 (below): the modulo stays i32
            return x
        def start_read(i):
            d = i32(i % DEPTH)
            pltpu.make_async_copy(
                table_ref.at[slots_ref[i]], rbuf.at[d],
                rsems.at[d]).start()

        def body(i, carry):
            s = slots_ref[i]
            d = i32(i % DEPTH)
            # row i has landed in rbuf[i%D]
            pltpu.make_async_copy(
                table_ref.at[s], rbuf.at[d],
                rsems.at[d]).wait()

            @pl.when(i >= DEPTH)
            def _():  # wbuf[i%D]'s previous writeback must be done
                pltpu.make_async_copy(
                    wbuf.at[d], table_ref.at[s],
                    wsems.at[d]).wait()

            wbuf[d] = rbuf[d] + jnp.int32(1)
            pltpu.make_async_copy(
                wbuf.at[d], table_ref.at[s],
                wsems.at[d]).start()

            @pl.when(i + DEPTH < BATCH)
            def _():  # rbuf[i%D] is free again: prefetch row i+DEPTH
                start_read(i + DEPTH)

            return carry

        for j in range(DEPTH):
            start_read(j)
        jax.lax.fori_loop(jnp.int32(0), jnp.int32(BATCH), body, 0)

        def drain(i, c):  # tail of in-flight writebacks
            d = i32(i % DEPTH)
            pltpu.make_async_copy(
                wbuf.at[d], table_ref.at[slots_ref[i]],
                wsems.at[d]).wait()
            return c
        jax.lax.fori_loop(jnp.int32(max(BATCH - DEPTH, 0)),
                          jnp.int32(BATCH), drain, 0)
        out_ref[0] = slots_ref[0]

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(table, slots):
        out = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(1,),
                in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
                out_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                           pl.BlockSpec(memory_space=pltpu.SMEM)],
                scratch_shapes=[
                    pltpu.VMEM((DEPTH, 128), jnp.int32),
                    pltpu.VMEM((DEPTH, 128), jnp.int32),
                    pltpu.SemaphoreType.DMA((DEPTH,)),
                    pltpu.SemaphoreType.DMA((DEPTH,)),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((CAP, 128), jnp.int32),
                jax.ShapeDtypeStruct((1,), jnp.int32),
            ],
            input_output_aliases={1: 0},
        )(slots, table)
        return out[0], out[1]

    rng = np.random.RandomState(5)
    table = jnp.zeros((CAP, 128), jnp.int32)  # Mosaic tiling floor:
    # HBM slices must span 128 lanes, so the smallest per-row DMA is
    # 512 B (vs the production 64 B row) — the probe measures the
    # per-DMA ISSUE rate, which is what binds at row granularity
    # (same burst size as the i64[8] production rows; x64 + traced SMEM
    # indices trips a jax recursion bug inside pallas tracing)
    slot_sets = [jnp.asarray(
        rng.choice(CAP, BATCH, replace=False).astype(np.int32))
        for _ in range(4)]

    table, out = step(table, slot_sets[0])
    _ = int(np.asarray(out[0]))
    t0 = time.perf_counter()
    table, out = step(table, slot_sets[1])
    _ = int(np.asarray(out[0]))
    per_call = max(time.perf_counter() - t0, 1e-6)
    iters = max(4, min(400, int(TARGET_S / per_call)))
    t0 = time.perf_counter()
    for i in range(iters):
        table, out = step(table, slot_sets[i % 4])
    _ = int(np.asarray(out[0]))
    el = time.perf_counter() - t0
    print(json.dumps({
        "variant": "pallas_row_dma",
        "rows_per_s": round(iters * BATCH / el, 1),
        "depth": DEPTH, "iters": iters,
        "device": str(jax.devices()[0]),
    }), flush=True)


if __name__ == "__main__":
    main()
