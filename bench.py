"""Headline benchmark: rate-limit decisions/sec on one chip at 10M active keys.

Measures the steady-state throughput of the batched decision kernel
(ops/decide.py) against a 10M-slot key table resident in HBM — the TPU-native
replacement for the reference's per-request bucket state machines
(reference: algorithms.go:24-336, production headline >2,000 req/s/node,
README.md:94-100; see BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import json
import time

import numpy as np

REFERENCE_BASELINE_RPS = 2_000.0  # reference production node (README.md:94-100)
TABLE_CAPACITY = 10_000_000  # north-star active key count (BASELINE.json)
BATCH_WIDTH = 4_096  # one aggregated batch window
N_BATCH_VARIANTS = 8
TARGET_SECONDS = 3.0


def main() -> None:
    import jax
    import jax.numpy as jnp

    from gubernator_tpu.ops.decide import ReqBatch, decide, make_table
    from gubernator_tpu.types import Algorithm
    from gubernator_tpu.utils.platform import donation_supported

    rng = np.random.RandomState(42)
    state = make_table(TABLE_CAPACITY)

    def make_batch(seed: int) -> ReqBatch:
        r = np.random.RandomState(seed)
        # distinct slots per window (engine guarantees via rounds)
        slots = r.choice(TABLE_CAPACITY, BATCH_WIDTH, replace=False).astype(np.int32)
        return ReqBatch(
            slot=jnp.asarray(slots),
            hits=jnp.asarray(r.randint(0, 5, BATCH_WIDTH), jnp.int64),
            limit=jnp.asarray(r.choice([100, 1000, 10000], BATCH_WIDTH), jnp.int64),
            duration=jnp.asarray(np.full(BATCH_WIDTH, 60_000), jnp.int64),
            algorithm=jnp.asarray(
                r.choice(
                    [int(Algorithm.TOKEN_BUCKET), int(Algorithm.LEAKY_BUCKET)],
                    BATCH_WIDTH,
                ),
                jnp.int32,
            ),
            behavior=jnp.zeros(BATCH_WIDTH, jnp.int32),
            greg_expire=jnp.zeros(BATCH_WIDTH, jnp.int64),
            greg_interval=jnp.zeros(BATCH_WIDTH, jnp.int64),
            fresh=jnp.zeros(BATCH_WIDTH, bool),
        )

    batches = [make_batch(s) for s in range(N_BATCH_VARIANTS)]
    donate = donation_supported()
    step = jax.jit(decide, donate_argnums=(0,) if donate else ())

    now = 1_700_000_000_000
    # Warm-up: compile + populate the touched rows.
    state, resp = step(state, batches[0], now)
    jax.block_until_ready(resp)

    # Calibrate iteration count for ~TARGET_SECONDS.
    t0 = time.perf_counter()
    state, resp = step(state, batches[1], now + 1)
    jax.block_until_ready(resp)
    per_call = max(time.perf_counter() - t0, 1e-5)
    iters = max(20, min(5000, int(TARGET_SECONDS / per_call)))

    lat = np.zeros(iters)
    t_start = time.perf_counter()
    for i in range(iters):
        t1 = time.perf_counter()
        state, resp = step(state, batches[i % N_BATCH_VARIANTS], now + 2 + i)
        jax.block_until_ready(resp)
        lat[i] = time.perf_counter() - t1
    elapsed = time.perf_counter() - t_start

    decisions_per_sec = iters * BATCH_WIDTH / elapsed
    p50 = float(np.percentile(lat, 50) * 1e3)
    p99 = float(np.percentile(lat, 99) * 1e3)

    print(
        json.dumps(
            {
                "metric": "rate-limit decisions/sec/chip @ 10M active keys",
                "value": round(decisions_per_sec, 1),
                "unit": "decisions/s",
                "vs_baseline": round(decisions_per_sec / REFERENCE_BASELINE_RPS, 2),
                "batch_width": BATCH_WIDTH,
                "table_capacity": TABLE_CAPACITY,
                "window_p50_ms": round(p50, 3),
                "window_p99_ms": round(p99, 3),
                "iters": iters,
                "device": str(jax.devices()[0]),
                "donated": donate,
            }
        )
    )


if __name__ == "__main__":
    main()
