"""Headline benchmark: rate-limit decisions/sec on one chip at 10M active keys.

Measures the steady-state throughput of the batched decision kernel
(ops/decide.py) against a 10M-slot key table resident in HBM — the TPU-native
replacement for the reference's per-request bucket state machines
(reference: algorithms.go:24-336, production headline >2,000 req/s/node,
README.md:94-100; see BASELINE.md).

Measurements, all on device-resident request windows (the serving tier's
own numbers — gRPC, batching, host prep — live in scripts/bench_suite.py):

- headline: sustained throughput with backlog coalescing — the engine's
  decide_scan_packed retires K=128 windows per dispatch (the serving engine
  uses the same path at depth 32 to retire duplicate-key rounds in one
  launch — _MAX_SCAN bounds window latency);
- extras: one-window-per-dispatch throughput, synchronous per-window
  latency p50/p99 (incl. readback), and the dispatch-only enqueue rate.

EVERY timed section ends on a data-dependent fetch, not
jax.block_until_ready: on the tunneled device platform BUR can return
before the device finishes, which silently turns throughput into
enqueue-rate fiction. On this rig the honest numbers are bounded by the
tunnel's RTT and re-upload bandwidth (~72 bytes/decision of request
columns), NOT by the chip — on local TPU hardware the same harness measures
the chip. The enqueue-only rate is reported alongside as a diagnostic.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

REFERENCE_BASELINE_RPS = 2_000.0  # reference production node (README.md:94-100)
METRIC = "rate-limit decisions/sec/chip @ 10M active keys"
UNIT = "decisions/s"
TABLE_CAPACITY = 10_000_000  # north-star active key count (BASELINE.json)
BATCH_WIDTH = 8_192  # one aggregated batch window (the engine's max_width
# design point; per-dispatch cost is width-flat through the tunnel, so the
# wider window is free throughput)
SCAN_K = 128  # windows retired per dispatch; at this depth the host can't
# outrun the device — per-call wall time stops growing with K, so the
# deeper scan amortizes launch overhead ~4x vs the engine's serving-path
# default of 32 (_MAX_SCAN, which stays smaller to bound window latency)
N_VARIANTS = 4
TARGET_SECONDS = 3.0


def _init_watchdog(seconds: float = 180.0):
    """A wedged device tunnel can hang backend init indefinitely; emit a
    parseable failure line and exit instead of hanging the harness."""
    import os
    import threading

    def fire():
        print(
            json.dumps(
                {
                    "metric": METRIC,
                    "value": 0,
                    "unit": UNIT,
                    "vs_baseline": 0,
                    "error": f"device backend unreachable: init exceeded "
                             f"{seconds:.0f}s (wedged tunnel?)",
                }
            ),
            flush=True,
        )
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def phase_breakdown() -> dict:
    """Phase split of the FULL serving stack, measured by the tracing tier
    itself (obs/trace.py): a 2-instance loopback cluster forwards singles
    non-owner -> owner at sample rate 1.0 and the recorded spans give each
    phase's latency — ingress (whole request at the non-owner), peer.hop
    (forward RPC incl. the micro-batch window), owner.apply, combiner.wait
    and kernel.dispatch (owner side). This is the combiner/kernel/peer-hop
    split the BENCH_*.json trajectory tracks per PR; absolute numbers are
    rig-dependent (loopback gRPC + this platform's dispatch latency), the
    RATIOS are the regression signal."""
    import numpy as np

    from gubernator_tpu.models.engine import Engine
    from gubernator_tpu.service.config import BehaviorConfig, InstanceConfig
    from gubernator_tpu.service.convert import req_to_pb
    from gubernator_tpu.service.grpc_api import close_channels, dial_v1
    from gubernator_tpu.service.instance import Instance
    from gubernator_tpu.service.pb import gubernator_pb2 as pb
    from gubernator_tpu.service.server import make_server
    from gubernator_tpu.obs.trace import Tracer
    from gubernator_tpu.types import PeerInfo, RateLimitReq

    N_REQ = 40
    nodes = []
    try:
        behaviors = BehaviorConfig(batch_wait_s=0.001, peer_link_offset=0)
        for _ in range(2):
            # one width bucket, no warmup: the handful of inline compiles
            # land on the first requests and fall out of the p50s
            eng = Engine(capacity=1024, min_width=64, max_width=64)
            inst = Instance(
                InstanceConfig(behaviors=behaviors, backend=eng,
                               tracer=Tracer(sample=1.0)),
                advertise_address="pending")
            server, port = make_server(inst, "127.0.0.1:0")
            inst.advertise_address = f"127.0.0.1:{port}"
            server.start()
            nodes.append((inst, server))
        infos = [PeerInfo(address=i.advertise_address) for i, _ in nodes]
        for inst, _ in nodes:
            inst.set_peers(infos)

        # send from whichever node does NOT own the key, forcing the hop
        key = "bk0"
        owner_addr = nodes[0][0].get_peer(
            RateLimitReq(name="ph", unique_key=key).hash_key()).info.address
        non_owner = next(inst for inst, _ in nodes
                         if inst.advertise_address != owner_addr)
        stub = dial_v1(non_owner.advertise_address)
        msg = pb.GetRateLimitsReq(requests=[req_to_pb(RateLimitReq(
            name="ph", unique_key=key, hits=1, limit=1 << 20,
            duration=3_600_000))])
        for _ in range(N_REQ):
            stub.GetRateLimits(msg, timeout=30)
        phases: dict = {}
        for inst, _ in nodes:
            for spans in inst.tracer.traces().values():
                for s in spans:
                    phases.setdefault(s["name"], []).append(s["duration_ms"])
        return {
            name: {
                "p50_ms": round(float(np.percentile(v, 50)), 4),
                "p99_ms": round(float(np.percentile(v, 99)), 4),
                "n": len(v),
            }
            for name, v in sorted(phases.items())
        }
    finally:
        for inst, server in nodes:
            server.stop(grace=0.2)
            close_channels(inst.advertise_address)
            inst.close()


def _obs_bench(n_calls: int = 1500, batch: int = 64, reps: int = 3) -> dict:
    """Observability-plane overhead on the serving path: the SAME
    single-node Instance serving identical batch streams with the flight
    recorder enabled vs GUBER_FLIGHT_RECORDER=0 (the escape hatch turns
    emit() into one attribute test). The anomaly engine's observe() runs
    on both sides — it IS the always-on plane; what the hatch removes is
    the recorder. The flag alternates every CHUNK calls within one pass
    (shared-CPU drift between coarse reps dwarfs the cost under test;
    fine interleaving lands both sides in the same drift regime);
    acceptance is overhead <= 2%.

    Steady-state serving emits no events (recorder kinds are rare state
    EDGES — circuit flips, brownout enter/exit, queue high-water), so
    this measures the per-batch fixed cost: the enabled check, the
    anomaly feed, and the wrapper bookkeeping. A per-sweep timing for
    the detector pass rides along informationally."""
    from gubernator_tpu.models.engine import Engine
    from gubernator_tpu.service.config import InstanceConfig
    from gubernator_tpu.service.instance import Instance
    from gubernator_tpu.types import PeerInfo, RateLimitReq

    inst = Instance(InstanceConfig(backend=Engine(capacity=262_144)),
                    advertise_address="127.0.0.1:1")
    inst.set_peers([PeerInfo(address="127.0.0.1:1")])  # self-owned: no RPC
    frames = [
        [RateLimitReq(name="obsbench", unique_key=f"k{(i * batch + j) % 4096}",
                      hits=1, limit=1 << 30, duration=3_600_000)
         for j in range(batch)]
        for i in range(n_calls)
    ]
    try:
        for f in frames[:100]:  # compile + warm the width bucket
            inst.get_rate_limits(f)

        import gc
        import statistics

        CHUNK = 25
        elapsed = {True: 0.0, False: 0.0}
        calls = {True: 0, False: 0}
        pair_overheads = []  # per adjacent on/off pair: scheduler
        # hiccups land in single chunks; the median ignores them
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            for rep in range(reps):
                i = 0
                while i + 2 * CHUNK <= n_calls:
                    first = len(pair_overheads) % 2 == 0
                    rate = {}
                    for enabled in (first, not first):
                        inst.recorder.enabled = enabled
                        chunk = frames[i:i + CHUNK]
                        i += CHUNK
                        t0 = time.perf_counter()
                        for f in chunk:
                            inst.get_rate_limits(f)
                        dt = time.perf_counter() - t0
                        elapsed[enabled] += dt
                        calls[enabled] += CHUNK
                        rate[enabled] = CHUNK * batch / dt
                    pair_overheads.append(
                        (rate[False] - rate[True]) / rate[False])
        finally:
            if gc_was_enabled:
                gc.enable()
        inst.recorder.enabled = True
        on = calls[True] * batch / elapsed[True]
        off = calls[False] * batch / elapsed[False]
        overhead_pct = statistics.median(pair_overheads) * 100.0

        t0 = time.perf_counter()
        sweeps = 50
        for _ in range(sweeps):
            inst.anomaly.check(now=time.monotonic())
            time.sleep(0.02)  # past the sweep-coalescing guard
        sweep_us = ((time.perf_counter() - t0) / sweeps - 0.02) * 1e6

        return {
            "observability": {
                "recorder_on_decisions_per_sec": round(on, 1),
                "recorder_off_decisions_per_sec": round(off, 1),
                # positive = the enabled recorder costs throughput;
                # median over on/off chunk pairs, hiccup-robust
                "overhead_pct": round(overhead_pct, 2),
                "chunk_pairs": len(pair_overheads),
                "anomaly_sweep_us": round(max(sweep_us, 0.0), 1),
                "slo_batches_observed": inst.anomaly.debug()["slo"]["total"],
                "reps": reps,
                "batch": batch,
                "calls_per_rep": n_calls,
            }
        }
    finally:
        inst.close()


def _cartography_bench(n_calls: int = 1200, batch: int = 64,
                       reps: int = 3) -> dict:
    """Cartography-plane overhead on the serving path: the SAME
    single-node Instance serving identical batch streams with the
    metrics-history tick running in-band once per chunk vs the
    GUBER_HISTORY=0 hatch (which turns the scrape piggyback into one
    attribute test). One tick per ~5 ms chunk is ~1000x the production
    5 s cadence, so the interleaved pct is a stress ceiling; the number
    the <= 2% budget is judged on is amortized_overhead_pct — per-op
    tick/harvest cost duty-cycled at the production cadence (5 s tick,
    60 s harvest). The flag alternates every CHUNK calls within one
    pass, same drift-regime rationale as _obs_bench.

    The keyspace harvest reads the device hit-counter column and
    resolves top-K off the serving path; it is timed separately
    (harvest_ms) because even one harvest per chunk would dominate a
    5 ms chunk and measure cadence, not cost."""
    from gubernator_tpu.models.engine import Engine
    from gubernator_tpu.service.config import InstanceConfig
    from gubernator_tpu.service.instance import Instance
    from gubernator_tpu.types import PeerInfo, RateLimitReq

    HIST_TICK_PROD_S = 5.0
    HARVEST_PROD_S = 60.0
    inst = Instance(InstanceConfig(backend=Engine(capacity=262_144),
                                   history_tick_s=1e-4,  # every tick records
                                   keyspace_interval_s=3600.0),
                    advertise_address="127.0.0.1:1")
    inst.set_peers([PeerInfo(address="127.0.0.1:1")])  # self-owned: no RPC
    frames = [
        [RateLimitReq(name="cartobench", unique_key=f"k{(i * batch + j) % 4096}",
                      hits=1, limit=1 << 30, duration=3_600_000)
         for j in range(batch)]
        for i in range(n_calls)
    ]
    try:
        for f in frames[:100]:  # compile + warm the width bucket
            inst.get_rate_limits(f)

        import gc
        import statistics

        CHUNK = 25
        elapsed = {True: 0.0, False: 0.0}
        calls = {True: 0, False: 0}
        pair_overheads = []  # median over adjacent on/off pairs
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            for rep in range(reps):
                i = 0
                while i + 2 * CHUNK <= n_calls:
                    first = len(pair_overheads) % 2 == 0
                    rate = {}
                    for ticking in (first, not first):
                        chunk = frames[i:i + CHUNK]
                        i += CHUNK
                        t0 = time.perf_counter()
                        for f in chunk:
                            inst.get_rate_limits(f)
                        if ticking:  # the scrape piggyback's real work
                            inst.history.tick()
                        dt = time.perf_counter() - t0
                        elapsed[ticking] += dt
                        calls[ticking] += CHUNK
                        rate[ticking] = CHUNK * batch / dt
                    pair_overheads.append(
                        (rate[False] - rate[True]) / rate[False])
        finally:
            if gc_was_enabled:
                gc.enable()
        on = calls[True] * batch / elapsed[True]
        off = calls[False] * batch / elapsed[False]
        overhead_pct = statistics.median(pair_overheads) * 100.0

        # per-op costs, timed directly for the production-cadence duty
        # cycle; synthetic timestamps defeat the tick gate so every
        # iteration pays the full collect+record path, not the no-op
        tick_costs = []
        base = time.monotonic()
        for j in range(200):
            t0 = time.perf_counter()
            s = inst.history.collect(base + float(j))
            inst.history.record(base + float(j), s)
            tick_costs.append(time.perf_counter() - t0)
        tick_us = statistics.median(tick_costs) * 1e6
        harvest_costs = []
        for _ in range(10):
            t0 = time.perf_counter()
            inst.keyspace.harvest(now=time.monotonic())
            harvest_costs.append(time.perf_counter() - t0)
        harvest_ms = statistics.median(harvest_costs) * 1e3
        amortized_pct = 100.0 * (tick_us * 1e-6 / HIST_TICK_PROD_S
                                 + harvest_ms * 1e-3 / HARVEST_PROD_S)

        rep_ks = inst.keyspace.last_report() or {}
        return {
            "cartography": {
                "ticker_on_decisions_per_sec": round(on, 1),
                "ticker_off_decisions_per_sec": round(off, 1),
                # in-band tick once per chunk (~1000x production cadence):
                # a stress ceiling, positive = ticking costs throughput
                "overhead_pct": round(overhead_pct, 2),
                # per-op cost duty-cycled at 5 s tick / 60 s harvest —
                # the number judged against the <= 2% budget
                "amortized_overhead_pct": round(amortized_pct, 4),
                "tick_us": round(tick_us, 1),
                "harvest_ms": round(harvest_ms, 3),
                "table_capacity": 262_144,
                "keys_harvested": (rep_ks.get("occupancy") or {}).get(
                    "key_count"),
                "chunk_pairs": len(pair_overheads),
                "history_samples": inst.history.sample_count(),
                "reps": reps,
                "batch": batch,
                "calls_per_rep": n_calls,
            }
        }
    finally:
        inst.close()


def _capture_bench(n_calls: int = 800, batch: int = 64,
                   reps: int = 3) -> dict:
    """Traffic-shape capture cost against the 2% observability budget.
    capture_trace() is a pure read of the history ring + cartographer +
    recorder, normally triggered by an operator hitting
    /v1/debug/capture — it is NOT on the serving path. Measured two
    ways, mirroring _cartography_bench: an in-band capture once per
    chunk (a stress ceiling ~orders beyond any real cadence) and the
    direct per-capture cost duty-cycled at a one-capture-per-minute
    operator cadence, which is the number judged against the budget."""
    from gubernator_tpu.models.engine import Engine
    from gubernator_tpu.obs.capture import capture_trace
    from gubernator_tpu.service.config import InstanceConfig
    from gubernator_tpu.service.instance import Instance
    from gubernator_tpu.types import PeerInfo, RateLimitReq

    CAPTURE_PROD_S = 60.0
    inst = Instance(InstanceConfig(backend=Engine(capacity=262_144),
                                   history_tick_s=1e-4,
                                   keyspace_interval_s=3600.0),
                    advertise_address="127.0.0.1:1")
    inst.set_peers([PeerInfo(address="127.0.0.1:1")])  # self-owned: no RPC
    frames = [
        [RateLimitReq(name="capbench", unique_key=f"k{(i * batch + j) % 4096}",
                      hits=1, limit=1 << 30, duration=3_600_000)
         for j in range(batch)]
        for i in range(n_calls)
    ]
    try:
        t_ring = time.monotonic()
        for f in frames[:100]:  # compile + warm the width bucket
            inst.get_rate_limits(f)
            # give the capture a real ring to read: the ring floors
            # tick_s at 50 ms, so sub-ms warm frames must stamp
            # synthetic tick times to land as distinct samples
            t_ring += 0.1
            inst.history.tick(now=t_ring)
        inst.keyspace.harvest()

        import gc
        import statistics

        CHUNK = 25
        elapsed = {True: 0.0, False: 0.0}
        calls = {True: 0, False: 0}
        pair_overheads = []
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            for rep in range(reps):
                i = 0
                while i + 2 * CHUNK <= n_calls:
                    first = len(pair_overheads) % 2 == 0
                    rate = {}
                    for capturing in (first, not first):
                        chunk = frames[i:i + CHUNK]
                        i += CHUNK
                        t0 = time.perf_counter()
                        for f in chunk:
                            inst.get_rate_limits(f)
                        if capturing:
                            capture_trace(inst, n_events=64)
                        dt = time.perf_counter() - t0
                        elapsed[capturing] += dt
                        calls[capturing] += CHUNK
                        rate[capturing] = CHUNK * batch / dt
                    pair_overheads.append(
                        (rate[False] - rate[True]) / rate[False])
        finally:
            if gc_was_enabled:
                gc.enable()
        on = calls[True] * batch / elapsed[True]
        off = calls[False] * batch / elapsed[False]
        overhead_pct = statistics.median(pair_overheads) * 100.0

        costs = []
        trace = None
        for _ in range(50):
            t0 = time.perf_counter()
            trace = capture_trace(inst, n_events=256)
            costs.append(time.perf_counter() - t0)
        capture_ms = statistics.median(costs) * 1e3
        amortized_pct = 100.0 * capture_ms * 1e-3 / CAPTURE_PROD_S

        return {
            "capture": {
                "capture_on_decisions_per_sec": round(on, 1),
                "capture_off_decisions_per_sec": round(off, 1),
                # one in-band capture per ~5 ms chunk: a stress ceiling
                "overhead_pct": round(overhead_pct, 2),
                # per-capture cost duty-cycled at one capture per minute
                # — the number judged against the <= 2% budget
                "amortized_overhead_pct": round(amortized_pct, 4),
                "capture_ms": round(capture_ms, 3),
                "trace_segments": len(trace["history"]["segments"]),
                "trace_events": len(trace["events"]["tail"]),
                "derived_mean_rate_rps": trace["derived"]["mean_rate_rps"],
                "chunk_pairs": len(pair_overheads),
                "reps": reps,
                "batch": batch,
                "calls_per_rep": n_calls,
            }
        }
    finally:
        inst.close()


def _scenarios_bench(profile: str = "short", autopilot: bool = True) -> dict:
    """The scenario atlas as a bench section: every named scenario runs
    against its own fresh in-process cluster and records its verdict.
    verdict_pass is the hard bench_check gate (a scenario flipping
    PASS->FAIL across rounds is a regression, full stop); the latency
    and goodput numbers ride along as operating-point context. Each
    shape then re-runs GUBER_AUTOPILOT-armed on the same seed, keyed
    `<name>@autopilot` — gated by bench_check at the SAME zero
    tolerance (the closed-loop controllers are not allowed to be a
    flakiness excuse)."""
    from gubernator_tpu.scenarios import run_atlas

    atlas = run_atlas(profile=profile)
    rows = dict(atlas["scenarios"])
    if autopilot:
        armed = run_atlas(profile=profile, autopilot=True)
        rows.update({f"{name}@autopilot": v
                     for name, v in armed["scenarios"].items()})
    out = {}
    for name, v in rows.items():
        out[name] = {
            "verdict_pass": int(v["passed"]),
            "goodput": v["goodput"],
            "over_limit_share": v["over_limit_share"],
            "error_share": v["error_share"],
            "p50_ms": v["stats"]["latency_ms"]["p50"],
            "p99_ms": v["stats"]["latency_ms"]["p99"],
            "offered": v["stats"]["offered"],
            "detectors_tripped": sum(
                v["stats"]["detectors_tripped"].values()),
        }
    out["passed_count"] = sum(
        v["verdict_pass"] for v in out.values() if isinstance(v, dict))
    out["total"] = len(rows)
    return {"scenarios": out}


def _profile_bench(n_calls: int = 1500, batch: int = 64, reps: int = 3) -> dict:
    """Profiling-plane overhead on the serving path: the SAME single-node
    Instance serving identical batch streams with the serving-cycle
    profiler enabled vs the GUBER_PROFILE=0 hatch (which turns every
    observe()/lock_wait() into one attribute test before the clock is
    even read). The flag alternates every CHUNK calls within one pass,
    same drift-regime rationale as _obs_bench. Budget <= 2%; target 0.5%
    — the profiler is ~10 perf_counter_ns reads + histogram increments
    per engine window group, amortized over a whole batch.

    A directly-timed per-observe cost and the /v1/debug/profile body
    render time ride along informationally."""
    from gubernator_tpu.models.engine import Engine
    from gubernator_tpu.service.config import InstanceConfig
    from gubernator_tpu.service.instance import Instance
    from gubernator_tpu.types import PeerInfo, RateLimitReq

    inst = Instance(InstanceConfig(backend=Engine(capacity=262_144)),
                    advertise_address="127.0.0.1:1")
    inst.set_peers([PeerInfo(address="127.0.0.1:1")])  # self-owned: no RPC
    prof = inst.profiler
    frames = [
        [RateLimitReq(name="profbench", unique_key=f"k{(i * batch + j) % 4096}",
                      hits=1, limit=1 << 30, duration=3_600_000)
         for j in range(batch)]
        for i in range(n_calls)
    ]
    try:
        for f in frames[:100]:  # compile + warm the width bucket
            inst.get_rate_limits(f)

        import gc
        import statistics

        CHUNK = 25
        elapsed = {True: 0.0, False: 0.0}
        calls = {True: 0, False: 0}
        pair_overheads = []  # median over adjacent on/off pairs
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            for rep in range(reps):
                i = 0
                while i + 2 * CHUNK <= n_calls:
                    first = len(pair_overheads) % 2 == 0
                    rate = {}
                    for enabled in (first, not first):
                        prof.enabled = enabled
                        chunk = frames[i:i + CHUNK]
                        i += CHUNK
                        t0 = time.perf_counter()
                        for f in chunk:
                            inst.get_rate_limits(f)
                        dt = time.perf_counter() - t0
                        elapsed[enabled] += dt
                        calls[enabled] += CHUNK
                        rate[enabled] = CHUNK * batch / dt
                    pair_overheads.append(
                        (rate[False] - rate[True]) / rate[False])
        finally:
            if gc_was_enabled:
                gc.enable()
        prof.enabled = True
        on = calls[True] * batch / elapsed[True]
        off = calls[False] * batch / elapsed[False]
        overhead_pct = statistics.median(pair_overheads) * 100.0

        # per-observe cost, timed directly (informational)
        t0 = time.perf_counter()
        N_OBS = 20_000
        for j in range(N_OBS):
            prof.observe("prep", 1000 + j)
        observe_ns = (time.perf_counter() - t0) / N_OBS * 1e9
        # endpoint render cost (off the serving path, but a dashboard
        # polling it every second should know what it costs the node)
        t0 = time.perf_counter()
        for _ in range(50):
            body = prof.endpoint_body()
        endpoint_us = (time.perf_counter() - t0) / 50 * 1e6

        return {
            "profiler": {
                "profiler_on_decisions_per_sec": round(on, 1),
                "profiler_off_decisions_per_sec": round(off, 1),
                # positive = the enabled profiler costs throughput;
                # median over on/off chunk pairs, hiccup-robust.
                # budget <= 2%, target 0.5%
                "overhead_pct": round(overhead_pct, 2),
                "observe_ns": round(observe_ns, 1),
                "endpoint_body_us": round(endpoint_us, 1),
                "phases_observed": sorted(
                    p for p, t in prof.totals().items() if t["n"]),
                "lock_sites": sorted(body["lock_sites"]),
                "chunk_pairs": len(pair_overheads),
                "reps": reps,
                "batch": batch,
                "calls_per_rep": n_calls,
            }
        }
    finally:
        inst.close()


def _product_combiner_bench(eng, threads: int = 12, scan: int = 8,
                            subs_per_thread: int = 24) -> dict:
    """Serving throughput through the PRODUCT combiner path — not a
    bespoke loop: `threads` callers block in BackendCombiner.submit()
    with max-width request-object batches against the 10M-key engine.
    Completion is forced by construction (a future resolves only after
    its window's data-dependent readback). Returns the bench JSON rows."""
    import threading as _t

    from gubernator_tpu.service.combiner import BackendCombiner

    width = eng.max_width
    # request objects over keys resident in the 10M directory ("b_k%d")
    rng = np.random.RandomState(21)
    from gubernator_tpu.types import RateLimitReq

    variants = []
    for _ in range(threads):
        ids = rng.choice(TABLE_CAPACITY, width, replace=False)
        variants.append([
            RateLimitReq(name="b", unique_key="k%d" % i, hits=1,
                         limit=1 << 30, duration=3_600_000)
            for i in ids
        ])
    # compile the scan-group shapes up front, exactly as a daemon boots —
    # a cold compile inside a timed segment would poison the measurement
    eng.warmup_pipeline(max_group=scan)

    def run(depth: int, n_subs: int) -> float:
        c = BackendCombiner(eng, depth=depth, scan=scan)
        try:
            errs = []

            def caller(v):
                try:
                    for _ in range(n_subs):
                        resp = c.submit(v)
                        if resp[0].status not in (0, 1):
                            raise RuntimeError("bad status")
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            ts = [_t.Thread(target=caller, args=(variants[i],), daemon=True)
                  for i in range(threads)]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            elapsed = time.perf_counter() - t0
            if errs:
                raise errs[0]
            stats = c.stats
        finally:
            c.close()
        return threads * n_subs * width / elapsed, stats

    run(3, 2)  # warm the full path (combiner threads, demux, staging ring)
    probe = {}
    probe_stats = {}
    for depth in (1, 3, 6):
        rate, stats = run(depth, subs_per_thread)
        probe[depth] = round(rate, 1)
        probe_stats[depth] = stats
    best_depth = max(probe, key=probe.get)
    stats = probe_stats[best_depth]
    return {
        "product_combiner_decisions_per_sec": probe[best_depth],
        "product_combiner": {
            "scope": "BackendCombiner.submit() request objects -> "
                     f"RateLimitResp objects, {threads} callers x "
                     f"{width}-wide submissions, scan groups <= {scan} "
                     "windows/launch, keydir(10M resident)",
            "depth_probe_decisions_per_sec":
                {str(d): r for d, r in probe.items()},
            "depth": best_depth,
            "serial_decisions_per_sec": probe[1],
            "speedup_vs_serial": round(
                probe[best_depth] / max(probe[1], 1.0), 2),
            "pipelined_windows": stats["pipelined_windows"],
            "group_launches": stats["group_launches"],
            "fill_stalls": stats["fill_stalls"],
        },
    }


def _overload_bench(eng, budget_ms: float = 150.0, seconds: float = 3.0,
                    batch: int = 64, offered_x: float = 2.0) -> dict:
    """Overload discipline through a REAL single-node Instance (admission
    controller + deadline budgets + combiner dequeue shed), owner-local
    serving (BENCH_r08 acceptance row).

    First a closed-loop capacity probe, then open-loop offered load at
    ~`offered_x` that capacity in two modes: ADMISSION (every call carries
    a `budget_ms` deadline, GUBER_MAX_PENDING sized by Little's law to the
    budget — capacity x budget) vs the no-admission, no-budget BASELINE
    (PR 4 behavior: work queues unboundedly). Records goodput (decisions
    answered WITHIN budget per second), shed rate, and accepted-call
    p50/p99 — the claim under test is that shedding the excess beats
    queueing it: the admission run's accepted p99 stays near the service
    time while the baseline's grows with the backlog."""
    import threading as _t
    from concurrent.futures import ThreadPoolExecutor

    from gubernator_tpu.cluster.harness import test_behaviors
    from gubernator_tpu.service import deadline as deadline_mod
    from gubernator_tpu.service.config import InstanceConfig
    from gubernator_tpu.service.deadline import (
        AdmissionRejectedError,
        DeadlineExceededError,
    )
    from gubernator_tpu.service.instance import Instance
    from gubernator_tpu.types import PeerInfo, RateLimitReq

    behaviors = test_behaviors()
    behaviors.max_pending = 0
    inst = Instance(InstanceConfig(behaviors=behaviors, backend=eng),
                    advertise_address="bench-local")
    inst.set_peers([PeerInfo(address="bench-local")])  # all owner-local

    rng = np.random.RandomState(31)
    pool_keys = ["k%d" % i
                 for i in rng.choice(TABLE_CAPACITY, 4096, replace=False)]

    def make_batch(i: int):
        base = (i * 17) % (len(pool_keys) - batch)
        return [RateLimitReq(name="b", unique_key=k, hits=1, limit=1 << 30,
                             duration=3_600_000)
                for k in pool_keys[base:base + batch]]

    try:
        # warm the instance path AND make the whole key pool resident:
        # first-touch inserts are slower than steady-state hits, and a
        # capacity probe over cold keys would under-measure — "2x
        # capacity" would then not actually overload the warm open loop
        for start in range(0, len(pool_keys), batch):
            inst.get_rate_limits(
                [RateLimitReq(name="b", unique_key=k, hits=1,
                              limit=1 << 30, duration=3_600_000)
                 for k in pool_keys[start:start + batch]])

        def measure_capacity() -> float:
            # ---- closed-loop capacity probe ----------------------------
            # concurrency matches the open loop's client pool order: the
            # combiner merges concurrent calls into wider windows, so a
            # low-thread probe would UNDER-measure capacity and 2x
            # "offered" would not actually overload the node
            n_probe_threads, probe_s = 24, 1.5
            counts = [0] * n_probe_threads
            stop_at = time.perf_counter() + probe_s

            def probe_worker(ti: int) -> None:
                i = ti
                while time.perf_counter() < stop_at:
                    inst.get_rate_limits(make_batch(i))
                    counts[ti] += batch
                    i += n_probe_threads

            ts = [_t.Thread(target=probe_worker, args=(ti,), daemon=True)
                  for ti in range(n_probe_threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            return sum(counts) / probe_s  # decisions/s, closed loop

        capacity = measure_capacity()

        def open_loop(admission_on: bool) -> dict:
            behaviors.max_pending = (
                max(2 * batch, int(capacity * budget_ms / 1e3))
                if admission_on else 0)
            lock = _t.Lock()
            lat_ms, sheds = [], [0]

            def one(i: int) -> None:
                dl = (deadline_mod.capture(budget_ms)
                      if admission_on else None)
                token = deadline_mod.use(dl) if dl is not None else None
                t0 = time.perf_counter()
                try:
                    err = inst.get_rate_limits(make_batch(i))[0].error
                except (AdmissionRejectedError, DeadlineExceededError):
                    err = "SHED"
                finally:
                    if token is not None:
                        deadline_mod.reset(token)
                dt = (time.perf_counter() - t0) * 1e3
                with lock:
                    if err:
                        sheds[0] += 1
                    else:
                        lat_ms.append(dt)

            # burst dispatch on a coarse tick: per-call sleep pacing
            # cannot sustain the offered rate (sleep granularity alone
            # would throttle the generator below capacity)
            tick = 0.02
            per_tick = max(1, int(round(
                offered_x * capacity * tick / batch)))
            n_ticks = max(4, int(seconds / tick))
            n_offered = per_tick * n_ticks
            pool = ThreadPoolExecutor(max_workers=256)
            futs = []
            idx = 0
            t_start = time.perf_counter()
            for ti in range(n_ticks):
                delay = t_start + ti * tick - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                for _ in range(per_tick):
                    futs.append(pool.submit(one, 100 + idx))
                    idx += 1
            for f in futs:
                f.result()
            wall = time.perf_counter() - t_start
            pool.shutdown()
            good = [d for d in lat_ms if d <= budget_ms]
            pct = (lambda q: round(float(np.percentile(lat_ms, q)), 1)) \
                if lat_ms else (lambda q: None)
            return {
                "offered_calls": n_offered,
                "served_calls": len(lat_ms),
                "shed_calls": sheds[0],
                "shed_rate": round(sheds[0] / max(n_offered, 1), 3),
                "goodput_decisions_per_sec": round(
                    len(good) * batch / wall, 1),
                "accepted_p50_ms": pct(50),
                "accepted_p99_ms": pct(99),
                "max_pending": behaviors.max_pending,
            }

        # A shared-rig probe can land in a descheduled window and report
        # a fraction of the node's real capacity. Such a draw fails the
        # bench's own premise — "offered at 2x capacity" then does not
        # overload anything (shed rate 0, baseline p99 inside budget) and
        # the row measures the rig hiccup, not the overload discipline.
        # Detect that and retake the probe instead of recording it.
        attempts = 1
        while True:
            baseline = open_loop(admission_on=False)
            admission = open_loop(admission_on=True)
            # sheds are the unambiguous signature that offered load
            # actually exceeded capacity (a backlogged-baseline p99 can
            # spike on an under-measured probe too, so it proves nothing)
            if admission["shed_calls"] > 0 or attempts >= 3:
                break
            attempts += 1
            behaviors.max_pending = 0  # re-probe closed-loop, no admission
            capacity = measure_capacity()
    finally:
        inst.close()
    return {
        "overload": {
            "scope": "Instance.get_rate_limits owner-local, open-loop "
                     f"offered at {offered_x}x closed-loop capacity, "
                     f"{batch}-wide calls, budget {budget_ms:.0f} ms",
            "capacity_decisions_per_sec": round(capacity, 1),
            "offered_x": offered_x,
            "budget_ms": budget_ms,
            "probe_attempts": attempts,
            "baseline_no_admission": baseline,
            "admission": admission,
        },
    }


FRAME_WIDTH = 1024  # peerlink MAX_FRAME_ITEMS: the wire's frame cap


def _columnar_pipeline_bench(eng, scan: int = 8,
                             n_windows: int = 96) -> dict:
    """The zero-object columnar owner path (peerlink wire columns ->
    engine, no RateLimitReq/Resp objects), lock-step vs depth-N
    pipelined, on the same 10M-resident keydir working set.

    Lock-step is the pre-PR-3 serving loop (`submit_columnar` then
    `complete_columnar` per window — every readback blocks the next
    submit); the pipelined path launches scan groups of <= `scan`
    windows via launch_columnar_windows with `depth` group launches in
    flight and drains in dispatch order — exactly what
    service/peerlink.py _columnar_chunk now drives. Completion is
    forced by construction (a window's response columns fill only after
    its readback).

    The HEADLINE probe runs at the wire's frame granularity
    (MAX_FRAME_ITEMS = 1024 — the widest window a single client frame
    can carry, i.e. a GUBER_MAX_BATCH_WIDTH=1024-class deployment):
    there the lock-step loop pays one full dispatch per frame and the
    scan-grouped pipeline amortizes it across up to `scan` frames, which
    is the structural win this PR ships. A max-width (8192) row rides
    along: at that width the kernel dominates the cycle, so on a
    shared-core CPU rig the pipeline adds only its overlap margin (on a
    link-bound rig it is the BENCH_r05 2x regime)."""
    from collections import deque

    now = 1_700_000_000_000
    rng = np.random.RandomState(33)

    def make_variants(w, n_var):
        out = []
        for _ in range(n_var):
            ids = rng.choice(TABLE_CAPACITY, w, replace=False)
            ukeys = [b"k%d" % i for i in ids]
            keys = b"".join(b"b" + u for u in ukeys)
            off = np.zeros(w + 1, np.int32)
            np.cumsum([1 + len(u) for u in ukeys], out=off[1:])
            out.append((
                w, keys, off, np.ones(w, np.int32),
                np.ones(w, np.int64), np.full(w, 1 << 30, np.int64),
                np.full(w, 3_600_000, np.int64),
                np.zeros(w, np.int32), np.zeros(w, np.int32)))
        return out

    wc = [0]  # monotone now_ms cursor across every run

    def make_runners(w, variants):
        nv = len(variants)
        outs_pool = [[(np.zeros(w, np.int32), np.zeros(w, np.int64),
                       np.zeros(w, np.int64), np.zeros(w, np.int64))
                      for _ in range(scan)] for _ in range(8)]
        st, li, re, rs = outs_pool[0][0]

        def run_lockstep(k_windows):
            t0 = time.perf_counter()
            for i in range(k_windows):
                h = eng.submit_columnar(
                    *variants[(wc[0] + i) % nv], 0, now_ms=now + wc[0] + i)
                left = eng.complete_columnar(h, st, li, re, rs)
                assert h is not None and not len(left)
            wc[0] += k_windows
            return k_windows * w / (time.perf_counter() - t0)

        def run_pipelined(k_windows, depth):
            staging = [dict() for _ in range(depth + 2)]
            inflight = deque()
            i = 0
            seq = 0
            t0 = time.perf_counter()
            while i < k_windows or inflight:
                while i < k_windows and len(inflight) < depth:
                    g = min(scan, k_windows - i)
                    wins = [variants[(wc[0] + i + d) % nv]
                            for d in range(g)]
                    h = eng.launch_columnar_windows(
                        wins, 0, now_ms=now + wc[0] + i,
                        staging=staging[seq % len(staging)])
                    assert h is not None and len(h[0]) == g \
                        and h[1] is None
                    inflight.append((h, g, seq % len(outs_pool)))
                    i += g
                    seq += 1
                h, g, oslot = inflight.popleft()
                lefts = eng.collect_columnar_windows(
                    h, outs_pool[oslot][:g])
                assert all(not len(l) for l in lefts)
            wc[0] += k_windows
            return k_windows * w / (time.perf_counter() - t0)

        return run_lockstep, run_pipelined

    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731

    # ---- headline: frame-width windows, depth probe {1, 3, 6} ----------
    fw_vars = make_variants(FRAME_WIDTH, 8)
    run_lockstep, run_pipelined = make_runners(FRAME_WIDTH, fw_vars)
    for _ in range(2):  # warm: compiles + page-faults the touched rows
        run_lockstep(24)
        run_pipelined(24, 3)
    lockstep = []
    probe = {d: [] for d in (1, 3, 6)}
    for _ in range(3):  # alternate so neither path rides warmer pages
        lockstep.append(run_lockstep(n_windows))
        for d in probe:
            probe[d].append(run_pipelined(n_windows, d))
    lockstep_med = med(lockstep)
    probe_med = {d: round(med(rs_), 1) for d, rs_ in probe.items()}
    best_depth = max(probe_med, key=probe_med.get)

    # ---- secondary: max-width windows (kernel-bound on a CPU rig) ------
    mw = eng.max_width
    mw_vars = make_variants(mw, 4)
    run_lockstep_mw, run_pipelined_mw = make_runners(mw, mw_vars)
    for _ in range(2):
        run_lockstep_mw(8)
        run_pipelined_mw(8, 3)
    mw_lock = med([run_lockstep_mw(24) for _ in range(3)])
    mw_pipe = med([run_pipelined_mw(24, 3) for _ in range(3)])

    return {
        "columnar_pipeline_decisions_per_sec": probe_med[best_depth],
        "columnar_pipeline": {
            "scope": "zero-object columnar wire path (peerlink layout "
                     "cols -> launch_columnar_windows -> response "
                     f"columns), {FRAME_WIDTH}-wide frame windows "
                     f"(MAX_FRAME_ITEMS), scan groups <= {scan} windows/"
                     "launch, keydir(10M resident)",
            "lockstep_decisions_per_sec": round(lockstep_med, 1),
            "depth_probe_decisions_per_sec":
                {str(d): r for d, r in probe_med.items()},
            "depth": best_depth,
            "speedup_vs_lockstep": round(
                probe_med[best_depth] / max(lockstep_med, 1.0), 2),
            "windows_per_run": n_windows,
            "max_width_row": {
                "width": mw,
                "lockstep_decisions_per_sec": round(mw_lock, 1),
                "pipelined_d3_decisions_per_sec": round(mw_pipe, 1),
                "speedup_vs_lockstep": round(mw_pipe / max(mw_lock, 1.0),
                                             2),
                "note": "kernel-bound at this width on a shared-core CPU "
                        "rig; the overlap margin is the link-bound rig's "
                        "lever (BENCH_r05)",
            },
        },
    }


def _multichip_section() -> dict:
    """Fold the latest MULTICHIP_r*.json into the bench record.

    The multichip runs land as sibling artifacts of the BENCH_r* files;
    surfacing the newest one here makes every bench record self-contained
    about the mesh tier's last known state instead of requiring a second
    artifact lookup."""
    import glob
    import os

    files = sorted(glob.glob(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "MULTICHIP_r*.json")))
    if not files:
        return {}
    latest = files[-1]
    try:
        with open(latest) as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        return {"multichip": {"source": os.path.basename(latest),
                              "error": str(e)}}
    out = {"source": os.path.basename(latest)}
    for k in ("n_devices", "rc", "ok", "skipped", "note"):
        if k in data:
            out[k] = data[k]
    return {"multichip": out}


def _skew_bench(n_calls: int = 1200, n_keys: int = 32,
                zipf_a: float = 1.1) -> dict:
    """Zipf-head skew through a REAL 2-node loopback cluster: the hot-key
    lease tier's acceptance row (BENCH_r09).

    Three workloads through the same client node, measured at the client
    (per-call p50/p99) and at the hot key's owner (engine-request share —
    the work consistent hashing concentrates on one host):

    - uniform: n_keys keys, flat — the no-skew reference row;
    - zipf_off: Zipf-`zipf_a` keys, leases disabled — every head hit is a
      forward RPC to the owner;
    - zipf_on: the SAME key sequence with GUBER_HOT_LEASES semantics armed
      — the owner detects the head, grants budgeted leases, and the client
      node answers the head locally, draining hits asynchronously.

    The claim under test: zipf_on cuts both the client p99 and the owner's
    work share vs zipf_off, approaching the uniform row."""
    from gubernator_tpu.cluster.harness import LocalCluster
    from gubernator_tpu.types import RateLimitReq

    rng = np.random.RandomState(9)
    zipf_seq = [int(z) % n_keys for z in rng.zipf(zipf_a, size=n_calls)]
    uniform_seq = [int(u) for u in rng.randint(0, n_keys, size=n_calls)]

    def reqs_for(seq, prefix):
        # leading digits vary: trailing-suffix keys can collapse onto one
        # fnv ring arc (cluster/harness.py ownership probes do the same)
        return [RateLimitReq(name="skew", unique_key=f"{k}{prefix}",
                             hits=1, limit=1 << 30, duration=3_600_000)
                for k in seq]

    head = int(np.bincount(zipf_seq).argmax())

    # The 2-node fnv ring can land arbitrarily lopsided for one boot's
    # random ports (one arc owning ~everything) — a row where the client
    # owns nothing measures only the micro-batch window, not skew. Re-roll
    # until both nodes own a real share of the workload's keys.
    c = None
    for _ in range(6):
        c = LocalCluster().start(2)
        owners = [c.owner_of(f"skew_{k}z").address for k in range(n_keys)]
        share = owners.count(owners[0]) / n_keys
        if 0.2 <= share <= 0.8:
            break
        c.stop()
    try:
        hot_owner = c.owner_of(f"skew_{head}z")
        # drive from the node that does NOT own the Zipf head, so head
        # hits actually cross the wire (the skew problem under test)
        client = next(ci for ci in c.instances if ci is not hot_owner)

        leased_before = [0]

        def run_row(reqs, head_unique):
            # per-engine request deltas attribute the row's work
            before = [ci.instance.backend.stats.requests
                      for ci in c.instances]
            lat = np.empty(len(reqs))
            head_mask = np.zeros(len(reqs), bool)
            t_start = time.perf_counter()
            for i, r in enumerate(reqs):
                head_mask[i] = r.unique_key == head_unique
                t0 = time.perf_counter()
                resp = client.instance.get_rate_limits([r])[0]
                lat[i] = time.perf_counter() - t0
                if resp.error:
                    raise RuntimeError(resp.error)
            wall = time.perf_counter() - t_start
            owner_i = c.instances.index(hot_owner)
            deltas = [ci.instance.backend.stats.requests - b
                      for ci, b in zip(c.instances, before)]
            leased = client.instance.leases.stats["local_answers"] \
                - leased_before[0]
            leased_before[0] += leased
            head_lat = lat[head_mask]
            row = {
                "calls": len(reqs),
                "calls_per_sec": round(len(reqs) / wall, 1),
                "client_p50_ms": round(
                    float(np.percentile(lat, 50) * 1e3), 3),
                "client_p99_ms": round(
                    float(np.percentile(lat, 99) * 1e3), 3),
                "hot_owner_engine_requests": int(deltas[owner_i]),
                "hot_owner_work_share": round(
                    deltas[owner_i] / max(sum(deltas), 1), 3),
                "leased_answers_total": int(leased),
            }
            if head_lat.size:
                # the skew victim's own latency: head-key calls are the
                # ones a lease converts from cross-host forwards (the
                # micro-batch window + RPC) into local table reads
                row["head_calls"] = int(head_lat.size)
                row["head_p50_ms"] = round(
                    float(np.percentile(head_lat, 50) * 1e3), 3)
                row["head_p99_ms"] = round(
                    float(np.percentile(head_lat, 99) * 1e3), 3)
            return row

        head_unique = f"{head}z"
        rows = {"uniform": run_row(reqs_for(uniform_seq, "u"), "")}
        rows["zipf_off"] = run_row(reqs_for(zipf_seq, "z"), head_unique)

        for ci in c.instances:
            b = ci.instance.conf.behaviors
            b.hot_leases = True
            # the head must cross the rate threshold at this rig's
            # closed-loop call rate (Zipf-1.1 head ≈ 11% of ~100-200/s)
            # while the ~2%-share tail keys stay cold
            b.hot_lease_rate = 5.0
            b.hot_lease_window_s = 0.5
            b.hot_lease_ttl_s = 1.0
            b.hot_lease_fraction = 0.5
            ci.instance.leases.arm()
        rows["zipf_on"] = run_row(reqs_for(zipf_seq, "z"), head_unique)
        rows["zipf_a"] = zipf_a
        rows["n_keys"] = n_keys
        return {"skew": rows}
    finally:
        c.stop()


class _LinkLagBackend:
    """Bench-only engine wrapper emulating a LINK-BOUND rig on the CPU
    fallback: a launched columnar group's readback lands `link_ms` after
    dispatch (the transfer progresses in the background while the host
    works, exactly how the BENCH_r05 tunnel rig behaves), so
    collect_columnar_windows blocks only for the REMAINDER. A serving
    loop that overlaps other work with in-flight readbacks pays nothing;
    one that drains right after launching pays the full latency."""

    def __init__(self, eng, link_ms: float):
        self._eng = eng
        self._lag = link_ms / 1e3
        self._due = {}

    def __getattr__(self, name):
        return getattr(self._eng, name)

    def launch_columnar_windows(self, *a, **kw):
        h = self._eng.launch_columnar_windows(*a, **kw)
        if h is not None:
            self._due[id(h)] = time.perf_counter() + self._lag
        return h

    def collect_columnar_windows(self, h, outs):
        wait = self._due.pop(id(h), 0) - time.perf_counter()
        if wait > 0:
            time.sleep(wait)
        return self._eng.collect_columnar_windows(h, outs)


def _wire_bench(n_frames: int = 48, frame_w: int = 1024,
                inflight: int = 8, link_ms: float = 8.0) -> dict:
    """Wire contract v1 vs v2 over a real loopback peerlink (BENCH_r10).

    The client keeps `inflight` frames of `frame_w` requests in flight
    (call_async closed loop, replenish-on-complete); the only variable
    is the wire contract: v1 whole-frame replies with _worker_v1's
    per-pull barrier (the PR-7 baseline) vs v2 seq-numbered partial
    posts with cross-pull pipelining (_worker_v2). One worker, so the
    contract itself — not worker-count parallelism — is what's measured;
    frame_w spans four max_width=256 sub-windows so every pull carries
    multiple scan groups.

    Two regimes per contract: the bare CPU-fallback rig (zero-latency
    loopback — the barrier has nothing to hide, so v1 and v2 should tie
    within the partial-post overhead), and a LINK-EMULATED rig
    (readbacks land `link_ms` after dispatch, BENCH_r05-class tunnel
    latency) — the link-bound regime where the v1 contract drains the
    pipeline at every pull boundary while v2 keeps it fed. The rows
    record the negotiated version and the server's boundary-stall and
    partial-post counters, so the win is attributable to removed
    stalls, not noise."""
    import collections

    from gubernator_tpu.models.engine import Engine
    from gubernator_tpu.service.config import InstanceConfig
    from gubernator_tpu.service.instance import Instance
    from gubernator_tpu.service.peerlink import (
        METHOD_GET_PEER_RATE_LIMITS,
        PeerLinkClient,
        PeerLinkService,
    )
    from gubernator_tpu.types import RateLimitReq

    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731

    def run(v2: bool, lag_ms: float) -> dict:
        eng = Engine(capacity=1 << 17, min_width=8, max_width=256)
        if not eng.supports_columnar():
            raise RuntimeError("native columnar prep unavailable")
        back = _LinkLagBackend(eng, lag_ms) if lag_ms else eng
        inst = Instance(InstanceConfig(backend=back),
                        advertise_address="self")
        svc = PeerLinkService(inst, port=0, workers=1, pipeline_depth=3,
                              pipeline_scan=2, wire_v2=v2)
        cli = PeerLinkClient(f"127.0.0.1:{svc.port}", wire_v2=v2)
        try:
            def frame(i):
                base = (i * frame_w) % (1 << 16)
                return [RateLimitReq(
                    name="w", unique_key=f"k{base + j}", hits=1,
                    limit=1 << 30, duration=3_600_000)
                    for j in range(frame_w)]

            def drive(k):
                pend = collections.deque()
                i = 0
                t0 = time.perf_counter()
                while i < k or pend:
                    while i < k and len(pend) < inflight:
                        fut, _ = cli.call_async(
                            METHOD_GET_PEER_RATE_LIMITS, frame(i))
                        pend.append(fut)
                        i += 1
                    resps = pend.popleft().result(timeout=120)
                    assert len(resps) == frame_w
                return k * frame_w / (time.perf_counter() - t0)

            drive(16)  # warm: compiles + server buffer ring
            rate = med([drive(n_frames) for _ in range(3)])
            return {
                "decisions_per_sec": round(rate, 1),
                "negotiated_version": cli.wire_version,
                "partial_posts": svc.wire_partial_posts(),
                "pull_boundary_stalls": svc.stats["pull_boundary_stalls"],
            }
        finally:
            cli.close()
            svc.close()
            inst.close()

    def pair(lag_ms: float) -> dict:
        v1 = run(False, lag_ms)
        v2 = run(True, lag_ms)
        return {
            "v1": v1,
            "v2": v2,
            "speedup_v2_vs_v1": round(
                v2["decisions_per_sec"]
                / max(v1["decisions_per_sec"], 1.0), 2),
        }

    cpu_rig = pair(0.0)
    emulated = pair(link_ms)
    return {
        "wire_v2_speedup_link_bound": emulated["speedup_v2_vs_v1"],
        "wire": {
            "scope": "loopback peerlink, closed loop with "
                     f"{inflight} x {frame_w}-request frames in flight, "
                     "1 worker, pipelined columnar server (depth 3, "
                     "scan 2, max_width 256); v1 = whole-frame + "
                     "per-pull barrier, v2 = partial posts + cross-pull "
                     "pipelining (docs/wire.md)",
            "cpu_rig": cpu_rig,
            "link_emulated": {
                **emulated,
                "link_ms": link_ms,
                "note": "readbacks land link_ms after dispatch "
                        "(BENCH_r05-class tunnel latency emulated on "
                        "the CPU fallback; transfers progress while "
                        "the host works) — the link-bound regime where "
                        "the per-pull barrier is the structural cost",
            },
            "frames_per_run": n_frames,
            "frame_width": frame_w,
            "inflight_frames": inflight,
        },
    }


def _reshard_bench(n_resident: int = 1_000_000,
                   fg_keys: int = 120) -> dict:
    """Live resharding at scale: handoff duration + serving-path impact
    with 1M resident counter rows on the departing owner (BENCH_r13).

    A real 2-node loopback cluster, reshard armed. The donor node is
    staged with `n_resident` donor-owned rows through the engine's
    snapshot-slab inject path (the same path transfer frames use), then
    `evacuate()` streams every row to the survivor over the debug RPC —
    plan, chunk-cut, stream, commit, measured wall-clock end to end.
    A foreground client meanwhile drives survivor-owned keys through
    the survivor (the importer: its serving path carries the intercept
    checks AND the frame injections), sampled per-call before and
    during the handoff — the serving-impact row.

    The claims under test: handoff duration scales with rows at
    wire+inject cost (no quadratic planning), and the importer's
    foreground p99 stays in the same regime while 1M rows stream in."""
    import dataclasses
    import threading

    from gubernator_tpu.cluster.harness import LocalCluster, test_behaviors
    from gubernator_tpu.types import RateLimitReq

    beh = dataclasses.replace(test_behaviors(), reshard=True,
                              reshard_ttl_s=10.0, reshard_grace_s=0.5)
    # table capacity: donor residents + foreground keys + slack, on
    # BOTH nodes (the survivor absorbs the whole donor set)
    c = LocalCluster().start(2, capacity=1 << 21, behaviors=beh)
    try:
        time.sleep(0.7)  # boot grace
        survivor, donor = c.instances[0], c.instances[1]

        # ---- stage: n_resident donor-OWNED rows via the slab inject
        # path. Ownership is the single-point ring's call, so candidate
        # keys are partitioned by the live picker and the donor takes
        # the majority side (re-rolling ports for a balanced ring at 1M
        # keys costs more than over-generating candidates).
        get_peer = survivor.instance.get_peer
        probe = [f"reshard_rk{i:07d}" for i in range(50_000)]
        donor_share = sum(get_peer(k).info.address == donor.address
                          for k in probe) / len(probe)
        if donor_share < 0.5:
            survivor, donor = donor, survivor
            donor_share = 1.0 - donor_share
        donor_keys: list = []
        i = 0
        cap = max(4 * n_resident, 200_000)
        while len(donor_keys) < n_resident and i < cap:
            k = f"reshard_rk{i:07d}"
            if get_peer(k).info.address == donor.address:
                donor_keys.append(k)
            i += 1
        now_ms = int(time.time() * 1000)
        chunk = 8192
        t0 = time.perf_counter()

        def slabs():
            for lo in range(0, len(donor_keys), chunk):
                ks = [k.encode() for k in donor_keys[lo:lo + chunk]]
                m = len(ks)
                off = np.zeros(m + 1, np.int64)
                np.cumsum([len(b) for b in ks], out=off[1:])
                rows = np.zeros((m, 7), np.int64)
                rows[:, 0] = 0  # TOKEN_BUCKET
                rows[:, 1] = 1 << 20  # limit
                rows[:, 2] = np.arange(lo, lo + m) % (1 << 20)  # remaining
                rows[:, 3] = 3_600_000  # duration
                rows[:, 4] = now_ms
                rows[:, 5] = now_ms + 3_600_000  # expire_at
                yield b"".join(ks), off, rows

        donor.instance.backend.load_snapshot_slabs(slabs())
        stage_s = time.perf_counter() - t0

        # ---- foreground load on the IMPORTER, sampled per call.
        # Leading digits vary: trailing-suffix keys can collapse onto
        # one fnv ring arc (the _skew_bench ownership-probe caveat), and
        # a draw where every foreground key lands on the DONOR measures
        # nothing — over-generate and keep the survivor-owned ones.
        fg = [r for r in
              (RateLimitReq(name="rfg", unique_key=f"{j:04d}fg", hits=1,
                            limit=1 << 30, duration=3_600_000)
               for j in range(20 * fg_keys))
              if get_peer(r.hash_key()).info.address == survivor.address
              ][:fg_keys]
        lat, marks, fg_errors = [], [], []
        stop = threading.Event()

        def drive():
            while not stop.is_set():
                for r in fg:
                    t1 = time.perf_counter()
                    try:
                        resp = survivor.instance.get_rate_limits([r])[0]
                    except Exception as e:  # noqa: BLE001
                        fg_errors.append(repr(e))
                        continue
                    lat.append(time.perf_counter() - t1)
                    if resp.error:
                        fg_errors.append(resp.error)
                time.sleep(0.005)

        th = threading.Thread(target=drive, daemon=True)
        th.start()
        time.sleep(1.5)  # quiet-window baseline
        marks.append(len(lat))

        # ---- the handoff: evacuate() returns once every export commits
        t0 = time.perf_counter()
        drained = donor.instance.reshard.evacuate(timeout_s=300)
        handoff_s = time.perf_counter() - t0
        marks.append(len(lat))
        time.sleep(1.0)  # post-handoff window
        stop.set()
        th.join(timeout=10)

        stats = donor.instance.reshard.debug()["stats"]
        quiet = np.asarray(lat[:marks[0]])
        during = np.asarray(lat[marks[0]:marks[1]])
        after = np.asarray(lat[marks[1]:])

        def pcts(a):
            if not a.size:
                return {}
            return {"calls": int(a.size),
                    "p50_ms": round(float(np.percentile(a, 50) * 1e3), 3),
                    "p99_ms": round(float(np.percentile(a, 99) * 1e3), 3)}

        return {"reshard": {
            "scope": "2-node loopback cluster, evacuate() streaming the "
                     "donor's whole resident set to the survivor over "
                     "the debug RPC (plan + chunk-cut + stream + "
                     "commit), foreground client on the importer",
            "resident_rows": len(donor_keys),
            "donor_ring_share": round(donor_share, 3),
            "stage_seconds": round(stage_s, 2),
            "drained": bool(drained),
            "handoff_seconds": round(handoff_s, 2),
            "rows_moved": int(stats["rows_out"]),
            "rows_per_sec": round(stats["rows_out"] / max(handoff_s, 1e-6), 1),
            "transfer_MBps": round(
                stats["bytes_out"] / max(handoff_s, 1e-6) / 1e6, 2),
            "export_commits": int(stats["export_commits"]),
            "export_aborts": int(stats["export_aborts"]),
            "chunk_rows": beh.reshard_chunk_rows,
            "importer_foreground": {
                "keys": len(fg),
                "errors": len(fg_errors),
                "quiet": pcts(quiet),
                "during_handoff": pcts(during),
                "after": pcts(after),
            },
        }}
    finally:
        c.stop()


def _ledger_bench(n_calls: int = 1500, batch: int = 64, reps: int = 3) -> dict:
    """Decision-ledger overhead on the serving path: the SAME single-node
    Instance serving identical batch streams with the ledger attributing
    every window vs the GUBER_LEDGER=0 hatch (which turns every engine
    hook into one attribute test — every hook site reads `led.enabled`
    live, so the flag flips on a running instance the way the profiler
    hatch does). The flag alternates every CHUNK calls within one pass,
    same drift-regime rationale as _obs_bench; acceptance is
    overhead <= 2%.

    The hot-path cost under test is the pending-ring parking: one numpy
    column copy + ring append per engine window group (the audit itself
    rides the harvest cadence, off the serving path). The per-audit
    drain/fold/roll cost is timed directly and duty-cycled at the 60 s
    harvest cadence (amortized_overhead_pct, informational)."""
    from gubernator_tpu.models.engine import Engine
    from gubernator_tpu.service.config import InstanceConfig
    from gubernator_tpu.service.instance import Instance
    from gubernator_tpu.types import PeerInfo, RateLimitReq

    AUDIT_PROD_S = 60.0
    inst = Instance(InstanceConfig(backend=Engine(capacity=262_144),
                                   ledger_enabled=True),
                    advertise_address="127.0.0.1:1")
    inst.set_peers([PeerInfo(address="127.0.0.1:1")])  # self-owned: no RPC
    led = inst.ledger
    frames = [
        [RateLimitReq(name="ledbench", unique_key=f"k{(i * batch + j) % 4096}",
                      hits=1, limit=1 << 30, duration=3_600_000)
         for j in range(batch)]
        for i in range(n_calls)
    ]
    try:
        for f in frames[:100]:  # compile + warm the width bucket
            inst.get_rate_limits(f)

        import gc
        import statistics

        CHUNK = 25
        elapsed = {True: 0.0, False: 0.0}
        calls = {True: 0, False: 0}
        pair_overheads = []  # median over ABBA chunk quads
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            for rep in range(reps):
                i = 0
                while i + 4 * CHUNK <= n_calls:
                    # ABBA within one quad: the second chunk of a pair
                    # always rides warmer state than the first, so a
                    # plain AB pairing measures the order effect (~1% on
                    # this rig — larger than the cost under test). The
                    # mirrored half cancels it and linear drift exactly.
                    rate = {True: [], False: []}
                    for enabled in (True, False, False, True):
                        led.enabled = enabled
                        chunk = frames[i:i + CHUNK]
                        i += CHUNK
                        t0 = time.perf_counter()
                        for f in chunk:
                            inst.get_rate_limits(f)
                        dt = time.perf_counter() - t0
                        elapsed[enabled] += dt
                        calls[enabled] += CHUNK
                        rate[enabled].append(CHUNK * batch / dt)
                    r_on = sum(rate[True]) / 2
                    r_off = sum(rate[False]) / 2
                    pair_overheads.append((r_off - r_on) / r_off)
                # drain the parked windows between reps so the pending
                # ring never saturates mid-measurement (the audit is
                # off-path; running it inside the quad loop perturbs the
                # cache right before a timed chunk)
                led.enabled = True
                led.audit(inst.backend, force=True)
        finally:
            if gc_was_enabled:
                gc.enable()
        led.enabled = True
        on = calls[True] * batch / elapsed[True]
        off = calls[False] * batch / elapsed[False]
        overhead_pct = statistics.median(pair_overheads) * 100.0

        # per-audit cost, timed directly and duty-cycled at the 60 s
        # harvest cadence (informational — the audit is off-path)
        audit_costs = []
        for _ in range(20):
            for f in frames[:10]:  # park fresh windows to drain
                inst.get_rate_limits(f)
            t0 = time.perf_counter()
            led.audit(inst.backend, force=True)
            audit_costs.append(time.perf_counter() - t0)
        audit_ms = statistics.median(audit_costs) * 1e3
        amortized_pct = 100.0 * audit_ms * 1e-3 / AUDIT_PROD_S

        lt = led.totals()
        return {
            "ledger": {
                "ledger_on_decisions_per_sec": round(on, 1),
                "ledger_off_decisions_per_sec": round(off, 1),
                # positive = the armed ledger costs throughput; median
                # over on/off chunk pairs, hiccup-robust. budget <= 2%
                "overhead_pct": round(overhead_pct, 2),
                # per-audit drain/fold cost duty-cycled at the 60 s
                # harvest cadence — off the serving path
                "amortized_audit_overhead_pct": round(amortized_pct, 4),
                "audit_ms": round(audit_ms, 3),
                "attempted_hits": lt["attempted"],
                "windows_rolled": lt["windows_rolled"],
                "violations": lt["violations"],
                "keys_tracked": lt["keys_tracked"],
                "pending_dropped": lt["pending_dropped"],
                "chunk_quads": len(pair_overheads),
                "reps": reps,
                "batch": batch,
                "calls_per_rep": n_calls,
            }
        }
    finally:
        inst.close()


def _witness_bench(n_calls: int = 1200, batch: int = 64, reps: int = 3) -> dict:
    """Lock-witness overhead on the serving path: two otherwise identical
    single-node Instances, one constructed under GUBER_LOCK_WITNESS=1
    (every canonical lock an order-checked wrapper validating against
    the committed lockmap) and one under the production default (bare
    threading primitives), serving identical batch streams. The flag
    alternates every CHUNK calls within one pass — same drift-regime
    rationale as _obs_bench — but by alternating INSTANCES: the witness
    wraps locks at construction time, so it cannot flip on a live
    object the way the profiler hatch can. Tier-1 pays this cost on
    every suite run; production pays zero (the off path is the
    differential-tested bit-identical hatch, tests/test_witness.py).
    Budget <= 30% (measured ~26%, r16): every canonical-lock
    acquisition pays ~2.3 us of pure-Python bookkeeping (held-list
    fetch, order scan against the committed lockmap, single-frame site
    stamp), and the serving path takes several locks per decision
    batch (engine, combiner windows, profiler phase hists). Report-side
    stack walks are lazy — only an inversion or a first-sighting
    unknown edge pays them — so the floor is interpreter call overhead,
    not capture; shaving it further would mean duplicating the
    bookkeeping inline in the wrapper, a correctness hazard in the
    instrument meant to catch correctness bugs. The cost is a tier-1
    tax only: production runs the bare primitives.

    A directly-timed bare acquire/release pair for each lock flavor
    rides along informationally."""
    import gc
    import os
    import statistics

    from gubernator_tpu.models.engine import Engine
    from gubernator_tpu.obs import witness
    from gubernator_tpu.service.config import InstanceConfig
    from gubernator_tpu.service.instance import Instance
    from gubernator_tpu.types import PeerInfo, RateLimitReq

    def make_instance(enabled: bool) -> Instance:
        prev = os.environ.get("GUBER_LOCK_WITNESS")
        os.environ["GUBER_LOCK_WITNESS"] = "1" if enabled else "0"
        try:
            inst = Instance(InstanceConfig(backend=Engine(capacity=65_536)),
                            advertise_address="127.0.0.1:1")
        finally:
            if prev is None:
                os.environ.pop("GUBER_LOCK_WITNESS", None)
            else:
                os.environ["GUBER_LOCK_WITNESS"] = prev
        inst.set_peers([PeerInfo(address="127.0.0.1:1")])  # self-owned
        return inst

    insts = {True: make_instance(True), False: make_instance(False)}
    frames = [
        [RateLimitReq(name="witbench", unique_key=f"k{(i * batch + j) % 4096}",
                      hits=1, limit=1 << 30, duration=3_600_000)
         for j in range(batch)]
        for i in range(n_calls)
    ]
    try:
        for f in frames[:100]:  # compile + warm both width buckets
            insts[True].get_rate_limits(f)
            insts[False].get_rate_limits(f)

        CHUNK = 25
        elapsed = {True: 0.0, False: 0.0}
        calls = {True: 0, False: 0}
        pair_overheads = []
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            for rep in range(reps):
                i = 0
                while i + 2 * CHUNK <= n_calls:
                    first = len(pair_overheads) % 2 == 0
                    rate = {}
                    for enabled in (first, not first):
                        chunk = frames[i:i + CHUNK]
                        i += CHUNK
                        inst = insts[enabled]
                        t0 = time.perf_counter()
                        for f in chunk:
                            inst.get_rate_limits(f)
                        dt = time.perf_counter() - t0
                        elapsed[enabled] += dt
                        calls[enabled] += CHUNK
                        rate[enabled] = CHUNK * batch / dt
                    pair_overheads.append(
                        (rate[False] - rate[True]) / rate[False])
        finally:
            if gc_was_enabled:
                gc.enable()
        on = calls[True] * batch / elapsed[True]
        off = calls[False] * batch / elapsed[False]
        overhead_pct = statistics.median(pair_overheads) * 100.0

        # bare acquire/release cost per flavor (informational): the
        # serving call amortizes a handful of acquisitions over a whole
        # batch. Explicit acquire()/release() rather than `with` — a
        # loop-variable context manager would be an unresolved scope to
        # the static lockmap (tests pin those to zero); the runtime
        # witness still checks every one of these acquisitions.
        N_ACQ = 20_000
        acq_ns = {}
        for label, lock in (("on", insts[True].backend._lock),
                            ("off", insts[False].backend._lock)):
            t0 = time.perf_counter()
            for _ in range(N_ACQ):
                lock.acquire()
                lock.release()
            acq_ns[label] = (time.perf_counter() - t0) / N_ACQ * 1e9

        snap = witness.the_witness().snapshot()
        return {
            "lock_witness": {
                "witness_on_decisions_per_sec": round(on, 1),
                "witness_off_decisions_per_sec": round(off, 1),
                # positive = the armed witness costs throughput; median
                # over on/off chunk pairs, hiccup-robust. budget <= 30%
                "overhead_pct": round(overhead_pct, 2),
                "acquire_release_ns_on": round(acq_ns["on"], 1),
                "acquire_release_ns_off": round(acq_ns["off"], 1),
                "observed_edges": len(snap["observed"]),
                "uncommitted_edges": len(snap["unknown"]),
                "inversions": len(snap["inversions"]),
                "chunk_pairs": len(pair_overheads),
                "reps": reps,
                "batch": batch,
                "calls_per_rep": n_calls,
            }
        }
    finally:
        insts[True].close()
        insts[False].close()


def main() -> None:
    watchdog = _init_watchdog()
    import jax
    import jax.numpy as jnp

    jax.devices()  # cheap reachability probe: THIS is what hangs on a
    watchdog.cancel()  # wedged tunnel; compiles/timing may run long safely

    from gubernator_tpu.ops.decide import (
        compact_window,
        decide_packed,
        decide_scan_packed,
        decide_scan_packed_compact,
        make_table,
    )
    from gubernator_tpu.utils.platform import donation_supported

    def make_windows(seed: int, k: int) -> np.ndarray:
        r = np.random.RandomState(seed)
        p = np.zeros((k, 9, BATCH_WIDTH), np.int64)
        for i in range(k):
            # distinct slots per window (engine guarantees via rounds)
            p[i, 0] = r.choice(TABLE_CAPACITY, BATCH_WIDTH, replace=False)
            p[i, 1] = r.randint(0, 5, BATCH_WIDTH)
            p[i, 2] = r.choice([100, 1000, 10000], BATCH_WIDTH)
            p[i, 3] = 60_000
            p[i, 4] = r.randint(0, 2, BATCH_WIDTH)
        return p

    donate = donation_supported()
    dargs = dict(donate_argnums=(0,)) if donate else {}
    scan_step = jax.jit(decide_scan_packed, **dargs)
    one_step = jax.jit(decide_packed, **dargs)

    def force(resp) -> int:
        """Completion barrier: a data-dependent scalar fetch.

        jax.block_until_ready proved unreliable on the tunneled device
        platform — it can return before the dispatched work completes, which
        silently turns a throughput benchmark into an enqueue-rate
        benchmark. Fetching one element of the result is the only barrier
        that provably waits for the whole dependency chain."""
        return int(np.asarray(resp[(0,) * resp.ndim]))

    # Device-resident inputs: measure the kernel tier, not host staging.
    scans = [jnp.asarray(make_windows(s, SCAN_K)) for s in range(N_VARIANTS)]
    singles = [jnp.asarray(make_windows(100 + s, 1)[0]) for s in range(N_VARIANTS)]

    now = 1_700_000_000_000
    state = make_table(TABLE_CAPACITY)

    # ---- warm-up / calibrate ------------------------------------------------
    state, resp = scan_step(state, scans[0], now)
    force(resp)
    t0 = time.perf_counter()
    state, resp = scan_step(state, scans[1], now + 1)
    force(resp)
    per_call = max(time.perf_counter() - t0, 1e-6)
    iters = max(5, min(3000, int(TARGET_SECONDS / per_call)))

    # ---- headline: scan-coalesced throughput, completion-forced -------------
    t_start = time.perf_counter()
    for i in range(iters):
        state, resp = scan_step(state, scans[i % N_VARIANTS], now + 2 + i)
    t_enqueue = time.perf_counter() - t_start  # dispatch-only (diagnostic)
    force(resp)  # wait for the WHOLE chain to really finish
    elapsed = time.perf_counter() - t_start
    decisions_per_sec = iters * SCAN_K * BATCH_WIDTH / elapsed
    enqueue_rate = iters * SCAN_K * BATCH_WIDTH / max(t_enqueue, 1e-9)

    # ---- extra: one-window-per-dispatch, completion-forced ------------------
    state, resp = one_step(state, singles[0], now)
    force(resp)
    t0 = time.perf_counter()
    state, resp = one_step(state, singles[1], now + 1)
    force(resp)
    sd_per_call = max(time.perf_counter() - t0, 1e-6)
    sd_iters = max(5, min(5000, int(TARGET_SECONDS / sd_per_call)))
    t0 = time.perf_counter()
    for i in range(sd_iters):
        state, resp = one_step(state, singles[i % N_VARIANTS], now + i)
    force(resp)
    single_dispatch = sd_iters * BATCH_WIDTH / (time.perf_counter() - t0)

    # ---- extra: synchronous per-window latency (incl. readback) -------------
    lat_iters = max(5, min(sd_iters, 50))
    lat = np.zeros(lat_iters)
    for i in range(lat_iters):
        t1 = time.perf_counter()
        state, resp = one_step(state, singles[i % N_VARIANTS], now + i)
        force(resp)
        lat[i] = time.perf_counter() - t1

    # ---- extra: compact (i32) staging variant — the wire format for
    # ingest-bound links (20 B/decision up instead of 72; see
    # ops/decide.py "compact") -----------------------------------------------
    compact_step = jax.jit(decide_scan_packed_compact, **dargs)
    compact_np = [compact_window(np.asarray(s)) for s in scans]
    assert all(c is not None for c in compact_np), \
        "bench windows must stay compact-eligible (no gregorian, values < 2^31)"
    compacts = [jnp.asarray(c) for c in compact_np]
    state, resp = compact_step(state, compacts[0], now)
    force(resp)
    t0 = time.perf_counter()
    c_iters = max(3, iters // 2)
    for i in range(c_iters):
        state, resp = compact_step(state, compacts[i % N_VARIANTS], now + i)
    force(resp)
    compact_rate = c_iters * SCAN_K * BATCH_WIDTH / (time.perf_counter() - t0)

    # ---- extra: FULL serving path — key directory + columnar prep +
    # staging + kernel + demux (VERDICT r2 item 1). Real key strings
    # resolve through the 10M-entry C++ LRU directory and the GIL-free
    # columnar prep into a K-deep staging stack shipped in the LEAN wire
    # format (native/keydir.cpp keydir_prep_pack_lean): ONE i32 word per
    # decision — 4 B up, 8 B back = 12 B/decision round trip (the r5 wire
    # lever, DESIGN.md "Next wire lever"; interned was 16, compact 36,
    # wide 104). One transfer up, ONE scan dispatch, ONE fetch back; the
    # demux scatters each window's response rows to its items. On local
    # hardware the same path runs per-window with µs readbacks. ---------------
    from gubernator_tpu import native
    from gubernator_tpu.models.engine import Engine
    from gubernator_tpu.ops.decide import decide_scan_packed_lean

    # min_width 64 (not BATCH_WIDTH) so the columnar-pipeline section's
    # frame-width windows bucket at their own width instead of padding to
    # 8192; every other section drives exact-max-width windows and is
    # unaffected (bucket_width(8192) == 8192 either way)
    eng = Engine(capacity=TABLE_CAPACITY, min_width=64,
                 max_width=BATCH_WIDTH)
    serving_row = {}
    if eng.supports_columnar():
        rng = np.random.RandomState(7)
        CH = 100_000
        for s in range(0, TABLE_CAPACITY, CH):  # resident directory: 10M keys
            eng.directory.lookup([f"b_k{i}" for i in range(s, s + CH)])
        variants = []
        for _ in range(N_VARIANTS):
            ids = rng.choice(TABLE_CAPACITY, BATCH_WIDTH, replace=False)
            ukeys = [b"k%d" % i for i in ids]
            keys = b"".join(b"b" + u for u in ukeys)
            off = np.zeros(BATCH_WIDTH + 1, np.int32)
            np.cumsum([1 + len(u) for u in ukeys], out=off[1:])
            variants.append((
                keys, off, np.ones(BATCH_WIDTH, np.int32),
                np.ones(BATCH_WIDTH, np.int64),
                np.full(BATCH_WIDTH, 1 << 30, np.int64),
                np.full(BATCH_WIDTH, 3_600_000, np.int64),
                np.zeros(BATCH_WIDTH, np.int32),
                np.zeros(BATCH_WIDTH, np.int32)))
        K_SERVE = 128
        N_BUF = 8  # buffer ring; up to 6 cycles stay in flight (auto-tuned)
        lanes = [[None] * K_SERVE for _ in range(N_BUF)]
        iws = [np.empty((K_SERVE, BATCH_WIDTH), np.int32)
               for _ in range(N_BUF)]
        st = np.zeros(BATCH_WIDTH, np.int32)
        li = np.zeros(BATCH_WIDTH, np.int64)
        re = np.zeros(BATCH_WIDTH, np.int64)
        rs = np.zeros(BATCH_WIDTH, np.int64)

        # The serving cycle ships the LEAN wire format — i32[K, B] lane
        # words + one i64[128, 4] config table (4 KB, re-shipped only on
        # config churn) = 4 B/decision up; responses fetch as i32[K, 2, B]:
        # remaining | status<<31, and the reset delta = 8 B/decision back.
        # `limit` is an input echo the host already holds (config table).
        # (On local hardware the per-window engine path fetches the plain
        # 4-row form in µs.)
        def _step2(state, iw, cfg, now_ms):
            state, out = decide_scan_packed_lean(state, iw, cfg, now_ms)
            packed2 = jnp.stack(
                [out[:, 2, :] | (out[:, 0, :] << 31), out[:, 3, :]],
                axis=1)
            return state, packed2

        step2 = jax.jit(_step2, **dargs)

        istate = native.LeanPrepState()

        def prep_cycle(buf, w):
            # the C lean prep: directory lookup + validation + round
            # split + LEAN staging emit (4 B/item written instead of the
            # 72 B wide rows) in one GIL-free pass per window
            iwk, lns = iws[buf], lanes[buf]
            for d in range(K_SERVE):
                v = variants[(w + d) % N_VARIANTS]
                n0, lane, left, _inj = native.prep_pack_lean(
                    eng.directory, BATCH_WIDTH, v[0], v[1], v[2], v[3],
                    v[4], v[5], v[6], v[7], 0, iwk[d], istate)
                assert n0 == BATCH_WIDTH and not len(left)
                lns[d] = lane
            return iwk

        # the live Profiler meters this offline loop too, so the emitted
        # serving_decomposition below is the SAME derivation the
        # /v1/debug/profile endpoint serves (obs/profile.py) — one source
        # of truth, pinned by tests/test_profile_plane.py
        from gubernator_tpu.obs.profile import Profiler, serving_decomposition
        prof = Profiler(enabled=True)

        def drain(out2, buf, w, limit_col):
            t0 = time.perf_counter_ns()
            packed = np.asarray(out2)  # the one readback fetch
            prof.observe("readback", time.perf_counter_ns() - t0)
            t0 = time.perf_counter_ns()
            for d in range(K_SERVE):  # demux scatter per window
                lane = lanes[buf][d]
                w0 = packed[d, 0]
                delta = packed[d, 1].astype(np.int64)
                st[lane] = w0 >> 31 & 1
                re[lane] = w0 & 0x7FFFFFFF
                rs[lane] = np.where(delta < 0, 0, (now + w) + delta)
                li[lane] = limit_col
            prof.observe("demux", time.perf_counter_ns() - t0)
            return packed

        limit_col = np.int64(1 << 30)

        def probe_link_MBps():
            """Measure the rig's host->device and device->host bandwidth
            with cycle-sized transfers (completion-forced), so the JSON
            can separate 'what the framework does' from 'what the link
            did that minute' (VERDICT r4 item 2). Best of 2 each way —
            the tunnel swings 2-4x on minute timescales."""
            up_bytes = K_SERVE * BATCH_WIDTH * 4  # one lean upload
            down_bytes = K_SERVE * BATCH_WIDTH * 8  # one 2-row readback
            up = np.zeros(up_bytes // 4, np.int32)
            up_s, down_s = [], []
            for _ in range(2):
                t0 = time.perf_counter()
                d = jnp.asarray(up)
                force(d)
                up_s.append(time.perf_counter() - t0)
                big = jnp.zeros(down_bytes // 4, jnp.int32) + d[0]
                force(big)
                t0 = time.perf_counter()
                np.asarray(big)
                down_s.append(time.perf_counter() - t0)
            return (up_bytes / min(up_s) / 1e6,
                    down_bytes / min(down_s) / 1e6)

        def run(cycles, w0, depth=2, prep_s=None):
            """A dedicated drainer thread owns the blocking readbacks, so
            the link is driven continuously; the main thread preps and
            dispatches (the columnar C prep releases the GIL, so the two
            overlap even on one core). Measured r3: a single-threaded loop
            made the cycle time the SUM of prep + transfer — this platform
            only moves bytes while a host thread is blocked in a fetch.
            `depth` bounds the in-flight cycles (queue backpressure)."""
            import queue as _q
            import threading as _t

            nonlocal state
            # buffer-ring safety: prep writes iws/lanes[c % N_BUF] while
            # up to `depth` earlier cycles (+1 inside the drainer) still
            # read theirs
            assert depth <= N_BUF - 2, (depth, N_BUF)
            q = _q.Queue(maxsize=depth)
            drain_err = []

            def drainer():
                while True:
                    item = q.get()
                    if item is None:
                        q.task_done()
                        return
                    try:
                        o, b, ww = item
                        drain(o, b, ww, limit_col)
                    except BaseException as e:  # surface, don't hang main
                        drain_err.append(e)
                    q.task_done()

            th = _t.Thread(target=drainer, daemon=True)
            th.start()
            cfg_dev = jnp.asarray(istate.cfg)  # ships once, not per cycle
            n_cfg0 = istate.n_cfg
            w = w0
            for c in range(cycles):
                t0 = time.perf_counter()
                iw = prep_cycle(c % N_BUF, w)
                if istate.n_cfg != n_cfg0:  # new config pairs: re-ship 4 KB
                    cfg_dev = jnp.asarray(istate.cfg)
                    n_cfg0 = istate.n_cfg
                dt = time.perf_counter() - t0
                prof.observe("prep", int(dt * 1e9))
                if prep_s is not None:
                    prep_s.append(dt)
                t0 = time.perf_counter_ns()
                state, out2 = step2(state, jnp.asarray(iw), cfg_dev, now + w)
                prof.observe("dispatch", time.perf_counter_ns() - t0)
                t0 = time.perf_counter_ns()
                q.put((out2, c % N_BUF, w))
                prof.observe("queue_wait", time.perf_counter_ns() - t0)
                w += K_SERVE
            q.put(None)
            q.join()
            if drain_err:
                raise drain_err[0]

        run(2, 0)  # warm + compile
        # auto-tune cycles-in-flight (VERDICT r4 item 2): probe each depth
        # with a run long enough that (a) the queue actually FILLS (a
        # probe shorter than ~2x the depth never engages backpressure and
        # measures nothing) and (b) fill/tail amortize enough for a
        # RELATIVE comparison — deeper pipelines hide more link jitter
        # until queueing stops paying
        depth_probe = {}
        w_base = 2 * K_SERVE
        PROBE_CYCLES = 12
        for depth in (3, 6):
            t0 = time.perf_counter()
            run(PROBE_CYCLES, w_base, depth=depth)
            depth_probe[depth] = (time.perf_counter() - t0) / PROBE_CYCLES
            w_base += PROBE_CYCLES * K_SERVE
        depth = min(depth_probe, key=depth_probe.get)
        per_cycle = max(depth_probe[depth], 1e-6)
        # enough cycles that pipeline fill + the serial drain tail (~1.5
        # cycles of link time) amortize below ~10% of the measurement —
        # 3-4 cycles UNDERSTATES the steady-state serving rate badly.
        # The tunnel's bandwidth swings 2-4x on minute timescales, so the
        # headline is the MEDIAN of NINE independent completion-forced
        # segments (each long enough to amortize fill/tail) rather than
        # one roll of the link dice; best/worst ride along, and the
        # link-bandwidth probes below turn 'bad tunnel day' into a number.
        # floor 16: the ~1.5-cycle fill/tail overhead stays <= ~10% of
        # each segment, honoring the amortization bound above
        N_SEG = 9
        seg_cycles = max(16, min(20, int(3 * TARGET_SECONDS / per_cycle)))
        seg_rates = []
        seg_elapsed = []
        prep_s = []
        totals_before = prof.totals()  # exclude warmup/probe cycles
        link_up, link_down = probe_link_MBps()  # same-run link weather
        for _seg in range(N_SEG):
            t0 = time.perf_counter()
            run(seg_cycles, w_base, depth=depth, prep_s=prep_s)
            seg_elapsed.append(time.perf_counter() - t0)
            seg_rates.append(
                seg_cycles * K_SERVE * BATCH_WIDTH / seg_elapsed[-1])
            w_base += seg_cycles * K_SERVE
        link_up2, link_down2 = probe_link_MBps()  # weather after, too
        seg_sorted = sorted(seg_rates)
        serving_rate = seg_sorted[N_SEG // 2]  # median of 9
        cycles = N_SEG * seg_cycles
        serving_elapsed = sum(seg_elapsed)  # measured, not back-computed

        # Latency decomposition (VERDICT r3 item 8, re-derived r14): two
        # Profiler totals() snapshots around the measured segments feed
        # obs/profile.serving_decomposition() — the SAME arithmetic the
        # live /v1/debug/profile endpoint uses, so offline and live
        # numbers cannot drift apart. readback is measured in the drainer
        # (device + link jointly on a tunnel rig; on attached hardware it
        # collapses toward pure device time), link_s_est is the residual.
        totals_after = prof.totals()
        dec_per_cycle = K_SERVE * BATCH_WIDTH
        host_s = float(np.mean(prep_s)) if prep_s else 0.0
        # Link-normalized figure (VERDICT r4 item 2): what the same-run
        # measured link bandwidth predicts for a link-bound pipeline at
        # 4 B/decision up + 8 B/decision down, capped by the measured
        # host-prep and device tiers. A serving median far below this
        # number is a framework regression; a median near it is the link.
        bw_up = max(link_up, link_up2) * 1e6
        bw_down = max(link_down, link_down2) * 1e6
        link_s_per_dec = 4.0 / bw_up + 8.0 / bw_down
        link_pred = 1.0 / max(link_s_per_dec, 1e-12)
        host_pred = dec_per_cycle / host_s if host_s > 0 else float("inf")
        norm_rate = min(link_pred, host_pred,
                        decisions_per_sec)  # device tier caps the rest
        serving_row = {
            "serving_path_decisions_per_sec": round(serving_rate, 1),
            "serving_path_scope":
                "keydir(10M resident)+columnar prep+LEAN staging "
                f"(4 B/dec up, 8 back)+kernel+demux, {K_SERVE} windows/"
                f"transfer, {depth} cycles in flight (auto-tuned; tunnel "
                "rig: link-bound — see link_normalized_decisions_per_sec)",
            "serving_segment_rates": [round(r, 1) for r in seg_rates],
            "serving_segments": {
                "best": round(seg_sorted[-1], 1),
                "median": round(serving_rate, 1),
                "worst": round(seg_sorted[0], 1),
                "n": N_SEG,
            },
            "link_bandwidth_MBps": {
                "up_before": round(link_up, 2),
                "down_before": round(link_down, 2),
                "up_after": round(link_up2, 2),
                "down_after": round(link_down2, 2),
            },
            "link_normalized_decisions_per_sec": round(norm_rate, 1),
            # the ~4 KB config table ships once per config change, not
            # per cycle — excluded from the steady-state byte figures.
            # derivation_version 2 = profiler-derived (bench_check only
            # gates decomposition keys between same-version rounds).
            "serving_decomposition": {
                **{k: round(v, 4) if isinstance(v, float) else v
                   for k, v in serving_decomposition(
                       totals_before, totals_after, cycles,
                       serving_elapsed,
                       upload_bytes=dec_per_cycle * 4 * cycles,
                       download_bytes=dec_per_cycle * 8 * cycles,
                       decisions=dec_per_cycle * cycles).items()},
                "derivation_version": 2,
            },
        }

    # ---- PRODUCT path: the shipped BackendCombiner serving loop ------------
    # The depth-N pipelined combiner (service/combiner.py) driving the SAME
    # 10M-key engine through real submit() calls — request objects in,
    # RateLimitResp objects out, the exact path gRPC/peer traffic takes.
    # Probes cycles-in-flight {1, 3, 6} (1 = the old lock-step combiner);
    # the ≥2 depths overlap host prep + H2D + device + D2H of DIFFERENT
    # window groups, which is bench's serving-loop structure productized.
    product_row = {}
    if eng.supports_columnar():
        try:
            product_row = _product_combiner_bench(eng)
        except Exception as e:  # noqa: BLE001 — report, don't die
            product_row = {"product_combiner": {"error": str(e)}}

    # ---- columnar wire path: lock-step vs the depth-N pipeline -------------
    # The zero-object owner path peer hops and standalone public traffic
    # ride (service/peerlink.py _columnar_chunk): PR 3 gives it the same
    # launch/collect pipeline the object path gained in PR 2. BENCH_r07
    # records the depth probe; acceptance is pipelined >= 1.5x lock-step.
    columnar_row = {}
    if eng.supports_columnar():
        try:
            columnar_row = _columnar_pipeline_bench(eng)
        except Exception as e:  # noqa: BLE001 — report, don't die
            columnar_row = {"columnar_pipeline": {"error": str(e)}}

    # ---- overload: admission + deadline shedding vs the queueing baseline
    # Offered load at ~2x measured capacity through a real Instance;
    # BENCH_r08 records goodput, shed rate, and accepted p99 for the
    # admission run vs the no-admission baseline (PR 5's acceptance row).
    try:
        overload_row = _overload_bench(eng)
    except Exception as e:  # noqa: BLE001 — report, don't die
        overload_row = {"overload": {"error": str(e)}}

    # ---- skew: Zipf-head traffic vs the hot-key lease tier -----------------
    # A real 2-node loopback cluster under Zipf-1.1 load; BENCH_r09 records
    # client p99 + hot-owner work share for uniform / leases-off / leases-on
    # (opt-in via --skew: the cluster boot pays two engine warmups).
    skew_row = {}
    if "--skew" in sys.argv:
        try:
            skew_row = _skew_bench()
        except Exception as e:  # noqa: BLE001 — report, don't die
            skew_row = {"skew": {"error": str(e)}}

    # ---- wire contract v2: partial posts vs the v1 whole-frame barrier ----
    # A real loopback peerlink client/server pair, closed loop with frames
    # in flight; BENCH_r10 records v1 vs v2 decisions/s plus the negotiated
    # version and the server's partial-post/boundary-stall counters
    # (opt-in via --wire; acceptance is v2 >= 1.3x the v1 pipelined row).
    wire_row = {}
    if "--wire" in sys.argv:
        try:
            wire_row = _wire_bench()
        except Exception as e:  # noqa: BLE001 — report, don't die
            wire_row = {"wire": {"error": str(e)}}

    # ---- live resharding: 1M-row handoff duration + importer impact ----
    # A real 2-node loopback cluster; BENCH_r13 records evacuate() wall
    # clock, rows/s, and the importer's foreground p50/p99 quiet vs
    # mid-handoff (opt-in via --reshard: staging 1M rows costs ~a minute).
    reshard_row = {}
    if "--reshard" in sys.argv:
        try:
            reshard_row = _reshard_bench()
        except Exception as e:  # noqa: BLE001 — report, don't die
            reshard_row = {"reshard": {"error": str(e)}}

    # ---- observability plane: flight recorder on vs the escape hatch ------
    # Single-node serving with the recorder enabled vs disabled on the same
    # Instance; BENCH_r11 records the overhead (acceptance <= 2%) plus the
    # anomaly detector sweep cost.
    try:
        obs_row = _obs_bench()
    except Exception as e:  # noqa: BLE001 — report, don't die
        obs_row = {"observability": {"error": str(e)}}

    # ---- capacity cartography: history ticker + keyspace harvest ----------
    # Single-node serving with the metrics-history tick in-band vs the
    # GUBER_HISTORY=0 hatch, plus directly-timed tick/harvest costs
    # duty-cycled at production cadence (acceptance: amortized <= 2%).
    try:
        carto_row = _cartography_bench()
    except Exception as e:  # noqa: BLE001 — report, don't die
        carto_row = {"cartography": {"error": str(e)}}

    # ---- traffic-shape capture: /v1/debug/capture assembly cost -----------
    # Same single-node Instance; one in-band capture per chunk (stress
    # ceiling) plus the direct per-capture cost duty-cycled at a
    # one-capture-per-minute operator cadence (acceptance: amortized <= 2%).
    try:
        capture_row = _capture_bench()
    except Exception as e:  # noqa: BLE001 — report, don't die
        capture_row = {"capture": {"error": str(e)}}

    # ---- scenario atlas: seeded traffic shapes judged by the obs plane ----
    # Every named scenario runs its short profile against a fresh
    # in-process cluster; verdict_pass gates hard in bench_check
    # (opt-in via --scenarios: six cluster boots cost ~a minute).
    scenarios_row = {}
    if "--scenarios" in sys.argv:
        try:
            scenarios_row = _scenarios_bench()
        except Exception as e:  # noqa: BLE001 — report, don't die
            scenarios_row = {"scenarios": {"error": str(e)}}

    # ---- profiling plane: serving-cycle profiler on vs GUBER_PROFILE=0 ----
    # Single-node serving with the cycle profiler enabled vs the escape
    # hatch on the same Instance; BENCH_r14 records the overhead
    # (acceptance <= 2%, target 0.5%) plus per-observe and endpoint costs.
    try:
        profile_row = _profile_bench()
    except Exception as e:  # noqa: BLE001 — report, don't die
        profile_row = {"profiler": {"error": str(e)}}

    # ---- decision ledger: attribution hooks on vs GUBER_LEDGER=0 ----------
    # Single-node serving with the ledger parking attribution columns vs
    # the escape hatch on the same Instance; BENCH_r17 records the
    # overhead (acceptance <= 2%) plus the off-path audit cost
    # duty-cycled at the 60 s harvest cadence.
    try:
        ledger_row = _ledger_bench()
    except Exception as e:  # noqa: BLE001 — report, don't die
        ledger_row = {"ledger": {"error": str(e)}}

    # ---- lockmap runtime witness: armed vs production-default locks -------
    # Two identical single-node Instances (the witness wraps locks at
    # construction, so the hatch can't flip live); BENCH_r16 records the
    # overhead tier-1 pays for running the whole suite order-checked
    # (acceptance <= 30%, ~26% measured; production pays zero via the
    # off hatch — see _witness_bench's docstring for why the floor is
    # interpreter call overhead, not stack capture).
    try:
        witness_row = _witness_bench()
    except Exception as e:  # noqa: BLE001 — report, don't die
        witness_row = {"lock_witness": {"error": str(e)}}

    # trace-derived serving-stack phase split (never fails the bench)
    try:
        phases = phase_breakdown()
    except Exception as e:  # noqa: BLE001
        phases = {"error": str(e)}

    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": round(decisions_per_sec, 1),
                **serving_row,
                **product_row,
                **columnar_row,
                **overload_row,
                **skew_row,
                **wire_row,
                **reshard_row,
                **obs_row,
                **carto_row,
                **capture_row,
                **scenarios_row,
                **profile_row,
                **ledger_row,
                **witness_row,
                **_multichip_section(),
                "phase_breakdown_ms": phases,
                "unit": UNIT,
                "vs_baseline": round(decisions_per_sec / REFERENCE_BASELINE_RPS, 2),
                "batch_width": BATCH_WIDTH,
                "scan_k": SCAN_K,
                "table_capacity": TABLE_CAPACITY,
                "single_dispatch_decisions_per_sec": round(single_dispatch, 1),
                "compact_staging_decisions_per_sec": round(compact_rate, 1),
                "window_p50_ms": round(float(np.percentile(lat, 50) * 1e3), 3),
                "window_p99_ms": round(float(np.percentile(lat, 99) * 1e3), 3),
                "latency_samples": lat_iters,  # p99 is ~max at small counts
                "iters": iters,
                "device": str(jax.devices()[0]),
                "donated": donate,
                "completion_barrier": "data-dependent fetch",
                # dispatch-only rate, for reference: through a tunneled
                # device, enqueue can run arbitrarily ahead of completion
                "enqueue_decisions_per_sec": round(enqueue_rate, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
