"""Peer-failure resilience drills: circuit breaker, degraded-local serving,
and recovery — proven deterministically via the fault-injection harness
(service/faults.py) in tier-1 wall time, instead of the ~minute-long
process-kill soaks.

The `chaos` marker groups these: they run fast and pinned-seed by default
(tier-1), and `make chaos` re-runs them with a randomized GUBER_CHAOS_SEED
(printed for reproduction)."""

import os
import random
import time
from concurrent.futures import Future

import pytest

from gubernator_tpu.cluster.harness import LocalCluster
from gubernator_tpu.cluster.harness import test_behaviors as _behaviors
from gubernator_tpu.service import faults
from gubernator_tpu.service.peer_client import (
    CIRCUIT_CLOSED,
    CIRCUIT_HALF_OPEN,
    CIRCUIT_OPEN,
    CircuitBreaker,
    CircuitOpenError,
    PeerClient,
    PeerNotReadyError,
)
from gubernator_tpu.types import PeerInfo, RateLimitReq

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.clear()


def _rl(key, hits=1, limit=5, duration=60_000, behavior=0, name="test"):
    return RateLimitReq(name=name, unique_key=key, hits=hits, limit=limit,
                        duration=duration, behavior=behavior)


def _key_owned_by(instance, owner_addr, prefix="cb"):
    """A key that `instance` routes to `owner_addr` (leading digits vary:
    trailing-suffix keys can collapse onto one fnv ring arc)."""
    for i in range(3000):
        k = f"{i}{prefix}"
        if instance.get_peer(f"test_{k}").info.address == owner_addr:
            return k
    raise AssertionError(f"no probe key routed to {owner_addr}")


class TestCircuitBreakerUnit:
    def test_transitions_and_single_probe(self):
        conf = _behaviors()
        conf.circuit_threshold = 3
        conf.circuit_open_s = 0.05
        cb = CircuitBreaker(conf, "peer:1")
        assert cb.allow() and not cb.blocked()
        cb.record_failure()
        cb.record_failure()
        assert cb.state == CIRCUIT_CLOSED  # below threshold
        cb.record_failure()
        assert cb.state == CIRCUIT_OPEN and cb.opened_total == 1
        assert cb.blocked() and not cb.allow()
        time.sleep(0.06)
        assert not cb.blocked()
        assert cb.allow()  # THE half-open probe
        assert cb.state == CIRCUIT_HALF_OPEN
        assert not cb.allow()  # concurrent caller blocked while probing
        cb.record_failure()  # probe failed: reopen for another cooldown
        assert cb.state == CIRCUIT_OPEN and cb.opened_total == 2
        time.sleep(0.06)
        assert cb.allow()
        cb.record_success()
        assert cb.state == CIRCUIT_CLOSED and cb.allow()

    def test_success_resets_consecutive_count(self):
        conf = _behaviors()
        conf.circuit_threshold = 3
        cb = CircuitBreaker(conf, "peer:1")
        for _ in range(5):  # interleaved successes never accumulate to open
            cb.record_failure()
            cb.record_failure()
            cb.record_success()
        assert cb.state == CIRCUIT_CLOSED

    def test_disabled_breaker_never_opens(self):
        conf = _behaviors()
        conf.circuit_threshold = 0
        cb = CircuitBreaker(conf, "peer:1")
        for _ in range(50):
            cb.record_failure()
        assert cb.state == CIRCUIT_CLOSED and cb.allow() and not cb.blocked()


class TestBreakerEndToEnd:
    """The acceptance drill: with one peer's transport killed (injected),
    (a) the breaker opens after the threshold and later forwards complete
    in < 50 ms, (b) GUBER_DEGRADED_LOCAL turns those into enforced
    degraded-local responses, (c) a half-open probe restores normal
    forwarding — transitions visible in the metrics exposition."""

    def test_breaker_opens_degrades_and_recovers(self):
        c = LocalCluster().start(3)
        try:
            for ci in c.instances:
                b = ci.instance.conf.behaviors
                b.circuit_threshold = 3
                b.circuit_open_s = 5.0  # long: the open phase is asserted
                b.degraded_local = False
            inst0 = c.instances[0].instance
            owner_addr = c.instances[1].address
            key = _key_owned_by(inst0, owner_addr)
            peer = inst0.get_peer(f"test_{key}")

            # kill the owner's transport (every call, both transports)
            faults.install(f"peer={owner_addr};action=error")

            # (a) exactly `threshold` transport failures, then open
            for i in range(3):
                r = inst0.get_rate_limits([_rl(key)])[0]
                assert "injected" in r.error, (i, r.error)
            assert peer.circuit.state == CIRCUIT_OPEN
            assert peer.circuit.opened_total == 1

            # open circuit: forwards fail fast — no batch_timeout_s stall
            for _ in range(5):
                t0 = time.monotonic()
                r = inst0.get_rate_limits([_rl(key)])[0]
                dt = time.monotonic() - t0
                assert "circuit open to owner" in r.error
                assert dt < 0.05, f"open-circuit forward took {dt * 1e3:.1f} ms"

            # (b) degraded-local: enforced decisions, marked in metadata
            inst0.conf.behaviors.degraded_local = True
            degraded = []
            for _ in range(3):
                t0 = time.monotonic()
                r = inst0.get_rate_limits([_rl(key, limit=2)])[0]
                dt = time.monotonic() - t0
                assert r.error == ""
                assert r.metadata["degraded"] == "true"
                assert r.metadata["owner"] == owner_addr
                assert dt < 0.05, f"degraded forward took {dt * 1e3:.1f} ms"
                degraded.append(r)
            # the local as-if-owner bucket ENFORCES the limit
            assert [r.remaining for r in degraded] == [1, 0, 0]
            assert degraded[2].status == 1  # OVER_LIMIT

            # breaker transitions + degraded serving in the exposition
            text = c.instances[0].metrics.render(inst0).decode()
            assert f'circuit_open_total{{peer="{owner_addr}"}} 1.0' in text
            assert f'circuit_state{{peer="{owner_addr}"}} 2.0' in text
            assert "degraded_local_total 3.0" in text

            # health reports the open circuit, bounded
            hc = inst0.health_check()
            assert hc.status == "unhealthy"
            assert "circuit open" in hc.message

            # (c) revive the peer: clear faults, shrink the cooldown so the
            # next call is the half-open probe (the breaker reads its
            # thresholds live), and watch normal forwarding return
            faults.clear()
            inst0.conf.behaviors.circuit_open_s = 0.05
            time.sleep(0.1)
            r = inst0.get_rate_limits([_rl(key)])[0]
            assert r.error == "", r.error
            assert r.metadata["owner"] == owner_addr
            assert "degraded" not in r.metadata
            assert peer.circuit.state == CIRCUIT_CLOSED
            text = c.instances[0].metrics.render(inst0).decode()
            assert f'circuit_state{{peer="{owner_addr}"}} 0.0' in text
            # still exactly one open transition: recovery was the probe
            assert f'circuit_open_total{{peer="{owner_addr}"}} 1.0' in text
        finally:
            faults.clear()
            c.stop()

    def test_group_forward_degrades_in_one_apply(self):
        """A multi-request same-owner group degrades as ONE local owner
        batch (order preserved), not request-by-request."""
        c = LocalCluster().start(2)
        try:
            inst0 = c.instances[0].instance
            b = inst0.conf.behaviors
            b.circuit_threshold = 1
            b.circuit_open_s = 5.0
            b.degraded_local = True
            owner_addr = c.instances[1].address
            key = _key_owned_by(inst0, owner_addr, prefix="grp")
            faults.install(f"peer={owner_addr};action=error")
            # trip the breaker (threshold 1: first failure opens it)
            r = inst0.get_rate_limits([_rl(key)])[0]
            assert "injected" in r.error
            # a same-key group rides one degraded owner-batch: strictly
            # decreasing remaining proves single-apply ordering
            rs = inst0.get_rate_limits([_rl(key, limit=10) for _ in range(4)])
            assert [r.remaining for r in rs] == [9, 8, 7, 6]
            assert all(r.metadata.get("degraded") == "true" for r in rs)
        finally:
            faults.clear()
            c.stop()


class TestChaosRandomized:
    def test_breaker_invariants_hold_for_any_seed(self):
        """Randomized drill (`make chaos`): the seed varies the threshold,
        the fault verb, and the extra-failure count; the invariants may
        not. Reproduce any failure with GUBER_CHAOS_SEED=<seed> make chaos."""
        seed = int(os.environ.get("GUBER_CHAOS_SEED", "0") or "0")
        rng = random.Random(seed)
        threshold = rng.randint(1, 4)
        verb = rng.choice(["error", "timeout", "drop"])
        extra = rng.randint(0, 2)
        print(f"chaos seed: {seed} (threshold={threshold} verb={verb} "
              f"extra={extra})")
        c = LocalCluster().start(2)
        try:
            inst0 = c.instances[0].instance
            b = inst0.conf.behaviors
            b.circuit_threshold = threshold
            b.circuit_open_s = 5.0
            b.degraded_local = True
            owner_addr = c.instances[1].address
            key = _key_owned_by(inst0, owner_addr, prefix=f"cs{seed}")
            peer = inst0.get_peer(f"test_{key}")
            faults.install(f"peer={owner_addr};action={verb}")
            # invariant 1: the breaker opens after EXACTLY threshold
            # consecutive transport failures, whatever the failure verb
            for i in range(threshold):
                assert peer.circuit.state == CIRCUIT_CLOSED, i
                r = inst0.get_rate_limits([_rl(key)])[0]
                assert "injected" in r.error, (i, r.error)
            assert peer.circuit.state == CIRCUIT_OPEN
            # invariant 2: open means degraded-local, marked, and fast
            for _ in range(1 + extra):
                t0 = time.monotonic()
                r = inst0.get_rate_limits([_rl(key)])[0]
                assert r.metadata.get("degraded") == "true"
                assert time.monotonic() - t0 < 0.05
            # invariant 3: revival closes the circuit via the probe
            faults.clear()
            b.circuit_open_s = 0.05
            time.sleep(0.1)
            r = inst0.get_rate_limits([_rl(key)])[0]
            assert r.error == "" and "degraded" not in r.metadata
            assert peer.circuit.state == CIRCUIT_CLOSED
        finally:
            faults.clear()
            c.stop()


@pytest.fixture(scope="module")
def duo():
    c = LocalCluster().start(2)
    yield c
    c.stop()


class TestPeerClientPaths:
    """Transport-path coverage for PeerClient: peerlink->gRPC fallback,
    timeout surfacing without resend, error-history TTL, shutdown sweep."""

    def test_peerlink_error_falls_back_to_grpc(self, duo):
        from gubernator_tpu.cluster.harness import wire_peerlink

        links = wire_peerlink(duo)
        assert links, "no peerlink offset bound"
        ci0, ci1 = duo.instances
        pc = PeerClient(ci0.instance.conf.behaviors,
                        PeerInfo(address=ci1.address))
        try:
            r = pc.get_peer_rate_limits([_rl("plfb_warm", limit=9)])[0]
            assert r.error == "" and pc._link is not None  # rides the link
            # counters start at install time: the next link call is call 1
            faults.install(f"peer={ci1.address};transport=peerlink;"
                           "calls=1;action=error")
            r = pc.get_peer_rate_limits([_rl("plfb_warm", limit=9)])[0]
            assert r.error == ""  # served over gRPC
            assert r.remaining == 7  # applied exactly once, same bucket
            assert pc._link is None  # broken link dropped + backed off
            assert any("peerlink" in e for e in pc.get_last_err())
            # the call SUCCEEDED via gRPC: a dead link port alone must
            # never accumulate toward opening the peer's circuit
            assert pc.circuit.state == CIRCUIT_CLOSED
            assert pc.circuit._failures == 0
        finally:
            faults.clear()
            pc.shutdown(timeout_s=2)
            for svc in links:
                svc.close()
            for ci in duo.instances:
                ci.instance.conf.behaviors.peer_link_offset = 0

    def test_peerlink_timeout_surfaces_without_resend(self, duo):
        from gubernator_tpu.cluster.harness import wire_peerlink
        from gubernator_tpu.service.peerlink import PeerLinkTimeout

        links = wire_peerlink(duo)
        assert links
        ci0, ci1 = duo.instances
        pc = PeerClient(ci0.instance.conf.behaviors,
                        PeerInfo(address=ci1.address))
        try:
            faults.install(f"peer={ci1.address};transport=peerlink;"
                           "calls=1;action=timeout")
            with pytest.raises(PeerLinkTimeout):
                pc.get_peer_rate_limits([_rl("plto", limit=7)])
            assert pc.circuit._failures == 1  # the breaker was charged
            assert pc._link is not None  # a timeout must NOT drop the link
            faults.clear()
            r = pc.get_peer_rate_limits([_rl("plto", limit=7)])[0]
            # remaining 6 proves the timed-out frame was never re-sent
            # over gRPC (a resend would have burned a second hit)
            assert r.error == "" and r.remaining == 6
            assert pc.circuit._failures == 0  # success reset the count
        finally:
            faults.clear()
            pc.shutdown(timeout_s=2)
            for svc in links:
                svc.close()
            for ci in duo.instances:
                ci.instance.conf.behaviors.peer_link_offset = 0

    def test_get_last_err_ttl_expiry(self, monkeypatch):
        monkeypatch.setattr(PeerClient, "ERR_TTL_MS", 30)
        pc = PeerClient(_behaviors(), PeerInfo(address="127.0.0.1:1"))
        pc._record_err("transient boom")
        assert any("transient boom" in e for e in pc.get_last_err())
        time.sleep(0.06)
        assert pc.get_last_err() == []  # expired, health no longer poisoned

    def test_shutdown_sweep_fails_queued_futures(self):
        """Requests the worker never reached must fail loudly with the
        clean not-ready signal, not sit orphaned until the batch timeout."""
        pc = PeerClient(_behaviors(), PeerInfo(address="127.0.0.1:9"))
        futs = [Future() for _ in range(3)]
        for fut in futs:  # queued, but no worker thread ever started
            pc._queue.put((_rl("orphan"), fut, None))
        pc.shutdown(timeout_s=0.1)
        for fut in futs:
            with pytest.raises(PeerNotReadyError):
                fut.result(timeout=1)


class TestLinkRetryKnob:
    def test_retry_delay_is_configurable_and_jittered(self):
        conf = _behaviors()
        conf.link_retry_s = 2.0
        pc = PeerClient(conf, PeerInfo(address="127.0.0.1:1"))
        delays = {pc._link_retry_delay() for _ in range(32)}
        assert all(1.0 <= d <= 3.0 for d in delays)  # base ±50%
        assert len(delays) > 1  # jittered, not a fleet-wide metronome

    def test_failed_connect_backs_off_by_knob(self):
        conf = _behaviors()
        conf.peer_link_offset = 1  # nothing listens there
        conf.link_retry_s = 0.01
        pc = PeerClient(conf, PeerInfo(address="127.0.0.1:9"))
        t0 = time.monotonic()
        assert pc._peer_link() is None
        assert pc._link_retry_at - t0 < 0.2  # seconds-scale, not LINK_RETRY_S

    def test_lost_install_race_never_returns_dead_link(self, monkeypatch):
        """The race tail: a loser thread must hand back None (gRPC
        fallback) when the winner's link already died, never the corpse."""
        import gubernator_tpu.service.peerlink as pl

        conf = _behaviors()
        conf.peer_link_offset = 1000
        pc = PeerClient(conf, PeerInfo(address="127.0.0.1:2345"))

        class FakeLink:
            _closed = False

            def close(self):
                self._closed = True

        dead = FakeLink()
        dead._closed = True

        def fake_ctor(addr, fault_key="", wire_v2=None, recorder=None):
            # interleave: another thread wins the install race with a link
            # that dies immediately after
            pc._link = dead
            return FakeLink()

        monkeypatch.setattr(pl, "PeerLinkClient", fake_ctor)
        assert pc._peer_link() is None


class TestForwardRepickBackoff:
    def test_repick_loop_backs_off_and_respects_deadline(self, duo,
                                                         monkeypatch):
        inst0 = duo.instances[0].instance
        owner_addr = duo.instances[1].address
        key = _key_owned_by(inst0, owner_addr, prefix="rp")
        peer = inst0.get_peer(f"test_{key}")
        calls = []

        def not_ready(req, trace_span=None, deadline=None):
            calls.append(time.monotonic())
            raise PeerNotReadyError(peer.info.address)

        monkeypatch.setattr(peer, "get_peer_rate_limit", not_ready)
        monkeypatch.setattr(inst0.conf.behaviors, "batch_timeout_s", 0.25)
        t0 = time.monotonic()
        resp = inst0._forward(_rl(key), f"test_{key}")
        dt = time.monotonic() - t0
        assert "not connected" in resp.error
        assert len(calls) == 6  # full retry budget inside the deadline
        assert dt >= 0.01, "re-picks spun hot with no backoff"
        assert dt <= 0.6, "re-pick loop outlived the client timeout"

    def test_repick_deadline_cuts_retries_short(self, duo, monkeypatch):
        inst0 = duo.instances[0].instance
        owner_addr = duo.instances[1].address
        key = _key_owned_by(inst0, owner_addr, prefix="rpd")
        peer = inst0.get_peer(f"test_{key}")
        calls = []

        def slow_not_ready(req, trace_span=None, deadline=None):
            calls.append(1)
            time.sleep(0.03)
            raise PeerNotReadyError(peer.info.address)

        monkeypatch.setattr(peer, "get_peer_rate_limit", slow_not_ready)
        monkeypatch.setattr(inst0.conf.behaviors, "batch_timeout_s", 0.05)
        t0 = time.monotonic()
        resp = inst0._forward(_rl(key), f"test_{key}")
        dt = time.monotonic() - t0
        assert resp.error != ""
        assert len(calls) < 6  # the deadline, not the count, ended the loop
        assert dt < 0.3


class TestHealthMessageBound:
    def test_sustained_failure_stays_bounded_with_counts(self, duo):
        from gubernator_tpu.utils.lru import LRUCache

        inst0 = duo.instances[0].instance
        owner_addr = duo.instances[1].address
        peer = inst0.get_peer(
            f"test_{_key_owned_by(inst0, owner_addr, prefix='hb')}")
        try:
            for i in range(150):  # sustained distinct failures
                peer._record_err(f"sustained failure {i} " + "x" * 120)
            for _ in range(inst0.conf.behaviors.circuit_threshold):
                peer.circuit.record_failure()
            hc = inst0.health_check()
            assert hc.status == "unhealthy"
            # bounded: counts + samples, never the multi-KB raw join
            # (150 errors x ~140 chars would exceed 20 KB unbounded)
            assert len(hc.message) <= inst0.HEALTH_MESSAGE_CHARS + 64
            assert "100 errors" in hc.message  # per-peer LRU retention cap
            assert "circuit open" in hc.message
            assert "sustained failure" in hc.message  # a sample survives
        finally:
            # restore the shared cluster's health for later tests
            peer.last_errs = LRUCache(max_size=100)
            peer.circuit.record_success()
        assert inst0.health_check().status == "healthy"
