"""Corpus: one unlocked donated-array read, one waived, several OK."""

import threading


def _array(n):
    return list(range(n))


class Engine:
    """A donated-array holder: `self.state` is assigned from a call."""

    def __init__(self):
        self._lock = threading.Lock()
        self.state = _array(8)  # construction scope: ok

    def snapshot_locked(self):
        return self.state[:]  # `_locked` suffix declares the contract: ok

    def apply(self):
        """Rebind under the lock. Caller holds the engine lock."""
        return self.state[:]  # docstring declares the contract: ok

    def good(self):
        with self._lock:
            return self.state[:]  # inside the lock scope: ok

    def bad(self):
        return self.state[:]  # VIOLATION: unlocked donated read

    def waived(self):
        # guberlint: disable=lock-discipline -- corpus: proves the inline waiver suppresses
        return self.state[:]
