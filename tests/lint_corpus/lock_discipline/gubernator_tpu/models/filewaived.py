"""Corpus: a file-scoped waiver covers every finding in the file."""
# guberlint: file-disable=lock-discipline -- corpus: stub engine, nothing donates at runtime


class StubEngine:
    def __init__(self):
        self.state = list()

    def read_one(self):
        return self.state

    def read_two(self):
        return self.state
