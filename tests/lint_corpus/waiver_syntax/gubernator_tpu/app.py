"""Corpus: malformed waivers are findings, not silent no-ops."""

X = 1  # guberlint: disable=knob-drift
# guberlint: disable
Y = 2
