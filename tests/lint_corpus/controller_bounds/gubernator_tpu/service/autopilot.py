"""Fake autopilot registry for the controller-bounds corpus.

Mirrors the real module's contract: module-level KNOBS dict of KnobSpec
literals plus a CONTROLLERS tuple of dicts. One good knob, three
deliberate violations, one waived twin, and a controller wired to a
knob the registry never declared.
"""


class KnobSpec:
    def __init__(self, **kw):
        self.kw = kw


KNOBS = {
    # clean: full band, positive step, documented env
    "good_knob": KnobSpec(name="good_knob", env="GUBER_CORPUS_GOOD",
                          floor=0.5, ceiling=2.0, step=0.25),
    # bad: no step declared — unbounded move size
    "stepless_knob": KnobSpec(name="stepless_knob",
                              env="GUBER_CORPUS_GOOD",
                              floor=0.5, ceiling=2.0),
    # bad: floor above ceiling — empty band
    "inverted_knob": KnobSpec(name="inverted_knob",
                              env="GUBER_CORPUS_GOOD",
                              floor=2.0, ceiling=0.5, step=0.25),
    # bad: env knob no operator doc mentions
    "ghost_env_knob": KnobSpec(name="ghost_env_knob",
                               env="GUBER_CORPUS_GHOST",
                               floor=0.5, ceiling=2.0, step=0.25),
    # same stepless bug as above, behind a justified waiver
    # guberlint: disable=controller-bounds -- corpus waived twin proving suppression
    "waived_knob": KnobSpec(name="waived_knob", env="GUBER_CORPUS_GOOD",
                            floor=0.5, ceiling=2.0),
}

CONTROLLERS = (
    {"name": "corpus", "knobs": ("good_knob", "unregistered_knob"),
     "side": "ceiling", "signal": "corpus.signal",
     "trip": 0.5, "clear": 0.25},
)
