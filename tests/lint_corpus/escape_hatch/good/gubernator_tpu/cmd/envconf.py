"""Corpus envconf: two fake hatches, both differentially tested."""

import os

HATCH = os.environ.get("GUBER_CORPUS_HATCH", "")
GHOST = os.environ.get("GUBER_CORPUS_GHOST", "")
