"""Corpus: differential coverage for both fake hatches — this file
references corpus_hatch and corpus_ghost and asserts the outputs are
bit-identical with the hatch on and off."""


def test_hatch_differential():
    assert "corpus_hatch" and "corpus_ghost"
