"""Corpus: references corpus_hatch but never proves equivalence (no
marker word from the rule's vocabulary may appear in this file).

(The second fake hatch must not be named anywhere under this root's
tests/ — its finding is the has-no-test-at-all variant.)
"""


def test_toggle():
    assert "corpus_hatch"  # toggled, never proven equivalent
