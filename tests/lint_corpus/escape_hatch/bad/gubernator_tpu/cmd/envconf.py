"""Corpus envconf: two fake hatches (finding anchor sites)."""

import os

HATCH = os.environ.get("GUBER_CORPUS_HATCH", "")
GHOST = os.environ.get("GUBER_CORPUS_GHOST", "")
