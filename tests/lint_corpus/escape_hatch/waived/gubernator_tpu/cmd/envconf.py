"""Corpus envconf: untested hatches, waived at their anchor sites."""

import os

HATCH = os.environ.get("GUBER_CORPUS_HATCH", "")  # guberlint: disable=escape-hatch -- corpus: equivalence proven out-of-tree
GHOST = os.environ.get("GUBER_CORPUS_GHOST", "")  # guberlint: disable=escape-hatch -- corpus: second hatch, same waiver path
