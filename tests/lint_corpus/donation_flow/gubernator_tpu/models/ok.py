"""Clean donated-buffer lifetimes: the capture is re-read under the
engine lock after the donate-and-rebind dispatch, and a read that
happens entirely BEFORE the dispatch is fine."""


def harvest_reread(backend):
    rows = backend.state
    backend.state, resp = backend.step(backend.state, 1)
    with backend._lock:
        rows = backend.state  # fresh post-rebind reference
    return rows.sum(), resp


def read_before_dispatch(backend):
    rows = backend.state
    total = rows.sum()  # read precedes the donation — valid buffer
    backend.state, resp = backend.step(backend.state, 1)
    return total, resp
