"""DELIBERATE donated-buffer lifetime bugs: `rows` is captured from
`backend.state` BEFORE the donate-and-rebind dispatch and read after it
— XLA deleted that buffer at dispatch (the PR 10 cartographer race)."""


def harvest(backend):
    rows = backend.state
    backend.state, resp = backend.step(backend.state, 1)
    return rows.sum(), resp  # stale donated capture


def harvest_waived(backend):
    rows = backend.state
    backend.state, resp = backend.step(backend.state, 1)
    # guberlint: disable=donation-flow -- corpus drill: stale read kept to prove waivers suppress
    return rows.sum(), resp
