// guberlint: disable=native-warnings -- corpus: proves the C++ waiver comment suppresses
int corpus_waived(int unused_arg) { return 9; }
