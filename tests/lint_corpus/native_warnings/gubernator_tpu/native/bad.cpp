int corpus_bad(int unused_arg) { return 7; }
