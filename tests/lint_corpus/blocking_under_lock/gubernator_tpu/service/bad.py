"""Corpus: blocking calls under a lock scope, plus the exemptions."""

import threading
import time

_lock = threading.Lock()


def bad():
    with _lock:
        time.sleep(0.1)  # VIOLATION: blocking under the lock


def waived():
    with _lock:
        time.sleep(0.1)  # guberlint: disable=blocking-under-lock -- corpus: proves the inline waiver suppresses


def deferred_ok():
    with _lock:
        def later():
            time.sleep(0.1)  # ok: definition is not execution
        return later


def io_lock_ok(sock, wlock):
    with wlock:
        sock.sendall(b"x")  # ok: IO locks exist to serialize socket writes
