"""Corpus code reads: one covered knob, one orphan, one waived."""

import os

GOOD = os.environ.get("GUBER_GOOD")  # in envconf + conf + docs: ok
ORPHAN = os.environ.get("GUBER_ORPHAN")  # VIOLATION: nowhere else
# guberlint: disable=knob-drift -- corpus: dev-only import-time switch, proves the waiver suppresses
SECRET = os.environ.get("GUBER_SECRET_DEV")
