"""Corpus envconf: resolves GUBER_GOOD and nothing else."""

import os

GOOD = os.environ.get("GUBER_GOOD", "1")
