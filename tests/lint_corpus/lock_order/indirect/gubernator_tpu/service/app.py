"""DELIBERATE call-graph-indirect lock-order cycle: neither function
nests two `with` statements lexically — forward() holds alpha and CALLS
a method that takes beta; backward() holds beta and calls one that takes
alpha. Only the interprocedural walk sees the cycle."""

from gubernator_tpu.obs import witness


class Indirect:
    def __init__(self):
        self._alock = witness.make_lock("alpha")
        self._block = witness.make_lock("beta")

    def take_alpha(self):
        with self._alock:
            return 1

    def take_beta(self):
        with self._block:
            return 2

    def forward(self):
        with self._alock:
            return self.take_beta()

    def backward(self):
        with self._block:
            return self.take_alpha()
