"""Same deliberate cycle as lock_order/cycle, waived at the finding's
anchor (the first witness site of the cycle's smallest edge)."""

from gubernator_tpu.obs import witness


class Pair:
    def __init__(self):
        self._alock = witness.make_lock("alpha")
        self._block = witness.make_lock("beta")

    def forward(self):
        with self._alock:  # guberlint: disable=lock-order -- corpus drill: deliberate cycle proving waivers suppress
            with self._block:
                return 1

    def backward(self):
        with self._block:
            with self._alock:
                return 2
