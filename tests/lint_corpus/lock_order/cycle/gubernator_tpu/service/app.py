"""DELIBERATE lock-order cycle: alpha -> beta in forward(), beta ->
alpha in backward() — two threads running these concurrently deadlock."""

from gubernator_tpu.obs import witness


class Pair:
    def __init__(self):
        self._alock = witness.make_lock("alpha")
        self._block = witness.make_lock("beta")

    def forward(self):
        with self._alock:
            with self._block:
                return 1

    def backward(self):
        with self._block:
            with self._alock:
                return 2
