"""Corpus schema contract: `ghost` is promised but never emitted."""

ALWAYS = {"engine"}
OPTIONAL = {"ghost"}
