# Corpus scenario registry: "steady" is documented (clean pair),
# "phantom-surge" has no doc row (registered-but-undocumented finding).
SCENARIO_NAMES = (
    "steady",
    "phantom-surge",
)
