"""Corpus fault registry: ghostlink is registered but undocumented."""

TRANSPORTS = ("grpc", "ghostlink")


def on_call(peer, transport):
    del peer, transport
