"""Corpus debug plane: the `extra` section is not in the schema test."""


def debug_vars(engine):
    out = {"engine": repr(engine)}
    out["extra"] = 1  # VIOLATION: not declared in ALWAYS/OPTIONAL
    return out
