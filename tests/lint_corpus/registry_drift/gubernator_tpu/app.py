"""Corpus: flight-recorder emits and fault choke points."""

from gubernator_tpu.service import faults


def emit(kind, **fields):
    del kind, fields


def serve(peer):
    emit("widget.stop")  # documented in the table: ok
    emit("widget.spin")  # VIOLATION: missing from the doc table
    emit("widget.secret")  # guberlint: disable=registry-drift -- corpus: proves the inline waiver suppresses
    faults.on_call(peer, "grpc")  # registered transport: ok
    faults.on_call(peer, "carrier")  # VIOLATION: not in TRANSPORTS
