"""Live resharding (service/reshard.py): move-set planning, the transfer
codec, the off-switch differential, and the two-node handoff protocol —
commit bit-identity, retry safety, and TTL fail-close under injected
transport faults.

The multi-node continuity drills (sustained load across scale-up,
evacuate, kill, rolling restart) live in tests/test_reshard_drills.py.
"""

import dataclasses
import time

import numpy as np
import pytest

from gubernator_tpu.cluster.harness import LocalCluster
from gubernator_tpu.cluster.harness import test_behaviors as _behaviors
from gubernator_tpu.cluster.pickers import ConsistentHashPicker
from gubernator_tpu.service import faults
from gubernator_tpu.service.reshard import (
    decode_msg,
    encode_ctl,
    encode_rows_msg,
    plan_move_set,
)
from gubernator_tpu.store import pack_rows_chunk, unpack_rows_chunk
from gubernator_tpu.types import PeerInfo, RateLimitReq


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.clear()


def _rl(i, hits=1, limit=1000, duration=600_000):
    return RateLimitReq(name=f"svc{i % 7}", unique_key=f"user-{i:04d}",
                       hits=hits, limit=limit, duration=duration)


def _drive(inst, n, hits=1):
    """Apply one hit batch per 50 keys; return {hash_key: remaining}."""
    out = {}
    for lo in range(0, n, 50):
        batch = [_rl(i, hits) for i in range(lo, min(lo + 50, n))]
        for resp, req in zip(inst.get_rate_limits(batch), batch):
            assert not resp.error, (req.unique_key, resp.error)
            out[req.hash_key()] = resp.remaining
    return out


def _reshard_behaviors(**kw):
    kw.setdefault("reshard", True)
    kw.setdefault("reshard_ttl_s", 5.0)
    kw.setdefault("reshard_grace_s", 0.5)
    return dataclasses.replace(_behaviors(), **kw)


def _quiesce(cluster, timeout=20.0):
    """Wait until no node is planning or mid-session."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        busy = False
        for ci in cluster.instances:
            d = ci.instance.reshard.debug()
            if d["planning"] or any(s["state"] in ("begin", "streaming")
                                    for s in d["sessions"]):
                busy = True
        if not busy:
            return True
        time.sleep(0.05)
    return False


def _agg_stats(cluster):
    agg = {}
    for ci in cluster.instances:
        for k, v in ci.instance.reshard.debug()["stats"].items():
            agg[k] = agg.get(k, 0) + v
    return agg


# ------------------------------------------------------------- move set


class _Peer:
    def __init__(self, address, is_owner=False):
        self.info = PeerInfo(address=address, is_owner=is_owner)


def _ring(addrs, self_addr=None):
    p = ConsistentHashPicker()
    for a in addrs:
        p.add(_Peer(a, is_owner=(a == self_addr)))
    return p


KEYS = [f"svc{i % 5}_user-{i:03d}" for i in range(400)]
A, B, C = "10.0.0.1:81", "10.0.0.2:81", "10.0.0.3:81"


class TestPlanMoveSet:
    def test_minimal_only_changed_owners_move(self):
        old = _ring([A, B], self_addr=A)
        new = _ring([A, B, C], self_addr=A)
        moves = plan_move_set(KEYS, old, new, A)
        moved = {k for ks in moves.values() for k in ks}
        for key in KEYS:
            was_mine = old.get(key).info.address == A
            now_addr = new.get(key).info.address
            should_move = was_mine and now_addr != A
            assert (key in moved) == should_move, key
            if should_move:
                assert key in moves[now_addr]
        # a self-owned-then-and-now key never appears; no empty dest lists
        assert all(moves.values())

    def test_unchanged_ring_plans_nothing(self):
        ring = _ring([A, B], self_addr=A)
        assert plan_move_set(KEYS, ring, _ring([A, B], self_addr=A), A) == {}

    def test_stable_across_recomputation(self):
        old = _ring([A, B], self_addr=B)
        new = _ring([A, B, C], self_addr=B)
        first = plan_move_set(KEYS, old, new, B)
        for _ in range(3):
            again = plan_move_set(KEYS, old, new, B)
            assert again == first  # same dests, same keys, same ORDER
            assert list(again) == list(first)

    def test_only_self_owned_keys_move(self):
        old = _ring([A, B], self_addr=A)
        new = _ring([A, B, C], self_addr=A)
        moves = plan_move_set(KEYS, old, new, A)
        for ks in moves.values():
            for k in ks:
                assert old.get(k).info.address == A

    def test_internal_prefix_never_planned(self):
        old = _ring([A], self_addr=A)
        new = _ring([B], self_addr=A)
        keys = ["__guber_reshard_barrier", "real_key"]
        moves = plan_move_set(keys, old, new, A)
        assert moves == {B: ["real_key"]}

    def test_empty_old_ring_plans_nothing(self):
        # a freshly started node diffing from nothing must not plan
        assert plan_move_set(KEYS, _ring([]), _ring([A, B]), A) == {}


# ---------------------------------------------------------------- codec


class TestCodec:
    def test_ctl_roundtrip(self):
        msg = {"op": "begin", "xfer": 123456789, "src": A, "ttl_ms": 5000}
        kind, decoded = decode_msg(encode_ctl(msg))
        assert kind == "ctl" and decoded == msg

    def test_rows_roundtrip(self):
        rows = np.arange(21, dtype=np.int64).reshape(3, 7)
        keys = ["a_1", "b_22", "c_333"]
        body = encode_rows_msg(0xDEAD, 7, True, keys, rows, ["gone_1"])
        kind, (rid, seq, final, got_keys, slab, vacant) = decode_msg(body)
        assert kind == "rows"
        assert (rid, seq, final) == (0xDEAD, 7, True)
        assert got_keys == keys
        assert list(vacant) == ["gone_1"]
        _blob, _off, got_rows = slab
        np.testing.assert_array_equal(np.asarray(got_rows), rows)

    def test_rows_empty_chunk(self):
        body = encode_rows_msg(1, 0, True, [], np.zeros((0, 7), np.int64),
                               ["only_vacant"])
        kind, (_rid, _seq, _final, keys, slab, vacant) = decode_msg(body)
        assert kind == "rows" and keys == [] and list(vacant) == ["only_vacant"]
        assert np.asarray(slab[2]).shape == (0, 7)

    def test_foreign_body_is_none(self):
        # a pre-reshard peer's JSON node report must not decode
        assert decode_msg(b'{"advertise_address": "x"}') is None
        assert decode_msg(b"") is None

    def test_pack_unpack_chunk_bit_identical(self):
        keys = [f"key_{i}".encode() for i in range(100)]
        rows = np.arange(700, dtype=np.int64).reshape(100, 7)
        buf = pack_rows_chunk(keys, rows)
        blob, off, got = unpack_rows_chunk(buf)
        assert [blob[off[i]:off[i + 1]] for i in range(100)] == keys
        np.testing.assert_array_equal(got, rows)
        assert pack_rows_chunk(keys, rows) == buf  # deterministic bytes

    def test_unpack_truncation_fails_loudly(self):
        buf = pack_rows_chunk([b"k1", b"k2"],
                              np.ones((2, 7), np.int64))
        for cut in (1, 5, len(buf) - 3):
            with pytest.raises(ValueError):
                unpack_rows_chunk(buf[:cut])


# -------------------------------------------------- the off differential


class TestReshardOff:
    def test_membership_change_bit_identical_with_knob_unset(self):
        """GUBER_RESHARD=0 (the default): a membership change leaves the
        engine rows byte-identical and the handoff plane dormant."""
        c = LocalCluster().start(1)  # plain test behaviors: reshard off
        try:
            inst = c.instances[0].instance
            _drive(inst, 120, hits=3)
            before = [
                (bytes(blob), np.asarray(off).tobytes(),
                 np.asarray(rows).tobytes())
                for blob, off, rows in inst.backend.snapshot_slabs()]
            # ring change: add a peer that does not even exist
            inst.set_peers([PeerInfo(address=inst.advertise_address),
                            PeerInfo(address="127.0.0.1:1")])
            time.sleep(0.2)
            after = [
                (bytes(blob), np.asarray(off).tobytes(),
                 np.asarray(rows).tobytes())
                for blob, off, rows in inst.backend.snapshot_slabs()]
            assert before == after
            d = inst.reshard.debug()
            assert d["enabled"] is False and d["active"] is False
            assert d["stats"]["plans"] == 0
            assert d["sessions"] == [] and d["recent"] == []
        finally:
            c.stop()


# --------------------------------------------------- two-node transfers


def _scale_up_with_moves(behaviors, n_keys=200, max_adds=4):
    """Boot 2 nodes, load n_keys, then add nodes until the ring diff
    actually moves keys (the single-point crc32 ring can add a node into
    an arc no key hashes to). Returns (cluster, moved_keys: {key: dest},
    pre_move_rows: {key: row_bytes})."""
    cluster = LocalCluster().start(2, behaviors=behaviors)
    ok = False
    try:
        time.sleep(behaviors.reshard_grace_s + 0.2)  # boot grace
        _drive(cluster.instances[0].instance, n_keys, hits=5)
        pre_rows = {}
        for ci in cluster.instances:
            for blob, off, rows in ci.instance.backend.snapshot_slabs():
                off = np.asarray(off)
                rows = np.asarray(rows)
                for i in range(len(off) - 1):
                    key = bytes(blob[off[i]:off[i + 1]]).decode()
                    pre_rows[key] = rows[i].tobytes()
        moved = {}
        for _ in range(max_adds):
            olds = {ci.address: ci.instance.local_picker
                    for ci in cluster.instances}
            cluster.start_instance(behaviors=behaviors)
            cluster.sync_peers()
            for ci in cluster.instances[:-1]:
                rm = ci.instance.reshard
                mv = plan_move_set(
                    rm._resident_keys(), olds[ci.address],
                    ci.instance.local_picker, ci.instance.advertise_address)
                for dest, ks in mv.items():
                    for k in ks:
                        moved[k] = dest
            if moved:
                break
        assert moved, "ring never moved a key"
        ok = True
        return cluster, moved, pre_rows
    finally:
        if not ok:
            cluster.stop()


@pytest.mark.chaos
class TestHandoffProtocol:
    def test_committed_handoff_rows_bit_identical(self):
        """With no load during the transfer, the new owner's rows for the
        moved keys are byte-for-byte the old owner's pre-move rows."""
        cluster, moved, pre_rows = _scale_up_with_moves(_reshard_behaviors())
        try:
            assert _quiesce(cluster)
            stats = _agg_stats(cluster)
            assert stats["export_commits"] >= 1
            assert stats["import_commits"] >= 1
            assert stats["export_aborts"] == 0, stats
            assert stats["fresh_serves"] == 0, stats
            assert stats["rows_out"] == stats["rows_in"] == len(moved)
            for key, dest in moved.items():
                owner = cluster.instance_for_host(dest).instance
                found, rows = owner.backend.rows_for_keys([key])
                assert found == [key], f"{key} missing on new owner"
                assert np.asarray(rows)[0].tobytes() == pre_rows[key], key
        finally:
            cluster.stop()

    def test_one_dropped_frame_is_retried_not_fatal(self):
        """A single faulted transfer RPC per peer is retried (begin and
        commit are idempotent, frames are seq-deduplicated) and the
        handoff still commits."""
        faults.install("transport=reshard;calls=1;action=error")
        cluster, moved, _ = _scale_up_with_moves(_reshard_behaviors())
        try:
            assert _quiesce(cluster)
            stats = _agg_stats(cluster)
            assert stats["export_commits"] >= 1, stats
            assert stats["export_aborts"] == 0, stats
            assert stats["fresh_serves"] == 0, stats
        finally:
            cluster.stop()

    def test_dead_transfer_plane_fails_closed_to_amnesty(self):
        """Every transfer RPC erroring = the handoff aborts fail-closed;
        serving continues, moved keys restart fresh (counted amnesty),
        and nothing wedges or over-admits."""
        faults.install("transport=reshard;action=error")
        behaviors = _reshard_behaviors(reshard_ttl_s=1.0,
                                       reshard_grace_s=0.3)
        cluster, moved, _ = _scale_up_with_moves(behaviors)
        try:
            assert _quiesce(cluster, timeout=25)
            stats = _agg_stats(cluster)
            assert stats["export_commits"] == 0
            assert stats["export_aborts"] >= 1, stats
            assert stats["rows_in"] == 0
            # serving keeps working THROUGH the dead plane: hit every key
            # once; no request may error or hang
            t0 = time.monotonic()
            after = _drive(cluster.instances[0].instance, 200, hits=1)
            assert time.monotonic() - t0 < 30.0
            assert len(after) == 200
            # no over-admission: a fresh serve can only LOWER admitted
            # budget (remaining resets up, but hits are still counted)
            for key in moved:
                assert after[key] >= 0
            sessions = [s for ci in cluster.instances
                        for s in ci.instance.reshard.debug()["recent"]]
            reasons = {s["reason"].split(":")[0] for s in sessions
                       if s["state"] == "aborted"}
            assert reasons & {"begin_failed", "frame_failed",
                              "commit_failed"}, reasons
        finally:
            cluster.stop()

    def test_importer_lease_expires_at_ttl(self):
        """An importer whose exporter goes silent after `begin` drops the
        session at the lease TTL (reason ttl_expired) and serves fresh —
        it must not wait for a commit that will never come."""
        behaviors = _reshard_behaviors(reshard_ttl_s=0.3,
                                       reshard_grace_s=0.2)
        c = LocalCluster().start(1, behaviors=behaviors)
        try:
            rm = c.instances[0].instance.reshard
            ack = decode_msg(rm.handle_message(encode_ctl(
                {"op": "begin", "xfer": 42, "src": "10.9.9.9:81",
                 "ttl_ms": 300, "planned": 10})))[1]
            assert ack.get("ok") and ack["ttl_ms"] <= 300
            assert any(s["state"] == "streaming"
                       for s in rm.debug()["sessions"])
            time.sleep(0.45)  # one TTL + slack, no renewal
            # the expired lease surfaces on the next touch
            body = encode_rows_msg(42, 0, False, ["x_y"],
                                   np.ones((1, 7), np.int64), [])
            kind, reply = decode_msg(rm.handle_message(body))
            assert kind == "ctl" and "unknown transfer" in reply["error"]
            d = rm.debug()
            assert d["stats"]["import_aborts"] == 1
            assert any(s["reason"] == "ttl_expired" for s in d["recent"])
            assert not d["active"] or d["sessions"] == []
        finally:
            c.stop()

    def test_pre_reshard_peer_degrades_not_wedges(self):
        """A peer whose Debug handler answers the legacy node report (no
        reshard plane) aborts the session cleanly — detected from the
        non-GRSH reply, degraded to amnesty."""
        old_style = _behaviors()  # reshard off: Debug answers node report
        cluster = LocalCluster().start(2, behaviors=old_style)
        try:
            # flip ONE node on; its exports must fail gracefully
            src = cluster.instances[0].instance
            src.reshard.enabled = True
            src.conf.behaviors.reshard = True
            _drive(src, 100, hits=2)
            cluster.start_instance(behaviors=old_style)
            cluster.sync_peers()
            assert _quiesce(cluster, timeout=15)
            d = src.reshard.debug()
            if d["stats"]["plans"] and d["recent"]:
                assert all(s["state"] in ("committed", "aborted")
                           for s in d["recent"])
            # serving never wedged
            assert len(_drive(src, 100, hits=1)) == 100
        finally:
            cluster.stop()


# ------------------------------------------------------------ env knobs


class TestEnvKnobs:
    def test_defaults(self, monkeypatch):
        for var in ("GUBER_RESHARD", "GUBER_RESHARD_TTL",
                    "GUBER_RESHARD_CHUNK_ROWS", "GUBER_RESHARD_GRACE"):
            monkeypatch.delenv(var, raising=False)
        from gubernator_tpu.cmd.envconf import config_from_env
        b = config_from_env([]).behaviors
        assert b.reshard is False
        assert b.reshard_ttl_s == 5.0
        assert b.reshard_chunk_rows == 2048
        assert b.reshard_grace_s == 1.0

    def test_round_trip(self, monkeypatch):
        monkeypatch.setenv("GUBER_RESHARD", "1")
        monkeypatch.setenv("GUBER_RESHARD_TTL", "2s")
        monkeypatch.setenv("GUBER_RESHARD_CHUNK_ROWS", "512")
        monkeypatch.setenv("GUBER_RESHARD_GRACE", "250ms")
        from gubernator_tpu.cmd.envconf import config_from_env
        b = config_from_env([]).behaviors
        assert b.reshard is True
        assert b.reshard_ttl_s == 2.0
        assert b.reshard_chunk_rows == 512
        assert b.reshard_grace_s == 0.25

    def test_validation_rejects_bad_values(self):
        from gubernator_tpu.service.config import (
            BehaviorConfig,
            InstanceConfig,
        )
        for field, bad in (("reshard_ttl_s", 0.0),
                           ("reshard_chunk_rows", 0),
                           ("reshard_chunk_rows", 9000),
                           ("reshard_grace_s", -1.0)):
            behaviors = dataclasses.replace(BehaviorConfig(), **{field: bad})
            with pytest.raises(ValueError, match=field):
                InstanceConfig(behaviors=behaviors).validate()
