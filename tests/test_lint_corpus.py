"""Golden-violation corpus for guberlint (tests/lint_corpus/).

Each corpus subdirectory is a miniature fake repo holding one deliberate
violation per rule plus a waived twin. These tests prove the two halves
of the analyzer's contract: every rule FIRES on the bug class it was
built for, and every waiver SUPPRESSES with its justification intact —
so a refactor that silently lobotomizes a rule (or breaks waiver
parsing) fails here even while the real tree stays green.

pytest never collects inside lint_corpus/ (conftest collect_ignore: the
fake repos deliberately mirror real file names like
tests/test_debug_schema.py), and the real repo scan prunes the directory
(RepoIndex.walk), so the corpus findings can never leak into the
zero-findings gate in test_lint.py.
"""

import os
import shutil

import pytest

from gubernator_tpu.analysis import core
from gubernator_tpu.analysis.rules.hatches import EscapeHatchRule

CORPUS = os.path.join(os.path.dirname(__file__), "lint_corpus")


def _run(name, rule_id):
    root = os.path.join(CORPUS, name)
    assert os.path.isdir(root), f"corpus root missing: {root}"
    return core.run(root, only=[rule_id])


def _justified(suppressed):
    return all(w.justification.strip() for _, w in suppressed)


def test_lock_discipline_fires_and_waives():
    findings, suppressed = _run("lock_discipline", "lock-discipline")
    assert len(findings) == 1, [f.render() for f in findings]
    f = findings[0]
    assert f.rule == "lock-discipline"
    assert f.path.endswith("models/bad.py")
    assert "outside a lock scope" in f.message
    # inline waiver (1) + file-scoped waiver covering two reads (2)
    assert len(suppressed) == 3
    assert _justified(suppressed)


def test_blocking_under_lock_fires_and_waives():
    findings, suppressed = _run("blocking_under_lock", "blocking-under-lock")
    assert len(findings) == 1, [f.render() for f in findings]
    f = findings[0]
    assert f.rule == "blocking-under-lock"
    assert "time.sleep" in f.message
    # the deferred closure and the IO-lock sendall must NOT have fired
    assert len(suppressed) == 1
    assert _justified(suppressed)


def test_knob_drift_fires_and_waives():
    findings, suppressed = _run("knob_drift", "knob-drift")
    assert len(findings) == 2, [f.render() for f in findings]
    by_knob = {f.message.split()[0]: f for f in findings}
    assert set(by_knob) == {"GUBER_ORPHAN", "GUBER_DEAD"}
    orphan = by_knob["GUBER_ORPHAN"]
    assert "cmd/envconf.py" in orphan.message
    assert "example.conf" in orphan.message
    assert "docs/" in orphan.message
    dead = by_knob["GUBER_DEAD"]
    assert dead.path == "example.conf"
    assert "no code" in dead.message
    # GUBER_SECRET_DEV: waived at its read site
    assert len(suppressed) == 1
    assert suppressed[0][0].message.startswith("GUBER_SECRET_DEV")
    assert _justified(suppressed)


# --------------------------------------------------------- escape hatch

class _CorpusHatchRule(EscapeHatchRule):
    """Same rule logic, pointed at fake hatches the corpus defines (the
    real HATCHES table would drag the whole repo's tests into scope)."""

    hatches = (
        ("GUBER_CORPUS_HATCH", ("corpus_hatch",)),
        ("GUBER_CORPUS_GHOST", ("corpus_ghost",)),
    )


def _run_hatch(sub):
    """core.run() only knows registered rules; replicate its waiver
    filtering for the unregistered corpus subclass."""
    repo = core.RepoIndex(os.path.join(CORPUS, "escape_hatch", sub))
    findings, suppressed = [], []
    for f in _CorpusHatchRule().check(repo):
        sf = repo.get(f.path)
        w = sf.waived(f.rule, f.line) if sf is not None else None
        if w is not None:
            suppressed.append((f, w))
        else:
            findings.append(f)
    return findings, suppressed


def test_escape_hatch_fires_on_missing_and_unmarked_tests():
    findings, suppressed = _run_hatch("bad")
    assert not suppressed
    msgs = sorted(f.message for f in findings)
    assert len(msgs) == 2, msgs
    # GUBER_CORPUS_GHOST: no test references it at all
    assert "GUBER_CORPUS_GHOST has no test" in msgs[0]
    # GUBER_CORPUS_HATCH: referenced, but no differential marker
    assert "GUBER_CORPUS_HATCH is referenced" in msgs[1]
    assert "differential marker" in msgs[1]
    # findings anchor at the envconf parse site
    assert all(f.path.endswith("cmd/envconf.py") for f in findings)


def test_escape_hatch_clean_with_differential_marker():
    findings, suppressed = _run_hatch("good")
    assert not findings, [f.render() for f in findings]
    assert not suppressed


def test_escape_hatch_waived_at_anchor():
    findings, suppressed = _run_hatch("waived")
    assert not findings, [f.render() for f in findings]
    assert len(suppressed) == 2
    assert _justified(suppressed)


def test_controller_bounds_fires_and_waives():
    findings, suppressed = _run("controller_bounds", "controller-bounds")
    msgs = [f.render() for f in findings]
    assert len(findings) == 4, msgs

    def one(substr):
        hits = [f for f in findings if substr in f.message]
        assert len(hits) == 1, (substr, msgs)
        return hits[0]

    unreg = one("'unregistered_knob' with no KNOBS entry")
    assert "'corpus'" in unreg.message
    stepless = one("'stepless_knob' KnobSpec declares no step")
    assert "unbounded" in stepless.message
    inverted = one("'inverted_knob' declares floor 2.0 > ceiling 0.5")
    ghost = one("GUBER_CORPUS_GHOST has no row in the knob docs")
    assert "docs/OPERATIONS.md" in ghost.message
    assert all(f.path.endswith("service/autopilot.py") for f in findings)
    assert inverted.line != ghost.line
    # good_knob is clean; waived_knob's stepless twin is suppressed
    assert not any("good_knob" in m for m in msgs)
    assert len(suppressed) == 1
    assert "waived_knob" in suppressed[0][0].message
    assert _justified(suppressed)


def test_registry_drift_fires_on_all_three_registries():
    findings, suppressed = _run("registry_drift", "registry-drift")
    msgs = [f.render() for f in findings]
    assert len(findings) == 7, msgs

    def one(substr):
        hits = [f for f in findings if substr in f.message]
        assert len(hits) == 1, (substr, msgs)
        return hits[0]

    spin = one("'widget.spin' is emitted but missing")
    assert spin.path.endswith("gubernator_tpu/app.py")
    ghostlink = one("'ghostlink' is registered in TRANSPORTS")
    assert ghostlink.path.endswith("service/faults.py")
    carrier = one("'carrier' is not in service/faults.py TRANSPORTS")
    assert carrier.path.endswith("gubernator_tpu/app.py")
    extra = one("'extra' is emitted by debug_vars()")
    assert extra.path.endswith("obs/introspect.py")
    ghost = one("'ghost' is declared in")
    assert ghost.path.endswith("tests/test_debug_schema.py")
    surge = one("'phantom-surge' is registered in SCENARIO_NAMES")
    assert surge.path.endswith("gubernator_tpu/scenarios/spec.py")
    drill = one("'ghost-drill' is documented but the registry")
    assert drill.path.endswith("docs/observability.md")
    # the documented-and-emitted pairs (widget.stop, engine, grpc,
    # steady) are clean
    assert not any("widget.stop" in m or "'engine'" in m or "'grpc'" in m
                   or "'steady'" in m for m in msgs)
    # emit("widget.secret") carries an inline waiver
    assert len(suppressed) == 1
    assert "widget.secret" in suppressed[0][0].message
    assert _justified(suppressed)


@pytest.mark.skipif(shutil.which("g++") is None,
                    reason="native-warnings rule self-skips without g++")
def test_native_warnings_fires_and_waives():
    findings, suppressed = _run("native_warnings", "native-warnings")
    assert len(findings) == 1, [f.render() for f in findings]
    f = findings[0]
    assert f.rule == "native-warnings"
    assert f.path.endswith("native/bad.cpp")
    assert "unused" in f.message  # -Wunused-parameter under -Wextra
    # waived.cpp has the same warning behind a `//` waiver comment
    assert len(suppressed) == 1
    assert suppressed[0][0].path.endswith("native/waived.cpp")
    assert _justified(suppressed)


def test_malformed_waivers_are_findings():
    # run any file-loading rule; waiver-syntax findings surface regardless
    findings, suppressed = _run("waiver_syntax", "knob-drift")
    assert not suppressed
    msgs = sorted(f.message for f in findings)
    assert len(msgs) == 2, msgs
    assert all(f.rule == "waiver-syntax" for f in findings)
    assert "without a justification" in msgs[0]
    assert "unparseable guberlint waiver" in msgs[1]


# ----------------------------------------------------------- lock order

def test_lock_order_fires_on_lexical_cycle():
    findings, suppressed = _run("lock_order/cycle", "lock-order")
    assert len(findings) == 1, [f.render() for f in findings]
    f = findings[0]
    assert f.rule == "lock-order"
    assert "alpha -> beta" in f.message
    # the message carries the path:line witness chain for every edge
    assert "via" in f.message and "app.py:" in f.message
    assert not suppressed


def test_lock_order_waiver_suppresses_at_anchor():
    findings, suppressed = _run("lock_order/waived", "lock-order")
    assert not findings, [f.render() for f in findings]
    assert len(suppressed) == 1
    assert suppressed[0][0].rule == "lock-order"
    assert _justified(suppressed)


def test_lock_order_sees_call_graph_indirect_cycle():
    # neither function nests two `with` lexically; only the
    # interprocedural held-set walk can see this one
    findings, suppressed = _run("lock_order/indirect", "lock-order")
    assert len(findings) == 1, [f.render() for f in findings]
    msg = findings[0].message
    assert "alpha" in msg and "beta" in msg
    # the witness chain must include the call hop, i.e. >2 sites
    assert msg.count("app.py:") >= 3, msg
    assert not suppressed


# -------------------------------------------------------- donation flow

def test_donation_flow_fires_on_read_after_donate():
    findings, suppressed = _run("donation_flow", "donation-flow")
    assert len(findings) == 1, [f.render() for f in findings]
    f = findings[0]
    assert f.rule == "donation-flow"
    assert f.path.endswith("models/bad.py")
    assert "`rows`" in f.message and "backend.state" in f.message
    # harvest_waived carries the same bug behind a justified waiver
    assert len(suppressed) == 1
    assert _justified(suppressed)


def test_donation_flow_clean_on_reread_and_pre_dispatch_read():
    findings, _ = _run("donation_flow", "donation-flow")
    assert not any(f.path.endswith("ok.py") for f in findings), \
        [f.render() for f in findings]
