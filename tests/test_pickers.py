"""Picker tests, modeled on the reference's suite
(reference: hash_test.go, replicated_hash_test.go)."""

import random
from types import SimpleNamespace

import pytest

from gubernator_tpu.cluster.pickers import (
    ConsistentHashPicker,
    PickerEmptyError,
    RegionPicker,
    ReplicatedConsistentHashPicker,
    crc32_hash,
    fnv1_32,
    fnv1a_32,
)
from gubernator_tpu.types import PeerInfo
from gubernator_tpu.utils.fnv import fnv1_64, fnv1a_64


def peer(addr, dc=""):
    return SimpleNamespace(info=PeerInfo(address=addr, datacenter=dc))


HOSTS = ["a.svc.local", "b.svc.local", "c.svc.local"]


class TestConsistentHash:
    @pytest.mark.parametrize("fn", [crc32_hash, fnv1_32, fnv1a_32])
    def test_deterministic_pinning(self, fn):
        """Same key always lands on the same peer across instances
        (reference: hash_test.go:18-37)."""
        p1 = ConsistentHashPicker(fn)
        p2 = ConsistentHashPicker(fn)
        for h in HOSTS:
            p1.add(peer(h))
            p2.add(peer(h))
        for i in range(100):
            key = f"key_{i}"
            assert p1.get(key).info.address == p2.get(key).info.address

    def test_empty_pool_raises(self):
        with pytest.raises(PickerEmptyError):
            ConsistentHashPicker().get("x")

    def test_size_peers_and_lookup(self):
        p = ConsistentHashPicker()
        for h in HOSTS:
            p.add(peer(h))
        assert p.size() == 3
        assert {x.info.address for x in p.peers()} == set(HOSTS)
        assert p.get_by_peer_info(PeerInfo(address="b.svc.local")) is not None
        assert p.get_by_peer_info(PeerInfo(address="zz")) is None

    def test_distribution_not_degenerate(self):
        """10k random IP keys must reach every peer
        (reference: hash_test.go:64-102)."""
        p = ConsistentHashPicker()
        for h in HOSTS:
            p.add(peer(h))
        rng = random.Random(1)
        counts = {h: 0 for h in HOSTS}
        for _ in range(10_000):
            ip = ".".join(str(rng.randint(0, 255)) for _ in range(4))
            counts[p.get(ip).info.address] += 1
        assert all(c > 0 for c in counts.values())

    def test_new_is_empty_same_config(self):
        p = ConsistentHashPicker(fnv1a_32)
        p.add(peer("a"))
        q = p.new()
        assert q.size() == 0 and q.hash_func is fnv1a_32


class TestReplicatedHash:
    @pytest.mark.parametrize("fn", [fnv1_64, fnv1a_64])
    def test_even_spread(self, fn):
        """512 vnodes keep per-peer share near the mean. The reference's
        distribution test only logs percentages (replicated_hash_test.go:42-79);
        we assert a 25% band — loose enough for ring variance, tight enough
        to catch degenerate point placement."""
        hosts = [f"host-{i}.local" for i in range(8)]
        p = ReplicatedConsistentHashPicker(fn)
        for h in hosts:
            p.add(peer(h))
        rng = random.Random(2)
        counts = {h: 0 for h in hosts}
        n = 10_000
        for _ in range(n):
            ip = ".".join(str(rng.randint(0, 255)) for _ in range(4))
            counts[p.get(ip).info.address] += 1
        mean = n / len(hosts)
        for h, c in counts.items():
            assert abs(c - mean) / mean < 0.25, f"{h}: {c} vs mean {mean}"

    def test_deterministic_pinning(self):
        p1 = ReplicatedConsistentHashPicker()
        p2 = ReplicatedConsistentHashPicker()
        for h in HOSTS:
            p1.add(peer(h))
            p2.add(peer(h))
        for i in range(100):
            key = f"test_{i}"
            assert p1.get(key).info.address == p2.get(key).info.address

    def test_size_counts_peers_not_points(self):
        p = ReplicatedConsistentHashPicker(replicas=16)
        p.add(peer("a"))
        p.add(peer("b"))
        assert p.size() == 2

    def test_empty_pool_raises(self):
        with pytest.raises(PickerEmptyError):
            ReplicatedConsistentHashPicker().get("x")


class TestRegionPicker:
    def test_one_owner_per_region(self):
        rp = RegionPicker()
        for dc in ["us-east-1", "us-west-2"]:
            for i in range(3):
                rp.add(peer(f"{dc}-{i}", dc=dc))
        owners = rp.get_clients("some_key")
        assert len(owners) == 2
        assert {o.info.datacenter for o in owners} == {"us-east-1", "us-west-2"}

    def test_get_by_peer_info_searches_all_regions(self):
        rp = RegionPicker()
        rp.add(peer("x", dc="dc1"))
        rp.add(peer("y", dc="dc2"))
        assert rp.get_by_peer_info(PeerInfo(address="y")).info.address == "y"
        assert rp.get_by_peer_info(PeerInfo(address="zz")) is None
        assert rp.size() == 2
        assert set(rp.pickers()) == {"dc1", "dc2"}


def test_fnv1_trailing_suffix_clusters_one_arc():
    """Document a reference-inherited hashing property (PARITY #15): fnv1
    (the ring hash, replicated_hash.go:24) mixes a differing byte only
    through the multiplies that FOLLOW it, so keys that differ near their
    END cluster within a few low bits — far closer than the ~2^54 average
    gap between 1024 ring points — and resolve to the same owner. Key
    families that differ in LEADING bytes spread normally. Anyone load
    balancing sequential keys ("user:1".."user:N") must put the sequence
    number early or salt the key."""
    from gubernator_tpu.cluster.pickers import ReplicatedConsistentHashPicker

    picker = ReplicatedConsistentHashPicker()
    for h in HOSTS:
        picker.add(peer(h))
    # trailing variation: same length, same prefix -> ONE owner arc
    trailing = {picker.get(f"xhost_conv{i:02d}").info.address
                for i in range(32)}
    assert len(trailing) == 1
    # leading variation: full avalanche -> spread over every peer
    leading = {picker.get(f"{i:02d}conv_xhost").info.address
               for i in range(32)}
    assert len(leading) == len(HOSTS)
